"""Pipeline save -> load -> transform parity sweep across model families.

The reference exercises ModelDataConverter round-trips per algorithm
(SURVEY §4 "converter round-trips"); this sweep fits one pipeline per
family, saves it, reloads in-place, and requires bit-identical transform
output — catching any converter field that fails to survive serialization.
"""

import numpy as np
import pytest

from alink_tpu import Pipeline, PipelineModel
from alink_tpu.operator.batch.source import MemSourceBatchOp


def _cls_src(rng, n=120):
    X = rng.randn(n, 4)
    y = (X[:, 0] + X[:, 1] > 0).astype(int)
    rows = [[*map(float, r), int(l)] for r, l in zip(X, y)]
    return MemSourceBatchOp(rows, "a DOUBLE, b DOUBLE, c DOUBLE, d DOUBLE, "
                                  "label INT")


FEATS = ["a", "b", "c", "d"]


def _stages():
    from alink_tpu import (GaussianMixture, GbdtClassifier, Imputer,
                           KMeans, LinearRegression, LogisticRegression,
                           MinMaxScaler, QuantileDiscretizer,
                           RandomForestClassifier, Softmax, StandardScaler)
    common = dict(prediction_col="pred")
    return [
        ("logreg", LogisticRegression(feature_cols=FEATS, label_col="label",
                                      max_iter=30, **common)),
        ("softmax", Softmax(feature_cols=FEATS, label_col="label",
                            max_iter=30, **common)),
        ("linreg", LinearRegression(feature_cols=FEATS, label_col="label",
                                    max_iter=30, **common)),
        ("rf", RandomForestClassifier(feature_cols=FEATS, label_col="label",
                                      num_trees=5, max_depth=3, **common)),
        ("gbdt", GbdtClassifier(feature_cols=FEATS, label_col="label",
                                num_trees=5, max_depth=3, **common)),
        ("kmeans", KMeans(feature_cols=FEATS, k=3, max_iter=10, **common)),
        ("gmm", GaussianMixture(feature_cols=FEATS, k=2, max_iter=10,
                                **common)),
        ("standard_scaler", StandardScaler(selected_cols=FEATS)),
        ("minmax_scaler", MinMaxScaler(selected_cols=FEATS)),
        ("imputer", Imputer(selected_cols=FEATS)),
        ("quantile", QuantileDiscretizer(selected_cols=FEATS, num_buckets=3)),
    ]


@pytest.mark.parametrize("name,stage", _stages(),
                         ids=[n for n, _ in _stages()])
def test_save_load_transform_parity(tmp_path, rng, name, stage):
    src = _cls_src(rng)
    model = Pipeline(stage).fit(src)
    before = model.transform(src).collect()
    path = str(tmp_path / f"{name}.model")
    model.save(path)
    loaded = PipelineModel.load(path)
    after = loaded.transform(src).collect()
    assert len(before) == len(after)
    for r1, r2 in zip(before, after):
        assert [str(v) for v in r1] == [str(v) for v in r2], name


def test_local_predictor_matches_transform(rng):
    """Embedded serving must agree with batch transform row-for-row."""
    from alink_tpu import LogisticRegression
    src = _cls_src(rng)
    model = Pipeline(LogisticRegression(
        feature_cols=FEATS, label_col="label", max_iter=30,
        prediction_col="pred")).fit(src)
    batch_rows = model.transform(src).collect()
    pred = model.get_local_predictor()
    schema = src.get_output_table().schema
    for row, want in zip(src.collect(), batch_rows):
        got = pred.map(tuple(row), schema)
        assert str(got[-1]) == str(want[-1])
