"""Early pytest plugin (loaded via ``addopts = -p bootenv`` in pytest.ini).

Re-execs the test process with a CPU 8-device JAX environment BEFORE pytest
installs fd capture (so child output reaches the terminal) and before any
jax backend is touched. Needed because the container's sitecustomize
registers the TPU backend in every python process and XLA flags latch at
backend init. See tests/conftest.py for the rationale of the 8-device mesh.
"""

import os
import sys

_MARK = "ALINK_TPU_TEST_ENV"


def cpu_mesh_env(n_devices: int, base_env=None) -> dict:
    """Env vars for a fresh interpreter with an n-device virtual CPU mesh.

    Centralizes the container-specific bootstrap: the sitecustomize registers
    the axon TPU backend in every python process (disabled via
    PALLAS_AXON_POOL_IPS) and XLA flags latch at backend init, so the mesh
    size must be in the env before jax is first touched.
    """
    env = dict(os.environ if base_env is None else base_env)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("ALINK_TPU_EXTRA_XLA_FLAGS", "") +
                        f" --xla_force_host_platform_device_count={n_devices}"
                        ).strip()
    env["PALLAS_AXON_POOL_IPS"] = ""  # disable axon sitecustomize TPU hook
    return env


if os.environ.get(_MARK) != "1" and "pytest" in sys.modules:
    env = cpu_mesh_env(8)
    env[_MARK] = "1"
    env["JAX_ENABLE_X64"] = "1"  # float64 parity on the CPU test mesh
    os.execvpe(sys.executable, [sys.executable, "-m", "pytest"] + sys.argv[1:], env)
