"""Hive connector (gated).

Re-design of connectors/connector-hive (HiveDB.java, HiveBatchSource,
Hive{Source,Sink}BatchOp). No Hive client ships in this image; ``HiveDB``
binds lazily to ``pyhive`` and raises a clear ImportError otherwise —
gated, not stubbed: with pyhive installed the DB-API path below is live,
since HiveDB reuses the JdbcDB query/write machinery unchanged.
"""

from __future__ import annotations

from ..common.params import ParamInfo
from ..operator.base import BatchOperator
from ..operator.batch.sink.sinks import DBSinkBatchOp
from ..operator.batch.source.sources import DBSourceBatchOp
from .db import JdbcDB


class HiveDB(JdbcDB):
    """reference: connectors/connector-hive HiveDB.java"""

    PARAM_STYLE = "%s"

    def __init__(self, name: str, host: str, port: int = 10000,
                 database: str = "default", username: str = None):
        def factory():
            try:
                from pyhive import hive
            except ImportError as e:
                raise ImportError(
                    "HiveDB needs pyhive (pip install 'pyhive[hive]'); "
                    "not installed in this image") from e
            return hive.Connection(host=host, port=port, database=database,
                                   username=username)

        super().__init__(name, factory)
        self.database = database

    def list_table_names(self):
        return [str(r[0]) for r in self.query("SHOW TABLES").to_rows()]


class _HasHiveDB:
    """Hive connection params + shared db resolution."""
    HOST = ParamInfo("host", str, optional=False)
    PORT = ParamInfo("port", int, default=10000)
    DB_NAME = ParamInfo("db_name", str, default="default")
    USERNAME = ParamInfo("username", str)

    def _make_db(self):
        p = self.params._m
        return HiveDB(f"hive:{p.get('db_name', 'default')}", p["host"],
                      int(p.get("port", 10000)),
                      p.get("db_name", "default"), p.get("username"))


class HiveSourceBatchOp(_HasHiveDB, DBSourceBatchOp):
    """reference: connector-hive HiveSourceBatchOp"""


class HiveSinkBatchOp(_HasHiveDB, DBSinkBatchOp):
    """reference: connector-hive HiveSinkBatchOp"""
