"""Round-5 inventory closers: LiftChart, TableBucketingSink,
FmModelInfoBatchOp (VERDICT r4 "What's missing" #2-4)."""

import numpy as np
import pytest

from alink_tpu.common.mtable import MTable
from alink_tpu.common.types import TableSchema
from alink_tpu.io.bucketing import TableBucketingSink
from alink_tpu.io.db import SqliteDB
from alink_tpu.operator.batch.source import MemSourceBatchOp
from alink_tpu.operator.batch.classification.fm_ops import (
    FmClassifierTrainBatchOp, FmModelInfoBatchOp)
from alink_tpu.operator.common.evaluation.metrics import binary_metrics


class TestLiftChart:
    def test_lift_chart_shape_and_monotonicity(self):
        # 6 samples: scores descending, labels 1,1,0,1,0,0
        labels = ["a", "a", "b", "a", "b", "b"]
        p = [0.9, 0.8, 0.7, 0.6, 0.4, 0.2]
        m = binary_metrics(np.asarray(labels), np.asarray(p), "a")
        xs, ys = m.get("LiftChart")
        # reference contract (BinaryMetricsSummary.java:179,224,231):
        # points ((TP+FP)/total, TP), starting at (0,0)
        assert xs[0] == 0.0 and ys[0] == 0.0
        assert xs[-1] == pytest.approx(1.0)
        assert ys[-1] == pytest.approx(3.0)  # all positives found at depth 1
        # depth strictly increases by 1/total per threshold step
        np.testing.assert_allclose(np.diff(xs), 1.0 / 6, atol=1e-12)
        # TP counts: at depth 2/6 two positives, at 4/6 three
        assert ys[2] == pytest.approx(2.0)
        assert ys[4] == pytest.approx(3.0)
        # TP cumulative => non-decreasing
        assert (np.diff(ys) >= 0).all()
        # getter resolves like the reference's getLiftChart()
        assert m.get_lift_chart() == m.get("LiftChart")


def _rows(n0, n1):
    return [(float(i), f"s{i}") for i in range(n0, n1)]


SCHEMA = TableSchema(["x", "s"], ["DOUBLE", "STRING"])


class TestTableBucketingSink:
    def test_ruler_mode_dir(self, tmp_path):
        # rows carry (bucket_id, n_tab, *payload) — TableBucketingSink.java:63-81
        sink = TableBucketingSink("t", SCHEMA, base_dir=str(tmp_path))
        for bucket, rows in [(0, _rows(0, 2)), (1, _rows(2, 5))]:
            for r in rows:
                sink.invoke((bucket, len(rows)) + r)
        # ruler buckets close themselves once their count is reached
        assert sink._open == {}
        assert sink.bucket_names() == ["t_0", "t_1"]
        txt = (tmp_path / "t_1.csv").read_text()
        assert txt.splitlines() == ["2.0,s2", "3.0,s3", "4.0,s4"]

    def test_size_rollover_db(self):
        db = SqliteDB("buck_test")
        sink = TableBucketingSink("b", SCHEMA, db=db, batch_size=3)
        for r in _rows(0, 7):
            sink.invoke(r)
        sink.close()
        names = sink.bucket_names()
        assert names == ["b_0", "b_1", "b_2"]
        assert db.read_table("b_0").num_rows == 3
        assert db.read_table("b_2").num_rows == 1  # tail flushed by close()
        db.close()

    def test_time_rollover(self, tmp_path):
        t = [0.0]
        sink = TableBucketingSink("c", SCHEMA, base_dir=str(tmp_path),
                                  batch_rollover_interval=10.0,
                                  clock=lambda: t[0])
        sink.invoke(_rows(0, 1)[0])
        t[0] = 11.0  # past the interval -> bucket closes on next write
        sink.invoke(_rows(1, 2)[0])
        sink.invoke(_rows(2, 3)[0])
        sink.close()
        assert sink.bucket_names() == ["c_0", "c_1"]

    def test_duplicate_bucket_rejected_in_ruler_mode(self, tmp_path):
        # the already-exists contract is RULER-mode only
        # (TableBucketingSink.java:94-95; size/time mode reuses the table)
        (tmp_path / "d_0.csv").write_text("stale\n")
        sink = TableBucketingSink("d", SCHEMA, base_dir=str(tmp_path))
        with pytest.raises(RuntimeError, match="already exists"):
            sink.invoke((0, 1) + _rows(0, 1)[0])

    def test_size_mode_reuses_existing_bucket(self, tmp_path):
        # size/time mode appends into a pre-existing bucket target, like
        # the reference's writeBySizeOrTime reusing the table across runs
        s1 = TableBucketingSink("d", SCHEMA, base_dir=str(tmp_path),
                                batch_size=2)
        for r in _rows(0, 2):
            s1.invoke(r)
        s1.close()
        s2 = TableBucketingSink("d", SCHEMA, base_dir=str(tmp_path),
                                batch_size=2)
        for r in _rows(2, 4):
            s2.invoke(r)
        s2.close()
        txt = (tmp_path / "d_0.csv").read_text()
        assert txt.splitlines() == ["0.0,s0", "1.0,s1", "2.0,s2", "3.0,s3"]

    def test_exactly_one_target(self, tmp_path):
        with pytest.raises(ValueError):
            TableBucketingSink("e", SCHEMA)

    def test_write_table_drain(self, tmp_path):
        sink = TableBucketingSink("f", SCHEMA, base_dir=str(tmp_path),
                                  batch_size=2)
        sink.write_table(MTable(_rows(0, 5), SCHEMA))
        sink.close()
        assert sink.bucket_names() == ["f_0", "f_1", "f_2"]


class TestFmModelInfo:
    def test_fm_model_info_op(self):
        rng = np.random.RandomState(0)
        X = rng.randn(80, 2)
        y = np.where(X[:, 0] * X[:, 1] > 0, "pos", "neg")
        src = MemSourceBatchOp(list(zip(X[:, 0], X[:, 1], y)),
                               "x1 DOUBLE, x2 DOUBLE, label STRING")
        train = FmClassifierTrainBatchOp(
            feature_cols=["x1", "x2"], label_col="label", num_factor=3,
            num_epochs=3, seed=7).link_from(src)
        op = FmModelInfoBatchOp().link_from(train)
        info = op.collect_model_info()
        assert info.get_task() == "BINARY_CLASSIFICATION"
        assert info.get_num_factor() == 3
        assert info.get_vector_size() == 2
        assert info.get_factors().shape == (2, 3)
        assert info.get_col_names() == ["x1", "x2"]
        t = op.get_output_table()
        assert t.col("num_factor")[0] == 3
        # trainer-side rich model info uses the same extraction
        ti = train.get_model_info()
        assert ti.col("task")[0] == "BINARY_CLASSIFICATION"

    def test_flat_namespace_resolution(self):
        import alink_tpu as A
        assert getattr(A, "FmModelInfoBatchOp") is FmModelInfoBatchOp
        assert getattr(A, "TableBucketingSink") is TableBucketingSink
