"""The supervised online-learning DAG (ISSUE 15): ingest -> FTRL ->
hot-swap serving -> windowed eval as ONE fault-tolerant program.

Load-bearing invariants:
  * deterministic pacing makes eval windows a pure function of the
    stream: two clean runs produce BYTE-identical journals;
  * kill-and-resume of the FULL DAG — kill mid-drain, restart from the
    artifacts on disk — continues served scores and eval windows
    bitwise exactly where they left off (the satellite-#5 contract);
  * every stage restart is TYPED (restart-from-last-checkpoint /
    respawn-with-last-good-model / resume-at-offset) and recorded with
    a measured recovery time; a crashed stage never silently drops or
    double-applies a micro-batch;
  * the SloContract's verdicts are typed and live;
  * with the fault env unset and the E2E flag family off, serving and
    trainer lowered HLO — and served response bytes — are
    byte-identical to the pre-DAG build (the acceptance criterion).
"""

import json
import os
import threading
import warnings

import numpy as np
import pytest

from alink_tpu.common.faults import (FAULT_ENV, FaultInjected,
                                     _AUTO_INDEX, maybe_crash,
                                     reset_faults, scoped_fault_env)
from alink_tpu.common.mtable import MTable
from alink_tpu.common.vector import DenseVector
from alink_tpu.online import (DagReport, OnlineDag, RESTART_POLICIES,
                              SloContract, load_model_table,
                              save_model_table)
from alink_tpu.operator.batch.classification.linear import (
    LogisticRegressionTrainBatchOp)
from alink_tpu.operator.batch.source.sources import MemSourceBatchOp
from alink_tpu.operator.stream.source.sources import MemSourceStreamOp

N_ROWS, DIM, BATCH = 768, 16, 128          # 6 micro-batches
INTERVAL = 2.0                             # emissions at t=2,4 + final


@pytest.fixture(scope="module")
def base():
    rng = np.random.RandomState(11)
    X = rng.randn(N_ROWS, DIM)
    y = (X @ rng.randn(DIM) + 0.25 * rng.randn(N_ROWS) > 0).astype(
        np.int64)
    vecs = np.empty(N_ROWS, object)
    vecs[:] = [DenseVector(X[i]) for i in range(N_ROWS)]
    tbl = MTable({"vec": vecs, "label": y}, "vec VECTOR, label LONG")
    warm = LogisticRegressionTrainBatchOp(
        vector_col="vec", label_col="label", max_iter=3).link_from(
        MemSourceBatchOp(tbl.first_n(256)))
    warm.get_output_table()
    return tbl, warm


def mkdag(base, art, **kw):
    tbl, warm = base
    kw.setdefault("time_interval", INTERVAL)
    kw.setdefault("checkpoint_every", 2)
    return OnlineDag(
        source_fn=lambda: MemSourceStreamOp(tbl, batch_size=BATCH),
        warm_model=warm, artifacts_dir=art, label_col="label",
        vector_col="vec", name="t_online", **kw)


def _read(path):
    with open(path) as f:
        return f.read()


def _eval_files(art):
    return (_read(os.path.join(art, "eval", "windows.jsonl")),
            _read(os.path.join(art, "eval", "scores.jsonl")))


@pytest.fixture(scope="module")
def golden(base, tmp_path_factory):
    """One uninterrupted run: the reference every fault scenario's
    journals are compared against."""
    art = str(tmp_path_factory.mktemp("dag_golden"))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        rep = mkdag(base, art).run()
    assert rep.failed is None
    return art, rep


class TestCleanRun:
    def test_report_windows_swaps_slo(self, base, golden):
        art, rep = golden
        assert rep.failed is None and not rep.restarts
        assert len(rep.windows) >= 3
        assert rep.scored_rows == N_ROWS
        assert rep.batches_scored == N_ROWS // BATCH
        # emissions at t=2, t=4 + the final snapshot
        assert rep.swaps >= 3
        assert rep.swap_staleness_max_s is not None
        assert rep.silent_drops == 0 and rep.typed_rejections == 0
        # the quality anchor: a real signal converges well above chance
        assert rep.final_window_auc > 0.9
        assert rep.auc_note is None
        # journals on disk match the in-memory report
        windows, scores = _eval_files(art)
        assert len(windows.strip().splitlines()) == len(rep.windows)
        assert len(scores.strip().splitlines()) == rep.batches_scored
        # last-good model artifact round-trips
        got = load_model_table(os.path.join(art, "serving",
                                            "last_good.json"))
        assert got is not None and got[1].num_rows > 0

    def test_deterministic_pacing_is_repeatable(self, base, golden,
                                                tmp_path):
        """Two clean runs -> byte-identical journals (the determinism
        the bitwise-resume contract is built on)."""
        g_art, _ = golden
        art = str(tmp_path / "repeat")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            rep = mkdag(base, art).run()
        assert rep.failed is None
        assert _eval_files(art) == _eval_files(g_art)


class TestKillAndResume:
    def test_full_dag_kill_and_resume_bitwise(self, base, golden,
                                              tmp_path):
        """Satellite #5: kill mid-drain, restart the DAG from the
        artifacts on disk — served scores AND eval windows continue
        bitwise exactly where they left off, and the final model is
        bitwise the golden run's."""
        g_art, _ = golden
        art = str(tmp_path / "killed")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            with scoped_fault_env("ftrl.batch:4-4"):
                r1 = mkdag(base, art, max_restarts=0).run()
            assert r1.failed is not None
            assert r1.restarts[0]["site"] == "ftrl.batch"
            assert r1.restarts[0]["policy"] == \
                RESTART_POLICIES["train"]
            # restart from artifacts on disk, storm cleared
            r2 = mkdag(base, art).run()
        assert r2.failed is None
        assert _eval_files(art) == _eval_files(g_art)
        m_g = json.load(open(os.path.join(g_art, "serving",
                                          "last_good.json")))
        m_k = json.load(open(os.path.join(art, "serving",
                                          "last_good.json")))
        assert m_k["rows"] == m_g["rows"]

    def test_supervised_in_process_restart_from_checkpoint(
            self, base, golden, tmp_path):
        """The train-stage supervisor catches a mid-drain kill, applies
        restart-from-last-checkpoint, measures the recovery, and the
        run still completes BITWISE-identical to golden (replay-prefix
        skip: no drop, no double-apply)."""
        g_art, _ = golden
        art = str(tmp_path / "supervised")
        seen = []

        def on_event(stage, exc):
            seen.append((stage, type(exc).__name__))
            # the injected kill fires on the batch NUMBER, so the
            # supervisor's replay would re-kill forever: the harness
            # clears the entry once observed (the e2e smoke's storm-
            # clearing pattern)
            os.environ.pop(FAULT_ENV, None)

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            with scoped_fault_env("ftrl.batch:4-4"):
                rep = mkdag(base, art, on_stage_event=on_event).run()
        assert rep.failed is None
        assert seen == [("train", "FaultInjected")]
        assert rep.restart_count("train") == 1
        rec = rep.restarts[0]
        assert rec["policy"] == "restart-from-last-checkpoint"
        assert rec["recovery_s"] is not None and rec["recovery_s"] > 0
        assert _eval_files(art) == _eval_files(g_art)

    def test_ingest_resume_at_offset(self, base, golden, tmp_path):
        """An ingest crash redelivers from the last offset (auto-
        indexed site: the kill window clears on redelivery) with the
        typed resume-at-offset policy; the run stays bitwise-golden."""
        g_art, _ = golden
        art = str(tmp_path / "ingest")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            with scoped_fault_env("ingest.batch:3-3"):
                rep = mkdag(base, art).run()
        assert rep.failed is None
        assert rep.restart_count("ingest") == 1
        rec = [r for r in rep.restarts if r["stage"] == "ingest"][0]
        assert rec["policy"] == "resume-at-offset"
        assert rec["offset"] == 2         # delivered before the crash
        assert rec["recovery_s"] is not None
        assert _eval_files(art) == _eval_files(g_art)

    def test_corrupt_snapshot_skipped_last_good_serves(
            self, base, golden, tmp_path):
        """A poisoned model snapshot is skipped exactly once (recorded)
        and the serving tier keeps the last good model — the eval leg
        never drops a window and quality holds."""
        _, g_rep = golden
        art = str(tmp_path / "corrupt")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            with scoped_fault_env("feeder.snapshot:1-1:corrupt"):
                rep = mkdag(base, art).run()
        assert rep.failed is None
        assert rep.feeder_skipped == 1
        assert rep.swaps == g_rep.swaps - 1
        assert len(rep.windows) == len(g_rep.windows)
        assert rep.silent_drops == 0
        assert rep.final_window_auc > 0.8   # warm model still serves


class TestSlo:
    def test_contract_typed_verdicts(self):
        slo = SloContract(serve_p99_s=0.010, swap_staleness_s=0.5,
                          final_window_auc=0.75, name="slo_t")
        v = slo.observe_p99(0.200, window=2)
        assert v is not None and not v.ok and v.slo == "serve_p99"
        assert v.observed == 0.200 and v.bound == 0.010
        assert "window 2" in v.detail
        assert slo.observe_p99(0.001, window=3) is None
        v2 = slo.observe_swap(0.9, version=4)
        assert v2 is not None and not v2.ok \
            and v2.slo == "swap_staleness"
        assert slo.breaches == [v, v2]
        final = slo.final(p99_s=0.2, max_staleness_s=0.9,
                          final_auc=0.93)
        by = {x.slo: x for x in final}
        assert not by["serve_p99"].ok
        assert not by["swap_staleness"].ok
        assert by["final_window_auc"].ok
        # unarmed clauses emit no verdicts
        assert SloContract().final(1.0, 1.0, 0.5) == []

    def test_live_breach_recorded_on_run(self, base, tmp_path):
        """A deliberately-tight p99 bound breaches live (typed, in
        report.breaches) and the final verdict marks the clause not
        ok; the generous clauses stay ok."""
        art = str(tmp_path / "slo_run")
        slo = SloContract(serve_p99_s=1e-6, swap_staleness_s=30.0,
                          final_window_auc=0.6)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            rep = mkdag(base, art, slo=slo).run()
        assert rep.failed is None
        assert any(b.slo == "serve_p99" for b in rep.breaches)
        by = {v.slo: v for v in rep.slo}
        assert not by["serve_p99"].ok
        assert by["swap_staleness"].ok
        assert by["final_window_auc"].ok

    def test_auc_note_is_self_explaining(self, base, tmp_path):
        """VERDICT #7: a final-window AUC under the floor must carry a
        convergence note with the window trajectory — never a bare
        chance-level number."""
        dag = mkdag(base, str(tmp_path / "note"))
        dag._pos_label = "1"
        rep = DagReport()
        rep.windows = [{"auc": 0.52, "logloss": 0.7},
                       {"auc": 0.61, "logloss": 0.68}]
        rep.final_window_auc = 0.61
        note = dag._auc_note(rep)
        assert note is not None
        assert "0.61" in note and "0.52" in note       # trajectory
        assert "rising" in note                        # the why
        rep2 = DagReport()
        rep2.windows = [{"auc": 0.50, "logloss": 0.7},
                        {"auc": 0.505, "logloss": 0.7}]
        rep2.final_window_auc = 0.505
        assert "chance" in dag._auc_note(rep2)
        rep3 = DagReport()
        rep3.windows = [{"auc": 0.9, "logloss": 0.3}]
        rep3.final_window_auc = 0.9
        assert dag._auc_note(rep3) is None

    def test_flags_registered_and_parsed(self, monkeypatch):
        from alink_tpu.common.flags import FLAGS
        from alink_tpu.online import slo as slomod
        from alink_tpu.online import dag as dagmod
        for name in ("ALINK_TPU_E2E_DAG", "ALINK_TPU_E2E_SLO_P99_MS",
                     "ALINK_TPU_E2E_SLO_STALENESS_MS",
                     "ALINK_TPU_E2E_SLO_AUC",
                     "ALINK_TPU_E2E_DEADLINE_MS",
                     "ALINK_TPU_E2E_MAX_RESTARTS",
                     "ALINK_TPU_E2E_PACING"):
            assert name in FLAGS, name
            assert FLAGS.get(name).key_neutral
        assert slomod.slo_p99_s() is None
        monkeypatch.setenv("ALINK_TPU_E2E_SLO_P99_MS", "250")
        assert slomod.slo_p99_s() == 0.25
        monkeypatch.setenv("ALINK_TPU_E2E_PACING", "throughput")
        assert dagmod.e2e_pacing() == "throughput"
        monkeypatch.setenv("ALINK_TPU_E2E_PACING", "weird")
        assert dagmod.e2e_pacing() == "deterministic"
        monkeypatch.setenv("ALINK_TPU_E2E_MAX_RESTARTS", "-3")
        assert dagmod.e2e_max_restarts() == 0
        # ALINK_TPU_E2E_DAG arms the flag-derived contract
        monkeypatch.setenv("ALINK_TPU_E2E_DAG", "1")
        monkeypatch.setenv("ALINK_TPU_E2E_SLO_AUC", "0.8")
        c = SloContract.from_flags()
        assert c.final_window_auc == 0.8 and c.serve_p99_s == 0.25


class TestArtifacts:
    def test_model_table_round_trip(self, base, tmp_path):
        _, warm = base
        tbl = warm.get_output_table()
        path = str(tmp_path / "m.json")
        save_model_table(path, 7, tbl)
        ver, got = load_model_table(path)
        assert ver == 7
        assert got.num_rows == tbl.num_rows
        for c in tbl.schema.names:
            assert list(got.col(c)) == list(tbl.col(c))

    def test_corrupt_last_good_warns_not_crashes(self, tmp_path):
        path = str(tmp_path / "bad.json")
        with open(path, "w") as f:
            f.write("{not json")
        with pytest.warns(RuntimeWarning, match="unreadable"):
            assert load_model_table(path) is None


class TestJournalDurability:
    """A kill mid-append leaves a TORN final journal line (the only
    tear the fsync-per-line contract allows); restart must truncate it
    off and resume — the crashed batch is redelivered — never crash on
    it or count it as a complete record."""

    def _log(self, tmp_path, sub="a"):
        from alink_tpu.online.dag import _EvalWindowLog
        d = tmp_path / sub
        d.mkdir(exist_ok=True)
        return _EvalWindowLog(str(d / "scores.jsonl"),
                              str(d / "windows.jsonl"), window_s=2.0)

    def _batches(self):
        rng = np.random.RandomState(5)
        for seq in range(1, 4):
            y = (rng.rand(8) > 0.5).astype(np.float64)
            yield seq, seq * 1.0, y, rng.rand(8)

    def test_torn_scores_tail_truncated_and_resumed(self, tmp_path):
        log = self._log(tmp_path)
        for seq, t, y, p in self._batches():
            log.add_batch(seq, t, y, p)
        log.close()
        sp = str(tmp_path / "a" / "scores.jsonl")
        whole = open(sp).read()
        with open(sp, "a") as f:          # the torn mid-write tail
            f.write('{"seq": 4, "t": 4.0, "y": [1.0, 0')
        re_log = self._log(tmp_path)
        assert re_log.resume_seq == 3      # batch 4 gets REDELIVERED
        assert open(sp).read() == whole    # tail physically truncated
        re_log.close()

    def test_torn_windows_tail_not_counted_and_regenerated(self, tmp_path):
        log = self._log(tmp_path, "b")
        for seq, t, y, p in self._batches():
            log.add_batch(seq, t, y, p)
        log.close()
        wp = str(tmp_path / "b" / "windows.jsonl")
        gold = open(wp).read()
        lines = gold.splitlines(keepends=True)
        with open(wp, "w") as f:           # last window line torn
            f.writelines(lines[:-1])
            f.write(lines[-1][: len(lines[-1]) // 2])
        re_log = self._log(tmp_path, "b")
        re_log.close()
        assert open(wp).read() == gold     # re-derived from scores log

    def test_mid_file_corruption_refuses_loudly(self, tmp_path):
        log = self._log(tmp_path, "c")
        for seq, t, y, p in self._batches():
            log.add_batch(seq, t, y, p)
        log.close()
        sp = str(tmp_path / "c" / "scores.jsonl")
        lines = open(sp).read().splitlines(keepends=True)
        with open(sp, "w") as f:           # NOT a torn tail: line 2 of 3
            f.write(lines[0])
            f.write(lines[1][:10] + "\n")
            f.write(lines[2])
        with pytest.raises(ValueError, match="mid-file"):
            self._log(tmp_path, "c")

    def test_scoring_leg_crash_stops_trainer(self, base, tmp_path):
        """A NON-DagFailed scoring-leg failure (the health watchdog's
        documented abort path out of _on_window_closed) must abort the
        pacer so the train thread dies at its next hook call — never
        keep training and hot-swapping into the closed server after
        run() raised."""
        import time

        class Watchdog:
            def record(self, *a):
                pass

            def evaluate(self):
                raise RuntimeError("watchdog abort")

        dag = mkdag(base, str(tmp_path / "wd"), health=Watchdog())
        with pytest.raises(RuntimeError, match="watchdog abort"):
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                dag.run()
        assert dag._pacer.aborted is not None

        def train_alive():
            return any(th.name == "alink-e2e-t_online-train"
                       and th.is_alive()
                       for th in threading.enumerate())
        deadline = time.monotonic() + 15.0
        while train_alive() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not train_alive()

    def test_throughput_hook_observes_abort(self):
        """pacing="throughput" never blocks, but a dead scoring leg
        must still stop the trainer: the batch hook raises the pending
        DagFailed instead of letting the drain keep training (and
        mutating the returned report) past the abort."""
        from alink_tpu.online.dag import DagFailed, _Pacer
        pacer = _Pacer(deterministic=False)
        pacer.hook("pre", 1, 0.0)          # no abort: free-running
        pacer.hook("post", 1, 0.0)
        pacer.abort("serve", RuntimeError("scoring leg died"))
        with pytest.raises(DagFailed):
            pacer.hook("pre", 2, 1.0)


class TestFaultHygiene:
    def test_scoped_fault_env_resets_on_entry_exit_and_failure(
            self, monkeypatch):
        """Satellite: one scenario's visit counters and armed spec must
        never bleed into the next — including when the scenario FAILS."""
        monkeypatch.delenv(FAULT_ENV, raising=False)
        reset_faults()
        # dirty the auto-index counters as a prior scenario would
        monkeypatch.setenv(FAULT_ENV, "somewhere.else:999")
        for _ in range(5):
            maybe_crash("serve.dispatch")
        assert _AUTO_INDEX.get("serve.dispatch") == 5
        monkeypatch.delenv(FAULT_ENV)
        with scoped_fault_env("serve.dispatch:1-1:error"):
            # counters were RESET on entry: the window fires on the
            # first visit of THIS scenario, not visit 6
            assert os.environ[FAULT_ENV] == "serve.dispatch:1-1:error"
            with pytest.raises(Exception):
                maybe_crash("serve.dispatch")
        assert FAULT_ENV not in os.environ
        assert not _AUTO_INDEX
        # failure path: the body raising still restores + resets
        monkeypatch.setenv(FAULT_ENV, "prior.spec:3")
        with pytest.raises(ValueError):
            with scoped_fault_env("ftrl.batch:1-1"):
                maybe_crash("serve.dispatch")     # advances a counter
                raise ValueError("scenario failed")
        assert os.environ[FAULT_ENV] == "prior.spec:3"
        assert not _AUTO_INDEX
        # spec=None guarantees a CLEAN scenario even with env armed
        with scoped_fault_env(None):
            assert FAULT_ENV not in os.environ
        assert os.environ[FAULT_ENV] == "prior.spec:3"

    def test_pace_hook_default_is_inert(self, base):
        """FtrlTrainStreamOp without a batch hook takes the hook-less
        path (pace is None -> zero calls); with one, pre/post bracket
        every batch in order."""
        from alink_tpu.operator.stream.onlinelearning.ftrl import (
            FtrlTrainStreamOp)
        tbl, warm = base
        calls = []
        op = FtrlTrainStreamOp(warm, vector_col="vec",
                               label_col="label",
                               time_interval=INTERVAL).link_from(
            MemSourceStreamOp(tbl, batch_size=BATCH))
        assert op._batch_hook is None
        op.set_batch_hook(lambda ph, b, t: calls.append((ph, b)))
        for _ in op.timed_batches():
            pass
        n = N_ROWS // BATCH
        assert calls == [(ph, b) for b in range(1, n + 1)
                         for ph in ("pre", "post")]


class TestFlagOffByteIdentity:
    def test_serving_and_trainer_hlo_and_response_bytes(
            self, base, monkeypatch):
        """The acceptance criterion: with the fault env unset and the
        DAG flag family off (or on! — it is all host-side policy), the
        serving bucket program's lowered HLO, the FTRL step program's
        lowered HLO, and served response bytes are byte-identical."""
        import jax
        from alink_tpu.common.params import Params
        from alink_tpu.operator.common.linear.mapper import (
            LinearModelMapper)
        from alink_tpu.serving import CompiledPredictor, PredictServer
        tbl, warm = base
        data_schema = tbl.select(["vec"]).schema
        mapper = LinearModelMapper(
            warm.get_output_table().schema, data_schema,
            Params({"prediction_col": "pred", "vector_col": "vec"}))
        mapper.load_model(warm.get_output_table())
        pred = CompiledPredictor(mapper, buckets=(4,), name="e2e_hlo")
        ver = pred._active
        kind, arrays = ver.kernel.encode(
            tbl.select(["vec"]).first_n(3), 4)

        def serving_hlo():
            return jax.jit(ver.kernel.device_fns[kind]).lower(
                ver.device_arrays, *arrays).as_text()

        from alink_tpu.operator.stream.onlinelearning.ftrl import (
            _ftrl_step_factory)
        from alink_tpu.common.mlenv import MLEnvironmentFactory
        mesh = MLEnvironmentFactory.get_default().mesh

        def trainer_hlo():
            step, _w = _ftrl_step_factory(mesh, 0.1, 1.0, 0.0, 0.0)
            import jax.numpy as jnp
            X = jnp.zeros((4, 16))
            y = jnp.zeros(4)
            z = jnp.zeros(16)
            n = jnp.zeros(16)
            return jax.jit(step).lower(X, y, z, n).as_text()

        def responses():
            srv = PredictServer(pred, name="e2e_bytes")
            try:
                return [srv.submit(tbl.select(["vec"]).row(i)).result(30)
                        for i in range(8)]
            finally:
                srv.close()

        ref_s, ref_t = serving_hlo(), trainer_hlo()
        ref_r = responses()
        for flags in ({"ALINK_TPU_E2E_DAG": "1",
                       "ALINK_TPU_E2E_SLO_P99_MS": "5",
                       "ALINK_TPU_E2E_SLO_AUC": "0.9",
                       "ALINK_TPU_E2E_PACING": "throughput",
                       "ALINK_TPU_E2E_DEADLINE_MS": "100"},):
            for k, v in flags.items():
                monkeypatch.setenv(k, v)
            assert serving_hlo() == ref_s
            assert trainer_hlo() == ref_t
            assert responses() == ref_r
            for k in flags:
                monkeypatch.delenv(k)
