"""Structured tracing subsystem (common/tracing.py) + instrumented runtime.

Covers the Tracer contract (contextvars nesting, thread lanes, the bounded
flight recorder, instant events, Chrome/JSONL exporters), the
ALINK_TPU_TRACE gate (including StepTimer's single-source-of-truth
emission), the compat.compiled_cost_analysis shim across return shapes,
and the end-to-end acceptance path: an L-BFGS train with tracing +
checkpointing produces a Chrome trace whose span tree nests
exec -> chunk -> superstep-phase spans with checkpoint instant events,
tools/trace.py summarizes it, the compiled program is byte-identical with
tracing on/off, and the traced run stays within the overhead budget.
"""

import importlib.util
import json
import os
import threading
import time

import numpy as np
import pytest

from alink_tpu.common.metrics import MetricsRegistry, set_registry
from alink_tpu.common.tracing import (Tracer, get_tracer, set_tracer,
                                      trace_instant, trace_span,
                                      tracing_enabled)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        f"tool_{name}", os.path.join(ROOT, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def fresh_tracer(monkeypatch):
    """Arm tracing and isolate the process tracer per test."""
    monkeypatch.setenv("ALINK_TPU_TRACE", "1")
    tr = Tracer()
    prev = set_tracer(tr)
    try:
        yield tr
    finally:
        set_tracer(prev)


@pytest.fixture
def fresh_registry():
    reg = MetricsRegistry()
    prev = set_registry(reg)
    try:
        yield reg
    finally:
        set_registry(prev)


def _by_id(events):
    return {e["id"]: e for e in events if "id" in e}


def _chain(events, ev):
    """Names along the parent chain of ``ev``, leaf first."""
    byid = _by_id(events)
    names = []
    while ev is not None:
        names.append(ev["name"])
        ev = byid.get(ev.get("parent"))
    return names


# ---------------------------------------------------------------------------
# tracer core
# ---------------------------------------------------------------------------

class TestTracerCore:
    def test_span_nesting_parent_child(self):
        tr = Tracer()
        with tr.span("root") as root:
            with tr.span("mid") as mid:
                with tr.span("leaf"):
                    pass
            with tr.span("mid2"):
                pass
        evs = tr.events()
        got = {e["name"]: e for e in evs}
        assert got["root"].get("parent") is None
        assert got["mid"]["parent"] == got["root"]["id"] == root.id
        assert got["leaf"]["parent"] == got["mid"]["id"] == mid.id
        assert got["mid2"]["parent"] == got["root"]["id"]
        # complete events carry duration; children within parents
        assert got["leaf"]["dur"] <= got["mid"]["dur"] <= got["root"]["dur"]
        assert got["root"]["ts"] <= got["mid"]["ts"] <= got["leaf"]["ts"]

    def test_span_args_and_set(self):
        tr = Tracer()
        with tr.span("s", cat="test", args={"a": 1}) as sp:
            sp.set(b=2)
        (ev,) = tr.events()
        assert ev["args"] == {"a": 1, "b": 2} and ev["cat"] == "test"

    def test_span_recorded_on_exception(self):
        tr = Tracer()
        with pytest.raises(RuntimeError):
            with tr.span("boom"):
                raise RuntimeError()
        assert [e["name"] for e in tr.events()] == ["boom"]
        # and the context unwound: a new span is a root again
        with tr.span("after"):
            pass
        assert {e["name"]: e.get("parent") for e in tr.events()}["after"] \
            is None

    def test_instant_parented_to_current_span(self):
        tr = Tracer()
        tr.instant("lonely")
        with tr.span("host") as sp:
            tr.instant("inside", args={"k": "v"})
        evs = {e["name"]: e for e in tr.events()}
        assert evs["lonely"].get("parent") is None
        assert evs["inside"]["parent"] == sp.id
        assert evs["inside"]["ph"] == "i"
        assert "dur" not in evs["inside"]

    def test_complete_retroactive_span(self):
        tr = Tracer()
        with tr.span("parent") as sp:
            tr.complete("late", 0.01, args={"n": 3})
        evs = {e["name"]: e for e in tr.events()}
        assert evs["late"]["parent"] == sp.id
        assert abs(evs["late"]["dur"] - 1e4) < 1e3   # ~10ms in µs
        # it ENDED inside the parent window (its start may precede the
        # parent's — the lookback is the caller's own timing)
        late_end = evs["late"]["ts"] + evs["late"]["dur"]
        parent_end = evs["parent"]["ts"] + evs["parent"]["dur"]
        assert late_end <= parent_end + 1.0

    def test_threads_are_separate_lanes(self):
        tr = Tracer()

        def work(i):
            with tr.span(f"t{i}"):
                with tr.span(f"t{i}.child"):
                    pass

        with tr.span("main"):
            ths = [threading.Thread(target=work, args=(i,)) for i in range(2)]
            for t in ths:
                t.start()
            for t in ths:
                t.join()
        evs = {e["name"]: e for e in tr.events()}
        # new threads start with a fresh context: their roots have NO
        # parent (not children of "main"), and their tids differ
        for i in range(2):
            assert evs[f"t{i}"].get("parent") is None
            assert evs[f"t{i}.child"]["parent"] == evs[f"t{i}"]["id"]
            assert evs[f"t{i}"]["tid"] != evs["main"]["tid"]

    def test_flight_recorder_bound_and_drop_count(self):
        tr = Tracer(capacity=8)
        for i in range(30):
            tr.instant(f"e{i}")
        evs = tr.events()
        assert len(evs) == 8
        assert tr.dropped == 22
        # the ring keeps the NEWEST events
        assert [e["name"] for e in evs] == [f"e{i}" for i in range(22, 30)]
        tr.clear()
        assert tr.events() == [] and tr.dropped == 0

    def test_capacity_env_default(self, monkeypatch):
        monkeypatch.setenv("ALINK_TPU_TRACE_BUFFER", "17")
        assert Tracer().capacity == 17
        monkeypatch.setenv("ALINK_TPU_TRACE_BUFFER", "junk")
        assert Tracer().capacity == 65536
        with pytest.raises(ValueError):
            Tracer(capacity=0)

    def test_thread_safe_concurrent_recording(self):
        tr = Tracer()
        n_threads, n_spans = 8, 200

        def work(i):
            for k in range(n_spans):
                with tr.span(f"w{i}"):
                    pass

        ths = [threading.Thread(target=work, args=(i,))
               for i in range(n_threads)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        evs = tr.events()
        assert len(evs) == n_threads * n_spans
        ids = [e["id"] for e in evs]
        assert len(set(ids)) == len(ids)      # ids never collide


# ---------------------------------------------------------------------------
# exporters + tools/trace.py
# ---------------------------------------------------------------------------

class TestExportersAndCli:
    def _record(self, tr):
        with tr.span("exec", cat="engine", args={"max_iter": 3}):
            with tr.span("prepare", cat="engine"):
                pass
            tr.instant("cache", cat="engine", args={"result": "miss"})
            with tr.span("execute", cat="engine"):
                time.sleep(0.002)

    def test_chrome_export_shape(self, tmp_path):
        tr = Tracer()
        self._record(tr)
        p = tr.export_chrome(str(tmp_path / "t.json"))
        doc = json.load(open(p))
        evs = doc["traceEvents"]
        assert {e["ph"] for e in evs} == {"M", "X", "i"}
        names = {e["name"] for e in evs if e["ph"] != "M"}
        assert names == {"exec", "prepare", "cache", "execute"}
        # metadata names the process and threads
        metas = [e for e in evs if e["ph"] == "M"]
        assert any(e["name"] == "process_name" for e in metas)
        assert any(e["name"] == "thread_name" for e in metas)
        # span ids survive the format (args.span_id / parent_id)
        ex = next(e for e in evs if e.get("name") == "execute")
        root = next(e for e in evs if e.get("name") == "exec")
        assert ex["args"]["parent_id"] == root["args"]["span_id"]
        assert doc["otherData"]["format"] == "alink_tpu_trace_v1"

    def test_jsonl_round_trip_through_cli_loader(self, tmp_path):
        tr = Tracer()
        self._record(tr)
        p = tr.export_jsonl(str(tmp_path / "t.jsonl"))
        first = json.loads(open(p).readline())
        assert first["kind"] == "meta"
        assert first["format"] == "alink_tpu_trace_v1"
        trace_tool = _load_tool("trace")
        meta, events = trace_tool.load_events(p)
        assert len(events) == 4
        assert meta["capacity"] == tr.capacity
        # chrome export loads back to the SAME normalized events
        pc = tr.export_chrome(str(tmp_path / "t.json"))
        _, events_c = trace_tool.load_events(pc)
        strip = lambda evs: [{k: e[k] for k in
                              ("ph", "name", "cat", "ts", "tid")}
                             for e in evs]
        assert strip(events_c) == strip(events)

    def test_cli_summary_and_conversion(self, tmp_path, capsys):
        tr = Tracer()
        self._record(tr)
        p = tr.export_jsonl(str(tmp_path / "t.jsonl"))
        out_json = str(tmp_path / "conv.json")
        trace_tool = _load_tool("trace")
        assert trace_tool.main([p, "--chrome", out_json]) == 0
        out = capsys.readouterr().out
        for section in ("Trace summary", "Top spans by self time",
                        "Per-phase rollup", "Instant events",
                        "Critical path"):
            assert section in out
        assert "execute" in out and "cache" in out
        # the conversion is a loadable chrome document
        doc = json.load(open(out_json))
        assert any(e.get("name") == "exec" for e in doc["traceEvents"])
        # and the CLI reads its own conversion
        assert trace_tool.main([out_json]) == 0

    def test_loads_foreign_chrome_shapes(self, tmp_path):
        """Pretty-printed object form and the bare-array form are both
        valid Chrome traces; the loader must take them (and infer
        parents by interval containment when there are no span ids)."""
        trace_tool = _load_tool("trace")
        evs = [{"ph": "X", "name": "outer", "cat": "c", "pid": 1,
                "tid": 7, "ts": 0.0, "dur": 100.0},
               {"ph": "X", "name": "inner", "cat": "c", "pid": 1,
                "tid": 7, "ts": 10.0, "dur": 50.0}]
        pretty = tmp_path / "pretty.json"
        pretty.write_text(json.dumps({"traceEvents": evs}, indent=2))
        _, got = trace_tool.load_events(str(pretty))
        byname = {e["name"]: e for e in got}
        assert byname["inner"]["parent"] == byname["outer"]["id"]
        arr = tmp_path / "array.json"
        arr.write_text(json.dumps(evs))
        _, got2 = trace_tool.load_events(str(arr))
        assert len(got2) == 2
        with pytest.raises(ValueError, match="neither"):
            bad = tmp_path / "bad.json"
            bad.write_text("not json at all")
            trace_tool.load_events(str(bad))

    def test_self_time_subtracts_children(self, tmp_path):
        tr = Tracer()
        with tr.span("outer"):
            with tr.span("inner"):
                time.sleep(0.02)
        trace_tool = _load_tool("trace")
        meta, events = trace_tool.load_events(
            tr.export_jsonl(str(tmp_path / "t.jsonl")))
        selfs = trace_tool.self_times(events)
        byname = {e["name"]: e for e in events}
        outer_self = selfs[byname["outer"]["id"]]
        assert outer_self < byname["outer"]["dur"] - 1.5e4  # inner removed


# ---------------------------------------------------------------------------
# env gate + StepTimer single source of truth
# ---------------------------------------------------------------------------

class TestGate:
    def test_disabled_records_nothing(self, monkeypatch):
        monkeypatch.delenv("ALINK_TPU_TRACE", raising=False)
        assert not tracing_enabled()
        tr = Tracer()
        prev = set_tracer(tr)
        try:
            with trace_span("nope") as sp:
                sp.set(k=1)          # the null span swallows args
            trace_instant("nope2")
        finally:
            set_tracer(prev)
        assert tr.events() == []

    @pytest.mark.parametrize("val,expect", [
        ("0", False), ("off", False), ("false", False),
        ("1", True), ("on", True)])
    def test_flag_parsing(self, monkeypatch, val, expect):
        monkeypatch.setenv("ALINK_TPU_TRACE", val)
        assert tracing_enabled() is expect

    def test_steptimer_emits_into_tracer_when_armed(self, fresh_tracer,
                                                    fresh_registry):
        from alink_tpu.common.profiling import StepTimer
        t = StepTimer()
        with fresh_tracer.span("outer"):
            with t.span("fit", labels={"algo": "kmeans"}):
                pass
        evs = {e["name"]: e for e in fresh_tracer.events()}
        assert evs["fit"]["parent"] == evs["outer"]["id"]
        assert evs["fit"]["args"] == {"algo": "kmeans"}
        assert evs["fit"]["cat"] == "steptimer"
        # the StepTimer itself and the registry mirror still work
        assert t.report()[0][1] == 1
        fam = fresh_registry.histogram(StepTimer.METRIC)
        assert sum(s.count for _, s in fam.series()) == 1

    def test_steptimer_quiet_when_disarmed(self, monkeypatch,
                                           fresh_registry):
        monkeypatch.delenv("ALINK_TPU_TRACE", raising=False)
        from alink_tpu.common.profiling import StepTimer
        tr = Tracer()
        prev = set_tracer(tr)
        try:
            t = StepTimer()
            with t.span("fit"):
                pass
        finally:
            set_tracer(prev)
        assert tr.events() == []
        assert t.report()[0][1] == 1


# ---------------------------------------------------------------------------
# compat.compiled_cost_analysis
# ---------------------------------------------------------------------------

class TestCostShim:
    def test_real_lowered_returns_flops_and_bytes(self):
        import jax
        import jax.numpy as jnp
        from alink_tpu.common.compat import compiled_cost_analysis

        low = jax.jit(lambda x: x @ x).lower(jnp.ones((16, 16)))
        cost = compiled_cost_analysis(low)
        assert cost is not None
        assert cost["flops"] > 0
        assert cost["bytes accessed"] > 0
        # compiled stage too (the historically list-shaped return)
        cost_c = compiled_cost_analysis(low.compile())
        assert cost_c is not None and cost_c["flops"] > 0

    def test_list_return_normalized(self):
        from alink_tpu.common.compat import compiled_cost_analysis

        class FakeListed:
            def cost_analysis(self):
                return [{"flops": 7.0, "bytes accessed": 3.0,
                         "weird": object()}]
        cost = compiled_cost_analysis(FakeListed())
        assert cost == {"flops": 7.0, "bytes accessed": 3.0}

    def test_degrades_to_none_never_raises(self):
        from alink_tpu.common.compat import compiled_cost_analysis

        class Raises:
            def cost_analysis(self):
                raise NotImplementedError("no cost analysis here")

        class Empty:
            def cost_analysis(self):
                return []

        class Weird:
            def cost_analysis(self):
                return "not a dict"

        assert compiled_cost_analysis(Raises()) is None
        assert compiled_cost_analysis(Empty()) is None
        assert compiled_cost_analysis(Weird()) is None
        assert compiled_cost_analysis(object()) is None   # no attr at all


# ---------------------------------------------------------------------------
# instrumented engine
# ---------------------------------------------------------------------------

def _make_queue(key, max_iter=4, **ck):
    import jax.numpy as jnp
    from alink_tpu.engine.communication import AllReduce
    from alink_tpu.engine.comqueue import IterativeComQueue

    X = np.arange(64.0).reshape(32, 2)

    def stage(ctx):
        if ctx.is_init_step:
            ctx.put_obj("s", jnp.zeros(()))
        ctx.put_obj("s", ctx.get_obj("X").sum())

    q = (IterativeComQueue(max_iter=max_iter, **ck)
         .init_with_partitioned_data("X", X)
         .add(stage)
         .add(AllReduce("s")))
    if key is not None:
        q.set_program_key(key)
    return q


class TestEngineTracing:
    def test_exec_span_tree_and_cost_gauges(self, fresh_tracer,
                                            fresh_registry):
        key = ("test_tracing_e2e", os.urandom(6).hex())
        r = _make_queue(key=key).exec()
        assert r.step_count == 4
        evs = fresh_tracer.events()
        byname = {e["name"]: e for e in evs}
        # exec is the root; prepare/execute (StepTimer spans) nest under it
        assert byname["comqueue.exec"].get("parent") is None
        for child in ("comqueue.prepare", "comqueue.execute"):
            assert byname[child]["parent"] == byname["comqueue.exec"]["id"]
        cache = byname["comqueue.program_cache"]
        assert cache["ph"] == "i" and cache["args"]["result"] == "miss"
        # per-program cost gauges (static + achieved), labelled by the
        # program key's leading string
        lbl = {"program": "test_tracing_e2e"}
        assert fresh_registry.value("alink_program_flops", lbl) > 0
        assert fresh_registry.value("alink_program_bytes_accessed", lbl) > 0
        assert fresh_registry.value("alink_program_achieved_flops_per_s",
                                    lbl) > 0
        assert fresh_registry.value("alink_program_achieved_bytes_per_s",
                                    lbl) > 0

    def test_untraced_run_skips_cost_and_events(self, monkeypatch,
                                                fresh_registry):
        monkeypatch.delenv("ALINK_TPU_TRACE", raising=False)
        tr = Tracer()
        prev = set_tracer(tr)
        try:
            key = ("test_tracing_off", os.urandom(6).hex())
            _make_queue(key=key).exec()
        finally:
            set_tracer(prev)
        assert tr.events() == []
        assert fresh_registry.value("alink_program_flops",
                                    {"program": "test_tracing_off"}) == 0

    def test_lowered_hlo_unchanged_by_tracing(self, monkeypatch):
        """Tracing must add NOTHING to compiled programs: the lowered
        text is byte-identical with the switch on and off."""
        key = ("test_tracing_hlo", os.urandom(6).hex())
        monkeypatch.delenv("ALINK_TPU_TRACE", raising=False)
        off = _make_queue(key=key).lowered().as_text()
        monkeypatch.setenv("ALINK_TPU_TRACE", "1")
        on = _make_queue(key=key).lowered().as_text()
        assert on == off
        assert "callback" not in on.lower()
        assert "outfeed" not in on.lower()

    def test_overhead_guard_and_ring_bound(self, monkeypatch,
                                           fresh_registry):
        """Always-on tracing must be cheap: a traced (cache-hit) run
        stays within 2x the untraced wall time, and the flight recorder
        never outgrows its bound."""
        key = ("test_tracing_overhead", os.urandom(6).hex())
        runs = 5
        # warm under tracing so compile AND the one-off cost lowering are
        # paid outside the measured window
        monkeypatch.setenv("ALINK_TPU_TRACE", "1")
        tr = Tracer(capacity=16)
        prev = set_tracer(tr)
        try:
            _make_queue(key=key).exec()

            monkeypatch.delenv("ALINK_TPU_TRACE")
            t0 = time.perf_counter()
            for _ in range(runs):
                _make_queue(key=key).exec()
            untraced = time.perf_counter() - t0

            monkeypatch.setenv("ALINK_TPU_TRACE", "1")
            t0 = time.perf_counter()
            for _ in range(runs):
                _make_queue(key=key).exec()
            traced = time.perf_counter() - t0
        finally:
            set_tracer(prev)
        # generous absolute slack so scheduler noise on ~ms-scale hits
        # cannot flake the ratio; the 2x bound is the contract
        assert traced <= 2.0 * untraced + 0.25, \
            f"traced {traced:.3f}s vs untraced {untraced:.3f}s"
        # ring bound respected with room to spare: 6 execs x ~5 events
        # wanted to land in a 16-slot buffer
        assert len(tr.events()) <= 16
        assert tr.dropped > 0


# ---------------------------------------------------------------------------
# acceptance: L-BFGS train -> chrome trace with nested chunk tree
# ---------------------------------------------------------------------------

def _lbfgs(data, **ck):
    from alink_tpu.operator.common.optim.objfunc import (LogLossFunc,
                                                         UnaryLossObjFunc)
    from alink_tpu.operator.common.optim.optimizers import (OptimParams,
                                                            optimize)
    obj = UnaryLossObjFunc(LogLossFunc(), dim=data["X"].shape[1])
    params = OptimParams(method="LBFGS", max_iter=12, epsilon=0.0, **ck)
    return optimize(obj, data, params)


class TestLbfgsTraceAcceptance:
    def test_lbfgs_chrome_trace_nests_and_summarizes(self, fresh_tracer,
                                                     fresh_registry,
                                                     tmp_path, capsys):
        r = np.random.RandomState(3)
        X = r.randn(256, 6).astype(np.float32)
        y = (X @ r.randn(6) > 0).astype(np.float32) * 2 - 1
        data = {"X": X, "y": y, "w": np.ones(256, np.float32)}
        _lbfgs(data, checkpoint_dir=str(tmp_path / "ck"),
               checkpoint_every=4)

        chrome = fresh_tracer.export_chrome(str(tmp_path / "trace.json"))
        trace_tool = _load_tool("trace")
        meta, events = trace_tool.load_events(chrome)

        # the span tree: exec -> ... -> chunk -> superstep-phase
        syncs = [e for e in events if e["name"] == "superstep.sync"]
        assert syncs, "no superstep phase spans in the trace"
        chain = _chain(events, syncs[0])
        assert chain[-1] == "comqueue.exec"
        assert "comqueue.chunk" in chain
        assert chain.index("comqueue.chunk") < chain.index("comqueue.exec")
        chunks = [e for e in events if e["name"] == "comqueue.chunk"]
        assert len(chunks) == 3                       # 12 supersteps / 4
        assert {c["args"]["limit"] for c in chunks} == {4, 8, 12}
        # checkpoint instant events made it into the chrome trace
        saves = [e for e in events if e["name"] == "checkpoint.save"]
        assert len(saves) == 3
        assert all(e["ph"] == "i" for e in saves)
        assert {s["args"]["tag"] for s in saves} == {4, 8, 12}

        # tools/trace.py summarizes the chrome file
        assert trace_tool.main([chrome]) == 0
        out = capsys.readouterr().out
        assert "comqueue.chunk" in out and "checkpoint.save" in out
        assert "Critical path" in out

        # cost analysis attached to the cached chunk program ("qn" is the
        # optimizer's program-key prefix)
        assert fresh_registry.value("alink_program_flops",
                                    {"program": "qn"}) > 0
        assert fresh_registry.value("alink_program_bytes_accessed",
                                    {"program": "qn"}) > 0

    def test_fault_injection_marker_lands_in_trace(self, fresh_tracer,
                                                   monkeypatch):
        from alink_tpu.common.faults import FaultInjected, maybe_crash
        monkeypatch.setenv("ALINK_TPU_FAULT_INJECT", "test.site:2")
        with pytest.raises(FaultInjected):
            maybe_crash("test.site", 5)
        evs = [e for e in fresh_tracer.events()
               if e["name"] == "fault.injected"]
        assert len(evs) == 1
        # r14: the instant additionally names the fault MODE (kill /
        # error / delay / corrupt) so a flight recorder distinguishes
        # an injected kill from an injected transient
        assert evs[0]["args"] == {"site": "test.site", "index": 5,
                                  "threshold": 2, "mode": "kill"}
