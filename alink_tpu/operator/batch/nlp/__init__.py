"""NLP batch operators.

Re-design of operator/batch/nlp/ (SegmentBatchOp, TokenizerBatchOp,
RegexTokenizerBatchOp, NGramBatchOp, StopWordsRemoverBatchOp,
WordCountBatchOp, DocCountVectorizerTrain/PredictBatchOp,
DocHashCountVectorizerTrain/PredictBatchOp, Word2VecTrain/PredictBatchOp).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ....common.mtable import MTable
from ....common.params import ParamInfo, Params
from ....common.types import AlinkTypes, TableSchema
from ....params.shared import HasOutputCol, HasSelectedCol, HasSeed
from ...base import BatchOperator
from ...common.nlp.segment import SegmentMapper
from ...common.nlp.text import (NGramMapper, RegexTokenizerMapper,
                                StopWordsRemoverMapper, TokenizerMapper,
                                word_count)
from ...common.nlp.vectorizer import (DocCountVectorizerModelMapper,
                                      DocHashCountVectorizerModelMapper,
                                      train_doc_count_vectorizer,
                                      train_doc_hash_count_vectorizer)
from ...common.nlp.word2vec import (Word2VecModelMapper, Word2VecParams,
                                    word2vec_model_table, word2vec_train)
from ..utils.model_map import MapBatchOp, ModelMapBatchOp


class TokenizerBatchOp(MapBatchOp, HasSelectedCol, HasOutputCol):
    """reference: batch/nlp/TokenizerBatchOp."""
    MAPPER_CLS = TokenizerMapper


class RegexTokenizerBatchOp(MapBatchOp, HasSelectedCol, HasOutputCol):
    """reference: batch/nlp/RegexTokenizerBatchOp."""
    MAPPER_CLS = RegexTokenizerMapper
    PATTERN = ParamInfo("pattern", str, default=r"\s+")
    GAPS = ParamInfo("gaps", bool, default=True)
    MIN_TOKEN_LENGTH = ParamInfo("min_token_length", int, default=1)
    TO_LOWER_CASE = ParamInfo("to_lower_case", bool, default=True)


class NGramBatchOp(MapBatchOp, HasSelectedCol, HasOutputCol):
    """reference: batch/nlp/NGramBatchOp."""
    MAPPER_CLS = NGramMapper
    N = ParamInfo("n", int, default=2)


class StopWordsRemoverBatchOp(MapBatchOp, HasSelectedCol, HasOutputCol):
    """reference: batch/nlp/StopWordsRemoverBatchOp."""
    MAPPER_CLS = StopWordsRemoverMapper
    CASE_SENSITIVE = ParamInfo("case_sensitive", bool, default=False)
    STOP_WORDS = ParamInfo("stop_words", list)


class SegmentBatchOp(MapBatchOp, HasSelectedCol, HasOutputCol):
    """reference: batch/nlp/SegmentBatchOp (jieba-ported segmenter)."""
    MAPPER_CLS = SegmentMapper
    USER_DEFINED_DICT = ParamInfo("user_defined_dict", list)


class WordCountBatchOp(BatchOperator, HasSelectedCol):
    """reference: batch/nlp/WordCountBatchOp — (word, cnt)."""

    def link_from(self, in_op: BatchOperator) -> "WordCountBatchOp":
        self._output = word_count(in_op.get_output_table(), self.get_selected_col())
        return self


class DocCountVectorizerTrainBatchOp(BatchOperator, HasSelectedCol):
    """reference: batch/nlp/DocCountVectorizerTrainBatchOp."""
    FEATURE_TYPE = ParamInfo("feature_type", str, default="WORD_COUNT")
    MAX_DF = ParamInfo("max_df", float, default=float("inf"))
    MIN_DF = ParamInfo("min_df", float, default=1.0)
    VOCAB_SIZE = ParamInfo("vocab_size", int, default=1 << 18)
    MIN_TF = ParamInfo("min_tf", float, default=1.0)

    def link_from(self, in_op: BatchOperator) -> "DocCountVectorizerTrainBatchOp":
        self._output = train_doc_count_vectorizer(
            in_op.get_output_table(), self.get_selected_col(),
            feature_type=self.get_feature_type().upper(),
            max_df=float(self.get_max_df()), min_df=float(self.get_min_df()),
            vocab_size=int(self.get_vocab_size()), min_tf=float(self.get_min_tf()))
        return self


class DocCountVectorizerPredictBatchOp(ModelMapBatchOp, HasSelectedCol, HasOutputCol):
    MAPPER_CLS = DocCountVectorizerModelMapper


class DocHashCountVectorizerTrainBatchOp(BatchOperator, HasSelectedCol):
    """reference: batch/nlp/DocHashCountVectorizerTrainBatchOp."""
    NUM_FEATURES = ParamInfo("num_features", int, default=1 << 18)
    FEATURE_TYPE = ParamInfo("feature_type", str, default="WORD_COUNT")
    MIN_DF = ParamInfo("min_df", float, default=1.0)
    MIN_TF = ParamInfo("min_tf", float, default=1.0)

    def link_from(self, in_op: BatchOperator) -> "DocHashCountVectorizerTrainBatchOp":
        self._output = train_doc_hash_count_vectorizer(
            in_op.get_output_table(), self.get_selected_col(),
            num_features=int(self.get_num_features()),
            feature_type=self.get_feature_type().upper(),
            min_df=float(self.get_min_df()), min_tf=float(self.get_min_tf()))
        return self


class DocHashCountVectorizerPredictBatchOp(ModelMapBatchOp, HasSelectedCol,
                                           HasOutputCol):
    MAPPER_CLS = DocHashCountVectorizerModelMapper


class Word2VecTrainBatchOp(BatchOperator, HasSelectedCol, HasSeed):
    """reference: batch/nlp/Word2VecTrainBatchOp (skip-gram + hierarchical
    softmax on the BSP engine; model = (word, vec) rows)."""
    VECTOR_SIZE = ParamInfo("vector_size", int, default=100)
    WINDOW = ParamInfo("window", int, default=5)
    MIN_COUNT = ParamInfo("min_count", int, default=5)
    NUM_ITER = ParamInfo("num_iter", int, default=5)
    LEARNING_RATE = ParamInfo("learning_rate", float, default=0.025)
    BATCH_SIZE = ParamInfo("batch_size", int, default=256)

    def link_from(self, in_op: BatchOperator) -> "Word2VecTrainBatchOp":
        p = Word2VecParams(
            vector_size=int(self.get_vector_size()), window=int(self.get_window()),
            min_count=int(self.get_min_count()), num_iter=int(self.get_num_iter()),
            learning_rate=float(self.get_learning_rate()),
            batch_size=int(self.get_batch_size()), seed=int(self.get_seed() or 0))
        vocab, vectors = word2vec_train(in_op.get_output_table(),
                                        self.get_selected_col(), p,
                                        env=self.get_ml_env())
        self._output = word2vec_model_table(vocab, vectors)
        return self


class Word2VecPredictBatchOp(ModelMapBatchOp, HasSelectedCol, HasOutputCol):
    MAPPER_CLS = Word2VecModelMapper
