"""ComQueue superstep recovery — durable snapshots + resumable runs.

The reference's ``IterativeComQueue`` is fault-tolerant because it compiles
to a Flink iterative dataflow and Flink checkpoints it; a preempted
TaskManager restarts from the last completed checkpoint and the BSP loop
continues. The TPU rebuild compiles the whole superstep loop into ONE XLA
program (engine/comqueue.py), which is the fast path and also the
durability problem: a preempted host loses every superstep since launch.

This module restores the Flink property without giving up the compiled
loop. With ``checkpoint_every=N`` the engine runs the SAME superstep body
through a *chunked* while-loop whose upper bound is a **traced scalar**
(one compiled program serves every chunk), and between chunks — on the
host, outside the compiled program — the stacked carry is fetched and
persisted through ``common/checkpoint.py``. ``resume_from=`` loads the
newest valid snapshot, validates it against the program's signature, and
re-enters the loop mid-run; because the snapshot round-trips bitwise and
the chunk program is deterministic, the resumed run's final state is
bit-identical to the uninterrupted one (tests/test_checkpoint.py proves
this for L-BFGS and KMeans).

What checkpointing costs: one device->host fetch of the carry every N
supersteps plus the file writes — and nothing inside the compiled
program. The lowered chunk programs contain no host callbacks and exactly
the collectives of the unchunked program (asserted by a lowered-HLO test,
the same discipline as the collective-manifest accounting).

Overlap (``ALINK_TPU_ASYNC_SNAPSHOT``, default on): the fetch + file
write above no longer sit on the accelerator's critical path. At a chunk
boundary the driver takes a device-side copy of the carry (one HBM copy;
with donation on, the original is about to be consumed by the next chunk
anyway), dispatches chunk t+1 immediately, and a bounded background
writer (ONE snapshot in flight) fetches and persists snapshot t while
the device runs t+1. The writer commits strictly in order and the driver
barriers on it before returning, so the on-disk snapshot sequence — and
kill-and-resume parity — is bitwise identical to the synchronous path;
``on_snapshot`` (the health watchdog) fires from the writer after each
publish, and its abort surfaces on the main thread at the next boundary,
at most one chunk later, with the triggering snapshot already durable.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from ..common.checkpoint import load_latest_validated, save_checkpoint
from ..common.faults import maybe_crash
from ..common.metrics import env_flag, get_registry, metrics_enabled
from ..common.profiling2 import hbm_snapshot, profile_window
from ..common.tracing import trace_instant, trace_span

__all__ = ["CheckpointConfig", "program_signature", "resume_state", "drive",
           "async_snapshot_enabled"]

SCOPE = "comqueue"
SITE = "comqueue.superstep"


@dataclass(frozen=True)
class CheckpointConfig:
    """Engine checkpoint knobs (``IterativeComQueue.set_checkpoint``).

    ``every``      — persist the carry at every superstep boundary that is
                     a multiple of this (and at the final state);
    ``directory``  — snapshot root (one ``ckpt-<step>`` dir per snapshot);
                     ``None`` runs the chunked loop WITHOUT persistence —
                     the boundary-driven execution mode of
                     ``IterativeComQueue.set_boundary`` (the tuning
                     sweep's ASHA rungs), same compiled chunk programs,
                     zero disk writes;
    ``keep_last``  — bounded retention, pruned after each publish;
    ``resume_from``— directory to resume from (usually == ``directory``);
                     the newest VALID snapshot wins; a signature mismatch
                     fails loudly instead of resuming the wrong program.
    """
    directory: Optional[str]
    every: int = 1
    keep_last: int = 3
    resume_from: Optional[str] = None

    def __post_init__(self):
        if int(self.every) < 1:
            raise ValueError(f"checkpoint_every must be >= 1, "
                             f"got {self.every}")
        if int(self.keep_last) < 1:
            # fail at construction, not mid-training from inside the
            # first snapshot's prune
            raise ValueError(f"checkpoint_keep must be >= 1, "
                             f"got {self.keep_last}")


def program_signature(*, num_workers: int, max_iter: int, seed: int,
                      part_sig: Tuple, bcast_names: Tuple,
                      stages_digest: Any,
                      data_token: Any = None,
                      probes_on: bool = False,
                      fuse_collectives: bool = False) -> Dict[str, Any]:
    """JSON identity of the compiled superstep program a snapshot belongs
    to. A resume target must match exactly: same worker count, same input
    geometry, same stage structure — otherwise the carry pytree would be
    fed to a different program and the 'bitwise-identical' contract would
    silently turn into garbage.

    ``data_token`` additionally fingerprints the training DATA (content
    hash for host arrays; shape/dtype only for already-device-resident
    inputs, where a content hash would round-trip device memory): without
    it, a finished run's final snapshot would be silently 'resumed' as
    already-done for a *different* dataset of the same geometry."""
    import hashlib
    stages = hashlib.blake2b(repr(stages_digest).encode(),
                             digest_size=12).hexdigest()
    sig = {"kind": "comqueue_carry", "num_workers": int(num_workers),
           "max_iter": int(max_iter), "seed": int(seed),
           "parts": [list(map(str, item)) for item in part_sig],
           "bcast": [str(n) for n in bcast_names],
           "stages_blake2b": stages}
    if probes_on:
        # health probes add stacked carry entries: a probe-less snapshot
        # must not resume a probed program (and vice versa). Emitted only
        # when on, so pre-health snapshots stay resumable unchanged.
        sig["health_probes"] = True
    if fuse_collectives:
        # fused programs produce bitwise-identical carries, but the
        # compiled program a resume re-enters is structurally different
        # (flattened psum lanes); refuse cross-flag resumes conservatively.
        # Emitted only when on, so pre-fusion snapshots stay resumable.
        sig["fuse_collectives"] = True
    if data_token is not None:
        sig["data_blake2b"] = hashlib.blake2b(
            repr(data_token).encode(), digest_size=12).hexdigest()
    return sig


def _next_limit(step: int, every: int, max_iter: int) -> int:
    """Next checkpoint boundary after ``step`` (multiples of ``every``,
    capped at ``max_iter``)."""
    return min(max_iter, (step // every + 1) * every)


def async_snapshot_enabled() -> bool:
    """``ALINK_TPU_ASYNC_SNAPSHOT`` (default on): persist boundary
    snapshots in a bounded background writer instead of blocking the
    chunk loop on the device->host fetch + file write. Off restores the
    strictly synchronous r02 behavior (identical on-disk artifacts)."""
    return env_flag("ALINK_TPU_ASYNC_SNAPSHOT", default=True)


def _device_copy(stacked) -> Dict[str, Any]:
    """Device-side copy of a stacked carry (sharding preserved). Taken at
    a boundary so the donated ``cont`` program is free to CONSUME the
    original while the background writer still holds live buffers to
    fetch. One HBM-to-HBM pass — orders of magnitude cheaper than the
    host fetch it decouples. Host leaves (a resumed numpy carry) copy on
    host."""
    import jax
    import jax.numpy as jnp
    return jax.tree_util.tree_map(
        lambda x: jnp.copy(x) if isinstance(x, jax.Array) else np.copy(x),
        dict(stacked))


def _to_host(stacked) -> Dict[str, Any]:
    """Fetch every carry leaf to host numpy in ONE batched transfer (the
    persistence payload) — the shared
    :func:`common.compat.device_get_tree` idiom. The ONLY persistence
    fetch: async writer and synchronous path both go through it, so the
    payload bytes cannot diverge between them."""
    from ..common.compat import device_get_tree
    return device_get_tree(dict(stacked))


class _SnapshotWriter:
    """Bounded background snapshot writer — ONE snapshot in flight.

    ``submit()`` hands over a device-side carry (a copy when donation is
    on) and returns once the PREVIOUS snapshot has committed (the bound:
    the driver can run at most one chunk ahead of durability). The worker
    thread fetches the carry to host (one batched ``jax.device_get``),
    persists it through ``save_checkpoint`` (same atomic-publish path as
    the synchronous writer — artifacts are bitwise identical), then fires
    ``on_snapshot``. Commits are strictly in submission order, so
    retention pruning, ``alink_checkpoint_last_tag`` and the health
    watchdog observe the same sequence the synchronous path produces.

    Any exception — an injected ``ckpt.save`` kill, a watchdog
    ``HealthAlertError``, a real IO error — is captured and re-raised ON
    THE MAIN THREAD (original object, type preserved) at the next
    ``submit()``/``check()``/``barrier()``, i.e. before the driver
    dispatches further work past the failed boundary."""

    def __init__(self, config: CheckpointConfig, signature: Dict[str, Any],
                 on_snapshot: Optional[Callable] = None):
        self._config = config
        self._signature = signature
        self._on_snapshot = on_snapshot
        self._q: "queue.Queue" = queue.Queue(maxsize=1)
        self._errs: list = []
        self._writes = 0
        self._th = threading.Thread(target=self._worker, daemon=True,
                                    name="alink-ckpt-writer")
        self._th.start()

    # -- worker thread ---------------------------------------------------
    def _worker(self):
        while True:
            item = self._q.get()
            try:
                if item is None:
                    return
                carry, step, stopped = item
                with trace_span("snapshot.write", cat="ckpt") as sp:
                    host = _to_host(carry)
                    save_checkpoint(
                        self._config.directory, step, host,
                        meta={"signature": self._signature, "step": step,
                              "stopped": stopped},
                        scope=SCOPE, keep_last=self._config.keep_last)
                    sp.set(step=step, mode="async")
                self._writes += 1
                if metrics_enabled():
                    get_registry().inc("alink_overlap_snapshot_writes_total",
                                       1, {"scope": SCOPE})
                if self._on_snapshot is not None:
                    # the watchdog hook: may raise HealthAlertError — it
                    # lands in _errs and aborts the run at the next
                    # boundary, with THIS snapshot already on disk
                    self._on_snapshot(host, step)
            except BaseException as e:
                self._errs.append(e)
            finally:
                self._q.task_done()

    # -- driver-thread API -----------------------------------------------
    def check(self):
        """Re-raise the first captured writer exception (original object,
        so FaultInjected/HealthAlertError keep their types)."""
        if self._errs:
            raise self._errs[0]

    def submit(self, carry, step: int, stopped: bool):
        t0 = time.perf_counter()
        self._q.join()       # previous snapshot must commit first (bound)
        wait = time.perf_counter() - t0
        self.check()         # a failed previous write aborts HERE, before
        #                      this boundary's state is handed over
        if metrics_enabled():
            get_registry().observe("alink_overlap_submit_wait_seconds",
                                   wait, {"scope": SCOPE})
        trace_instant("snapshot.submit", cat="ckpt",
                      args={"step": step, "waited_s": round(wait, 6)})
        self._q.put((carry, step, stopped))

    def barrier(self):
        """Final durability barrier: every submitted snapshot is on disk
        (or its error raised) before the driver returns."""
        self._q.join()
        self.check()

    def shutdown(self):
        """Stop the worker without raising (the ``finally`` path). Any
        queued snapshot is still committed first — a run aborted by a
        superstep fault keeps the durability of its last boundary, same
        as the synchronous writer."""
        self._q.put(None)
        self._th.join(timeout=60.0)


def resume_state(config: CheckpointConfig,
                 signature: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Load the newest valid snapshot from ``config.resume_from`` and
    check it against ``signature``; returns the host carry (stacked
    layout) or None when there is nothing to resume from."""
    if not config.resume_from:
        return None
    got = load_latest_validated(config.resume_from, signature, scope=SCOPE,
                                what="program")
    return None if got is None else got[0]


def drive(config: CheckpointConfig, *,
          first: Callable, cont: Callable,
          parts: Dict[str, Any], bcast: Dict[str, Any],
          max_iter: int, signature: Dict[str, Any],
          resumed: Optional[Dict[str, Any]] = None,
          on_snapshot: Optional[Callable] = None,
          donate: bool = False,
          on_boundary: Optional[Callable] = None
          ) -> Tuple[Any, Dict[str, Any]]:
    """Run the chunked superstep loop with host-side persistence.

    ``first(parts, bcast, limit)`` runs the init pass + loop to ``limit``;
    ``cont(parts, bcast, carry, limit)`` continues a stacked carry.
    ``resumed`` is a host carry from :func:`resume_state` (skips
    ``first``). ``on_snapshot(host_carry, step)`` — if given — fires
    right after each snapshot publishes, with the host carry the save
    already fetched (the health monitor's mid-run hook; it may raise to
    abort the run, and because the snapshot is already on disk the
    aborted run stays resumable; with the async writer the abort
    surfaces on the main thread at the next boundary, at most one chunk
    later). ``donate=True`` declares that ``cont`` CONSUMES its carry
    argument (``ALINK_TPU_DONATE``), so the async writer is handed a
    device-side copy instead of the live carry. Returns
    ``(stacked_carry, info)`` where ``info`` carries the superstep
    accounting the metrics tail needs (``steps_executed``, ``init_ran``,
    ``resumed_at``).

    ``on_boundary(stacked, step)`` — if given — runs at every chunk
    boundary AFTER the snapshot published (and once right after a
    resume, BEFORE any new chunk dispatches) and may return a
    replacement stacked carry (``None`` = keep). This is the tuning
    sweep's ASHA pruning hook: it flips carry-resident alive lanes
    between chunks without touching program geometry. Because it runs
    after persistence but is re-applied on resume, a resumed run
    re-derives the same (deterministic) boundary decision the
    uninterrupted run made — kill-and-resume parity holds for the whole
    population. The hook may also rewrite ``__stop`` (the whole
    surviving population has converged); the driver re-reads it.

    With ``config.directory`` None nothing is persisted: the chunked
    loop runs purely for its boundaries (``IterativeComQueue.
    set_boundary`` — the sweep's rung cadence without durability).
    """
    import jax.numpy as jnp

    every = int(config.every)
    max_iter = int(max_iter)

    def boundary(stacked):
        # worker 0's copy — __step/__stop are replicated by construction.
        # ONE batched fetch: this sits inside the per-chunk critical path
        # (superstep.sync), where two serialized np.asarray round trips
        # cost ~200 ms per chunk on tunneled backends
        import jax
        step, stop = jax.device_get([stacked["__step"], stacked["__stop"]])
        return int(np.asarray(step)[0]), bool(np.asarray(stop)[0])

    def chunk(fn, args, from_step, limit):
        """One compiled-chunk pass: dispatch + the boundary sync that
        flushes it. The span tree (exec -> execute -> chunk ->
        superstep.sync) is what lets a trace answer 'which chunk of
        which exec was slow' — the aggregate metrics cannot."""
        with trace_span("comqueue.chunk", cat="engine") as sp:
            # measured-profiling window (ALINK_TPU_PROFILE): dispatch =
            # time the chunk call held the host thread; device = the
            # boundary sync that flushes it. Host wall clock only — the
            # chunk program is untouched.
            with profile_window("comqueue.chunk", capture=True) as pw:
                _pt0 = time.perf_counter()
                out = fn(*args, jnp.asarray(limit, jnp.int32))
                pw.dispatch(time.perf_counter() - _pt0)
                # the device work materializes at this host fetch — timed
                # as its own phase span so dispatch vs sync split is
                # visible
                with trace_span("superstep.sync", cat="engine"):
                    _pt1 = time.perf_counter()
                    step, stop = boundary(out)
                    pw.device(time.perf_counter() - _pt1)
            sp.set(from_step=from_step, limit=limit, step=step)
        # superstep-chunk boundary: the live-HBM accounting point (the
        # carry, any writer-held snapshot copy, and the inputs are all
        # resident here — the donation savings show up in this gauge)
        hbm_snapshot("comqueue.chunk")
        return out, step, stop

    writer = _SnapshotWriter(config, signature, on_snapshot) \
        if (async_snapshot_enabled() and config.directory) else None

    def persist(stacked, step, stopped):
        if not config.directory:
            return          # boundary-only mode: chunking without disk
        if writer is not None:
            # hand the writer buffers the next chunk cannot invalidate:
            # a device-side copy when the donated cont will consume the
            # carry; the live carry itself otherwise (a non-donated cont
            # only READS it, and a concurrent device_get is safe)
            writer.submit(_device_copy(stacked) if donate else stacked,
                          step, stopped)
            return
        host = _to_host(stacked)
        save_checkpoint(config.directory, step, host,
                        meta={"signature": signature, "step": step,
                              "stopped": stopped},
                        scope=SCOPE, keep_last=config.keep_last)
        if on_snapshot is not None:
            on_snapshot(host, step)

    info: Dict[str, Any] = {"init_ran": resumed is None, "resumed_at": None}
    try:
        if resumed is None:
            stacked, step, stop = chunk(first, (parts, bcast), 1,
                                        _next_limit(1, every, max_iter))
            start_step = 0
        else:
            stacked = resumed
            step, stop = boundary(stacked)
            start_step = step
            info["resumed_at"] = start_step
        last_saved = start_step if resumed is not None else None
        while True:
            # the injected-preemption point: BEFORE the snapshot publish,
            # so a killed run genuinely loses the work since the last
            # checkpoint and the resume has supersteps to re-execute
            maybe_crash(SITE, step)
            if step != last_saved:
                persist(stacked, step, stop or step >= max_iter)
                last_saved = step
            if on_boundary is not None and not stop and step < max_iter:
                # boundary transform (ASHA rung pruning): runs after the
                # snapshot published — the on-disk state is pre-decision,
                # and a resume re-derives the decision deterministically
                new = on_boundary(stacked, step)
                if new is not None:
                    stacked = new
                    step, stop = boundary(stacked)
            if stop or step >= max_iter:
                break
            # an exhausted boundary hook (the ASHA rung maker once the
            # population is down to its floor) has no further decisions:
            # with persistence OFF the rest of the run is ONE chunk —
            # boundaries are host syncs, pure overhead past that point.
            # With a checkpoint directory the snapshot cadence wins.
            if on_boundary is not None and not config.directory \
                    and getattr(on_boundary, "exhausted", False):
                limit = max_iter
            else:
                limit = _next_limit(step, every, max_iter)
            # snapshot t is now fetching/writing in the background; chunk
            # t+1 dispatches immediately — THE overlap this module buys
            stacked, step, stop = chunk(cont, (parts, bcast, stacked), step,
                                        limit)
        if writer is not None:
            # durability barrier: drive returns only once every boundary
            # is on disk (or its failure raised) — callers observe the
            # exact guarantees of the synchronous path
            writer.barrier()
    finally:
        if writer is not None:
            writer.shutdown()
    info["steps_executed"] = step - start_step
    return stacked, info


