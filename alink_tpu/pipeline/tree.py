"""Pipeline wrappers — tree family (reference pipeline/classification+regression)."""

from ..operator.batch.classification.tree_ops import (
    DecisionTreeRegTrainBatchOp, DecisionTreeTrainBatchOp, GbdtRegTrainBatchOp,
    GbdtTrainBatchOp, RandomForestRegTrainBatchOp, RandomForestTrainBatchOp,
    TreeModelMapper)
from .fm_nb import _wrap

GbdtClassifier, GbdtClassifierModel = _wrap("GbdtClassifier", GbdtTrainBatchOp,
                                            TreeModelMapper)
GbdtRegressor, GbdtRegressorModel = _wrap("GbdtRegressor", GbdtRegTrainBatchOp,
                                          TreeModelMapper)
RandomForestClassifier, RandomForestClassifierModel = _wrap(
    "RandomForestClassifier", RandomForestTrainBatchOp, TreeModelMapper)
RandomForestRegressor, RandomForestRegressorModel = _wrap(
    "RandomForestRegressor", RandomForestRegTrainBatchOp, TreeModelMapper)
DecisionTreeClassifier, DecisionTreeClassifierModel = _wrap(
    "DecisionTreeClassifier", DecisionTreeTrainBatchOp, TreeModelMapper)
DecisionTreeRegressor, DecisionTreeRegressorModel = _wrap(
    "DecisionTreeRegressor", DecisionTreeRegTrainBatchOp, TreeModelMapper)
