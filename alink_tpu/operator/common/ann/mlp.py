"""Multilayer perceptron objective — TPU-native.

Re-design of the reference ann/ package (23 files, 1,174 LoC:
FeedForwardTopology.multiLayerPerceptron, AffineLayer, SigmoidFunction,
SoftmaxLayerWithCrossEntropyLoss, Stacker, AnnObjFunc): all weights are
flattened into ONE coefficient vector (the Stacker contract) so the MLP
plugs into the same distributed L-BFGS engine as the linear models
(MultilayerPerceptronTrainBatchOp.java:146-147). Gradients come from
``jax.grad`` instead of hand-written layer backprop.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..optim.objfunc import OptimObjFunc, matvec


def stack_sizes(layer_sizes: Sequence[int]) -> int:
    """Total flattened parameter count (reference Stacker)."""
    total = 0
    for a, b in zip(layer_sizes[:-1], layer_sizes[1:]):
        total += a * b + b
    return total


def unstack(coef, layer_sizes: Sequence[int]) -> List[Tuple]:
    """coef -> [(W (in,out), b (out,)), ...]."""
    out = []
    pos = 0
    for a, b in zip(layer_sizes[:-1], layer_sizes[1:]):
        W = coef[pos:pos + a * b].reshape(a, b)
        pos += a * b
        bias = coef[pos:pos + b]
        pos += b
        out.append((W, bias))
    return out


def mlp_forward(coef, X, layer_sizes: Sequence[int]):
    """Logits of the final layer; sigmoid hidden activations (reference
    SigmoidFunction between AffineLayers)."""
    h = X
    layers = unstack(coef, layer_sizes)
    for i, (W, b) in enumerate(layers):
        z = h @ W + b
        h = z if i == len(layers) - 1 else jax.nn.sigmoid(z)
    return h


class MlpObjFunc(OptimObjFunc):
    """Cross-entropy over softmax outputs (reference
    SoftmaxLayerWithCrossEntropyLoss + AnnObjFunc)."""

    def __init__(self, layer_sizes: Sequence[int], l2: float = 0.0):
        super().__init__(stack_sizes(layer_sizes), l1=0.0, l2=l2)
        self.layer_sizes = list(layer_sizes)

    def _loss_sum(self, coef, X, y, w):
        logits = mlp_forward(coef, X, self.layer_sizes)
        lse = jax.nn.logsumexp(logits, axis=1)
        picked = jnp.take_along_axis(logits, y[:, None].astype(jnp.int32), 1)[:, 0]
        return (w * (lse - picked)).sum()

    def calc_grad_shard(self, data, coef):
        X, y, w = data["X"], data["y"], data["w"]
        loss, grad = jax.value_and_grad(self._loss_sum)(coef, X, y, w)
        return grad, loss, w.sum()

    def line_losses_shard(self, data, coef, direction, steps, eta0=None):
        X, y, w = data["X"], data["y"], data["w"]

        def one(s):
            return self._loss_sum(coef - s * direction, X, y, w)

        return jax.vmap(one)(steps)
