"""GLM / Isotonic / AFT tests."""

import numpy as np
import pytest

from alink_tpu.operator.batch.source import MemSourceBatchOp
from alink_tpu.operator.batch.regression.glm_ops import (
    GlmTrainBatchOp, GlmPredictBatchOp, GlmEvaluationBatchOp,
    IsotonicRegTrainBatchOp, IsotonicRegPredictBatchOp,
    AftSurvivalRegTrainBatchOp, AftSurvivalRegPredictBatchOp, GlmModelConverter,
    pav)


def test_glm_poisson():
    rng = np.random.RandomState(0)
    n = 500
    x1, x2 = rng.randn(n) * 0.5, rng.randn(n) * 0.5
    lam = np.exp(0.5 + 1.0 * x1 - 0.7 * x2)
    y = rng.poisson(lam).astype(float)
    src = MemSourceBatchOp(list(zip(x1, x2, y)), "a DOUBLE, b DOUBLE, y DOUBLE")
    train = GlmTrainBatchOp(feature_cols=["a", "b"], label_col="y",
                            family="Poisson").link_from(src)
    m = GlmModelConverter().load_model(train.get_output_table())
    assert np.allclose(m["beta"], [0.5, 1.0, -0.7], atol=0.15)
    out = (GlmPredictBatchOp(prediction_col="mu", link_pred_result_col="eta")
           .link_from(train, src)).collect_mtable()
    assert np.corrcoef(np.asarray(out.col("mu")), lam)[0, 1] > 0.95
    ev = (GlmEvaluationBatchOp(label_col="y", prediction_col="mu",
                               family="Poisson").link_from(
        train.from_table(out))).collect_mtable()
    import json
    assert json.loads(ev.row(0)[0])["deviance"] > 0


def test_glm_binomial_logit():
    rng = np.random.RandomState(1)
    n = 800
    x = rng.randn(n)
    p = 1 / (1 + np.exp(-(0.3 + 2.0 * x)))
    y = (rng.rand(n) < p).astype(float)
    src = MemSourceBatchOp(list(zip(x, y)), "x DOUBLE, y DOUBLE")
    train = GlmTrainBatchOp(feature_cols=["x"], label_col="y",
                            family="Binomial").link_from(src)
    m = GlmModelConverter().load_model(train.get_output_table())
    assert abs(m["beta"][1] - 2.0) < 0.4


def test_glm_gamma_log_link():
    rng = np.random.RandomState(2)
    n = 600
    x = rng.rand(n)
    mu = np.exp(1.0 + 1.5 * x)
    shape = 5.0
    y = rng.gamma(shape, mu / shape)
    src = MemSourceBatchOp(list(zip(x, y)), "x DOUBLE, y DOUBLE")
    train = GlmTrainBatchOp(feature_cols=["x"], label_col="y", family="Gamma",
                            link="Log").link_from(src)
    m = GlmModelConverter().load_model(train.get_output_table())
    assert abs(m["beta"][1] - 1.5) < 0.3


def test_isotonic():
    rng = np.random.RandomState(3)
    x = np.sort(rng.rand(200) * 10)
    y = np.log1p(x) + 0.2 * rng.randn(200)
    src = MemSourceBatchOp(list(zip(x, y)), "x DOUBLE, y DOUBLE")
    train = IsotonicRegTrainBatchOp(feature_col="x", label_col="y").link_from(src)
    out = (IsotonicRegPredictBatchOp(prediction_col="p").link_from(train, src)
           ).collect_mtable()
    p = np.asarray(out.col("p"))
    # fitted curve is monotone nondecreasing in x order
    order = np.argsort(np.asarray(out.col("x")))
    assert (np.diff(p[order]) >= -1e-9).all()
    assert np.abs(p - np.log1p(x)).mean() < 0.15


def test_pav_simple():
    bx, bv = pav(np.asarray([1.0, 2, 3, 4]), np.asarray([1.0, 3, 2, 4]),
                 np.ones(4))
    assert (np.diff(bv) >= 0).all()
    assert bv[1] == pytest.approx(2.5)  # pooled violators


def test_aft_survival():
    rng = np.random.RandomState(4)
    n = 600
    x = rng.randn(n)
    scale = np.exp(1.0 + 0.8 * x)
    t_true = scale * rng.weibull(2.0, n)
    censor_time = np.quantile(t_true, 0.8)
    observed = np.minimum(t_true, censor_time)
    event = (t_true <= censor_time).astype(float)
    src = MemSourceBatchOp(list(zip(x, observed, event)),
                           "x DOUBLE, time DOUBLE, status DOUBLE")
    train = AftSurvivalRegTrainBatchOp(feature_cols=["x"], label_col="time",
                                       censor_col="status").link_from(src)
    m = GlmModelConverter().load_model(train.get_output_table())
    # beta = [intercept, slope, log_sigma]; slope recovers 0.8
    assert abs(m["beta"][1] - 0.8) < 0.15
    out = (AftSurvivalRegPredictBatchOp(prediction_col="p")
           .link_from(train, src)).collect_mtable()
    assert np.corrcoef(np.log(np.asarray(out.col("p"))), np.log(scale))[0, 1] > 0.95
