"""COLLECTIVE-SITE negative: collectives route through the
manifest-recording wrappers (engine/communication.py), and a method
merely NAMED psum on another object is not a collective."""
from alink_tpu.engine.communication import manifest_psum


def shard_fn(x, nw):
    total = manifest_psum(x, "d", name="fixture", num_workers=nw)
    return total


def not_a_collective(accumulator, x):
    return accumulator.psum(x)    # attribute psum NOT under lax
