"""Tree-family batch operators: GBDT, RandomForest, DecisionTree
(classification + regression).

Re-design of batch/classification/{GbdtTrainBatchOp, RandomForestTrainBatchOp,
DecisionTreeTrainBatchOp} (+Reg variants, + predict ops) over the
histogram-parallel device builder (common/tree/).
"""

from __future__ import annotations

import json
from typing import List, Optional

import numpy as np

from ....common.mtable import MTable
from ....common.params import ParamInfo, Params, RangeValidator
from ....common.types import AlinkTypes, TableSchema
from ....mapper.base import ModelMapper, OutputColsHelper
from ....model.converters import (SimpleModelDataConverter, decode_array,
                                  encode_array)
from ....params.shared import (HasFeatureCols, HasLabelCol, HasPredictionCol,
                               HasPredictionDetailCol, HasReservedCols, HasSeed,
                               HasVectorCol, HasWeightCol)
from ...base import BatchOperator
from ...common.dataproc.feature_extract import extract_design, resolve_feature_cols
from ...common.tree.hist import bins_to_thresholds, tree_apply_values
from ...common.tree.trainers import TreeTrainParams, forest_train, gbdt_train
from ..utils.model_map import ModelMapBatchOp


class TreeModelData:
    def __init__(self, algo: str, is_regression: bool, max_depth: int,
                 features: np.ndarray, thresholds: np.ndarray,
                 leaf_values: np.ndarray, base_score: float, learning_rate: float,
                 labels: List, feature_cols: Optional[List[str]],
                 vector_col: Optional[str], label_type: str = AlinkTypes.STRING,
                 split_masks: Optional[np.ndarray] = None,
                 cat_cols: Optional[List[str]] = None,
                 cat_vocabs: Optional[dict] = None,
                 importances: Optional[np.ndarray] = None):
        self.algo = algo
        self.is_regression = is_regression
        self.max_depth = max_depth
        self.features = features          # (T, 2^d - 1) int
        self.thresholds = thresholds      # (T, 2^d - 1) float
        self.leaf_values = leaf_values    # (T, 2^d) or (T, 2^d, k)
        self.base_score = base_score
        self.learning_rate = learning_rate
        self.labels = labels
        self.feature_cols = feature_cols
        self.vector_col = vector_col
        self.label_type = label_type
        # categorical support (reference seriestree/CategoricalSplitter):
        self.split_masks = split_masks    # (T, 2^d - 1, n_bins) bool or None
        self.cat_cols = cat_cols or []    # feature col names that are categorical
        self.cat_vocabs = cat_vocabs or {}  # col -> [category strings] (code = index)
        self.importances = importances    # (F,) summed split gain or None


class TreeModelDataConverter(SimpleModelDataConverter):
    """reference: common/tree/TreeModelDataConverter.java"""

    def serialize_model(self, m: TreeModelData):
        meta = Params({
            "algo": m.algo, "is_regression": m.is_regression,
            "max_depth": m.max_depth, "base_score": m.base_score,
            "learning_rate": m.learning_rate,
            "labels": [str(l) for l in m.labels], "label_type": m.label_type,
            "feature_cols": m.feature_cols, "vector_col": m.vector_col,
            "cat_cols": m.cat_cols, "cat_vocabs": m.cat_vocabs})
        blobs = [encode_array(m.features), encode_array(m.thresholds),
                 encode_array(m.leaf_values)]
        if m.split_masks is not None:
            blobs.append(encode_array(m.split_masks.astype(np.int8)))
        if m.importances is not None:
            if m.split_masks is None:
                blobs.append(encode_array(
                    np.zeros((0,), np.int8)))  # keep blob positions fixed
            blobs.append(encode_array(np.asarray(m.importances, np.float64)))
        return meta, blobs

    def deserialize_model(self, meta, data):
        labels = meta._m.get("labels", [])
        lt = meta._m.get("label_type", AlinkTypes.STRING)
        if lt in (AlinkTypes.LONG, AlinkTypes.INT):
            labels = [int(float(v)) for v in labels]
        elif lt in (AlinkTypes.DOUBLE, AlinkTypes.FLOAT):
            labels = [float(v) for v in labels]
        split_masks = (decode_array(data[3], np.int8).astype(bool)
                       if len(data) > 3 and decode_array(data[3]).size
                       else None)
        importances = decode_array(data[4]) if len(data) > 4 else None
        return TreeModelData(
            meta._m["algo"], bool(meta._m["is_regression"]),
            int(meta._m["max_depth"]),
            decode_array(data[0], np.int64), decode_array(data[1]),
            decode_array(data[2]), float(meta._m.get("base_score", 0.0)),
            float(meta._m.get("learning_rate", 1.0)), labels,
            meta._m.get("feature_cols"), meta._m.get("vector_col"), lt,
            split_masks=split_masks, cat_cols=meta._m.get("cat_cols"),
            cat_vocabs=meta._m.get("cat_vocabs"), importances=importances)


class _TreeTrainParamsMixin(HasLabelCol, HasFeatureCols, HasVectorCol,
                            HasWeightCol, HasSeed):
    NUM_TREES = ParamInfo("num_trees", int, default=100,
                          validator=RangeValidator(1, None))
    MAX_DEPTH = ParamInfo("max_depth", int, default=5,
                          validator=RangeValidator(1, 14))
    MAX_BINS = ParamInfo("max_bins", int, default=64,
                         validator=RangeValidator(2, 256))
    MIN_SAMPLES_PER_LEAF = ParamInfo("min_samples_per_leaf", int, default=2)
    LEARNING_RATE = ParamInfo("learning_rate", float, default=0.3)
    SUBSAMPLING_RATIO = ParamInfo("subsampling_ratio", float, default=1.0)
    FEATURE_SUBSAMPLING_RATIO = ParamInfo("feature_subsampling_ratio", float,
                                          default=1.0)
    REG_LAMBDA = ParamInfo("reg_lambda", float, default=1.0)
    CATEGORICAL_COLS = ParamInfo("categorical_cols", list, default=None)


def _encode_feature_matrix(t: MTable, feature_cols, cat_cols):
    """(X, cat_mask, cat_vocabs): categorical columns ordinal-encode via a
    sorted per-column vocabulary (code = vocab index, stored in the model
    for serving); numeric columns pass through."""
    n = t.num_rows
    cat_set = set(cat_cols)
    X = np.empty((n, len(feature_cols)), np.float64)
    vocabs = {}
    for j, c in enumerate(feature_cols):
        col = t.col(c)
        if c in cat_set:
            vocab = sorted({str(v) for v in col})
            vocabs[c] = vocab
            lut = {v: i for i, v in enumerate(vocab)}
            X[:, j] = [lut[str(v)] for v in col]
        else:
            X[:, j] = np.asarray(col, np.float64)
    cat_mask = np.asarray([c in cat_set for c in feature_cols], bool)
    return X, cat_mask, vocabs


def _extract_xy(op, t: MTable, regression: bool):
    vector_col = op.params._m.get("vector_col")
    feature_cols = op.params._m.get("feature_cols")
    cat_cols = list(op.params._m.get("categorical_cols") or [])
    label_col = op.get_label_col()
    weight_col = op.params._m.get("weight_col")
    cat_mask, vocabs = None, {}
    if not vector_col:
        feature_cols = resolve_feature_cols(
            t, feature_cols, label_col, exclude=[weight_col] if weight_col else [])
        for c in cat_cols:                 # string cols aren't numeric-resolvable
            if c not in feature_cols:
                feature_cols = feature_cols + [c]
        X, cat_mask, vocabs = _encode_feature_matrix(t, feature_cols, cat_cols)
        if not cat_mask.any():
            cat_mask = None
    else:
        if cat_cols:
            raise ValueError("categorical_cols requires feature_cols input "
                             "(vector input has no column identity)")
        design = extract_design(t, feature_cols, vector_col, np.float64)
        X = design["X"] if design["kind"] == "dense" else None
        if X is None:
            from ....common.vector import SparseBatch
            X = SparseBatch(design["idx"], design["val"],
                            design["dim"]).to_dense(np.float64)
    raw = t.col(label_col)
    label_type = t.schema.type_of(label_col)
    if regression:
        labels, y = [], np.asarray(raw, np.float64)
    else:
        labels = sorted({str(v) for v in raw})
        y = np.asarray([labels.index(str(v)) for v in raw], np.float64)
        if label_type in (AlinkTypes.LONG, AlinkTypes.INT):
            labels = [int(float(v)) for v in labels]
        elif label_type in (AlinkTypes.DOUBLE, AlinkTypes.FLOAT):
            labels = [float(v) for v in labels]
    w = (np.asarray(t.col(weight_col), np.float64) if weight_col
         else np.ones(len(y)))
    return (X, y, w, labels, feature_cols, vector_col, label_type,
            cat_mask if not vector_col else None, cat_cols, vocabs)


def _model_info_table(m: "TreeModelData") -> MTable:
    """Model summary incl. gain-based feature importances (reference
    GbdtModelInfo / RandomForestModelInfo feature importance output)."""
    if m.importances is not None:
        t = _importance_table(m.feature_cols, m.importances)
        rows = {"item": np.asarray(
                    ["algo", "num_trees", "max_depth"]
                    + [f"importance[{f}]" for f in t.col("feature")], object),
                "value": np.asarray(
                    [m.algo, str(m.features.shape[0]), str(m.max_depth)]
                    + [f"{v:.6f}" for v in t.col("importance")], object)}
        return MTable(rows)
    return MTable({"item": np.asarray(["algo", "num_trees", "max_depth"], object),
                   "value": np.asarray([m.algo, str(m.features.shape[0]),
                                        str(m.max_depth)], object)})


def _importance_table(feature_cols, imp) -> MTable:
    """Gain-based feature importances, normalized to sum 1 (reference
    TreeModelInfo feature importance)."""
    imp = np.asarray(imp, np.float64)
    tot = imp.sum()
    names = (list(feature_cols) if feature_cols
             else [f"f{i}" for i in range(len(imp))])
    return MTable({"feature": np.asarray(names, object),
                   "importance": imp / (tot if tot > 0 else 1.0)})


def _tree_params(op) -> TreeTrainParams:
    return TreeTrainParams(
        num_trees=op.get_num_trees(), max_depth=op.get_max_depth(),
        n_bins=op.get_max_bins(), learning_rate=op.get_learning_rate(),
        min_samples_leaf=op.get_min_samples_per_leaf(),
        reg_lambda=op.get_reg_lambda(),
        subsample_ratio=op.get_subsampling_ratio(),
        feature_subsample_ratio=op.get_feature_subsampling_ratio(),
        seed=op.get_seed())


class GbdtTrainBatchOp(BatchOperator, _TreeTrainParamsMixin):
    """reference: batch/classification/GbdtTrainBatchOp.java (binary)."""
    IS_REGRESSION = False

    def link_from(self, in_op: BatchOperator):
        t = in_op.get_output_table()
        (X, y, w, labels, fc, vc, lt, cat_mask, cat_cols,
         vocabs) = _extract_xy(t=t, op=self, regression=self.IS_REGRESSION)
        if not self.IS_REGRESSION and len(labels) != 2:
            raise ValueError(f"GBDT classifier is binary; got labels {labels}")
        p = _tree_params(self)
        tf, tb, tm, tv, edges, base, curve, imp = gbdt_train(
            X, y, p, self.IS_REGRESSION, sample_weight=w, cat_mask=cat_mask)
        thr = np.stack([bins_to_thresholds(np.asarray(tf[i]), np.asarray(tb[i]),
                                           edges) for i in range(p.num_trees)])
        model = TreeModelData(
            "gbdt", self.IS_REGRESSION, p.max_depth, np.asarray(tf), thr,
            np.asarray(tv), base, p.learning_rate, labels, fc, vc, lt,
            split_masks=np.asarray(tm), cat_cols=cat_cols, cat_vocabs=vocabs,
            importances=np.asarray(imp))
        self._output = TreeModelDataConverter().save_model(model)
        self._side_outputs = [MTable({"tree": np.arange(1, len(curve) + 1),
                                      "loss": curve.astype(np.float64)}),
                              _importance_table(fc, imp)]
        return self


    def get_model_info(self) -> MTable:
        m = TreeModelDataConverter().load_model(self.get_output_table())
        return _model_info_table(m)


class GbdtRegTrainBatchOp(GbdtTrainBatchOp):
    """reference: batch/regression/GbdtRegTrainBatchOp.java"""
    IS_REGRESSION = True


class RandomForestTrainBatchOp(BatchOperator, _TreeTrainParamsMixin):
    """reference: batch/classification/RandomForestTrainBatchOp.java"""
    IS_REGRESSION = False
    NUM_TREES = ParamInfo("num_trees", int, default=10,
                          validator=RangeValidator(1, None))
    SUBSAMPLING_RATIO = ParamInfo("subsampling_ratio", float, default=0.8)
    FEATURE_SUBSAMPLING_RATIO = ParamInfo("feature_subsampling_ratio", float,
                                          default=0.7)
    # True ensemble parallelism (whole trees per worker, reference
    # SeriesTrainFunction); None = auto (on for multi-tree forests)
    ENSEMBLE_PARALLEL = ParamInfo("ensemble_parallel", bool, default=None)

    def link_from(self, in_op: BatchOperator):
        t = in_op.get_output_table()
        (X, y, w, labels, fc, vc, lt, cat_mask, cat_cols,
         vocabs) = _extract_xy(t=t, op=self, regression=self.IS_REGRESSION)
        p = _tree_params(self)
        if self.IS_REGRESSION:
            stats = np.stack([y * w, y * y * w, w], axis=1)
            kind = "variance"
        else:
            k = len(labels)
            onehot = np.eye(k)[y.astype(int)] * w[:, None]
            stats = np.concatenate([onehot, w[:, None]], axis=1)
            kind = "gini"
        tf, tb, tm, tv, edges, imp = forest_train(
            X, stats, p, kind, cat_mask=cat_mask,
            ensemble=self.params._m.get("ensemble_parallel"))
        thr = np.stack([bins_to_thresholds(np.asarray(tf[i]), np.asarray(tb[i]),
                                           edges) for i in range(p.num_trees)])
        model = TreeModelData(
            "rf", self.IS_REGRESSION, p.max_depth, np.asarray(tf), thr,
            np.asarray(tv), 0.0, 1.0, labels, fc, vc, lt,
            split_masks=np.asarray(tm), cat_cols=cat_cols, cat_vocabs=vocabs,
            importances=np.asarray(imp))
        self._output = TreeModelDataConverter().save_model(model)
        self._side_outputs = [_importance_table(fc, imp)]
        return self


    def get_model_info(self) -> MTable:
        m = TreeModelDataConverter().load_model(self.get_output_table())
        return _model_info_table(m)


class RandomForestRegTrainBatchOp(RandomForestTrainBatchOp):
    IS_REGRESSION = True


class DecisionTreeTrainBatchOp(RandomForestTrainBatchOp):
    """reference: batch/classification/DecisionTreeTrainBatchOp.java"""
    NUM_TREES = ParamInfo("num_trees", int, default=1,
                          validator=RangeValidator(1, 1))
    SUBSAMPLING_RATIO = ParamInfo("subsampling_ratio", float, default=1.0)
    FEATURE_SUBSAMPLING_RATIO = ParamInfo("feature_subsampling_ratio", float,
                                          default=1.0)


class DecisionTreeRegTrainBatchOp(DecisionTreeTrainBatchOp):
    IS_REGRESSION = True


class TreeModelMapper(ModelMapper):
    """Host-side batched forest traversal (reference common/tree/predictors/)."""

    def __init__(self, model_schema, data_schema, params=None, **kwargs):
        super().__init__(model_schema, data_schema, params, **kwargs)
        self.model: Optional[TreeModelData] = None

    def load_model(self, model_table: MTable):
        self.model = TreeModelDataConverter().load_model(model_table)

    def get_output_schema(self) -> TableSchema:
        """Output schema without running the mapper — what the stream
        predict twins (`ModelMapStreamOp._open`) need; the batch path
        derives it from `map_table`'s result and never noticed this was
        missing, which kept every tree stream twin from opening."""
        m = self.model
        return self._pred_output_schema(
            m.label_type if m else AlinkTypes.STRING,
            bool(m is not None and m.is_regression))

    def _model_width(self) -> int:
        """The feature width the model's splits can address: column
        count for feature_cols models, max split feature index + 1 for
        vector models (the model stores no vector size). Encoding to at
        least this width makes a batch's width independent of which
        sparse vectors happen to be in it — absent vector entries read
        as 0 instead of clamping the split's gather to a WRONG column
        (device) or raising (host numpy)."""
        m = self.model
        if m.feature_cols:
            return len(m.feature_cols)
        return int(max(int(m.features.max()), 0)) + 1

    def _encode_matrix(self, data: MTable, dtype=np.float64) -> np.ndarray:
        """Request table -> raw feature-value matrix (categorical columns
        ordinal-coded via the model vocabularies, OOV -> -1 which every
        traversal routes right), always :meth:`_model_width` columns
        wide. Shared by the host ``map_table`` path and the serving
        kernel's encode so the two cannot diverge."""
        m = self.model
        if m.cat_cols:
            n = data.num_rows
            X = np.empty((n, len(m.feature_cols)), dtype)
            for j, c in enumerate(m.feature_cols):
                col = data.col(c)
                if c in m.cat_vocabs:
                    lut = {v: i for i, v in enumerate(m.cat_vocabs[c])}
                    X[:, j] = [lut.get(str(v), -1) for v in col]  # OOV -> right
                else:
                    X[:, j] = np.asarray(col, np.float64)
            return X
        width = self._model_width()
        design = extract_design(data, m.feature_cols, m.vector_col,
                                np.float64,
                                vector_size=width if m.vector_col else None)
        X = design["X"] if design["kind"] == "dense" else None
        if X is None:
            from ....common.vector import SparseBatch
            X = SparseBatch(design["idx"], design["val"],
                            design["dim"]).to_dense(np.float64)
        if X.shape[1] < width:          # batch narrower than the splits
            X = np.concatenate(
                [X, np.zeros((X.shape[0], width - X.shape[1]), X.dtype)],
                axis=1)
        return np.asarray(X, dtype)

    def _cat_mask(self) -> Optional[np.ndarray]:
        m = self.model
        return (np.asarray([c in set(m.cat_cols) for c in
                            (m.feature_cols or [])], bool)
                if m.cat_cols else None)

    def map_table(self, data: MTable) -> MTable:
        m = self.model
        X = self._encode_matrix(data)
        T = m.features.shape[0]
        n = X.shape[0]
        cat_mask = self._cat_mask()

        def apply(t):
            return tree_apply_values(
                X, m.features[t], m.thresholds[t], m.max_depth,
                cat_mask=cat_mask,
                split_masks=(m.split_masks[t]
                             if m.split_masks is not None else None))

        if m.algo == "gbdt":
            score = np.full(n, m.base_score)
            for t in range(T):
                score += m.learning_rate * m.leaf_values[t][apply(t)]
            if m.is_regression:
                return self._emit(data, score, None, None)
            p_pos = 1.0 / (1.0 + np.exp(-np.clip(score, -500, 500)))
            probs = np.stack([1 - p_pos, p_pos], axis=1)  # labels sorted asc
            return self._emit(data, None, probs, m.labels)
        # random forest / decision tree
        if m.is_regression:
            acc = np.zeros(n)
            for t in range(T):
                acc += m.leaf_values[t][apply(t)]
            return self._emit(data, acc / T, None, None)
        k = m.leaf_values.shape[2]
        probs = np.zeros((n, k))
        for t in range(T):
            probs += m.leaf_values[t][apply(t)]
        probs /= np.maximum(probs.sum(1, keepdims=True), 1e-12)
        return self._emit(data, None, probs, m.labels)

    def serving_kernel(self):
        """Compiled-serving contract (serving/predictor.py) for the tree
        family — the gathered leaf-index traversal: every level of every
        tree is ONE batched gather of (feature, threshold[, split-mask])
        at the current node frontier, ``node -> 2*node + go_right``, and
        after ``max_depth`` levels the leaf values gather per tree and
        accumulate in the HOST mapper's exact order (a ``lax.scan`` over
        the tree axis whose xs are the already-rounded per-tree terms —
        serving/sharded.py ``scan_sum``). On the f64 test mesh the device
        scores are therefore bitwise-identical to the numpy traversal,
        so labels AND detail strings match the host mapper exactly; the
        per-row integer traversal makes bucket padding a bitwise no-op.
        The kernel signature carries tree GEOMETRY only (T, depth, node
        count, leaf arity, feature count) — weights (thresholds, leaf
        values, base score) are program arguments, so hot-swapped
        same-shaped forests reuse every compiled program."""
        m = self.model
        if m is None:
            raise RuntimeError(
                "load_model must be called before serving_kernel")
        import jax

        from ....serving.predictor import ServingKernel
        ship_dt = np.float64 if jax.config.jax_enable_x64 else np.float32
        T, nodes = m.features.shape
        depth = int(m.max_depth)
        n_class = (int(m.leaf_values.shape[2])
                   if m.leaf_values.ndim == 3 else 0)
        cat_mask = self._cat_mask()
        has_masks = m.split_masks is not None and cat_mask is not None
        n_bins = int(m.split_masks.shape[2]) if has_masks else 0
        n_feat = int(len(m.feature_cols)) if m.feature_cols else None
        gbdt = m.algo == "gbdt"

        model_arrays = [np.asarray(m.features, np.int32),
                        np.asarray(m.thresholds, ship_dt),
                        np.asarray(m.leaf_values, ship_dt),
                        np.asarray(m.base_score, ship_dt),
                        np.asarray(m.learning_rate, ship_dt)]
        if has_masks:
            model_arrays.append(np.asarray(m.split_masks, bool))
            model_arrays.append(np.asarray(cat_mask, bool))
        model_arrays = tuple(model_arrays)
        signature = ("tree", m.algo, bool(m.is_regression), T, depth,
                     nodes, n_class, n_feat, has_masks, n_bins,
                     str(ship_dt.__name__))

        def encode(data: MTable, bucket: int):
            Xf = self._encode_matrix(data, ship_dt)
            X = np.zeros((bucket, Xf.shape[1]), ship_dt)
            X[:data.num_rows] = Xf
            return ("dense", (X,))

        def _apply_all(mdl, X):
            """(n, T) leaf indices — the vectorized device twin of the
            host ``tree_apply_values`` descent."""
            import jax.numpy as jnp
            features, thresholds = mdl[0], mdl[1]
            n = X.shape[0]
            tr = jnp.arange(T)[None, :]
            rows = jnp.arange(n)[:, None]
            node = jnp.zeros((n, T), jnp.int32)
            offset = 0
            for _level in range(depth):
                gi = offset + node
                f = features[tr, gi]
                thr = thresholds[tr, gi]
                x = X[rows, jnp.maximum(f, 0)]
                go_right = (f >= 0) & (x > thr)
                if has_masks:
                    masks, catm = mdl[5], mdl[6]
                    code = jnp.round(x).astype(jnp.int32)
                    in_left = jnp.where(
                        code >= 0,
                        masks[tr, gi, jnp.clip(code, 0, n_bins - 1)],
                        False)
                    is_cat = catm[jnp.maximum(f, 0)] & (f >= 0)
                    go_right = jnp.where(is_cat, (f >= 0) & ~in_left,
                                         go_right)
                node = node * 2 + go_right
                offset += 1 << _level
            return node, tr

        def _score(mdl, X):
            from ....serving.sharded import scan_sum
            leafs, base, lr = mdl[2], mdl[3], mdl[4]
            node, tr = _apply_all(mdl, X)
            if gbdt:
                # host order: score = full(base); score += lr*leaf[t]
                # per tree, left to right — the scan carry starts at
                # base and adds the rounded lr*leaf terms, reproducing
                # the numpy loop bitwise
                return _gbdt_acc(base, lr * leafs[tr, node])
            # rf/dt: per-tree leaf stats sum over the tree axis — (n,)
            # regression / (n, k) classification; decode normalizes
            return scan_sum(leafs[tr, node], axis=1)

        def _gbdt_acc(base, terms):
            """base + terms[0] + terms[1] + ... in the host loop's exact
            association: the scan carry STARTS at base."""
            import jax
            import jax.numpy as jnp
            t = jnp.moveaxis(terms, 1, 0)
            acc0 = jnp.broadcast_to(base, (terms.shape[0],)).astype(
                terms.dtype)

            def body(acc, x):
                return acc + x, None

            acc, _ = jax.lax.scan(body, acc0, t)
            return acc

        def decode(outputs, data: MTable) -> MTable:
            out = np.asarray(outputs[0], np.float64)
            if gbdt:
                if m.is_regression:
                    return self._emit(data, out, None, None)
                p_pos = 1.0 / (1.0 + np.exp(-np.clip(out, -500, 500)))
                probs = np.stack([1 - p_pos, p_pos], axis=1)
                return self._emit(data, None, probs, m.labels)
            if m.is_regression:
                return self._emit(data, out / T, None, None)
            probs = out / np.maximum(out.sum(1, keepdims=True), 1e-12)
            return self._emit(data, None, probs, m.labels)

        return ServingKernel(signature=signature,
                             model_arrays=model_arrays,
                             encode=encode, device_fns={"dense": _score},
                             decode=decode)

    def _emit(self, data, scores, probs, labels):
        m = self.model
        pred_col = self.params._m.get("prediction_col", "pred")
        detail_col = self.params._m.get("prediction_detail_col")
        reserved = self.params._m.get("reserved_cols")
        if probs is None:
            helper = OutputColsHelper(data.schema, [pred_col],
                                      [AlinkTypes.DOUBLE], reserved)
            return helper.build_output(data, [scores])
        pick = probs.argmax(1)
        preds = np.empty(len(pick), object)
        preds[:] = [labels[i] for i in pick]
        cols, types, vals = [pred_col], [m.label_type], [preds]
        if detail_col:
            details = np.asarray(
                [json.dumps({str(l): float(p) for l, p in zip(labels, row)})
                 for row in probs], object)
            cols.append(detail_col)
            types.append(AlinkTypes.STRING)
            vals.append(details)
        helper = OutputColsHelper(data.schema, cols, types, reserved)
        return helper.build_output(data, vals)


class _TreePredictBase(ModelMapBatchOp, HasPredictionCol, HasPredictionDetailCol,
                       HasReservedCols):
    MAPPER_CLS = TreeModelMapper


class GbdtPredictBatchOp(_TreePredictBase):
    pass


class GbdtRegPredictBatchOp(_TreePredictBase):
    pass


class RandomForestPredictBatchOp(_TreePredictBase):
    pass


class RandomForestRegPredictBatchOp(_TreePredictBase):
    pass


class DecisionTreePredictBatchOp(_TreePredictBase):
    pass


class DecisionTreeRegPredictBatchOp(_TreePredictBase):
    pass
