"""SQL-style stream operators.

Re-design of operator/stream/sql/ (Select/As/Where/Filter/UnionAll — the
stream subset of the batch SQL family) plus WindowGroupByStreamOp
(stream/sql/WindowGroupByStreamOp.java:40-75 — generates TUMBLE/HOP/SESSION
window SQL in the reference; here event-time tumbling/hopping windows over
the micro-batch stream with the same aggregate clause language).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ....common.mtable import MTable
from ....common.params import ParamInfo, Params
from ....common.types import AlinkTypes, TableSchema
from ...base import BatchOperator, StreamOperator
from ...batch.sql import (GroupByBatchOp, SelectBatchOp, _as_bool,
                          evaluate_expr)
from ..core import BaseStreamTransformOp, BatchApplyStreamOp

_CLAUSE = ParamInfo("clause", str, "expression clause", optional=False)


class BaseSqlApiStreamOp(BaseStreamTransformOp):
    """Base of the SQL-clause stream operators (reference
    stream/sql/BaseSqlApiStreamOp.java)."""


class SelectStreamOp(BatchApplyStreamOp):
    """reference: stream/sql/SelectStreamOp."""
    CLAUSE = _CLAUSE

    def _batch_cls(self):
        return SelectBatchOp


class AsStreamOp(BaseStreamTransformOp):
    CLAUSE = _CLAUSE

    def _open(self, in_schema):
        names = [n.strip() for n in self.get_clause().split(",")]
        return TableSchema(names, list(in_schema.types))

    def _transform(self, mt):
        return mt.rename([n.strip() for n in self.get_clause().split(",")])


class WhereStreamOp(BaseStreamTransformOp):
    CLAUSE = _CLAUSE

    def _transform(self, mt):
        return mt.filter_mask(_as_bool(evaluate_expr(mt, self.get_clause())))


class FilterStreamOp(WhereStreamOp):
    pass


class UnionAllStreamOp(StreamOperator):
    """Event-time merge of streams (reference stream/sql/UnionAllStreamOp)."""

    def link_from(self, *inputs: StreamOperator) -> "UnionAllStreamOp":
        from ..core import merge_timed
        try:
            self._schema = inputs[0].get_schema()
        except RuntimeError:
            self._schema = None  # upstream schema data-dependent

        def gen():
            for t, _, mt in merge_timed(*[i.timed_batches() for i in inputs]):
                yield (t, mt)

        self._stream_fn = gen
        return self


class WindowGroupByStreamOp(StreamOperator):
    """Tumbling/hopping event-time window group-by.

    reference: stream/sql/WindowGroupByStreamOp.java:40-75 (TUMBLE/HOP/
    SESSION window SQL). ``window_length`` / ``slide_length`` are in event-
    time units (the sources' simulated seconds); each closed window runs the
    batch group-by aggregate clause and emits one result table stamped with
    the window end.
    """

    GROUP_BY_CLAUSE = ParamInfo("group_by_clause", str, optional=False)
    SELECT_CLAUSE = ParamInfo("select_clause", str, optional=False)
    WINDOW_LENGTH = ParamInfo("window_length", float, default=1.0)
    SLIDE_LENGTH = ParamInfo("slide_length", float, default=None)

    def link_from(self, in_op: StreamOperator) -> "WindowGroupByStreamOp":
        length = float(self.get_window_length())
        slide = self.params._m.get("slide_length") or length

        def agg(tbl: MTable) -> MTable:
            op = GroupByBatchOp(group_by_predicate=self.get_group_by_clause(),
                                select_clause=self.get_select_clause())
            op.link_from(BatchOperator.from_table(tbl))
            return op.get_output_table()

        def window_table(pending, lo, hi):
            """Rows with lo <= t < hi (HOP windows overlap, so rows stay in
            ``pending`` until they age past every window containing them)."""
            parts = [mt for pt, mt in pending if lo <= pt < hi]
            if not parts:
                return None
            whole = parts[0]
            for p in parts[1:]:
                whole = whole.concat_rows(p)
            return whole

        def gen():
            pending: List = []   # (t, MTable), time-ordered
            window_end = None
            for t, mt in in_op.timed_batches():
                if window_end is None:
                    # first slide-aligned window end after t (Flink HOP
                    # emits every `slide`, windows cover [end-length, end))
                    window_end = (np.floor(t / slide) + 1) * slide
                while t >= window_end:
                    whole = window_table(pending, window_end - length, window_end)
                    if whole is not None:
                        yield (window_end, agg(whole))
                    window_end += slide
                    pending = [(pt, m) for pt, m in pending
                               if pt >= window_end - length]
                pending.append((t, mt))
            while pending:
                we = window_end if window_end is not None else length
                whole = window_table(pending, we - length, we)
                if whole is not None:
                    yield (we, agg(whole))
                window_end = we + slide
                pending = [(pt, m) for pt, m in pending
                           if pt >= window_end - length]

        self._stream_fn = gen
        # schema resolved on first window; aggregates can't be probed empty.
        self._schema = None
        return self
