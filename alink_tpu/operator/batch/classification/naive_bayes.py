"""Naive Bayes operators.

Re-design of common/classification/NaiveBayesText* (multinomial/bernoulli
over vector features) and the mixed categorical/gaussian NaiveBayes
(batch/classification/NaiveBayesTrainBatchOp). Fitting is one pass of
label-grouped sufficient statistics (psum-able count vectors).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np

from ....common.mtable import MTable
from ....common.params import InValidator, ParamInfo, Params
from ....common.types import AlinkTypes, TableSchema
from ....common.vector import SparseBatch
from ....mapper.base import ModelMapper, OutputColsHelper
from ....model.converters import (SimpleModelDataConverter, decode_array,
                                  encode_array)
from ....params.shared import (HasFeatureCols, HasLabelCol, HasPredictionCol,
                               HasPredictionDetailCol, HasReservedCols,
                               HasVectorCol, HasWeightCol)
from ...base import BatchOperator
from ...common.dataproc.feature_extract import extract_design
from ..utils.model_map import ModelMapBatchOp


class NaiveBayesTextModelConverter(SimpleModelDataConverter):
    def serialize_model(self, model):
        meta = Params({"model_type": model["model_type"],
                       "vector_col": model["vector_col"],
                       "label_type": model["label_type"],
                       "labels": [str(l) for l in model["labels"]]})
        return meta, [encode_array(model["log_prior"]),
                      encode_array(model["log_prob"])]

    def deserialize_model(self, meta, data):
        labels = meta._m.get("labels", [])
        lt = meta._m.get("label_type", AlinkTypes.STRING)
        if lt in (AlinkTypes.LONG, AlinkTypes.INT):
            labels = [int(float(v)) for v in labels]
        elif lt in (AlinkTypes.DOUBLE, AlinkTypes.FLOAT):
            labels = [float(v) for v in labels]
        return {"model_type": meta._m.get("model_type", "Multinomial"),
                "vector_col": meta._m.get("vector_col"),
                "label_type": lt, "labels": labels,
                "log_prior": decode_array(data[0]),
                "log_prob": decode_array(data[1])}


class NaiveBayesTextTrainBatchOp(BatchOperator, HasLabelCol, HasVectorCol,
                                 HasWeightCol):
    """reference: batch/classification/NaiveBayesTextTrainBatchOp."""
    MODEL_TYPE = ParamInfo("model_type", str, default="Multinomial",
                           validator=InValidator(["Multinomial", "Bernoulli"]))
    SMOOTHING = ParamInfo("smoothing", float, default=1.0)

    def link_from(self, in_op: BatchOperator) -> "NaiveBayesTextTrainBatchOp":
        t = in_op.get_output_table()
        vec_col = self.params._m.get("vector_col")
        design = extract_design(t, None, vec_col, np.float64)
        X = design["X"] if design["kind"] == "dense" else \
            SparseBatch(design["idx"], design["val"], design["dim"]).to_dense(np.float64)
        label_col = self.get_label_col()
        raw = t.col(label_col)
        labels = sorted({str(v) for v in raw})
        label_type = t.schema.type_of(label_col)
        y = np.asarray([labels.index(str(v)) for v in raw])
        w = (np.asarray(t.col(self.params._m["weight_col"]), np.float64)
             if self.params._m.get("weight_col") else np.ones(len(y)))
        k, d = len(labels), X.shape[1]
        sm = self.get_smoothing()
        if self.get_model_type() == "Bernoulli":
            X = (X != 0).astype(np.float64)
        counts = np.zeros((k, d))
        prior = np.zeros(k)
        for c in range(k):
            mask = (y == c)
            counts[c] = (X[mask] * w[mask, None]).sum(0)
            prior[c] = w[mask].sum()
        if self.get_model_type() == "Bernoulli":
            log_prob = np.log((counts + sm) / (prior[:, None] + 2 * sm))
        else:
            log_prob = np.log((counts + sm) /
                              (counts.sum(1, keepdims=True) + sm * d))
        log_prior = np.log(prior / prior.sum())
        typed_labels = [_typed(l, label_type) for l in labels]
        self._output = NaiveBayesTextModelConverter().save_model({
            "model_type": self.get_model_type(), "vector_col": vec_col,
            "label_type": label_type, "labels": typed_labels,
            "log_prior": log_prior, "log_prob": log_prob})
        return self


def _typed(v: str, label_type: str):
    if label_type in (AlinkTypes.LONG, AlinkTypes.INT):
        return int(float(v))
    if label_type in (AlinkTypes.DOUBLE, AlinkTypes.FLOAT):
        return float(v)
    return v


class NaiveBayesTextModelMapper(ModelMapper):
    def __init__(self, model_schema, data_schema, params=None, **kwargs):
        super().__init__(model_schema, data_schema, params, **kwargs)
        self.model = None

    def load_model(self, model_table: MTable):
        self.model = NaiveBayesTextModelConverter().load_model(model_table)

    def map_table(self, data: MTable) -> MTable:
        m = self.model
        d = m["log_prob"].shape[1]
        design = extract_design(data, None, m["vector_col"], np.float64,
                                vector_size=d)
        X = design["X"] if design["kind"] == "dense" else \
            SparseBatch(design["idx"], design["val"], design["dim"]).to_dense(np.float64)
        if X.shape[1] < d:
            X = np.concatenate([X, np.zeros((X.shape[0], d - X.shape[1]))], 1)
        if m["model_type"] == "Bernoulli":
            Xb = (X != 0).astype(np.float64)
            lp = m["log_prob"]
            lq = np.log1p(-np.exp(np.minimum(lp, -1e-12)))
            scores = Xb @ lp.T + (1 - Xb) @ lq.T + m["log_prior"]
        else:
            scores = X @ m["log_prob"].T + m["log_prior"]
        pick = scores.argmax(1)
        probs = np.exp(scores - scores.max(1, keepdims=True))
        probs /= probs.sum(1, keepdims=True)
        pred_col = self.params._m.get("prediction_col", "pred")
        detail_col = self.params._m.get("prediction_detail_col")
        preds = np.empty(len(pick), object)
        preds[:] = [m["labels"][i] for i in pick]
        cols, types, vals = [pred_col], [m["label_type"]], [preds]
        if detail_col:
            details = np.asarray(
                [json.dumps({str(l): float(p) for l, p in zip(m["labels"], row)})
                 for row in probs], object)
            cols.append(detail_col)
            types.append(AlinkTypes.STRING)
            vals.append(details)
        helper = OutputColsHelper(data.schema, cols, types,
                                  self.params._m.get("reserved_cols"))
        return helper.build_output(data, vals)


class NaiveBayesTextPredictBatchOp(ModelMapBatchOp, HasPredictionCol,
                                   HasPredictionDetailCol, HasReservedCols):
    MAPPER_CLS = NaiveBayesTextModelMapper


# ---------------------------------------------------------------------------
# Mixed categorical/gaussian NaiveBayes over table columns
# ---------------------------------------------------------------------------

class NaiveBayesModelConverter(SimpleModelDataConverter):
    def serialize_model(self, model):
        meta = Params({"feature_cols": model["feature_cols"],
                       "is_cat": model["is_cat"],
                       "label_type": model["label_type"],
                       "labels": [str(l) for l in model["labels"]]})
        return meta, [json.dumps(model["stats"]), encode_array(model["log_prior"])]

    def deserialize_model(self, meta, data):
        labels = meta._m.get("labels", [])
        lt = meta._m.get("label_type", AlinkTypes.STRING)
        if lt in (AlinkTypes.LONG, AlinkTypes.INT):
            labels = [int(float(v)) for v in labels]
        elif lt in (AlinkTypes.DOUBLE, AlinkTypes.FLOAT):
            labels = [float(v) for v in labels]
        return {"feature_cols": meta._m["feature_cols"],
                "is_cat": meta._m["is_cat"], "labels": labels, "label_type": lt,
                "stats": json.loads(data[0]), "log_prior": decode_array(data[1])}


class NaiveBayesTrainBatchOp(BatchOperator, HasLabelCol, HasFeatureCols,
                             HasWeightCol):
    """reference: batch/classification/NaiveBayesTrainBatchOp (categorical
    columns -> smoothed frequency tables, numeric -> gaussians)."""
    SMOOTHING = ParamInfo("smoothing", float, default=1.0)

    def link_from(self, in_op: BatchOperator) -> "NaiveBayesTrainBatchOp":
        t = in_op.get_output_table()
        label_col = self.get_label_col()
        cols = self.params._m.get("feature_cols") or \
            [c for c in t.col_names if c != label_col]
        raw = t.col(label_col)
        labels = sorted({str(v) for v in raw})
        y = np.asarray([labels.index(str(v)) for v in raw])
        w = (np.asarray(t.col(self.params._m["weight_col"]), np.float64)
             if self.params._m.get("weight_col") else np.ones(len(y)))
        sm = self.get_smoothing()
        is_cat = [not AlinkTypes.is_numeric(t.schema.type_of(c)) for c in cols]
        stats = []
        prior = np.asarray([w[y == c].sum() for c in range(len(labels))], np.float64)
        for c, cat in zip(cols, is_cat):
            col = t.col(c)
            if cat:
                values = sorted({str(v) for v in col})
                table = {}
                for ci in range(len(labels)):
                    cnt = {val: 0.0 for val in values}
                    tot = sm * len(values)
                    for v, yy, wt in zip(col, y, w):
                        if yy == ci:
                            cnt[str(v)] += wt
                            tot += wt
                    table[str(ci)] = {val: float(np.log((cnt[val] + sm) / tot))
                                      for val in values}
                stats.append({"kind": "cat", "table": table})
            else:
                v = np.asarray(col, np.float64)
                mu, var = [], []
                for ci in range(len(labels)):
                    sub, sw = v[y == ci], w[y == ci]
                    tot = max(sw.sum(), 1e-12)
                    if sub.size:
                        m_ = float((sub * sw).sum() / tot)
                        mu.append(m_)
                        var.append(float(((sub - m_) ** 2 * sw).sum() / tot + 1e-9))
                    else:
                        mu.append(0.0)
                        var.append(1.0)
                stats.append({"kind": "gauss", "mu": mu, "var": var})
        label_type = t.schema.type_of(label_col)
        self._output = NaiveBayesModelConverter().save_model({
            "feature_cols": cols, "is_cat": is_cat,
            "labels": [_typed(l, label_type) for l in labels],
            "label_type": label_type,
            "stats": stats, "log_prior": np.log(prior / prior.sum())})
        return self


class NaiveBayesModelMapper(ModelMapper):
    def __init__(self, model_schema, data_schema, params=None, **kwargs):
        super().__init__(model_schema, data_schema, params, **kwargs)
        self.model = None

    def load_model(self, model_table: MTable):
        self.model = NaiveBayesModelConverter().load_model(model_table)

    def map_table(self, data: MTable) -> MTable:
        m = self.model
        k = len(m["labels"])
        n = data.num_rows
        scores = np.tile(m["log_prior"], (n, 1))
        for c, stat in zip(m["feature_cols"], m["stats"]):
            col = data.col(c)
            if stat["kind"] == "cat":
                floor = np.log(1e-12)
                for ci in range(k):
                    table = stat["table"][str(ci)]
                    scores[:, ci] += np.asarray(
                        [table.get(str(v), floor) for v in col])
            else:
                v = np.asarray(col, np.float64)
                mu = np.asarray(stat["mu"])
                var = np.asarray(stat["var"])
                scores += (-0.5 * np.log(2 * np.pi * var)[None, :]
                           - 0.5 * (v[:, None] - mu[None, :]) ** 2 / var[None, :])
        pick = scores.argmax(1)
        probs = np.exp(scores - scores.max(1, keepdims=True))
        probs /= probs.sum(1, keepdims=True)
        preds = np.empty(n, object)
        preds[:] = [m["labels"][i] for i in pick]
        pred_col = self.params._m.get("prediction_col", "pred")
        detail_col = self.params._m.get("prediction_detail_col")
        cols, types, vals = [pred_col], [m["label_type"]], [preds]
        if detail_col:
            details = np.asarray(
                [json.dumps({str(l): float(p) for l, p in zip(m["labels"], row)})
                 for row in probs], object)
            cols.append(detail_col)
            types.append(AlinkTypes.STRING)
            vals.append(details)
        helper = OutputColsHelper(data.schema, cols, types,
                                  self.params._m.get("reserved_cols"))
        return helper.build_output(data, vals)


class NaiveBayesPredictBatchOp(ModelMapBatchOp, HasPredictionCol,
                               HasPredictionDetailCol, HasReservedCols):
    MAPPER_CLS = NaiveBayesModelMapper
