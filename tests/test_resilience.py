"""Serving & stream resilience tier (ISSUE 14): deadlines, load
shedding, circuit-broken degradation, supervised feeders, generalized
fault modes.

The load-bearing invariants:
  * a deadline-shed request NEVER reaches the compiled program — the
    typed DeadlineExceeded lands through the future before the dispatch
    is paid (counted via the serving metrics);
  * the circuit breaker's closed -> open -> half-open -> closed sequence
    is DETERMINISTIC under a scripted fault schedule, including the
    no-flap rule (a failed half-open probe re-opens with the NEXT
    backoff step, not the first);
  * supervised feeders retry transient swap failures, skip-and-record
    poisoned snapshots, and the server keeps serving the LAST GOOD
    model either way;
  * a crashed serving loop quarantines its in-flight requests (typed
    rejection, never silence) and respawns;
  * default flags + no armed faults = the exact pre-resilience serving
    behavior (responses bitwise vs the host mapper, zero resilience
    counters moving).
"""

import threading
import time
import warnings

import numpy as np
import pytest

from alink_tpu.common.faults import (FAULT_ENV, FaultInjected, FaultRule,
                                     TransientFault, fault_spec,
                                     maybe_crash, reset_faults)
from alink_tpu.common.mtable import MTable
from alink_tpu.common.params import Params
from alink_tpu.common.vector import DenseVector
from alink_tpu.operator.batch.classification.linear import (
    LogisticRegressionTrainBatchOp)
from alink_tpu.operator.batch.source.sources import MemSourceBatchOp
from alink_tpu.operator.common.linear.mapper import LinearModelMapper
from alink_tpu.serving import (CompiledPredictor, DeadlineExceeded,
                               ModelStreamFeeder, PredictServer,
                               ReplicaCrashed, RequestCancelled)
from alink_tpu.serving.resilience import (CLOSED, HALF_OPEN, OPEN,
                                          CircuitBreaker,
                                          _reset_feeder_warnings)


@pytest.fixture
def fresh_registry():
    from alink_tpu.common.metrics import MetricsRegistry, set_registry
    reg = MetricsRegistry()
    prev = set_registry(reg)
    try:
        yield reg
    finally:
        set_registry(prev)


@pytest.fixture
def clean_faults(monkeypatch):
    """Arm-from-zero fault state: counters reset before AND after, env
    cleared after (the reset_faults satellite contract)."""
    reset_faults()
    yield monkeypatch
    monkeypatch.delenv(FAULT_ENV, raising=False)
    reset_faults()


def _metric(reg, name, **labels):
    total = 0.0
    found = False
    for rec in reg.snapshot():
        if rec["name"] != name:
            continue
        lb = rec.get("labels") or {}
        if all(lb.get(k) == v for k, v in labels.items()):
            total += rec.get("value") or 0.0
            found = True
    return total if found else 0.0


@pytest.fixture(scope="module")
def base():
    """One shared trained model for the default-geometry tests (the
    mapper is immutable post-load; every test builds its OWN predictor
    and server). Variant-seed tests call :func:`_fixture` directly."""
    return _fixture()


def _fixture(seed=0, n=192, d=12):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d)
    y = (X @ rng.randn(d) > 0).astype(np.int64)
    vecs = np.empty(n, object)
    vecs[:] = [DenseVector(X[i]) for i in range(n)]
    tbl = MTable({"vec": vecs, "label": y}, "vec VECTOR, label LONG")
    warm = LogisticRegressionTrainBatchOp(
        vector_col="vec", label_col="label",
        max_iter=3).link_from(MemSourceBatchOp(tbl))
    data_schema = tbl.select(["vec"]).schema
    mapper = LinearModelMapper(warm.get_output_table().schema, data_schema,
                               Params({"prediction_col": "pred",
                                       "vector_col": "vec"}))
    mapper.load_model(warm.get_output_table())
    return tbl, warm, mapper, data_schema


# ---------------------------------------------------------------------------
# fault-mode grammar (common/faults.py)
# ---------------------------------------------------------------------------

class TestFaultGrammar:
    def test_kill_backward_compat(self, clean_faults):
        clean_faults.setenv(FAULT_ENV, "a.b:3; c.d:1")
        maybe_crash("a.b", 2)
        maybe_crash("other", 99)
        with pytest.raises(FaultInjected) as ei:
            maybe_crash("a.b", 5)      # open-ended window: >= 3 fires
        assert ei.value.site == "a.b" and ei.value.threshold == 3

    def test_range_window_clears(self, clean_faults):
        clean_faults.setenv(FAULT_ENV, "s.x:2-3:error")
        maybe_crash("s.x", 1)                       # below
        with pytest.raises(TransientFault):
            maybe_crash("s.x", 2)
        with pytest.raises(TransientFault):
            maybe_crash("s.x", 3)
        maybe_crash("s.x", 4)                       # the storm CLEARED

    def test_error_is_catchable_kill_is_distinct(self, clean_faults):
        clean_faults.setenv(FAULT_ENV, "s.y:1:error")
        with pytest.raises(TransientFault) as ei:
            maybe_crash("s.y", 1)
        assert not isinstance(ei.value, FaultInjected)
        assert isinstance(ei.value, RuntimeError)

    def test_delay_sleeps_and_returns_false(self, clean_faults):
        clean_faults.setenv(FAULT_ENV, "s.d:1:delay:60")
        t0 = time.perf_counter()
        assert maybe_crash("s.d", 1) is False
        assert time.perf_counter() - t0 >= 0.05

    def test_corrupt_signals_caller(self, clean_faults):
        clean_faults.setenv(FAULT_ENV, "s.c:2-2:corrupt")
        assert maybe_crash("s.c", 1) is False
        assert maybe_crash("s.c", 2) is True
        assert maybe_crash("s.c", 3) is False

    def test_auto_index_and_reset(self, clean_faults):
        clean_faults.setenv(FAULT_ENV, "s.auto:2-2:corrupt")
        assert maybe_crash("s.auto") is False       # visit 1
        assert maybe_crash("s.auto") is True        # visit 2
        reset_faults()                              # counters cleared
        assert maybe_crash("s.auto") is False       # visit 1 again
        assert maybe_crash("s.auto") is True        # visit 2 again

    def test_non_integer_index_names_site_and_env(self, clean_faults):
        clean_faults.setenv(FAULT_ENV, "serve.dispatch:oops")
        with pytest.raises(ValueError) as ei:
            fault_spec()
        msg = str(ei.value)
        assert FAULT_ENV in msg and "serve.dispatch" in msg \
            and "oops" in msg and "malformed" in msg

    def test_malformed_variants_refused(self, clean_faults):
        for bad in ("justasite", "s.x:1:frobnicate", "s.x:1:delay",
                    "s.x:1:delay:NaNms", "s.x:5-2:error",
                    "s.x:1:error:9"):
            clean_faults.setenv(FAULT_ENV, bad)
            with pytest.raises(ValueError, match="malformed"):
                fault_spec()

    def test_duplicate_site_refused(self, clean_faults):
        """Last-wins would silently drop the earlier rule — a storm
        spec that tests nothing; duplicates refuse loudly like every
        other malformed spec."""
        clean_faults.setenv(
            FAULT_ENV, "serve.dispatch:1-14:error;serve.dispatch:20:delay:30")
        with pytest.raises(ValueError, match="already has a rule"):
            fault_spec()

    def test_rule_window_semantics(self):
        r = FaultRule(3, None, "kill", 0.0)
        assert not r.active(2) and r.active(3) and r.active(10**9)
        r = FaultRule(3, 5, "error", 0.0)
        assert [r.active(i) for i in (2, 3, 5, 6)] == \
            [False, True, True, False]


# ---------------------------------------------------------------------------
# circuit breaker state machine (deterministic, scripted clock)
# ---------------------------------------------------------------------------

class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _breaker(clock, threshold=3, backoff=0.1, factor=2.0, max_s=1.0):
    return CircuitBreaker("t", 1, threshold=threshold, backoff_s=backoff,
                          factor=factor, max_s=max_s, clock=clock)


class TestCircuitBreaker:
    def test_full_cycle_pinned(self):
        """closed -> open -> half-open -> closed under a scripted fault
        schedule, transitions pinned exactly."""
        clk = _Clock()
        br = _breaker(clk)
        # closed: failures below threshold keep the compiled route
        for _ in range(2):
            assert br.acquire() == "compiled"
            br.on_failure()
        assert br.state == CLOSED
        # third consecutive failure trips it
        assert br.acquire() == "compiled"
        br.on_failure()
        assert br.state == OPEN and br.opens == 1
        # open: everything falls back until the backoff elapses
        assert br.acquire() == "fallback"
        clk.t = 0.11
        route = br.acquire()
        assert route == "probe" and br.state == HALF_OPEN
        # concurrent dispatch during the probe stays on the fallback
        assert br.acquire() == "fallback"
        br.on_success(probe=True)
        assert br.state == CLOSED
        assert [(f, t) for f, t, _ in br.transitions] == \
            [(CLOSED, OPEN), (OPEN, HALF_OPEN), (HALF_OPEN, CLOSED)]

    def test_no_flap_probe_failure_next_backoff_step(self):
        """A failed half-open probe re-opens with the NEXT backoff step:
        0.1 -> 0.2 -> 0.4, capped at max_s."""
        clk = _Clock()
        br = _breaker(clk, threshold=1)
        br.acquire()
        br.on_failure()                      # open, step 0 (backoff 0.1)
        assert br.backoff_for(0) == pytest.approx(0.1)
        clk.t = 0.11
        assert br.acquire() == "probe"
        br.on_failure(probe=True)            # re-open, step 1
        assert br.state == OPEN and br.reopens == 1
        clk.t += 0.11                        # 0.1 elapsed < 0.2: still open
        assert br.acquire() == "fallback"
        clk.t += 0.11                        # now past the 0.2 step
        assert br.acquire() == "probe"
        br.on_failure(probe=True)            # re-open, step 2 (0.4)
        assert br.reopens == 2
        clk.t += 0.41
        assert br.acquire() == "probe"
        br.on_success(probe=True)            # recovery resets the step
        assert br.state == CLOSED
        br.on_failure()                      # threshold=1: opens again
        assert br.snapshot()["step"] == 0    # fresh spell, first backoff

    def test_success_resets_consecutive_count(self):
        clk = _Clock()
        br = _breaker(clk, threshold=2)
        br.on_failure()
        br.on_success()
        br.on_failure()                      # 1 consecutive, not 2
        assert br.state == CLOSED

    def test_stale_signals_cannot_steal_the_probe_verdict(self):
        """Replica-fleet race (review hardening): a dispatch that
        STARTED before the trip lands its verdict after another
        replica's probe is in flight — neither a stale success (must
        not close / release the probe slot) nor a stale failure (must
        not re-open / bump the backoff step) moves the breaker; only
        the probe's own verdict does."""
        clk = _Clock()
        br = _breaker(clk, threshold=1)
        br.acquire()
        br.on_failure()                        # trip open
        clk.t = 0.11
        assert br.acquire() == "probe"         # replica C holds the slot
        br.on_success(probe=False)             # stale pre-trip success
        assert br.state == HALF_OPEN           # probe slot NOT released
        assert br.acquire() == "fallback"      # still exactly one probe
        br.on_failure(probe=False)             # stale pre-trip failure
        assert br.state == HALF_OPEN and br.reopens == 0
        br.on_success(probe=True)              # the probe's OWN verdict
        assert br.state == CLOSED

    def test_probe_slot_released_on_dispatch_escape(self, base,
                                                    clean_faults):
        """Review hardening: a probe-routed dispatch that dies OUTSIDE
        the paired handler (an injected kill) must still release the
        breaker slot — a leaked half-open probe would wedge the server
        in fallback forever."""
        clean_faults.setenv("ALINK_TPU_SERVE_BREAKER_THRESHOLD", "1")
        clean_faults.setenv("ALINK_TPU_SERVE_BREAKER_BACKOFF_MS", "30")
        tbl, _w, mapper, _s = base
        pred = CompiledPredictor(mapper, buckets=(1,), name="probeleak")
        pred.predict_table(tbl.select(["vec"]).first_n(1))
        srv = PredictServer(pred, max_batch=1, name="probeleak")
        row = tbl.select(["vec"]).row(0)
        try:
            reset_faults()
            # dispatch 1 fails (opens, threshold 1); dispatch 2 is the
            # half-open probe and DIES with a kill — the slot must be
            # released with the next backoff step, not leaked
            clean_faults.setenv(FAULT_ENV, "serve.dispatch:1-2:kill")
            with pytest.raises(ReplicaCrashed):
                srv.submit(row).result(30)
            assert srv.breaker_stats()["state"] == OPEN
            time.sleep(0.05)
            with pytest.raises(ReplicaCrashed):
                srv.submit(row).result(30)     # the probe, killed
            bs = srv.breaker_stats()
            assert bs["state"] == OPEN and bs["reopens"] == 1
            # past the NEXT backoff step the breaker probes again and
            # (the fault window over) recovers — not wedged
            time.sleep(0.12)
            assert srv.submit(row).result(30) is not None
            assert srv.breaker_stats()["state"] == CLOSED
        finally:
            srv.close()

    def test_backoff_schedule_deterministic_and_capped(self):
        br = _breaker(_Clock(), backoff=0.05, factor=3.0, max_s=0.2)
        assert [br.backoff_for(k) for k in range(4)] == \
            [pytest.approx(v) for v in (0.05, 0.15, 0.2, 0.2)]


# ---------------------------------------------------------------------------
# deadlines, shedding, cancellation (server integration)
# ---------------------------------------------------------------------------

class TestDeadlineShedding:
    def test_shed_request_never_reaches_compiled_program(
            self, base, clean_faults, fresh_registry):
        """THE regression (ISSUE 14 satellite): a deadline-shed request
        resolves to a typed DeadlineExceeded and the compiled program
        never sees it — dispatches counted via the serving metrics."""
        tbl, _w, mapper, _s = base
        pred = CompiledPredictor(mapper, buckets=(1, 4), name="shed")
        row = tbl.select(["vec"]).row(0)
        pred.predict_table(tbl.select(["vec"]).first_n(1))   # warm compile
        srv = PredictServer(pred, max_batch=1, name="shed")
        try:
            # stall the serving loop: the FIRST dispatch sleeps 300 ms
            # (injected latency), so the second request's queue wait
            # blows its 1 ms deadline deterministically
            reset_faults()
            clean_faults.setenv(FAULT_ENV, "serve.dispatch:1-1:delay:300")
            before = _metric(fresh_registry, "alink_serve_batches_total")
            f1 = srv.submit(row)
            time.sleep(0.05)                 # f1 is in its delayed dispatch
            f2 = srv.submit(row, deadline_s=0.001)
            assert f1.result(30) is not None
            with pytest.raises(DeadlineExceeded) as ei:
                f2.result(30)
            assert ei.value.deadline_s == pytest.approx(0.001)
            assert ei.value.waited_s > 0.001
            # exactly ONE batch was dispatched (f1's); the shed request
            # paid no compiled execution
            after = _metric(fresh_registry, "alink_serve_batches_total")
            assert after - before == 1
            assert _metric(fresh_registry, "alink_serve_shed_total",
                           reason="deadline") == 1
            st = srv.stats()
            assert st["shed"] == 1 and st["failed"] == 0
        finally:
            srv.close()

    def test_timeout_leaves_request_live(self, base, clean_faults):
        """result(timeout) raising TimeoutError does NOT cancel — the
        request still dispatches and the answer lands (the documented
        pre-deadline semantics, now stated in the error message)."""
        tbl, _w, mapper, _s = base
        pred = CompiledPredictor(mapper, buckets=(1,), name="late")
        pred.predict_table(tbl.select(["vec"]).first_n(1))
        srv = PredictServer(pred, max_batch=1, name="late")
        try:
            reset_faults()
            clean_faults.setenv(FAULT_ENV, "serve.dispatch:1-1:delay:150")
            fut = srv.submit(tbl.select(["vec"]).row(0))
            with pytest.raises(TimeoutError, match="deadline_s"):
                fut.result(0.005)
            assert fut.result(30) is not None      # still delivered
        finally:
            srv.close()

    def test_cancel_sheds_before_dispatch(self, base, clean_faults,
                                          fresh_registry):
        tbl, _w, mapper, _s = base
        pred = CompiledPredictor(mapper, buckets=(1,), name="cxl")
        pred.predict_table(tbl.select(["vec"]).first_n(1))
        srv = PredictServer(pred, max_batch=1, name="cxl")
        row = tbl.select(["vec"]).row(0)
        try:
            reset_faults()
            clean_faults.setenv(FAULT_ENV, "serve.dispatch:1-1:delay:200")
            f1 = srv.submit(row)
            time.sleep(0.05)
            f2 = srv.submit(row)
            assert f2.cancel() is True
            assert f1.result(30) is not None
            with pytest.raises(RequestCancelled):
                f2.result(30)
            assert f2.cancel() is False            # already resolved
            assert _metric(fresh_registry, "alink_serve_shed_total",
                           reason="cancelled") == 1
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# circuit-broken degradation (server integration, scripted fault storm)
# ---------------------------------------------------------------------------

class TestBreakerIntegration:
    def _server(self, base, monkeypatch, name):
        monkeypatch.setenv("ALINK_TPU_SERVE_BREAKER_THRESHOLD", "2")
        monkeypatch.setenv("ALINK_TPU_SERVE_BREAKER_BACKOFF_MS", "40")
        tbl, _w, mapper, _s = base
        pred = CompiledPredictor(mapper, buckets=(1, 4), name=name)
        req = tbl.select(["vec"])
        pred.predict_table(req.first_n(1))
        pred.predict_table(req.first_n(4))
        return tbl, mapper, PredictServer(pred, max_batch=1, name=name)

    def test_storm_opens_degrades_and_recovers(self, base, clean_faults,
                                               fresh_registry):
        """The tentpole integration: transient dispatch errors trip the
        breaker, open traffic serves CORRECT answers through the host
        mapper, and once the storm clears a half-open probe recovers
        the compiled path."""
        tbl, mapper, srv = self._server(base, clean_faults, "storm")
        row = tbl.select(["vec"]).row(0)
        expected = mapper.map_row(row)
        try:
            reset_faults()
            clean_faults.setenv(FAULT_ENV, "serve.dispatch:1-2:error")
            # dispatches 1-2 fail (closed-state contract: the batch
            # fails its own requests) and trip the threshold-2 breaker
            for _ in range(2):
                with pytest.raises(TransientFault):
                    srv.submit(row).result(30)
            assert srv.breaker_stats()["state"] == OPEN
            assert srv.breaker_stats()["opens"] == 1
            # open: requests SUCCEED through the host-mapper fallback —
            # degraded, not dropped — with correct answers
            out = srv.submit(row).result(30)
            assert out == tuple(expected)
            assert srv.stats()["fallback_batches"] >= 1
            # past the backoff the probe re-tests the compiled path;
            # the fault window (1-2) has cleared, so it succeeds
            time.sleep(0.06)
            compiled_before = _metric(fresh_registry,
                                      "alink_serve_batches_total")
            out = srv.submit(row).result(30)
            assert out == tuple(expected)
            assert srv.breaker_stats()["state"] == CLOSED
            # the recovery is measurable: the probe ran COMPILED
            assert _metric(fresh_registry,
                           "alink_serve_batches_total") \
                == compiled_before + 1
            st = srv.stats()
            assert st["failed"] == 2 and st["shed"] == 0
            assert _metric(fresh_registry,
                           "alink_serve_breaker_fallback_total") >= 1
        finally:
            srv.close()

    def test_failed_probe_reopens(self, base, clean_faults):
        """No-flap at the integration level: a storm outliving the first
        probe re-opens the breaker instead of flapping closed."""
        tbl, mapper, srv = self._server(base, clean_faults, "flap")
        row = tbl.select(["vec"]).row(0)
        expected = mapper.map_row(row)
        try:
            reset_faults()
            clean_faults.setenv(FAULT_ENV, "serve.dispatch:1-3:error")
            for _ in range(2):
                with pytest.raises(TransientFault):
                    srv.submit(row).result(30)
            assert srv.breaker_stats()["state"] == OPEN
            time.sleep(0.06)
            # the probe (dispatch 3) fails INSIDE the window: the batch
            # still serves through the fallback (degraded traffic stays
            # degraded) and the breaker re-opens at the next step
            out = srv.submit(row).result(30)
            assert out == tuple(expected)
            bs = srv.breaker_stats()
            assert bs["state"] == OPEN and bs["reopens"] == 1 \
                and bs["step"] == 1
        finally:
            srv.close()

    def test_breaker_disabled_restores_pre_resilience(self, base, clean_faults):
        clean_faults.setenv("ALINK_TPU_SERVE_BREAKER", "0")
        tbl, _mapper, srv = self._server(base, clean_faults, "nobrk")
        row = tbl.select(["vec"]).row(0)
        try:
            reset_faults()
            clean_faults.setenv(FAULT_ENV, "serve.dispatch:1-4:error")
            for _ in range(4):
                with pytest.raises(TransientFault):
                    srv.submit(row).result(30)
            st = srv.stats()
            assert st["fallback_batches"] == 0
            assert st["breaker"]["opens"] == 0
        finally:
            srv.close()

    def test_swap_resets_breaker_per_model_version(self, base, clean_faults):
        """A hot swap starts the NEW version's breaker closed — breaker
        state is per model version."""
        tbl, _mapper, srv = self._server(base, clean_faults, "perver")
        row = tbl.select(["vec"]).row(0)
        try:
            reset_faults()
            clean_faults.setenv(FAULT_ENV, "serve.dispatch:1-2:error")
            for _ in range(2):
                with pytest.raises(TransientFault):
                    srv.submit(row).result(30)
            assert srv.breaker_stats()["state"] == OPEN
            clean_faults.delenv(FAULT_ENV)
            _tbl2, warm2, _m2, _s2 = _fixture(seed=9)
            srv.swap_model(warm2.get_output_table())
            assert srv.submit(row).result(30) is not None
            bs = srv.breaker_stats()
            # the NEW version starts closed at step 0 (per-model-version
            # state); the retired version's trip stays in the run totals
            assert bs["state"] == CLOSED and bs["step"] == 0
            assert bs["opens"] == 1
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# supervised serving loops (crash -> quarantine + respawn)
# ---------------------------------------------------------------------------

class TestLoopRespawn:
    def test_kill_fault_quarantines_and_respawns(self, base, clean_faults,
                                                 fresh_registry):
        tbl, _w, mapper, _s = base
        pred = CompiledPredictor(mapper, buckets=(1,), name="crash")
        pred.predict_table(tbl.select(["vec"]).first_n(1))
        srv = PredictServer(pred, max_batch=1, name="crash")
        row = tbl.select(["vec"]).row(0)
        try:
            reset_faults()
            clean_faults.setenv(FAULT_ENV, "serve.dispatch:1-1:kill")
            fut = srv.submit(row)
            with pytest.raises(ReplicaCrashed) as ei:
                fut.result(30)
            assert isinstance(ei.value.cause, FaultInjected)
            # the respawned loop serves the next request normally
            assert srv.submit(row).result(30) is not None
            st = srv.stats()
            assert st["loop_respawns"] == 1 and st["quarantined"] == 1
            assert _metric(fresh_registry,
                           "alink_serve_loop_respawns_total",
                           server="crash") == 1
        finally:
            srv.close()

    def test_channel_fault_respawns_loop(self, base, clean_faults):
        """A prefetch.get fault (the admission channel itself) is a
        loop crash too — supervised the same way."""
        tbl, _w, mapper, _s = base
        pred = CompiledPredictor(mapper, buckets=(1,), name="chfault")
        pred.predict_table(tbl.select(["vec"]).first_n(1))
        reset_faults()
        # the serving loop's FIRST get crashes; later gets are clean
        clean_faults.setenv(FAULT_ENV, "prefetch.get:1-1:error")
        srv = PredictServer(pred, max_batch=1, name="chfault")
        row = tbl.select(["vec"]).row(0)
        try:
            assert srv.submit(row).result(30) is not None
            assert srv.stats()["loop_respawns"] >= 1
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# supervised feeders
# ---------------------------------------------------------------------------

class _ListStream:
    """A minimal stream op: timed_batches() yields the given tables."""

    def __init__(self, tables):
        self._tables = list(tables)

    def timed_batches(self):
        for i, t in enumerate(self._tables):
            yield (float(i), t)


def _corrupt_copy(model_table):
    from alink_tpu.operator.stream.onlinelearning.ftrl import (
        _corrupt_snapshot_table)
    return _corrupt_snapshot_table(model_table)


class TestFeederSupervision:
    def test_poisoned_snapshot_skips_and_keeps_last_good(
            self, base, clean_faults, fresh_registry):
        tbl, warm, mapper, _s = base
        _t2, warm2, _m2, _s2 = _fixture(seed=5)
        pred = CompiledPredictor(mapper, buckets=(1, 4), name="poison")
        srv = PredictServer(pred, name="poison")
        good1 = warm.get_output_table()
        good2 = warm2.get_output_table()
        bad = _corrupt_copy(good1)
        _reset_feeder_warnings()
        try:
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                feeder = ModelStreamFeeder(
                    srv, _ListStream([good1, bad, good2])).start()
                swaps = feeder.join(timeout=60)
            assert swaps == 2                       # the bad one skipped
            assert feeder.skipped == 1
            # last-good guarantee: the active version is good2's swap
            assert srv.stats()["model_version"] == \
                feeder.versions[-1][0]
            assert _metric(fresh_registry,
                           "alink_serve_feeder_errors_total",
                           feeder="ModelStreamFeeder",
                           kind="poisoned") == 1
            warns = [w for w in caught
                     if "poisoned" in str(w.message)]
            assert len(warns) == 1                  # once per feeder+kind
        finally:
            srv.close()

    def test_transient_swap_failures_retry_then_succeed(
            self, base, clean_faults, fresh_registry):
        tbl, warm, mapper, _s = base
        pred = CompiledPredictor(mapper, buckets=(1,), name="retry")
        srv = PredictServer(pred, name="retry")
        clean_faults.setenv("ALINK_TPU_SERVE_FEEDER_BACKOFF_MS", "5")
        _reset_feeder_warnings()
        try:
            reset_faults()
            # swap visits 1-2 fail transiently; visit 3 (the 2nd retry)
            # succeeds — inside the default retry budget of 3
            clean_faults.setenv(FAULT_ENV, "serve.swap:1-2:error")
            feeder = ModelStreamFeeder(
                srv, _ListStream([warm.get_output_table()])).start()
            swaps = feeder.join(timeout=60)
            assert swaps == 1 and feeder.retried == 2
            assert _metric(fresh_registry,
                           "alink_serve_feeder_retries_total",
                           feeder="ModelStreamFeeder") == 2
            assert _metric(fresh_registry,
                           "alink_serve_feeder_errors_total",
                           feeder="ModelStreamFeeder",
                           kind="transient") == 2
        finally:
            srv.close()

    def test_retry_budget_exhausted_is_fatal_and_recorded(
            self, base, clean_faults, fresh_registry):
        tbl, warm, mapper, _s = base
        pred = CompiledPredictor(mapper, buckets=(1,), name="fatal")
        srv = PredictServer(pred, name="fatal")
        clean_faults.setenv("ALINK_TPU_SERVE_FEEDER_BACKOFF_MS", "2")
        clean_faults.setenv("ALINK_TPU_SERVE_FEEDER_RETRIES", "1")
        _reset_feeder_warnings()
        try:
            reset_faults()
            clean_faults.setenv(FAULT_ENV, "serve.swap:1-50:error")
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                feeder = ModelStreamFeeder(
                    srv, _ListStream([warm.get_output_table()])).start()
                with pytest.raises(TransientFault):
                    feeder.join(timeout=60)
            # visible AT the failure, not only via the join re-raise
            assert _metric(fresh_registry,
                           "alink_serve_feeder_errors_total",
                           feeder="ModelStreamFeeder", kind="fatal") == 1
            assert any("fatal" in str(w.message) for w in caught)
            # the server still serves the warm-start model (version 1)
            assert srv.stats()["model_version"] == 1
        finally:
            srv.close()

    def test_ftrl_corrupt_snapshot_end_to_end(self, clean_faults,
                                              fresh_registry):
        """feeder.snapshot:1-1:corrupt poisons exactly the FIRST emitted
        FTRL snapshot; the supervised feeder skips it, swaps the later
        good ones, zero torn serving."""
        from alink_tpu.operator.stream.onlinelearning.ftrl import (
            FtrlTrainStreamOp)
        from alink_tpu.operator.stream.source.sources import (
            MemSourceStreamOp)
        tbl, warm, mapper, _s = _fixture(n=256)
        pred = CompiledPredictor(mapper, buckets=(1, 4), name="ftrlpois")
        srv = PredictServer(pred, name="ftrlpois")
        _reset_feeder_warnings()
        try:
            reset_faults()
            clean_faults.setenv(FAULT_ENV, "feeder.snapshot:1-1:corrupt")
            src = MemSourceStreamOp(tbl, batch_size=64)
            ftrl = FtrlTrainStreamOp(warm, vector_col="vec",
                                     label_col="label", alpha=0.1,
                                     update_mode="batch",
                                     time_interval=1.0).link_from(src)
            feeder = ModelStreamFeeder(srv, ftrl).start()
            swaps = feeder.join(timeout=120)
            assert feeder.skipped == 1 and swaps >= 1
            assert _metric(fresh_registry,
                           "alink_serve_feeder_errors_total",
                           feeder="ModelStreamFeeder",
                           kind="poisoned") == 1
            # the served model is a real (uncorrupted) swap
            row = tbl.select(["vec"]).row(0)
            assert srv.submit(row).result(30) is not None
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# default flags + no faults = pre-resilience behavior
# ---------------------------------------------------------------------------

class TestDefaultPathUnchanged:
    def test_fault_free_serving_identical_and_counters_quiet(
            self, base, clean_faults, fresh_registry):
        """Fault env unset, default flags: responses are bitwise the
        host mapper's (the pre-PR parity contract) and ZERO resilience
        machinery engages — no sheds, no fallbacks, no respawns, the
        breaker never leaves closed."""
        tbl, _w, mapper, _s = base
        pred = CompiledPredictor(mapper, buckets=(1, 4, 16),
                                 name="default")
        srv = PredictServer(pred, name="default")
        req = tbl.select(["vec"])
        try:
            ref = mapper.map_table(req.first_n(16))
            outs = [srv.submit(req.row(i)).result(30) for i in range(16)]
            for i, out in enumerate(outs):
                assert out == tuple(ref.row(i))
            st = srv.stats()
            assert st["shed"] == 0 and st["fallback_batches"] == 0
            assert st["loop_respawns"] == 0 and st["failed"] == 0
            assert st["breaker"]["state"] == CLOSED \
                and st["breaker"]["opens"] == 0
            assert _metric(fresh_registry, "alink_serve_shed_total") == 0
            assert _metric(fresh_registry,
                           "alink_serve_breaker_fallback_total") == 0
        finally:
            srv.close()

    def test_serving_lowered_hlo_invariant_to_resilience_flags(
            self, base, clean_faults):
        """The whole resilience tier is host-side policy: the lowered
        HLO of a serving bucket program is BYTE-identical with the
        fault env unset, armed-out-of-window, and the breaker toggled
        — the acceptance criterion's no-new-compiled-ops contract."""
        import jax
        tbl, _w, mapper, _s = base
        pred = CompiledPredictor(mapper, buckets=(4,), name="hlo")
        ver = pred._active
        kind, arrays = ver.kernel.encode(tbl.select(["vec"]).first_n(3), 4)

        def lowered():
            return jax.jit(ver.kernel.device_fns[kind]).lower(
                ver.device_arrays, *arrays).as_text()

        ref_hlo = lowered()
        clean_faults.setenv(FAULT_ENV, "serve.dispatch:999999:error")
        assert lowered() == ref_hlo
        clean_faults.delenv(FAULT_ENV)
        for flag in ("0", "1"):
            clean_faults.setenv("ALINK_TPU_SERVE_BREAKER", flag)
            assert lowered() == ref_hlo

    def test_doctor_chaos_and_shed_verdicts(self):
        """tools/doctor.py renders the serve_chaos SLO verdict (CRITICAL
        on torn/silent/non-recovery) and the shed fix line for ordinary
        serving rows with a nonzero shed rate."""
        import tools.doctor as doctor
        chaos_row = {
            "qps_per_chip": 2000.0, "p99_ms_before": 8.0,
            "p99_ms_during": 40.0, "p99_ms_after": 9.0,
            "typed_rejections": 47, "silent_drops": 0,
            "torn_responses": 0, "shed_requests": 6,
            "breaker_opens": 1, "breaker_reopens": 4,
            "recovered_compiled": True, "model_swaps": 15,
            "feeder_skipped": 1, "loop_respawns": 0,
        }
        bench = {"workloads": {"serve_chaos": dict(chaos_row)}}
        doc = doctor.diagnose(bench, None, None, 100.0, 800.0)
        v = [x for x in doc["serving"]
             if x["workload"] == "serve_chaos"][0]
        assert v["recovered_compiled"] is True and not v["fixes"]
        text = doctor.render(doc)
        assert "6 shed" in text and "breaker opened 1x" in text
        assert "47 typed rejections / 0 silent" in text
        assert "recovered to compiled" in text
        # SLO breaks turn CRITICAL
        broken = dict(chaos_row)
        broken.update(silent_drops=3, recovered_compiled=False)
        doc2 = doctor.diagnose({"workloads": {"serve_chaos": broken}},
                               None, None, 100.0, 800.0)
        fixes = "\n".join(
            [x for x in doc2["serving"]
             if x["workload"] == "serve_chaos"][0]["fixes"])
        assert "SILENT" in fixes and "never recovered" in fixes \
            and "CRITICAL" in fixes
        # an ordinary serving row shedding requests gets the fix line;
        # and a shed metric without a chaos row gets the summary verdict
        plain = {"workloads": {"serve_logreg": {
            "qps_per_chip": 5000.0, "shed_requests": 12,
            "batch_occupancy": 0.9, "bucket_hit_rate": 1.0}}}
        doc3 = doctor.diagnose(plain, None,
                               {"serve": {"shed": 12,
                                          "feeder_errors": 2}},
                               100.0, 800.0)
        names = {x["workload"]: x for x in doc3["serving"]}
        assert any("load shedding is ACTIVE" in f
                   for f in names["serve_logreg"]["fixes"])
        assert any("feeders hit 2 errors" in f
                   for f in names["serving (metrics)"]["fixes"])

    def test_breaker_toggle_is_response_invariant(self, base, clean_faults):
        """ALINK_TPU_SERVE_BREAKER on/off serves byte-identical
        responses when nothing fails (the routing only diverges on
        failure)."""
        tbl, _w, mapper, _s = base
        req = tbl.select(["vec"])
        outs = {}
        for flag in ("1", "0"):
            clean_faults.setenv("ALINK_TPU_SERVE_BREAKER", flag)
            pred = CompiledPredictor(mapper, buckets=(1, 4),
                                     name=f"tog{flag}")
            srv = PredictServer(pred, name=f"tog{flag}")
            try:
                outs[flag] = [srv.submit(req.row(i)).result(30)
                              for i in range(8)]
            finally:
                srv.close()
        assert outs["1"] == outs["0"]
