#!/usr/bin/env python
"""Pre-export the serving program grid into the persistent AOT store
(ISSUE 20) — off the request path.

A cold serving restart pays one XLA compile per (kind, bucket, dtype)
program before it can answer its first request.  With
``ALINK_TPU_AOT_CACHE_DIR`` set, every compile also exports its
executable to disk, and the NEXT restart deserializes instead of
compiling (``PredictServer``/``FleetServer`` pre-load the grid before
``/readyz`` flips).  This CLI runs that first, expensive pass in a
throwaway process at deploy time, so even the first serving process
after a binary roll starts warm:

    python tools/warmcache.py --dir /srv/alink/aotcache \\
        --name lr_demo --dim 16 --buckets 16,64 --dtypes f32,int8

The fixture is the repo's deterministic demo-LR model (the same one
``tools/compilez_smoke.py`` serves); pass the SAME ``--name``, ``--dim``
and bucket ladder the server will use — artifacts key on the full
execution plan plus a rig fingerprint, so a mismatched grid simply
never loads (refused loudly, never deserialized wrong).  Real rigs
warming a production model instead run one admission pass of real
traffic with the cache dir set; this tool covers the demo/bench loop.
"""

import argparse
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)


def _build_fixture(dim: int, rows: int):
    """The deterministic dense-LR fixture shared with compilez_smoke."""
    import numpy as np

    from alink_tpu.common.mtable import MTable
    from alink_tpu.common.params import Params
    from alink_tpu.common.vector import DenseVector
    from alink_tpu.operator.batch.classification.linear import (
        LogisticRegressionTrainBatchOp)
    from alink_tpu.operator.batch.source.sources import MemSourceBatchOp
    from alink_tpu.operator.common.linear.mapper import LinearModelMapper

    rng = np.random.RandomState(11)
    X = rng.randn(rows, dim)
    y = (X @ rng.randn(dim) > 0).astype(np.int64)
    vecs = np.empty(rows, object)
    vecs[:] = [DenseVector(X[i]) for i in range(rows)]
    tbl = MTable({"vec": vecs, "label": y}, "vec VECTOR, label LONG")
    warm = LogisticRegressionTrainBatchOp(
        vector_col="vec", label_col="label", max_iter=2).link_from(
        MemSourceBatchOp(tbl.first_n(min(32, rows))))
    model = warm.get_output_table()
    mapper = LinearModelMapper(model.schema, tbl.select(["vec"]).schema,
                               Params({"prediction_col": "pred",
                                       "vector_col": "vec"}))
    mapper.load_model(model)
    return mapper, tbl


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="pre-compile + export the serving program grid "
                    "into the persistent AOT store")
    ap.add_argument("--dir", required=True,
                    help="AOT cache directory (ALINK_TPU_AOT_CACHE_DIR)")
    ap.add_argument("--name", default="warm",
                    help="predictor name — artifacts land under "
                         "serve.<name> and only a predictor with the "
                         "same name warms from them")
    ap.add_argument("--dim", type=int, default=16,
                    help="fixture feature dimension")
    ap.add_argument("--buckets", default="16",
                    help="comma-separated bucket ladder, e.g. 16,64")
    ap.add_argument("--dtypes", default="f32",
                    help="comma-separated ALINK_TPU_SERVE_DTYPE values "
                         "to warm, e.g. f32,int8")
    args = ap.parse_args(argv)

    os.environ["ALINK_TPU_AOT_CACHE_DIR"] = os.path.abspath(args.dir)
    os.environ.setdefault("ALINK_TPU_AOT_CACHE", "1")

    from alink_tpu.common import aotcache, compileledger
    from alink_tpu.serving import CompiledPredictor

    buckets = tuple(sorted({int(b) for b in args.buckets.split(",")
                            if b.strip()}))
    dtypes = [d.strip() for d in args.dtypes.split(",") if d.strip()]
    if not buckets or not dtypes:
        ap.error("--buckets and --dtypes must be non-empty")
    mapper, tbl = _build_fixture(args.dim, rows=max(buckets) * 2)

    warmed = 0
    for dtype in dtypes:
        os.environ["ALINK_TPU_SERVE_DTYPE"] = dtype
        pred = CompiledPredictor(mapper, buckets=buckets, name=args.name)
        for b in buckets:
            # one request sized to each rung compiles (or disk-hits)
            # exactly that rung's program and exports it on miss
            pred.predict_table(tbl.select(["vec"]).first_n(b))
            warmed += 1
    st = aotcache.stats()
    doc = compileledger.compilez_doc()
    cache = f"serve.{args.name}"
    row = (doc.get("caches") or {}).get(cache) or {}
    print(f"warmcache: {warmed} grid point(s) over buckets={buckets} "
          f"dtypes={dtypes} -> {st['stores']} artifact(s) exported, "
          f"{st['loads']} already on disk "
          f"(cache {cache}: {row.get('misses', 0)} compile(s), "
          f"{row.get('disk_hits', 0)} disk hit(s)) under "
          f"{os.environ['ALINK_TPU_AOT_CACHE_DIR']}")
    if st["export_skipped"]:
        print(f"warmcache: WARNING — {st['export_skipped']} program(s) "
              f"could not be exported on this rig (see warnings above); "
              f"the XLA fallback cache still covers them",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
