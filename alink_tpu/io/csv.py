"""CSV read/write utilities.

Re-design of common/io/csv/ (CsvUtil, CsvParser, CsvFormatter): schema-aware
CSV <-> MTable with the reference's "col TYPE, col TYPE" schema strings.
"""

from __future__ import annotations

import csv
import io
import os
from typing import List, Optional, Sequence
from urllib.request import urlopen

import numpy as np

from ..common.mtable import MTable
from ..common.types import AlinkTypes, TableSchema
from ..common.vector import VectorUtil


def _parse_cell(s: str, type_: str):
    if s is None or s == "":
        return None
    t = type_.upper()
    if t in (AlinkTypes.DOUBLE, AlinkTypes.FLOAT):
        return float(s)
    if t in (AlinkTypes.LONG, AlinkTypes.INT):
        return int(float(s))
    if t == AlinkTypes.BOOLEAN:
        return s.strip().lower() in ("true", "1", "t")
    if AlinkTypes.is_vector(t):
        return VectorUtil.parse(s)
    return s


def _read_csv_native(path: str, schema: TableSchema, field_delimiter: str,
                     quote_char: str, ignore_first_line: bool):
    """Numeric-only fast path through the native parser (parser.cpp
    csv_dims/csv_fill). Returns an MTable or None to fall back."""
    if len(field_delimiter) != 1 or path.startswith(("http://", "https://")):
        return None
    num = {AlinkTypes.DOUBLE, AlinkTypes.FLOAT, AlinkTypes.LONG, AlinkTypes.INT}
    if not all(t.upper() in num for t in schema.types):
        return None
    from ..native import parse_numeric_csv_bytes
    with open(path, "rb") as f:
        data = f.read()
    if quote_char.encode() in data:
        return None
    if ignore_first_line:
        nl = data.find(b"\n")
        data = data[nl + 1:] if nl >= 0 else b""
    m = parse_numeric_csv_bytes(data, field_delimiter)
    if m is None or m.shape[1] != len(schema.names) or np.isnan(m).any():
        return None  # missing cells need the None-aware python path
    cols = {}
    for j, (n, t) in enumerate(zip(schema.names, schema.types)):
        c = m[:, j]
        if t.upper() in (AlinkTypes.LONG, AlinkTypes.INT):
            c = c.astype(np.int64)
        cols[n] = c
    return MTable(cols, schema)


def read_csv(path: str, schema: TableSchema, field_delimiter: str = ",",
             quote_char: str = '"', skip_blank: bool = True,
             ignore_first_line: bool = False) -> MTable:
    fast = _read_csv_native(path, schema, field_delimiter, quote_char,
                            ignore_first_line)
    if fast is not None:
        return fast
    if path.startswith(("http://", "https://")):
        raw = urlopen(path).read().decode("utf-8")  # pragma: no cover - no egress in CI
        f = io.StringIO(raw)
    else:
        f = open(path, "r", encoding="utf-8")
    try:
        reader = csv.reader(f, delimiter=field_delimiter, quotechar=quote_char)
        rows = []
        for i, rec in enumerate(reader):
            if ignore_first_line and i == 0:
                continue
            if skip_blank and not rec:
                continue
            vals = [_parse_cell(rec[j] if j < len(rec) else None, t)
                    for j, t in enumerate(schema.types)]
            rows.append(tuple(vals))
    finally:
        f.close()
    return MTable(rows, schema)


def write_csv(table: MTable, path: str, field_delimiter: str = ",",
              quote_char: str = '"', with_header: bool = False):
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w", newline="", encoding="utf-8") as f:
        writer = csv.writer(f, delimiter=field_delimiter, quotechar=quote_char)
        if with_header:
            writer.writerow(table.col_names)
        for row in table.rows():
            out = []
            for v, t in zip(row, table.schema.types):
                if v is None:
                    out.append("")
                elif AlinkTypes.is_vector(t):
                    out.append(VectorUtil.to_string(VectorUtil.parse(v)))
                else:
                    out.append(v)
            writer.writerow(out)


def format_csv_rows(table: MTable, field_delimiter: str = ",",
                    quote_char: str = '"') -> str:
    """CSV-encode a table to a string (stream sinks append per micro-batch)."""
    buf = io.StringIO()
    writer = csv.writer(buf, delimiter=field_delimiter, quotechar=quote_char)
    for row in table.rows():
        out = []
        for v, t in zip(row, table.schema.types):
            if v is None:
                out.append("")
            elif AlinkTypes.is_vector(t):
                out.append(VectorUtil.to_string(VectorUtil.parse(v)))
            else:
                out.append(v)
        writer.writerow(out)
    return buf.getvalue()


def format_libsvm_rows(table: MTable, label_col: str, vector_col: str,
                       start_index: int = 1) -> str:
    from ..common.vector import DenseVector
    lines = []
    for lbl, vec in zip(table.col(label_col), table.col(vector_col)):
        v = VectorUtil.parse(vec)
        if isinstance(v, DenseVector):
            pairs = [(i, x) for i, x in enumerate(v.data) if x != 0]
        else:
            pairs = list(zip(v.indices, v.values))
        body = " ".join(f"{int(i) + start_index}:{x}" for i, x in pairs)
        lines.append(f"{lbl} {body}\n")
    return "".join(lines)


def read_libsvm(path: str, start_index: int = 1) -> MTable:
    """LibSVM format -> (label DOUBLE, features SPARSE_VECTOR)
    (reference common/io/LibSvmSourceBatchOp).

    Parses through the native C++ two-pass parser
    (alink_tpu/native/parser.cpp svm_count/svm_fill) when available;
    falls back to the pure-Python loop.
    """
    from ..common.vector import SparseVector
    from ..native import get_lib, parse_libsvm_bytes
    if get_lib() is not None:
        with open(path, "rb") as f:
            data = f.read()
        labels_a, indptr, indices, values = parse_libsvm_bytes(data,
                                                               start_index)
        max_idx = int(indices.max()) + 1 if indices.size else 0
        col = [SparseVector(max_idx, indices[indptr[i]:indptr[i + 1]],
                            values[indptr[i]:indptr[i + 1]])
               for i in range(len(labels_a))]
        return MTable({"label": labels_a, "features": col},
                      TableSchema(["label", "features"],
                                  [AlinkTypes.DOUBLE,
                                   AlinkTypes.SPARSE_VECTOR]))
    # pure-Python fallback streams line-by-line (no whole-file slurp)
    labels: List[float] = []
    vecs = []
    max_idx = 0
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            parts = line.strip().split()
            if not parts:
                continue
            labels.append(float(parts[0]))
            idx, val = [], []
            for p in parts[1:]:
                k, v = p.split(":")
                idx.append(int(k) - start_index)
                val.append(float(v))
            if idx:
                max_idx = max(max_idx, max(idx) + 1)
            vecs.append((idx, val))
    col = [SparseVector(max_idx, i, v) for i, v in vecs]
    return MTable({"label": np.asarray(labels), "features": col},
                  TableSchema(["label", "features"],
                              [AlinkTypes.DOUBLE, AlinkTypes.SPARSE_VECTOR]))


def write_libsvm(table: MTable, path: str, label_col: str, vector_col: str,
                 start_index: int = 1):
    with open(path, "w", encoding="utf-8") as f:
        for lbl, vec in zip(table.col(label_col), table.col(vector_col)):
            v = VectorUtil.parse(vec)
            from ..common.vector import DenseVector
            if isinstance(v, DenseVector):
                pairs = [(i, x) for i, x in enumerate(v.data) if x != 0]
            else:
                pairs = list(zip(v.indices, v.values))
            body = " ".join(f"{int(i) + start_index}:{x}" for i, x in pairs)
            f.write(f"{lbl} {body}\n")
