"""Generic stream-side mapper adapters.

Re-design of stream/utils/ (ModelMapStreamOp — model loaded once, applied
per record; here per micro-batch with the batched mapper kernel) and the
stateless MapStreamOp family. The model arrives from a *batch* operator via
the DirectReader side channel in the reference (common/io/directreader/
DirectReader.java:43-77); here a batch table handle crosses directly.
"""

from __future__ import annotations

from typing import Optional, Type

from ....common.mtable import MTable
from ....common.params import Params
from ....mapper.base import Mapper, ModelMapper
from ...base import BatchOperator, StreamOperator
from ..core import BaseStreamTransformOp


class MapperStreamOp(BaseStreamTransformOp):
    """Stateless mapper applied to each micro-batch."""

    MAPPER_CLS: Optional[Type[Mapper]] = None

    def __init__(self, params: Optional[Params] = None, mapper_cls=None, **kwargs):
        super().__init__(params, **kwargs)
        if mapper_cls is not None:
            self.MAPPER_CLS = mapper_cls
        self._mapper: Optional[Mapper] = None

    def _open(self, in_schema):
        self._mapper = self.MAPPER_CLS(in_schema, self.params)
        return self._mapper.get_output_schema()

    def _transform(self, mt: MTable):
        return self._mapper.map_table(mt)


class ModelMapStreamOp(BaseStreamTransformOp):
    """Apply a trained (batch) model to a stream (reference
    stream/utils/ModelMapStreamOp; model via DataBridge broadcast)."""

    MAPPER_CLS: Optional[Type[ModelMapper]] = None

    def __init__(self, model_op: Optional[BatchOperator] = None,
                 params: Optional[Params] = None, mapper_cls=None, **kwargs):
        super().__init__(params, **kwargs)
        if mapper_cls is not None:
            self.MAPPER_CLS = mapper_cls
        self._model_op = model_op
        self._mapper: Optional[ModelMapper] = None

    def _open(self, in_schema):
        model_table = self._model_op.get_output_table()
        self._mapper = self.MAPPER_CLS(model_table.schema, in_schema, self.params)
        self._mapper.load_model(model_table)
        # ALINK_TPU_SERVE_COMPILED (default off): route micro-batches
        # through the compiled serving path — the same shape-bucketed
        # jitted programs the PredictServer dispatches, so batch, stream
        # and serving share ONE compiled scoring path. Flag off (or a
        # mapper without a serving kernel) runs the exact host mapper
        # code this class always ran.
        self._predictor = None
        from ....serving.predictor import (CompiledPredictor,
                                           serve_compiled_enabled)
        if serve_compiled_enabled():
            self._predictor = CompiledPredictor.for_mapper(
                self._mapper, name=type(self).__name__)
        return self._mapper.get_output_schema()

    def _transform(self, mt: MTable):
        if self._predictor is not None:
            try:
                return self._predictor.predict_table(mt)
            except ValueError as e:
                # a kernel refusing the request geometry (e.g. more
                # features than the model) must not kill the stream —
                # THIS batch falls back to the host mapper, RECORDED
                # (alink_serve_fallback_total per batch + one
                # RuntimeWarning per mapper); the predictor stays, so
                # one malformed batch never downgrades the rest of the
                # stream to the host path
                from ....serving.predictor import record_serve_fallback
                record_serve_fallback(type(self._mapper).__name__,
                                      "geometry-refused", str(e))
        return self._mapper.map_table(mt)

    def link_from(self, *inputs) -> "ModelMapStreamOp":
        if len(inputs) == 2 and isinstance(inputs[0], BatchOperator):
            self._model_op = inputs[0]
            inputs = inputs[1:]
        return super().link_from(*inputs)


class PrintStreamOp(BaseStreamTransformOp):
    """Print each micro-batch, pass the stream through (reference
    stream/utils/PrintStreamOp.java)."""

    def _transform(self, mt: MTable):
        print(mt.to_display_string())
        return mt


class _FnBatchApplyStreamOp(BaseStreamTransformOp):
    """Apply a user-function batch op (UDF/UDTF/FlatMap) per micro-batch."""

    _BATCH = None  # set by subclass

    def __init__(self, params: Optional[Params] = None, func=None, **kwargs):
        super().__init__(params, **kwargs)
        self.func = func

    def set_func(self, func) -> "_FnBatchApplyStreamOp":
        self.func = func
        return self

    def _apply(self, mt: MTable) -> MTable:
        op = self._BATCH(self.params.clone(), func=self.func)
        op.link_from(BatchOperator.from_table(mt))
        return op.get_output_table()

    def _open(self, in_schema):
        return self._apply(MTable([], in_schema)).schema

    def _transform(self, mt: MTable):
        return self._apply(mt)


def _fn_stream_twin(name: str, batch_cls) -> type:
    ns = {"_BATCH": batch_cls,
          "__doc__": f"stream twin of {batch_cls.__name__} "
                     f"(reference stream/utils/{name}.java)",
          "__module__": __name__}
    for info in batch_cls.param_infos().values():
        ns[info.name.upper()] = info
    return type(_FnBatchApplyStreamOp)(name, (_FnBatchApplyStreamOp,), ns)


from ...batch.utils import FlatMapBatchOp as _FlatMapBatchOp
from ...batch.utils import UDFBatchOp as _UDFBatchOp
from ...batch.utils import UDTFBatchOp as _UDTFBatchOp

UDFStreamOp = _fn_stream_twin("UDFStreamOp", _UDFBatchOp)
UDTFStreamOp = _fn_stream_twin("UDTFStreamOp", _UDTFBatchOp)
FlatMapStreamOp = _fn_stream_twin("FlatMapStreamOp", _FlatMapBatchOp)

# reference stream/utils/MapStreamOp applies a Mapper per record — that is
# exactly MapperStreamOp's contract
MapStreamOp = MapperStreamOp
