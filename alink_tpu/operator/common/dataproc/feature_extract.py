"""Host->device feature-encode boundary.

The reference trains on ``Tuple3(weight, label, vec)`` rows built by
``BaseLinearModelTrainBatchOp.transform`` (common/linear/BaseLinearModelTrainBatchOp.java:75-77)
where ``vec`` is a DenseVector or SparseVector per row. Here the whole
table crosses the host->device boundary ONCE as static-shape arrays:
dense ``(n, d)`` blocks, or padded-COO batches for sparse input
(SURVEY §7: "design the padded-CSR batch format early").
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ....common.mtable import MTable
from ....common.vector import DenseVector, SparseBatch, SparseVector, VectorUtil


def extract_design(table: MTable, feature_cols: Optional[Sequence[str]],
                   vector_col: Optional[str], dtype=np.float64,
                   vector_size: Optional[int] = None) -> Dict:
    """Returns {"kind": "dense", "X": (n,d)} or
    {"kind": "sparse", "idx": (n,nnz), "val": (n,nnz)}, plus "dim".
    """
    if vector_col:
        from ....common.vector import SparseVectorColumn
        col = table.col(vector_col)
        if isinstance(col, SparseVectorColumn):
            # columnar hasher output: zero-copy into the padded design
            return {"kind": "sparse",
                    "idx": col.idx.astype(np.int32, copy=False),
                    "val": col.val.astype(dtype, copy=False),
                    "dim": max(int(vector_size or 0), col.dim)}
        fast = _native_sparse_fast_path(col, vector_size, dtype)
        if fast is not None:
            return fast
        vecs = [VectorUtil.parse(v) for v in table.col(vector_col)]
        any_sparse = any(isinstance(v, SparseVector) for v in vecs)
        dim = vector_size or 0
        for v in vecs:
            if isinstance(v, DenseVector):
                dim = max(dim, v.size())
            else:
                dim = max(dim, v.n if v.n >= 0 else
                          (int(v.indices[-1]) + 1 if v.indices.size else 0))
        if not any_sparse:
            X = np.zeros((len(vecs), dim), dtype)
            for i, v in enumerate(vecs):
                X[i, :v.size()] = v.data
            return {"kind": "dense", "X": X, "dim": dim}
        batch = SparseBatch.from_vectors(vecs, n_cols=dim, dtype=dtype)
        return {"kind": "sparse", "idx": batch.indices, "val": batch.values, "dim": dim}
    if not feature_cols:
        raise ValueError("either feature_cols or vector_col must be set")
    X = table.numeric_block(list(feature_cols), dtype)
    return {"kind": "dense", "X": X, "dim": X.shape[1]}


def _native_sparse_fast_path(col, vector_size, dtype) -> Optional[Dict]:
    """Batch-parse string sparse-vector literals through the native parser
    (alink_tpu/native/parser.cpp vec_count/vec_fill) when every value is a
    "$n$i:v ..." / "i:v ..." literal — the Criteo-style hot path. Returns
    the padded sparse design dict, or None to fall back to per-row parse.
    """
    vals = list(col)
    if not vals:
        return None
    for v in vals[: min(len(vals), 8)]:
        if not isinstance(v, str) or (":" not in v):
            return None
    if not all(isinstance(v, str) and ":" in v for v in vals):
        return None
    from ....native import parse_vector_lines
    parsed = parse_vector_lines(("\n".join(vals) + "\n").encode())
    if parsed is None:
        return None
    indptr, indices, values, mx = parsed
    n = len(vals)
    if indptr.shape[0] != n + 1:
        return None  # blank lines collapsed; fall back to exact per-row path
    dim = max(int(vector_size or 0), mx)
    lens = np.diff(indptr)
    width = max(int(lens.max()), 1)
    # CSR -> padded (n, width); padding repeats index 0 with value 0
    idx = np.zeros((n, width), np.int32)
    val = np.zeros((n, width), dtype)
    pos = np.arange(width)[None, :] < lens[:, None]
    idx[pos] = indices
    val[pos] = values.astype(dtype)
    return {"kind": "sparse", "idx": idx, "val": val, "dim": dim}


def resolve_feature_cols(table: MTable, feature_cols, label_col=None,
                         exclude: Sequence[str] = ()) -> List[str]:
    """Default feature columns: all numeric columns except label/excluded."""
    if feature_cols:
        return list(feature_cols)
    from ....common.types import AlinkTypes
    skip = set(exclude) | ({label_col} if label_col else set())
    return [n for n, t in zip(table.schema.names, table.schema.types)
            if n not in skip and AlinkTypes.is_numeric(t)]


def add_intercept(design: Dict, dtype=np.float64) -> Dict:
    """Prefix the constant-1 feature at index 0 (reference Vector.prefix(1.0))."""
    if design["kind"] == "dense":
        X = design["X"]
        ones = np.ones((X.shape[0], 1), X.dtype)
        return {"kind": "dense", "X": np.concatenate([ones, X], 1),
                "dim": design["dim"] + 1}
    idx, val = design["idx"], design["val"]
    n = idx.shape[0]
    idx2 = np.concatenate([np.zeros((n, 1), idx.dtype), idx + 1], 1)
    val2 = np.concatenate([np.ones((n, 1), val.dtype), val], 1)
    return {"kind": "sparse", "idx": idx2, "val": val2, "dim": design["dim"] + 1}


def extract_dense_matrix(t, selected_cols, vector_col,
                         dtype=np.float64) -> np.ndarray:
    """extract_design densified: dense design matrices regardless of the
    input encoding (sparse designs go through SparseBatch.to_dense)."""
    design = extract_design(t, selected_cols, vector_col, dtype)
    if design["kind"] == "dense":
        return design["X"]
    from ....common.vector import SparseBatch
    return SparseBatch(design["idx"], design["val"],
                       design["dim"]).to_dense(dtype)
