from .linear import (LinearRegTrainBatchOp, LinearRegPredictBatchOp,
                     RidgeRegTrainBatchOp, RidgeRegPredictBatchOp,
                     LassoRegTrainBatchOp, LassoRegPredictBatchOp,
                     LinearSvrTrainBatchOp, LinearSvrPredictBatchOp)
