"""Session / environment layer.

Re-design of ``MLEnvironment`` / ``MLEnvironmentFactory``
(common/MLEnvironment.java:38-44,115-138; common/MLEnvironmentFactory.java:42-90).

The reference session holds Flink batch+stream execution environments sized
to the local cores. The TPU-native session instead holds a
``jax.sharding.Mesh``: the data axis ``'d'`` replaces Flink task slots
(BatchOperator partitions map 1:1 to chips — BASELINE.json north star), and
an optional model axis ``'m'`` carries feature-sharded state (FTRL-style
tensor parallelism, SURVEY §2.3).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from .lazy import LazyObjectsManager


def mesh_device_request() -> int:
    """``ALINK_TPU_MESH_DEVICES`` (default 0 = all of ``jax.devices()``):
    how many devices the default session mesh should span. On CPU rigs
    this is the knob that turns the historical 1-device virtual axis into
    a real ≥4-device host-platform mesh (measured multi-device execution,
    SCALING_r06) — set it before the first jax backend touch so
    :func:`ensure_host_platform_devices` can still widen the platform."""
    from .flags import flag_value
    return int(flag_value("ALINK_TPU_MESH_DEVICES"))


def _jax_backend_initialized() -> bool:
    """Best-effort: has any jax backend already been instantiated? XLA
    flags latch at backend init, so widening the host platform is only
    possible before this returns True."""
    import sys
    xb = sys.modules.get("jax._src.xla_bridge")
    if xb is None:
        return False
    try:
        backends = getattr(xb, "_backends", None)
    except Exception:           # unknown internals: assume initialized
        return True
    if backends is None:        # attribute renamed/missing: conservative
        return True
    return bool(backends)       # present-but-empty dict = not initialized


def ensure_host_platform_devices(n: int) -> bool:
    """Arrange for >= ``n`` devices on a CPU rig by forcing the XLA host
    platform device count BEFORE the backend initializes (the bootenv
    mechanism, in-process). Returns True when the flag could be set (or
    enough devices already exist); False when the backend already latched
    with fewer devices — callers then respawn a fresh interpreter with
    ``bootenv.cpu_mesh_env(n)`` (tools/scaling_evidence.py does)."""
    if _jax_backend_initialized():
        import jax
        return len(jax.devices()) >= n
    flags = os.environ.get("XLA_FLAGS", "")
    import re
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m is not None:
        # caller already chose a count; respect it — but report honestly
        # whether it satisfies the request (a smaller pinned count means
        # the caller must respawn, exactly like the initialized case)
        return int(m.group(1)) >= n
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={int(n)}").strip()
    return True


class MLEnvironment:
    """One session: device mesh + lazy-objects manager + RNG seed stream."""

    def __init__(self, parallelism: Optional[int] = None, model_parallelism: int = 1,
                 devices=None):
        import jax

        if devices is None:
            req = mesh_device_request()
            if req > 0:
                # widen the CPU host platform before the backend latches
                # (no-op on TPU or once a backend exists)
                ensure_host_platform_devices(req)
            devices = jax.devices()
            if req > 0:
                if len(devices) < req:
                    raise ValueError(
                        f"ALINK_TPU_MESH_DEVICES={req} but only "
                        f"{len(devices)} devices are available and the "
                        f"host platform could not be widened (jax backend "
                        f"already initialized, or XLA_FLAGS already pins a "
                        f"smaller device count); set "
                        f"XLA_FLAGS=--xla_force_host_platform_device_count="
                        f"{req} before the first jax use, or respawn via "
                        f"bootenv.cpu_mesh_env({req})")
                devices = devices[:req]
        n = len(devices)
        if parallelism is None:
            parallelism = max(1, n // model_parallelism)
        total = parallelism * model_parallelism
        if total > n:
            raise ValueError(
                f"requested {parallelism}x{model_parallelism} devices but only {n} available")
        self._devices = devices[:total]
        self.parallelism = parallelism
        self.model_parallelism = model_parallelism
        self._mesh = None
        self.lazy_objects_manager = LazyObjectsManager()
        self._seed_counter = 0

    @property
    def mesh(self):
        from jax.sharding import Mesh
        if self._mesh is None:
            arr = np.asarray(self._devices).reshape(self.parallelism, self.model_parallelism)
            self._mesh = Mesh(arr, ("d", "m"))
        return self._mesh

    @property
    def num_workers(self) -> int:
        """Flink parallelism analogue: number of data-axis shards."""
        return self.parallelism

    def next_seed(self) -> int:
        self._seed_counter += 1
        return self._seed_counter

    def data_sharding(self, *extra_axes):
        """NamedSharding that shards dim 0 along 'd' and replicates the rest."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self.mesh, P("d", *extra_axes))

    def replicated_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self.mesh, P())


class MLEnvironmentFactory:
    """id -> MLEnvironment registry (reference MLEnvironmentFactory.java:42-90)."""

    DEFAULT_ML_ENVIRONMENT_ID = 0
    _lock = threading.Lock()
    _map: Dict[int, MLEnvironment] = {}
    _next_id = 1

    @classmethod
    def get(cls, session_id: int) -> MLEnvironment:
        with cls._lock:
            if session_id not in cls._map:
                if session_id == cls.DEFAULT_ML_ENVIRONMENT_ID:
                    cls._map[session_id] = MLEnvironment()
                else:
                    raise KeyError(
                        f"Cannot find MLEnvironment for id {session_id}; "
                        "call get_new_ml_environment_id()/set_default first.")
            return cls._map[session_id]

    @classmethod
    def get_default(cls) -> MLEnvironment:
        return cls.get(cls.DEFAULT_ML_ENVIRONMENT_ID)

    @classmethod
    def set_default(cls, env: MLEnvironment):
        with cls._lock:
            cls._map[cls.DEFAULT_ML_ENVIRONMENT_ID] = env

    @classmethod
    def get_new_ml_environment_id(cls) -> int:
        with cls._lock:
            sid = cls._next_id
            cls._next_id += 1
            cls._map[sid] = MLEnvironment()
            return sid

    @classmethod
    def register(cls, env: MLEnvironment) -> int:
        with cls._lock:
            sid = cls._next_id
            cls._next_id += 1
            cls._map[sid] = env
            return sid

    @classmethod
    def remove(cls, session_id: int) -> Optional[MLEnvironment]:
        with cls._lock:
            if session_id == cls.DEFAULT_ML_ENVIRONMENT_ID:
                return cls._map.get(session_id)
            return cls._map.pop(session_id, None)

    @classmethod
    def reset(cls):
        with cls._lock:
            cls._map.clear()
            cls._next_id = 1


def use_local_env(parallelism: Optional[int] = None, model_parallelism: int = 1) -> MLEnvironment:
    """PyAlink-style entry (reference README.md:49-58 ``useLocalEnv``)."""
    env = MLEnvironment(parallelism=parallelism, model_parallelism=model_parallelism)
    MLEnvironmentFactory.set_default(env)
    return env


def use_remote_env(coordinator_address: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None,
                   parallelism: Optional[int] = None,
                   model_parallelism: int = 1) -> MLEnvironment:
    """Multi-host entry (reference ``useRemoteEnv``: session on a cluster).

    Where the reference connects the Py4J gateway to a remote Flink cluster,
    the TPU build joins a multi-host JAX runtime: every host in the slice
    calls this with the same coordinator address; ``jax.distributed``
    initializes cross-host ICI/DCN collectives and ``jax.devices()`` then
    spans ALL hosts' chips, so the returned session's mesh — and therefore
    every BSP program, psum, and all_gather — runs slice-wide with no other
    code changes. On Cloud TPU the three arguments are auto-detected from
    the environment and may be omitted.

    The data each host feeds the engine should be that host's input shard
    (per-host sharded readers, SURVEY §7 "scaling 8->128 chips").
    """
    import jax

    already = getattr(jax.distributed, "is_initialized", None)
    if not (callable(already) and already()):
        kwargs = {}
        if coordinator_address is not None:
            kwargs["coordinator_address"] = coordinator_address
        if num_processes is not None:
            kwargs["num_processes"] = num_processes
        if process_id is not None:
            kwargs["process_id"] = process_id
        try:
            jax.distributed.initialize(**kwargs)
        except (RuntimeError, ValueError) as e:
            # RuntimeError: backends already up (jax touched before
            # connecting). ValueError: nothing to auto-detect on this host.
            # A genuinely multi-host request must fail loudly — degrading
            # would train num_processes independent wrong models — but a
            # single/unspecified-process session can continue locally.
            if num_processes is not None and num_processes > 1:
                raise RuntimeError(
                    f"use_remote_env: could not join the {num_processes}-"
                    f"process distributed runtime: {e}") from e
            print(f"[alink_tpu] use_remote_env: jax.distributed not joined "
                  f"({e}); continuing with this process's devices only")
    return use_local_env(parallelism=parallelism,
                         model_parallelism=model_parallelism)
