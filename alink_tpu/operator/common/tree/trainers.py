"""Distributed tree trainers on the BSP engine.

Re-design of:
  GBDT  — BaseGbdtTrainBatchOp.java:204-224 histogram boosting (one tree per
          superstep; histograms psum'd per level inside the stage)
  RF    — BaseRandomForestTrainBatchOp.java:152-163,264 (reference trains
          whole trees per worker; here trees are built histogram-parallel —
          same model class, bagging via per-tree weight masks + feature
          column subsampling)
  DecisionTree — RF with one tree, no subsampling.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ....common.mlenv import MLEnvironment, MLEnvironmentFactory
from ....engine import IterativeComQueue
from ....engine.communication import manifest_psum
from .hist import (bin_data, build_tree, fused_hist_mode, gini_gain,
                   gini_leaf, make_bin_edges, make_xgb_gain, make_xgb_leaf,
                   tree_apply_binned, variance_gain, variance_leaf)


def _feature_subsample_mask(key, F: int, ratio: float, dtype):
    """Exactly ``max(1, round(ratio * F))`` features survive, chosen
    uniformly per tree. A Bernoulli-per-feature draw (the former
    implementation) selects ZERO features with probability (1-ratio)^F —
    on a 1-feature dataset at the default RF ratio that is a 30% chance
    per tree of a root-only stump (tier-1 regression: the seed-0 draw
    masked the only feature on every kept ensemble worker). Exact-count
    subsets are also the reference's featureSubsamplingRatio semantics
    (BaseRandomForestTrainBatchOp.java) and sklearn's ``max_features``."""
    kf = max(1, int(round(ratio * F)))
    u = jax.random.uniform(key, (F,))
    thr = jnp.sort(u)[kf - 1]
    return (u <= thr).astype(dtype)


@dataclass
class TreeTrainParams:
    num_trees: int = 100
    max_depth: int = 5
    n_bins: int = 64
    learning_rate: float = 0.3         # gbdt shrinkage
    min_samples_leaf: int = 1
    reg_lambda: float = 1.0            # gbdt leaf regularization
    subsample_ratio: float = 1.0       # bagging row fraction
    feature_subsample_ratio: float = 1.0
    seed: int = 0


def gbdt_train(X: np.ndarray, y: np.ndarray, p: TreeTrainParams,
               is_regression: bool, env: Optional[MLEnvironment] = None,
               sample_weight: Optional[np.ndarray] = None,
               cat_mask: Optional[np.ndarray] = None):
    """Returns (features (T, 2^d-1), split_bins, split_masks
    (T, 2^d-1, n_bins), leaf_values (T, 2^d), edges, base_score,
    loss_curve, importance (F,)).

    ``cat_mask``: (F,) bool — categorical columns (integer category codes)
    bin by identity and split on category subsets (hist.build_tree)."""
    n, F = X.shape
    dtype = np.float32
    edges = make_bin_edges(X, p.n_bins, cat_mask, env=env)
    binned = bin_data(X, edges)
    w = np.ones(n, dtype) if sample_weight is None else np.asarray(sample_weight, dtype)
    y = np.asarray(y, dtype)
    base = float((y * w).sum() / max(w.sum(), 1e-12)) if is_regression else 0.0
    d = p.max_depth
    T = p.num_trees
    gain_fn = make_xgb_gain(p.reg_lambda)
    leaf_fn = make_xgb_leaf(p.reg_lambda)
    n_internal, n_leaves = (1 << d) - 1, 1 << d

    def grow(ctx):
        if ctx.is_init_step:
            nloc = ctx.get_obj("binned").shape[0]
            ctx.put_obj("F", jnp.full((nloc,), base, dtype))
            ctx.put_obj("trees_f", jnp.zeros((T, n_internal), jnp.int32))
            ctx.put_obj("trees_b", jnp.zeros((T, n_internal), jnp.int32))
            ctx.put_obj("trees_v", jnp.zeros((T, n_leaves), dtype))
            ctx.put_obj("trees_m", jnp.zeros((T, n_internal, p.n_bins), bool))
            ctx.put_obj("importance", jnp.zeros((F,), dtype))
            ctx.put_obj("loss_curve", jnp.zeros((T,), dtype))
        binned_l = ctx.get_obj("binned")
        yl = ctx.get_obj("y")
        wl = ctx.get_obj("w")
        Fcur = ctx.get_obj("F")
        if is_regression:
            g = (Fcur - yl) * wl
            h = wl
            loss = 0.5 * ((Fcur - yl) ** 2 * wl).sum()
        else:
            prob = jax.nn.sigmoid(Fcur)
            g = (prob - yl) * wl           # y in {0,1}
            h = jnp.maximum(prob * (1 - prob), 1e-6) * wl
            loss = (wl * (jnp.logaddexp(0.0, Fcur) - yl * Fcur)).sum()
        # bagging + feature subsample, per tree
        key = ctx.rng_key()
        if p.subsample_ratio < 1.0:
            bag = jax.random.bernoulli(key, p.subsample_ratio, g.shape)
            g = g * bag
            h = h * bag
            wb = wl * bag
        else:
            wb = wl
        fmask = _feature_subsample_mask(
            jax.random.fold_in(key, 1), F, p.feature_subsample_ratio,
            dtype) if p.feature_subsample_ratio < 1.0 else None
        stats = jnp.stack([g, h, wb], axis=1)
        tf, tb, tm, tv, node_id, _, imp = build_tree(
            binned_l, stats, d, p.n_bins, gain_fn, leaf_fn,
            min_samples_leaf=float(p.min_samples_leaf), feature_mask=fmask,
            axis_name="d", num_workers=ctx.num_task, cat_feats=cat_mask,
            cat_order_fn=lambda h_: jnp.where(
                h_[..., 1] > 0, h_[..., 0] / (h_[..., 1] + p.reg_lambda),
                jnp.inf))
        t = ctx.step_no - 1
        ctx.put_obj("trees_f", jax.lax.dynamic_update_index_in_dim(
            ctx.get_obj("trees_f"), tf, t, 0))
        ctx.put_obj("trees_b", jax.lax.dynamic_update_index_in_dim(
            ctx.get_obj("trees_b"), tb, t, 0))
        ctx.put_obj("trees_v", jax.lax.dynamic_update_index_in_dim(
            ctx.get_obj("trees_v"), tv.astype(dtype), t, 0))
        ctx.put_obj("trees_m", jax.lax.dynamic_update_index_in_dim(
            ctx.get_obj("trees_m"), tm, t, 0))
        ctx.put_obj("importance", ctx.get_obj("importance") + imp)
        ctx.put_obj("F", Fcur + p.learning_rate * tv[node_id].astype(dtype))
        lw = manifest_psum(jnp.stack([loss, wl.sum()]), "d",
                           name="gbdt_loss", num_workers=ctx.num_task)
        ctx.put_obj("loss_curve", jax.lax.dynamic_update_index_in_dim(
            ctx.get_obj("loss_curve"), lw[0] / jnp.maximum(lw[1], 1e-12), t, 0))

    from ....engine.comqueue import freeze_config
    queue = (IterativeComQueue(env=env, max_iter=T, seed=p.seed)
             .init_with_partitioned_data("binned", binned)
             .init_with_partitioned_data("y", y)
             .init_with_partitioned_data("w", w)
             .add(grow)
             # base is a data-derived Python float baked into the trace;
             # the fused-histogram mode selects a different lowering, so
             # it must ride the key (a toggle recompiles, never serves a
             # stale program)
             .set_program_key(("gbdt", is_regression, F, base,
                               fused_hist_mode(),
                               freeze_config(p), freeze_config(cat_mask))))
    res = queue.exec()
    return (res.get("trees_f"), res.get("trees_b"), res.get("trees_m"),
            res.get("trees_v"), edges, base,
            np.asarray(res.get("loss_curve")), res.get("importance"))


def forest_train(X: np.ndarray, y_stats: np.ndarray, p: TreeTrainParams,
                 kind: str, env: Optional[MLEnvironment] = None,
                 cat_mask: Optional[np.ndarray] = None,
                 ensemble: Optional[bool] = None):
    """Random forest / decision tree. ``y_stats``: (n, m) per-sample stats —
    (onehot(y), 1) for classification (kind="gini") or (y, y^2, 1) for
    regression (kind="variance"). Returns (features, split_bins,
    split_masks, leaf_values (T, 2^d, ...), edges, importance (F,)).

    ``ensemble`` selects TRUE ensemble parallelism (reference
    BaseRandomForestTrainBatchOp.java:264 SeriesTrainFunction: every
    worker grows whole independent trees on its own data partition, no
    histogram allreduce): W trees materialize per superstep, so T trees
    cost ceil(T/W) supersteps. False grows one data-parallel tree per
    superstep with psum'd histograms (better per-tree quality, W-fold
    more supersteps). Default: ensemble when T > 1.
    """
    n, F = X.shape
    dtype = np.float32
    edges = make_bin_edges(X, p.n_bins, cat_mask, env=env)
    binned = bin_data(X, edges)
    d = p.max_depth
    T = p.num_trees
    m = y_stats.shape[1]
    gain_fn = gini_gain if kind == "gini" else variance_gain
    leaf_fn = gini_leaf if kind == "gini" else variance_leaf
    leaf_w = (m - 1) if kind == "gini" else 1
    n_internal, n_leaves = (1 << d) - 1, 1 << d
    env_ = env or MLEnvironmentFactory.get_default()
    W = env_.num_workers
    if ensemble is None:
        ensemble = T > 1
    if ensemble and W > 1:
        # ensemble trees see ONLY their worker's partition; contiguous
        # splits of an ordered dataset (e.g. sorted by label) would hand
        # each worker a biased — possibly single-class — slice. Shuffle
        # rows before partitioning, the analogue of the reference's
        # AvgPartition re-distribution (BaseRandomForestTrainBatchOp.java:350)
        perm = np.random.RandomState(p.seed).permutation(n)
        binned = binned[perm]
        y_stats = y_stats[perm]
    T_store = -(-T // W) if ensemble else T   # per-worker tree slots
    axis = None if ensemble else "d"

    def grow(ctx):
        if ctx.is_init_step:
            ctx.put_obj("trees_f", jnp.zeros((T_store, n_internal), jnp.int32))
            ctx.put_obj("trees_b", jnp.zeros((T_store, n_internal), jnp.int32))
            shape = ((T_store, n_leaves, leaf_w) if kind == "gini"
                     else (T_store, n_leaves))
            ctx.put_obj("trees_v", jnp.zeros(shape, dtype))
            ctx.put_obj("trees_m",
                        jnp.zeros((T_store, n_internal, p.n_bins), bool))
            ctx.put_obj("importance", jnp.zeros((F,), dtype))
        binned_l = ctx.get_obj("binned")
        stats = ctx.get_obj("stats")
        key = ctx.rng_key()      # per-worker, per-step: trees differ per worker
        if p.subsample_ratio < 1.0:
            bag = jax.random.bernoulli(key, p.subsample_ratio,
                                       (stats.shape[0],)).astype(dtype)
            stats = stats * bag[:, None]
        fmask = _feature_subsample_mask(
            jax.random.fold_in(key, 1), F, p.feature_subsample_ratio,
            dtype) if p.feature_subsample_ratio < 1.0 else None
        tf, tb, tm, tv, _, _, imp = build_tree(
            binned_l, stats, d, p.n_bins, gain_fn, leaf_fn,
            min_samples_leaf=float(p.min_samples_leaf), feature_mask=fmask,
            axis_name=axis, num_workers=ctx.num_task, cat_feats=cat_mask)
        t = ctx.step_no - 1
        ctx.put_obj("trees_f", jax.lax.dynamic_update_index_in_dim(
            ctx.get_obj("trees_f"), tf, t, 0))
        ctx.put_obj("trees_b", jax.lax.dynamic_update_index_in_dim(
            ctx.get_obj("trees_b"), tb, t, 0))
        ctx.put_obj("trees_v", jax.lax.dynamic_update_index_in_dim(
            ctx.get_obj("trees_v"), tv.astype(dtype), t, 0))
        ctx.put_obj("trees_m", jax.lax.dynamic_update_index_in_dim(
            ctx.get_obj("trees_m"), tm, t, 0))
        if ensemble:
            # surplus trees past T (T not a multiple of W) are trimmed from
            # the returned forest; keep their gains out of the importances
            kept = (t * W + ctx.task_id) < T
            imp = jnp.where(kept, imp, jnp.zeros_like(imp))
        ctx.put_obj("importance", ctx.get_obj("importance") + imp)

    from ....engine.comqueue import freeze_config
    queue = (IterativeComQueue(env=env_, max_iter=T_store, seed=p.seed)
             .init_with_partitioned_data("binned", binned)
             .init_with_partitioned_data("stats", y_stats.astype(dtype))
             .add(grow)
             .set_program_key(("forest", kind, F, m, bool(ensemble), T,
                               fused_hist_mode(),
                               freeze_config(p), freeze_config(cat_mask))))
    res = queue.exec()
    if not ensemble:
        return (res.get("trees_f"), res.get("trees_b"), res.get("trees_m"),
                res.get("trees_v"), edges, res.get("importance"))
    # ensemble: per-worker tree slices -> interleaved (T, ...) global forest
    # (superstep-major: tree s*W + w grew on worker w at superstep s+1)
    def gather(name):
        v = res.shards(name)                       # (W, T_store, ...)
        v = np.swapaxes(v, 0, 1).reshape((W * T_store,) + v.shape[2:])
        return v[:T]
    importance = res.shards("importance").sum(0)   # no psum ran: host-sum
    return (gather("trees_f"), gather("trees_b"), gather("trees_m"),
            gather("trees_v"), edges, importance)
