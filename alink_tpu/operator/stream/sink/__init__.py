from .sinks import (BaseSinkStreamOp, CheckpointSinkStreamOp,
                    CollectSinkStreamOp, CsvSinkStreamOp,
                    DBSinkStreamOp, JdbcRetractSinkStreamOp, LibSvmSinkStreamOp,
                    MySqlSinkStreamOp, TextSinkStreamOp)

__all__ = ["BaseSinkStreamOp", "CheckpointSinkStreamOp",
           "CollectSinkStreamOp", "CsvSinkStreamOp",
           "DBSinkStreamOp", "JdbcRetractSinkStreamOp", "LibSvmSinkStreamOp",
           "MySqlSinkStreamOp", "TextSinkStreamOp"]
