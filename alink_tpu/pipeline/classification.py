"""Pipeline wrappers — classification.

Re-design of pipeline/classification/ (LogisticRegression, LinearSvm,
Softmax + *Model classes): declarative shells over the batch ops
(reference pipeline/Trainer.java reflection pattern). Each estimator
carries both train and predict params so the fitted model transforms
directly.
"""

from ..operator.batch.classification.linear import (
    _LinearPredictParams, _LinearTrainParams, LinearSvmTrainBatchOp,
    LogisticRegressionTrainBatchOp, PerceptronTrainBatchOp, SoftmaxTrainBatchOp)
from ..operator.common.linear.mapper import LinearModelMapper
from ..params.shared import HasPositiveLabelValueString
from .base import MapModel, Trainer


class _LinearParams(_LinearTrainParams, _LinearPredictParams):
    pass


class LogisticRegressionModel(MapModel, _LinearPredictParams):
    MAPPER_CLS = LinearModelMapper


class LogisticRegression(Trainer, _LinearParams, HasPositiveLabelValueString):
    TRAIN_OP_CLS = LogisticRegressionTrainBatchOp
    MODEL_CLS = LogisticRegressionModel


class LinearSvmModel(MapModel, _LinearPredictParams):
    MAPPER_CLS = LinearModelMapper


class LinearSvm(Trainer, _LinearParams, HasPositiveLabelValueString):
    TRAIN_OP_CLS = LinearSvmTrainBatchOp
    MODEL_CLS = LinearSvmModel


class SoftmaxModel(MapModel, _LinearPredictParams):
    MAPPER_CLS = LinearModelMapper


class Softmax(Trainer, _LinearParams):
    TRAIN_OP_CLS = SoftmaxTrainBatchOp
    MODEL_CLS = SoftmaxModel


class PerceptronModel(MapModel, _LinearPredictParams):
    MAPPER_CLS = LinearModelMapper


class Perceptron(Trainer, _LinearParams):
    TRAIN_OP_CLS = PerceptronTrainBatchOp
    MODEL_CLS = PerceptronModel
