"""tools/doctor.py + tools/bench_history.py — pure-host CLI coverage.

The doctor's verdict is PINNED on a canned bench+profile+metrics
fixture (the ISSUE-8 acceptance shape): measured ``bound:`` next to the
preserved ``bound_static``, the bucket table, achieved-vs-roof rates,
the top-3 fixes, the live-HBM section with the measured donation
verification, and the metrics summary. bench_history covers the
r01→rNN trajectory shapes, the regression threshold gate and the
``--baseline-provenance`` mixed-fingerprint refusal.
"""

import importlib.util
import json
import os

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        f"{name}_cli", os.path.join(ROOT, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def doctor():
    return _load_tool("doctor")


@pytest.fixture
def history():
    return _load_tool("bench_history")


# -- the canned run-dir fixture ---------------------------------------------

def _canned_attr():
    """A measured attribution: 82% dispatch — the ISSUE's example."""
    return {"dispatch_s": 8.2, "transfer_s": 0.4, "device_s": 0.9,
            "collective_s": 0.0, "host_s": 0.5, "measured_wall_s": 10.0,
            "dispatch_calls": 640, "transfer_bytes": 4096,
            "source": "timing-harness",
            "fractions": {"dispatch": 0.82, "transfer": 0.04,
                          "device": 0.09, "collective": 0.0,
                          "host": 0.05},
            "bound_measured": "latency"}


def _canned_run_dir(d):
    os.makedirs(d, exist_ok=True)
    bench = {
        "metric": "logreg_criteo_samples_per_sec_per_chip",
        "value": 1000.0, "mode": "quick",
        "workloads": {
            "ftrl_criteo": {
                "samples_per_sec_per_chip": 50000.0,
                "flops_per_sample": 1000.0,
                "hbm_bytes_per_sample": 64.0,
                "bound": "latency", "bound_static": "latency",
                "profile": _canned_attr()},
            # profiled but model-less: verdict must still render
            "kmeans_iris": {
                "samples_per_sec_per_chip": 2.0e6,
                "bound": "device",
                "profile": {**_canned_attr(), "dispatch_s": 0.5,
                            "device_s": 9.0,
                            "fractions": {"dispatch": 0.05,
                                          "transfer": 0.04,
                                          "device": 0.86,
                                          "collective": 0.0,
                                          "host": 0.05},
                            "bound_measured": "device"}},
        },
        "rig": {"dispatch_gap_est_s": 0.0128, "baseline_fp": "fp00",
                "peak_tflops": 197.0, "peak_hbm_gbps": 819.0,
                "profile": True}}
    profile = {
        "format": "alink_tpu_profile_v1", "enabled": True,
        "workloads": {"ftrl_criteo": _canned_attr()},
        "marks": [], "windows": [],
        "hbm": [{"workload": "ftrl_criteo", "scope": "comqueue.chunk",
                 "count": 4, "last_bytes": 1048576,
                 "max_bytes": 2097152}],
        "captures": [],
        "donation": {"state_bytes": 1048576, "steps": 2,
                     "donated_peak_bytes": 1048576,
                     "undonated_peak_bytes": 2097152,
                     "ratio": 0.5, "verified": True,
                     "note": "canned"}}
    metrics = [
        {"name": "alink_comqueue_program_cache_total",
         "labels": {"result": "hit"}, "value": 9},
        {"name": "alink_comqueue_program_cache_total",
         "labels": {"result": "miss"}, "value": 1},
        {"name": "alink_collective_calls_total",
         "labels": {"collective": "AllReduce"}, "value": 12},
        {"name": "alink_hbm_live_bytes",
         "labels": {"scope": "comqueue.chunk"}, "value": 1048576},
    ]
    with open(os.path.join(d, "bench.json"), "w") as f:
        json.dump(bench, f)
    with open(os.path.join(d, "profile.json"), "w") as f:
        json.dump(profile, f)
    with open(os.path.join(d, "metrics.jsonl"), "w") as f:
        for rec in metrics:
            f.write(json.dumps(rec) + "\n")
    return d


class TestDoctorPinned:
    def test_render_pinned_on_canned_fixture(self, doctor, tmp_path,
                                             capsys):
        d = _canned_run_dir(str(tmp_path / "run"))
        assert doctor.main(["--run-dir", d]) == 0
        out = capsys.readouterr().out
        # the measured bound next to the preserved static projection
        assert "== workload: ftrl_criteo ==" in out
        assert "bound: latency (measured; static: latency)" in out
        assert "source: timing-harness" in out
        # bucket table with the 82%-dispatch headline share
        assert "host dispatch" in out
        assert " 82.0%" in out
        # top fix names dispatch batching, citing the rig floor
        assert "fix 1: 82% of measured wall is host dispatch" in out
        assert "~13 ms/dispatch" in out
        assert "batch more supersteps" in out
        # achieved-vs-roof, device-time-normalized: 50k sps / 0.09
        # device share * 1k flops = 5.6e8 flop/s
        assert "achieved (device-time)" in out
        assert "0.0006 TFLOP/s" in out
        # HBM section + the measured donation verification
        assert "== HBM (live device buffers) ==" in out
        assert "ftrl_criteo/comqueue.chunk" in out
        assert "donation: VERIFIED" in out and "0.5x" in out
        # metrics summary
        assert "program cache: 9 hits / 1 misses (90% hit rate)" in out
        assert "AllReduce=12" in out
        # the model-less workload renders too, with its honest bound
        assert "== workload: kmeans_iris ==" in out
        assert "bound: device" in out

    def test_json_verdict_shape(self, doctor, tmp_path, capsys):
        d = _canned_run_dir(str(tmp_path / "run"))
        assert doctor.main(["--run-dir", d, "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["format"] == "alink_tpu_doctor_v1"
        wl = {v["workload"]: v for v in doc["workloads"]}
        v = wl["ftrl_criteo"]
        assert v["bound"] == "latency"
        assert v["bound_static"] == "latency"
        assert v["fractions"]["dispatch"] == pytest.approx(0.82)
        assert v["fixes"] and "dispatch" in v["fixes"][0]
        assert v["achieved_device_time"]["pct_peak_flops"] > 0
        assert doc["donation"]["verified"] is True
        assert doc["rig"]["dispatch_gap_est_s"] == pytest.approx(0.0128)
        assert doc["metrics"]["cache"]["hit"] == 9

    def test_multi_leg_device_time_skips_achieved(self, doctor,
                                                  tmp_path, capsys):
        """Device time merged from several program legs must not be
        normalized against one leg's headline rate: no achieved-vs-roof
        line, honest dominant-bucket fix instead."""
        d = _canned_run_dir(str(tmp_path / "run"))
        bench = json.load(open(os.path.join(d, "bench.json")))
        row = bench["workloads"]["ftrl_criteo"]
        row["profile"].update(
            dispatch_s=0.5, device_s=9.0,
            fractions={"dispatch": 0.05, "transfer": 0.04,
                       "device": 0.86, "collective": 0.0, "host": 0.05},
            bound_measured="device",
            device_scopes=["ftrl.kernel", "ftrl.snapshot"])
        with open(os.path.join(d, "bench.json"), "w") as f:
            json.dump(bench, f)
        assert doctor.main(["--run-dir", d, "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        wl = {v["workload"]: v for v in doc["workloads"]}
        assert "achieved_device_time" not in wl["ftrl_criteo"]
        # the device fix must explain the multi-leg refusal, not claim
        # the (present) cost model is missing
        dev_fix = [f for f in wl["ftrl_criteo"]["fixes"]
                   if "program legs" in f]
        assert dev_fix and "ftrl.kernel" in dev_fix[0]
        assert not any("no per-sample cost model" in f
                       for f in wl["ftrl_criteo"]["fixes"])

    def test_profile_only_no_bench(self, doctor, tmp_path, capsys):
        d = _canned_run_dir(str(tmp_path / "run"))
        os.remove(os.path.join(d, "bench.json"))
        assert doctor.main(["--run-dir", d]) == 0
        out = capsys.readouterr().out
        # attribution comes straight from the profile artifact
        assert "== workload: ftrl_criteo ==" in out
        assert "donation: VERIFIED" in out

    def test_no_input_exits_1(self, doctor, tmp_path, capsys):
        assert doctor.main([]) == 1
        assert doctor.main(["--run-dir", str(tmp_path / "nope")]) == 1

    def test_driver_wrapped_bench_accepted(self, doctor, tmp_path,
                                           capsys):
        d = _canned_run_dir(str(tmp_path / "run"))
        inner = json.load(open(os.path.join(d, "bench.json")))
        with open(os.path.join(d, "bench.json"), "w") as f:
            json.dump({"rc": 0, "parsed": inner}, f)
        assert doctor.main(["--run-dir", d]) == 0
        assert "ftrl_criteo" in capsys.readouterr().out


def _round(path, workloads, fp=None, mode="quick"):
    doc = {"workloads_sps_vs": {k: [v, 1.0, 0.5]
                                for k, v in workloads.items()},
           "mode": mode}
    if fp is not None:
        doc["rig"] = {"baseline_fp": fp}
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


class TestBenchHistory:
    def test_table_and_sparkline(self, history, tmp_path, capsys):
        a = _round(str(tmp_path / "BENCH_r01.json"), {"x": 100.0})
        b = _round(str(tmp_path / "BENCH_r02.json"),
                   {"x": 200.0, "y": 5.0})
        assert history.main([a, b]) == 0
        out = capsys.readouterr().out
        assert "r01" in out and "r02" in out
        assert "x" in out and "y" in out
        # y missed r01 → placeholder cell and dot in the sparkline
        assert "·" in out

    def test_regression_flag_and_threshold_exit(self, history, tmp_path,
                                                capsys):
        a = _round(str(tmp_path / "BENCH_r01.json"), {"x": 100.0})
        b = _round(str(tmp_path / "BENCH_r02.json"), {"x": 40.0})
        assert history.main([a, b, "--threshold", "30"]) == 2
        out = capsys.readouterr().out
        assert "REGRESSION x" in out and "-60.0%" in out
        # within threshold: exit 0
        assert history.main([a, b, "--threshold", "70"]) == 0

    def test_mixed_fingerprint_refused(self, history, tmp_path, capsys):
        a = _round(str(tmp_path / "BENCH_r05.json"), {"x": 1.0}, fp="A")
        b = _round(str(tmp_path / "BENCH_r06.json"), {"x": 2.0}, fp="B")
        assert history.main([a, b, "--baseline-provenance"]) == 3
        assert "REFUSING" in capsys.readouterr().err
        # same fingerprint passes
        c = _round(str(tmp_path / "BENCH_r07.json"), {"x": 3.0}, fp="B")
        assert history.main([b, c, "--baseline-provenance"]) == 0

    def test_fingerprint_gap_does_not_launder_rig_change(self, history,
                                                         tmp_path,
                                                         capsys):
        """fp=A, fingerprint-less round, fp=B: the refusal compares
        against the LAST KNOWN fingerprint, so the gap round cannot
        launder a rig change past --baseline-provenance."""
        a = _round(str(tmp_path / "BENCH_r05.json"), {"x": 1.0}, fp="A")
        b = _round(str(tmp_path / "BENCH_r06.json"), {"x": 2.0})
        c = _round(str(tmp_path / "BENCH_r07.json"), {"x": 3.0}, fp="B")
        assert history.main([a, b, c, "--baseline-provenance"]) == 3
        err = capsys.readouterr().err
        assert "REFUSING to compare r05 -> r07" in err

    def test_regression_across_missed_round_still_flagged(self, history,
                                                          tmp_path,
                                                          capsys):
        """r04=1000, r05 misses the workload, r06=500: the 50% drop
        compares against the last PRESENT round — a skipped round must
        not hide it from the threshold gate."""
        a = _round(str(tmp_path / "BENCH_r04.json"), {"x": 1000.0})
        b = _round(str(tmp_path / "BENCH_r05.json"), {"other": 1.0})
        c = _round(str(tmp_path / "BENCH_r06.json"), {"x": 500.0,
                                                      "other": 1.0})
        assert history.main([a, b, c, "--threshold", "30"]) == 2
        out = capsys.readouterr().out
        assert "REGRESSION x: r04 -> r06" in out

    def test_missing_fingerprint_warns_not_refuses(self, history,
                                                   tmp_path, capsys):
        a = _round(str(tmp_path / "BENCH_r01.json"), {"x": 1.0})
        b = _round(str(tmp_path / "BENCH_r02.json"), {"x": 2.0}, fp="B")
        assert history.main([a, b, "--baseline-provenance"]) == 0
        assert "not verifiable" in capsys.readouterr().err

    def test_broken_round_skipped(self, history, tmp_path, capsys):
        a = _round(str(tmp_path / "BENCH_r01.json"), {"x": 1.0})
        broken = str(tmp_path / "BENCH_r02.json")
        with open(broken, "w") as f:
            json.dump({"parsed": None}, f)    # the r03 incident shape
        c = _round(str(tmp_path / "BENCH_r03.json"), {"x": 2.0})
        assert history.main([a, broken, c]) == 0
        err = capsys.readouterr().err
        assert "skipping r02" in err

    def test_fewer_than_two_readable_exits_1(self, history, tmp_path,
                                             capsys):
        a = _round(str(tmp_path / "BENCH_r01.json"), {"x": 1.0})
        assert history.main([a]) == 1

    def test_json_output(self, history, tmp_path, capsys):
        a = _round(str(tmp_path / "BENCH_r01.json"), {"x": 100.0})
        b = _round(str(tmp_path / "BENCH_r02.json"), {"x": 50.0})
        assert history.main([a, b, "--json", "--threshold", "10"]) == 2
        doc = json.loads(capsys.readouterr().out)
        assert doc["workloads"]["x"] == [100.0, 50.0]
        assert doc["regressions"][0]["delta_pct"] == -50.0

    def test_r01_final_line_shape(self, history, tmp_path, capsys):
        """The bare r01 dump (flagship metric only) maps onto the
        flagship workload column."""
        r01 = str(tmp_path / "BENCH_r01.json")
        with open(r01, "w") as f:
            json.dump({"metric": "logreg", "value": 123.0,
                       "unit": "sps"}, f)
        b = _round(str(tmp_path / "BENCH_r02.json"),
                   {"logreg_criteo": 456.0})
        assert history.main([r01, b]) == 0
        assert "logreg_criteo" in capsys.readouterr().out


class TestDoctorSweepVerdict:
    def _row(self, **over):
        row = {"samples_per_sec_per_chip": 95.8, "points": 24,
               "iters": 100, "dt_s": 0.25, "serial_s": 1.7,
               "speedup_vs_serial": 6.6, "sweep_full_speedup": 1.4,
               "rungs": 19, "rung_every": 5, "eta": 5,
               "pruned_fraction": 0.958, "winner_match": True,
               "parity": "bitwise", "compiled_programs": 1}
        row.update(over)
        return row

    def _render(self, doctor, row):
        doc = doctor.diagnose(
            {"workloads": {"tuning_sweep": row},
             "rig": {"dispatch_gap_est_s": 0.001, "peak_tflops": 1.0,
                     "peak_hbm_gbps": 1.0}}, None, None, 1.0, 1.0)
        return doc, doctor.render(doc)

    def test_healthy_verdict(self, doctor):
        doc, text = self._render(doctor, self._row())
        assert doc["tuning"][0]["fixes"] == []
        assert "tuning sweep: tuning_sweep" in text
        assert "6.6x the serial candidate loop" in text
        assert "96% pruned" in text
        assert "winner MATCHES serial grid" in text
        assert "per-point parity bitwise" in text
        # the sweep row never enters the generic capture-window section
        assert all(v["workload"] != "tuning_sweep"
                   for v in doc["workloads"])

    def test_fix_lines_name_the_problem(self, doctor):
        doc, text = self._render(doctor, self._row(
            parity="MISMATCH", winner_match=False,
            speedup_vs_serial=1.2, compiled_programs=24))
        fixes = "\n".join(doc["tuning"][0]["fixes"])
        assert "CRITICAL" in fixes and "bitwise" in fixes
        assert "ALINK_TPU_SWEEP_RUNG" in fixes
        assert "alink_sweep_fallback_total" in fixes
        assert "trace-shaping" in fixes
        assert "fix 1:" in text

    def test_bench_history_labels_points_per_sec(self, history):
        assert history._display_name("tuning_sweep") == \
            "tuning_sweep (points/s)"
        assert history._display_name("serve_logreg") == \
            "serve_logreg (qps)"

    def test_bench_compare_labels_points_per_sec(self, history):
        import importlib
        bc = importlib.import_module("tools.bench_compare")
        rows = [{"workload": "tuning_sweep", "old": 50.0, "new": 95.0,
                 "delta_pct": 90.0}]
        text = bc.render(rows, "a.json", "b.json")
        assert "tuning_sweep (points/s)" in text
        # the two gate tools must label rows identically (unit parity)
        for name in ("tuning_sweep", "serve_logreg",
                     "serve_logreg_sharded", "serve_logreg_p99inv",
                     "logreg_criteo"):
            assert bc._display_name(name) == history._display_name(name)


class TestKernelTierVerdicts:
    """ISSUE 13: doctor fix lines name the Pallas kernel tier when
    scatter-bound FTRL or HBM-round-trip serving shows."""

    def test_ftrl_device_low_roof_names_ftrl_kernel(self, doctor,
                                                    tmp_path, capsys):
        d = _canned_run_dir(str(tmp_path / "run"))
        bench = json.load(open(os.path.join(d, "bench.json")))
        row = bench["workloads"]["ftrl_criteo"]
        # single-leg device-dominated with a cost model whose achieved
        # rate sits far under the roof — the scatter-bound signature
        row["profile"].update(
            dispatch_s=0.5, device_s=9.0,
            fractions={"dispatch": 0.05, "transfer": 0.04,
                       "device": 0.86, "collective": 0.0, "host": 0.05},
            bound_measured="device",
            device_scopes=["ftrl.kernel"])
        with open(os.path.join(d, "bench.json"), "w") as f:
            json.dump(bench, f)
        assert doctor.main(["--run-dir", d, "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        wl = {v["workload"]: v for v in doc["workloads"]}
        tier = [f for f in wl["ftrl_criteo"]["fixes"]
                if "ALINK_TPU_FTRL_KERNEL=pallas" in f]
        assert tier and "scatter-bound" in tier[0]
        # the non-FTRL device-bound workload does NOT get the FTRL line
        assert not any("ALINK_TPU_FTRL_KERNEL" in f
                       for f in wl["kmeans_iris"]["fixes"])

    def _serve_fused_doc(self, doctor, tmp_path, capsys, **row):
        d = _canned_run_dir(str(tmp_path / "run"))
        bench = json.load(open(os.path.join(d, "bench.json")))
        bench["workloads"]["serve_fused"] = {
            "samples_per_sec_per_chip": 1000.0,
            "xla_rows_per_sec_per_chip": 2000.0,
            "fused_vs_xla": 0.5, "dtype_winner": "f32",
            "label_agreement_bf16": 1.0, "label_agreement_int8": 1.0,
            "parity": "bitwise", "bound": "serving-host",
            "rig_note": "interpret-mode Pallas (no TPU)", **row}
        with open(os.path.join(d, "bench.json"), "w") as f:
            json.dump(bench, f)
        assert doctor.main(["--run-dir", d, "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        return {v["workload"]: v for v in doc.get("serving", [])}

    def test_serve_fused_losing_names_recapture(self, doctor, tmp_path,
                                                capsys):
        sv = self._serve_fused_doc(doctor, tmp_path, capsys)
        v = sv["serve_fused"]
        assert v["fused_vs_xla"] == 0.5
        fix = [f for f in v["fixes"] if "ALINK_TPU_SERVE_FUSED" in f]
        assert fix and "physical TPU slice" in fix[0]

    def test_serve_fused_losing_on_native_rig_flags_regression(
            self, doctor, tmp_path, capsys):
        """A native-Mosaic rig losing fused-vs-xla is a real kernel
        regression, not an interpret artifact — the fix line must say
        so instead of telling the operator to recapture the
        measurement they already have."""
        sv = self._serve_fused_doc(doctor, tmp_path, capsys,
                                   rig_note="native Mosaic kernels")
        fix = [f for f in sv["serve_fused"]["fixes"]
               if "kernel-tier regression" in f]
        assert fix and "native rig" in fix[0]
        assert not any("recapture there" in f
                       for f in sv["serve_fused"]["fixes"])

    def test_serve_fused_parity_mismatch_is_critical(self, doctor,
                                                     tmp_path, capsys):
        sv = self._serve_fused_doc(doctor, tmp_path, capsys,
                                   parity="MISMATCH")
        fix = [f for f in sv["serve_fused"]["fixes"]
               if f.startswith("CRITICAL")]
        assert fix and "kernels/serve.py" in fix[0]
        # not the sharded-mesh message — this is the fused kernel's
        assert "serving/sharded.py" not in fix[0]

    def test_bench_history_labels_kernel_rows(self, history):
        assert history._display_name("serve_fused") \
            == "serve_fused (rows/s)"
        assert "kernel tier" in history._display_name("ftrl_pallas")


class TestDoctorE2eVerdict:
    """ISSUE 15: the serve_online_e2e row gets its own whole-loop
    verdict section naming the weakest stage."""

    def _row(self, **over):
        row = {"samples_per_sec_per_chip": 3193.8, "qps": 3193.8,
               "p99_ms": 330.5, "windows": 4,
               "final_window_auc": 0.9963, "auc_note": None,
               "model_swaps": 4, "swap_staleness_max_ms": 1.267,
               "slo_ok": True, "slo_breaches": 0,
               "slo": [
                   {"slo": "serve_p99", "ok": True, "observed": 0.33,
                    "bound": 2.0, "detail": "x"},
                   {"slo": "swap_staleness", "ok": True,
                    "observed": 0.0013, "bound": 30.0, "detail": "x"},
                   {"slo": "final_window_auc", "ok": True,
                    "observed": 0.9963, "bound": 0.75, "detail": "x"}],
               "silent_drops": 0, "typed_rejections": 768,
               "storm_restarts": 3, "storm_bitwise_journals": True,
               "recovery_s_by_fault": {"ftrl.batch": 0.084,
                                       "ckpt.save": 0.043,
                                       "ingest.batch": 0.0005},
               "recovery_train_restart_s": 0.084,
               "recovered_compiled": True, "feeder_skipped": 1,
               "shed_requests": 0, "dt_s": 4.0}
        row.update(over)
        return row

    def _render(self, doctor, row):
        doc = doctor.diagnose(
            {"workloads": {"serve_online_e2e": row},
             "rig": {"dispatch_gap_est_s": 0.001, "peak_tflops": 1.0,
                     "peak_hbm_gbps": 1.0}}, None, None, 1.0, 1.0)
        return doc, doctor.render(doc)

    def test_healthy_verdict_names_weakest_stage(self, doctor):
        doc, text = self._render(doctor, self._row())
        v = doc["e2e"][0]
        assert v["fixes"] == []
        assert "online DAG e2e: serve_online_e2e" in text
        assert "3,194 qps steady-state" in text
        assert "4 eval windows" in text and "final AUC 0.9963" in text
        assert "journals bitwise" in text
        assert "breaker recovered to compiled" in text
        # the AUC clause runs at 75% of budget — the tightest margin —
        # so the weakest stage names train/eval quality
        assert v["weakest_stage"] == "train"
        assert "weakest stage: train" in text
        assert "verdict: healthy" in text
        # the e2e row enters NEITHER the generic capture-window section
        # NOR the per-serve-row section (it has its own)
        assert all(w["workload"] != "serve_online_e2e"
                   for w in doc["workloads"])
        assert all(w["workload"] != "serve_online_e2e"
                   for w in doc.get("serving", []))

    def test_tight_p99_margin_moves_weakest_to_serve(self, doctor):
        row = self._row()
        row["slo"][0] = {"slo": "serve_p99", "ok": True,
                         "observed": 1.9, "bound": 2.0, "detail": "x"}
        doc, _ = self._render(doctor, row)
        assert doc["e2e"][0]["weakest_stage"] == "serve"

    def test_breached_clause_and_broken_storm_are_critical(self, doctor):
        doc, text = self._render(doctor, self._row(
            slo_ok=False,
            slo=[{"slo": "final_window_auc", "ok": False,
                  "observed": 0.52, "bound": 0.75,
                  "detail": "final-window AUC 0.52 vs floor 0.75"}],
            auc_note="final-window AUC 0.52 is below the 0.75 anchor",
            storm_bitwise_journals=False, recovered_compiled=False,
            silent_drops=2))
        fixes = "\n".join(doc["e2e"][0]["fixes"])
        assert "SILENT drops" in fixes
        assert "did NOT resume bitwise" in fixes
        assert "never recovered to the compiled path" in fixes
        assert "SLO clause final_window_auc failed" in fixes
        assert "quality anchor did not clear" in fixes
        assert "CRITICAL" in text and "SLO BREACHED" in text

    def test_errored_row_renders_error(self, doctor):
        doc, text = self._render(doctor, {"error": "boom"})
        assert doc["e2e"][0]["error"] == "boom"
        assert "ERROR: boom" in text

    def test_bench_history_labels_e2e_row(self, history):
        assert history._display_name("serve_online_e2e") == \
            "serve_online_e2e (qps, whole-loop DAG)"
        import importlib
        bc = importlib.import_module("tools.bench_compare")
        assert bc._display_name("serve_online_e2e") == \
            history._display_name("serve_online_e2e")
