"""Pipeline wrappers — NLP (reference pipeline/nlp/: Segment, Tokenizer,
RegexTokenizer, NGram, StopWordsRemover, DocCountVectorizer,
DocHashCountVectorizer, Word2Vec)."""

from __future__ import annotations

from ..operator.batch.nlp import (DocCountVectorizerTrainBatchOp,
                                  DocHashCountVectorizerTrainBatchOp,
                                  NGramBatchOp, RegexTokenizerBatchOp,
                                  SegmentBatchOp, StopWordsRemoverBatchOp,
                                  TokenizerBatchOp, Word2VecTrainBatchOp)
from ..operator.common.nlp.vectorizer import (DocCountVectorizerModelMapper,
                                              DocHashCountVectorizerModelMapper)
from ..operator.common.nlp.word2vec import Word2VecModelMapper
from .feature import BatchOpTransformer, _trainer


def _op_transformer(name, op_cls):
    cls = type(name, (BatchOpTransformer,),
               {"OP_CLS": op_cls, "__module__": __name__})
    cls._PARAM_INFOS = {**op_cls._PARAM_INFOS, **cls._PARAM_INFOS}
    return cls


Segment = _op_transformer("Segment", SegmentBatchOp)
Tokenizer = _op_transformer("Tokenizer", TokenizerBatchOp)
RegexTokenizer = _op_transformer("RegexTokenizer", RegexTokenizerBatchOp)
NGram = _op_transformer("NGram", NGramBatchOp)
StopWordsRemover = _op_transformer("StopWordsRemover", StopWordsRemoverBatchOp)


DocCountVectorizer, DocCountVectorizerModel = _trainer(
    "DocCountVectorizer", DocCountVectorizerTrainBatchOp,
    DocCountVectorizerModelMapper)
DocHashCountVectorizer, DocHashCountVectorizerModel = _trainer(
    "DocHashCountVectorizer", DocHashCountVectorizerTrainBatchOp,
    DocHashCountVectorizerModelMapper)
Word2Vec, Word2VecModel = _trainer(
    "Word2Vec", Word2VecTrainBatchOp, Word2VecModelMapper)
