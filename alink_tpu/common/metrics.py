"""Process-wide runtime metrics — counters, gauges, histograms, exporters.

The reference gets operator-level visibility for free from the Flink web UI
(every dataflow stage is ``.name()``d) plus slf4j taskId/stepNo logs threaded
through hot paths (communication/AllReduce.java:208-261). The TPU build's
named-scope/XProf layer (``common/profiling.py``) covers *device-time*
attribution, but nothing quantitative survived a run: supersteps, collective
traffic, recompiles and stream latency lived only in ad-hoc bench timings.

This module is the missing substrate: a **zero-dependency, thread-safe**
``MetricsRegistry`` the runtime reports into, with two exporters —

  * ``registry.dump(path)``  — JSONL run report (one JSON object per line;
    ``MetricsRegistry.load`` round-trips it, ``tools/run_report.py``
    renders it);
  * ``registry.render_text()`` — Prometheus exposition text, for scraping
    or eyeballing.

Instrumented producers (all host-side; nothing here adds callbacks inside
compiled programs):

  * ``engine/comqueue.py``      — execs, supersteps, program-cache
    hits/misses, per-phase wall time;
  * ``engine/communication.py`` — per-collective invocation counts and
    logical bytes moved (trace-time manifest x supersteps executed);
  * ``operator/base.py``        — batch op wall time, rows in/out;
  * ``operator/stream/*``       — micro-batch throughput and latency,
    FTRL snapshots, model reloads, model staleness;
  * ``common/profiling.py``     — every ``StepTimer.span`` mirrors into
    the registry, so one dump captures the whole run.

Metrics are ON by default; export ``ALINK_TPU_METRICS=0`` (or ``false`` /
``off``) and every producer skips its registry updates. The recording cost
is a dict update behind one lock per event — events are per-exec /
per-micro-batch / per-span, never per-superstep or per-sample.
"""

from __future__ import annotations

import json
import os
import threading
import time
import warnings
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "MetricsRegistry", "get_registry", "set_registry", "metrics_enabled",
    "env_flag", "DEFAULT_BUCKETS",
]

# the one boolean parser every ``ALINK_TPU_*`` on/off switch goes
# through, so "``=0`` disables" holds everywhere (it did not for
# ``ALINK_TPU_STEP_LOG``). The implementation — and the declarative
# registry of every flag with its cache-key fold metadata — lives in
# ``common/flags.py``; re-exported here because this module is the
# historical import point for every instrumented producer.
from .flags import _FALSY, env_flag  # noqa: F401  (re-export)


def metrics_enabled() -> bool:
    """Runtime switch for every instrumented hot path (``ALINK_TPU_METRICS``,
    default on). Read live so tests and long-lived processes can toggle it."""
    return env_flag("ALINK_TPU_METRICS", default=True)


# Latency-shaped default buckets (seconds): micro-batch dispatches sit in
# the 1 ms band, comqueue compiles in the 1-30 s band — one fixed ladder
# covers both without per-metric tuning.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

_KINDS = ("counter", "gauge", "histogram")


def _label_key(labels: Optional[Dict[str, Any]]) -> Tuple[Tuple[str, str], ...]:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Series:
    __slots__ = ("value", "counts", "sum", "count", "exemplars")

    def __init__(self, n_buckets: int = 0):
        self.value = 0.0
        if n_buckets:                      # histogram series
            self.counts = [0] * n_buckets  # per-bucket (non-cumulative)
            self.sum = 0.0
            self.count = 0
            # one exemplar slot per bucket (OpenMetrics-style, last
            # observation wins) — bounded by construction, so a p99
            # bucket can link to a concrete request timeline (ISSUE 18)
            # without the registry ever growing per-request state
            self.exemplars: List[Optional[Dict[str, Any]]] = \
                [None] * n_buckets


class _Family:
    """One named metric: a kind, fixed buckets (histograms), and a series
    per distinct label set, capped to bound cardinality."""

    def __init__(self, registry: "MetricsRegistry", name: str, kind: str,
                 help: str = "", buckets: Optional[Sequence[float]] = None):
        self._registry = registry
        self.name = name
        self.kind = kind
        self.help = help
        if kind == "histogram":
            bs = tuple(buckets) if buckets is not None else DEFAULT_BUCKETS
            if list(bs) != sorted(bs) or len(set(bs)) != len(bs):
                raise ValueError(f"histogram {name}: buckets must be "
                                 f"strictly increasing, got {bs}")
            # final implicit +Inf bucket
            self.buckets: Tuple[float, ...] = bs
        else:
            self.buckets = ()
        self._series: Dict[Tuple[Tuple[str, str], ...], _Series] = {}
        self._overflow_warned = False

    # -- series management ------------------------------------------------
    _OVERFLOW_KEY = (("alink_overflow", "true"),)

    def _get_series(self, labels: Optional[Dict[str, Any]]) -> _Series:
        key = _label_key(labels)
        s = self._series.get(key)
        if s is None:
            if len(self._series) >= self._registry.max_series_per_metric \
                    and key != self._OVERFLOW_KEY:
                # cardinality guard: runaway label values (e.g. an id
                # leaking into a label) collapse into one overflow series
                # instead of growing the registry without bound. Warn ONCE
                # per metric name — per-sample warnings on a hot path
                # would be their own flood (the samples keep folding into
                # the overflow series regardless)
                if not self._overflow_warned:
                    self._overflow_warned = True
                    warnings.warn(
                        f"metric {self.name!r}: label-set cardinality cap "
                        f"({self._registry.max_series_per_metric}) reached; "
                        f"further new label sets fold into the "
                        f"alink_overflow=true series (is an unbounded id "
                        f"leaking into a label?)",
                        RuntimeWarning, stacklevel=4)
                self._registry._dropped_series += 1
                return self._get_series(dict(self._OVERFLOW_KEY))
            n_b = len(self.buckets) + 1 if self.kind == "histogram" else 0
            s = self._series[key] = _Series(n_b)
        return s

    # -- recording (caller holds the registry lock via public methods) ----
    def inc(self, amount: float = 1.0,
            labels: Optional[Dict[str, Any]] = None) -> None:
        if self.kind != "counter":
            raise TypeError(f"{self.name} is a {self.kind}, not a counter")
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative increment")
        with self._registry._lock:
            self._get_series(labels).value += amount

    def set(self, value: float,
            labels: Optional[Dict[str, Any]] = None) -> None:
        if self.kind != "gauge":
            raise TypeError(f"{self.name} is a {self.kind}, not a gauge")
        with self._registry._lock:
            self._get_series(labels).value = float(value)

    def observe(self, value: float,
                labels: Optional[Dict[str, Any]] = None,
                exemplar: Optional[Dict[str, Any]] = None) -> None:
        if self.kind != "histogram":
            raise TypeError(f"{self.name} is a {self.kind}, not a histogram")
        value = float(value)
        with self._registry._lock:
            s = self._get_series(labels)
            i = 0
            n = len(self.buckets)
            while i < n and value > self.buckets[i]:
                i += 1
            s.counts[i] += 1
            s.sum += value
            s.count += 1
            if exemplar:
                ex = dict(exemplar)
                ex["value"] = value
                s.exemplars[i] = ex

    # -- reading ----------------------------------------------------------
    def series(self) -> List[Tuple[Dict[str, str], _Series]]:
        with self._registry._lock:
            return [(dict(k), s) for k, s in self._series.items()]

    def value(self, labels: Optional[Dict[str, Any]] = None) -> float:
        """Current value of one counter/gauge series (0.0 if never set)."""
        if self.kind == "histogram":
            raise TypeError(f"{self.name} is a histogram; read it via "
                            f"series() (sum/count/counts), not value()")
        with self._registry._lock:
            s = self._series.get(_label_key(labels))
            return s.value if s is not None else 0.0


class MetricsRegistry:
    """Thread-safe registry of counters, gauges and fixed-bucket histograms.

    >>> reg = MetricsRegistry()
    >>> reg.inc("requests_total", 1, {"route": "/fit"})
    >>> reg.set_gauge("queue_depth", 3)
    >>> reg.observe("latency_seconds", 0.012)
    >>> reg.dump("/tmp/run.jsonl"); print(reg.render_text())

    One process-wide instance (``get_registry()``) backs the runtime's
    instrumentation; independent instances can be created freely (tests,
    per-run isolation via ``set_registry``).
    """

    def __init__(self, max_series_per_metric: int = 256):
        self._lock = threading.RLock()
        self._families: Dict[str, _Family] = {}
        self.max_series_per_metric = int(max_series_per_metric)
        self._dropped_series = 0
        self._created_unix = time.time()

    # -- family accessors (create-or-get; kind conflicts fail loudly) -----
    def _family(self, name: str, kind: str, help: str = "",
                buckets: Optional[Sequence[float]] = None) -> _Family:
        if kind not in _KINDS:
            raise ValueError(f"unknown metric kind {kind!r}")
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = _Family(self, name, kind,
                                                     help, buckets)
            elif fam.kind != kind:
                raise TypeError(f"metric {name!r} already registered as "
                                f"{fam.kind}, requested {kind}")
            elif (kind == "histogram" and buckets is not None
                  and tuple(buckets) != fam.buckets):
                raise ValueError(f"histogram {name!r} already registered "
                                 f"with buckets {fam.buckets}")
            if help and not fam.help:
                fam.help = help
            return fam

    def counter(self, name: str, help: str = "") -> _Family:
        return self._family(name, "counter", help)

    def gauge(self, name: str, help: str = "") -> _Family:
        return self._family(name, "gauge", help)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Sequence[float]] = None) -> _Family:
        return self._family(name, "histogram", help, buckets)

    # -- one-call conveniences (the instrumentation call sites) -----------
    def inc(self, name: str, amount: float = 1.0,
            labels: Optional[Dict[str, Any]] = None) -> None:
        self.counter(name).inc(amount, labels)

    def set_gauge(self, name: str, value: float,
                  labels: Optional[Dict[str, Any]] = None) -> None:
        self.gauge(name).set(value, labels)

    def observe(self, name: str, value: float,
                labels: Optional[Dict[str, Any]] = None,
                buckets: Optional[Sequence[float]] = None,
                exemplar: Optional[Dict[str, Any]] = None) -> None:
        self.histogram(name, buckets=buckets).observe(value, labels,
                                                      exemplar)

    def value(self, name: str,
              labels: Optional[Dict[str, Any]] = None) -> float:
        """Read one counter/gauge series (0.0 when absent — reads never
        create series)."""
        with self._lock:
            fam = self._families.get(name)
        return fam.value(labels) if fam is not None else 0.0

    def reset(self) -> None:
        with self._lock:
            self._families.clear()
            self._dropped_series = 0
            self._created_unix = time.time()

    # -- snapshots / exporters -------------------------------------------
    def snapshot(self) -> List[Dict[str, Any]]:
        """List of plain-dict records, one per series (JSONL line shape)."""
        out: List[Dict[str, Any]] = []
        with self._lock:
            for name in sorted(self._families):
                fam = self._families[name]
                for labels, s in fam.series():
                    rec: Dict[str, Any] = {"kind": fam.kind, "name": name,
                                           "labels": labels}
                    if fam.help:
                        rec["help"] = fam.help
                    if fam.kind == "histogram":
                        rec["buckets"] = list(fam.buckets)
                        rec["counts"] = list(s.counts)
                        rec["sum"] = s.sum
                        rec["count"] = s.count
                        if any(s.exemplars):
                            rec["exemplars"] = [dict(e) if e else None
                                                for e in s.exemplars]
                    else:
                        rec["value"] = s.value
                    out.append(rec)
        return out

    def dump(self, path: str) -> str:
        """Write the JSONL run report; returns ``path``. First line is a
        meta record; every following line is one series."""
        with self._lock:
            meta = {"kind": "meta", "format": "alink_tpu_metrics_v1",
                    "created_unix": self._created_unix,
                    "dumped_unix": time.time(),
                    "dropped_series": self._dropped_series}
            lines = [json.dumps(meta)]
            lines += [json.dumps(rec) for rec in self.snapshot()]
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            f.write("\n".join(lines))
            f.write("\n")
        os.replace(tmp, path)
        return path

    @classmethod
    def load(cls, path: str) -> "MetricsRegistry":
        """Rebuild a registry from a ``dump()`` JSONL file (round-trip).

        Tolerates a TORN FINAL line (a process killed mid-``dump`` —
        same crash class the PR-15 eval journals repair): the complete
        prefix loads and a ``RuntimeWarning`` names the truncation.
        An unparsable line anywhere BEFORE the end is real corruption
        and still raises — silent mid-file skips would fabricate
        report numbers."""
        reg = cls()
        with open(path) as f:
            lines = f.readlines()
        while lines and not lines[-1].strip():
            lines.pop()
        for i, ln in enumerate(lines):
            ln = ln.strip()
            if not ln:
                continue
            try:
                rec = json.loads(ln)
            except ValueError:
                if i == len(lines) - 1:
                    warnings.warn(
                        f"{path}: final metrics record is torn "
                        f"(truncated dump, {len(ln)} bytes) — loaded "
                        f"the {i} complete record(s) before it",
                        RuntimeWarning, stacklevel=2)
                    break
                raise ValueError(
                    f"{path}: unparsable metrics record at line "
                    f"{i + 1} (mid-file corruption, not a torn tail)")
            kind = rec.get("kind")
            if kind == "meta":
                reg._created_unix = rec.get("created_unix",
                                            reg._created_unix)
                reg._dropped_series = rec.get("dropped_series", 0)
                continue
            if kind not in _KINDS:
                raise ValueError(f"{path}: unknown record kind {kind!r}")
            labels = rec.get("labels") or None
            if kind == "histogram":
                fam = reg.histogram(rec["name"], rec.get("help", ""),
                                    buckets=rec["buckets"])
                with reg._lock:
                    s = fam._get_series(labels)
                    s.counts = list(rec["counts"])
                    s.sum = float(rec["sum"])
                    s.count = int(rec["count"])
                    if rec.get("exemplars"):
                        ex = list(rec["exemplars"])
                        ex += [None] * (len(s.counts) - len(ex))
                        s.exemplars = ex[:len(s.counts)]
            elif kind == "counter":
                reg.counter(rec["name"], rec.get("help", "")) \
                   .inc(float(rec["value"]), labels)
            else:
                reg.gauge(rec["name"], rec.get("help", "")) \
                   .set(float(rec["value"]), labels)
        return reg

    @staticmethod
    def _fmt_labels(labels: Dict[str, str],
                    extra: Optional[Tuple[str, str]] = None) -> str:
        items = sorted(labels.items())
        if extra is not None:
            items.append(extra)
        if not items:
            return ""
        body = ",".join('%s="%s"' % (k, str(v).replace("\\", "\\\\")
                                     .replace('"', '\\"').replace("\n", "\\n"))
                        for k, v in items)
        return "{%s}" % body

    def render_text(self) -> str:
        """Prometheus exposition text (histograms as cumulative
        ``_bucket{le=...}`` + ``_sum`` + ``_count``)."""
        lines: List[str] = []
        with self._lock:
            for name in sorted(self._families):
                fam = self._families[name]
                if fam.help:
                    lines.append(f"# HELP {name} {fam.help}")
                lines.append(f"# TYPE {name} {fam.kind}")
                for labels, s in fam.series():
                    if fam.kind == "histogram":
                        cum = 0
                        for le, c in zip(list(fam.buckets) + ["+Inf"],
                                         s.counts):
                            cum += c
                            lines.append(
                                f"{name}_bucket"
                                f"{self._fmt_labels(labels, ('le', str(le)))}"
                                f" {cum}")
                        lines.append(f"{name}_sum"
                                     f"{self._fmt_labels(labels)} {s.sum}")
                        lines.append(f"{name}_count"
                                     f"{self._fmt_labels(labels)} {s.count}")
                    else:
                        lines.append(f"{name}{self._fmt_labels(labels)}"
                                     f" {s.value}")
        return "\n".join(lines) + "\n"


# -- the process-wide registry ------------------------------------------

_default_registry = MetricsRegistry()
_default_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The registry every runtime producer reports into."""
    return _default_registry


def set_registry(reg: MetricsRegistry) -> MetricsRegistry:
    """Swap the process-wide registry (per-run isolation, tests); returns
    the previous one."""
    global _default_registry
    with _default_lock:
        prev, _default_registry = _default_registry, reg
    return prev


# -- once-per-key fallback recording ------------------------------------
# Shared by the serving tier (record_serve_fallback) and the tuning
# sweep (record_sweep_fallback): a fallback must always count in the
# registry but only WARN once per (scope, key) per process — per-event
# warnings would be noise, and a second distinct key is a distinct
# problem that must not be muted by the first.

_fallback_once_lock = threading.Lock()
_fallback_once_seen: set = set()


def record_fallback_once(scope: str, metric: str, labels: Dict[str, str],
                         message: str, *, stacklevel: int = 4) -> bool:
    """Increment ``metric{labels}`` (metrics on), then emit ``message``
    as a RuntimeWarning the FIRST time this (scope, labels-key) is seen.
    Returns True when the warning fired. ``labels`` values must be a
    small stable enum (they are metric labels AND the dedup key)."""
    if metrics_enabled():
        get_registry().inc(metric, 1, labels)
    key = (scope,) + tuple(sorted(labels.items()))
    with _fallback_once_lock:
        if key in _fallback_once_seen:
            return False
        _fallback_once_seen.add(key)
    warnings.warn(message, RuntimeWarning, stacklevel=stacklevel)
    return True


def reset_fallback_warnings(scope: Optional[str] = None) -> None:
    """Test hook: re-arm the once-per-key warnings (one scope, or all)."""
    with _fallback_once_lock:
        if scope is None:
            _fallback_once_seen.clear()
        else:
            for k in [k for k in _fallback_once_seen if k[0] == scope]:
                _fallback_once_seen.discard(k)
