"""Hand-written Pallas kernels for the sparse FTRL hot loop (ISSUE 13).

PR 6's ceiling-anatomy note (docs/performance.md "Reaching the
roofline") established that strict FTRL cannot drop below O(B)
dependent ops — so the remaining win is making each dependent op cheap.
The two ops XLA refuses to make cheap on TPU are exactly the two this
module replaces:

* **state gather/scatter** — XLA serializes random gather/scatter
  (~5M touched elements/s measured, the ftrl.py wall). The kernels here
  keep the (z, n) slot tiles resident in VMEM: :func:`gather_rows` is
  one VMEM-indexed read of the touched slots, :func:`scatter_add_rows`
  grids over contiguous slot blocks and applies every update to its
  block with a sequential select-accumulate — duplicate slots
  accumulate in update order, which makes the kernel BITWISE-identical
  to XLA's in-order scatter-add (``.at[idx].add``), pinned by
  tests/test_kernels.py. Untouched slots pass through by *selection*
  (never ``+ 0.0``, which would flip ``-0.0``), so the whole state
  round-trips bitwise.
* **the chained-correction einsum** — the dense (K, w, 2) correction
  einsum in ``_ftrl_sparse_chained_step_factory`` contracts over all K
  delta rows even though rows ``j >= k`` are structurally zero.
  :func:`chained_corr` grids over exactly the ``k`` live rows (the
  triangle the dense einsum pays double for) and accumulates
  ``M[k, j] @ D[j]`` in full input precision (the
  ``Precision.HIGHEST`` contract of the XLA path, so chained parity
  stays inside the pinned 1e-12 tolerance).

Availability/demotion ride :mod:`alink_tpu.kernels.runtime` (the
``ALINK_TPU_FUSED_HIST`` contract): kernels run on TPU or under
``ALINK_TPU_PALLAS_INTERPRET=1``, demote to the XLA path with a
one-time warning otherwise, and the flag-off factories lower
byte-identically to pre-kernel-tier programs.

``ALINK_TPU_FTRL_KERNEL`` gates the tier; the RESOLVED mode rides the
FTRL step factories' lru keys (a toggle can never serve a stale step
program) and — in chained mode — the checkpoint signature (the
triangular accumulation order differs from the dense einsum's at the
last ulp, so a chained resume refuses across the toggle).
"""

from __future__ import annotations

import numpy as np

from .runtime import demote_once, eager_probe, interpret_mode, \
    pallas_available

__all__ = ["ftrl_kernel_mode", "gather_rows", "scatter_add_rows",
           "chained_corr", "FTRL_KERNEL_ENV"]

FTRL_KERNEL_ENV = "ALINK_TPU_FTRL_KERNEL"

# scatter grid: slot blocks of this many state rows live in VMEM per
# grid step (f64 on the CPU rig: 512 * 2 * 8 B = 8 KB per (z, n) tile)
_SLOT_BLOCK = 512


def ftrl_kernel_mode() -> str:
    """Resolved FTRL kernel mode: ``"off"`` (default) | ``"pallas"``.

    ``ALINK_TPU_FTRL_KERNEL`` values: 0/off/false -> "off"; anything
    truthy -> "pallas" when the backend can run it (TPU, or
    ``ALINK_TPU_PALLAS_INTERPRET=1``), else a RECORDED demotion to
    "off" (one RuntimeWarning per process +
    ``alink_kernel_demotions_total``). The RESOLVED mode is what the
    step factories fold into their lru keys, so the interpret flag
    needs no fold of its own."""
    from ..common.flags import flag_value
    v = flag_value(FTRL_KERNEL_ENV)
    if v == "off":
        return "off"
    if not pallas_available():
        demote_once("ftrl_scatter", "backend-unavailable",
                    "ALINK_TPU_FTRL_KERNEL requested but the backend is "
                    "not TPU and ALINK_TPU_PALLAS_INTERPRET is off")
        return "off"
    return "pallas"


def _pl():
    from jax.experimental import pallas as pl
    return pl


# ---------------------------------------------------------------------------
# gather
# ---------------------------------------------------------------------------

def _gather_call(state, idx2):
    import jax
    import jax.numpy as jnp
    pl = _pl()
    S, C = state.shape
    M = idx2.shape[0]

    def kernel(st_ref, idx_ref, out_ref):
        # the whole state tile is VMEM-resident; the touched slots read
        # out in one vectorized index (no serialized HBM gather)
        out_ref[...] = st_ref[...][idx_ref[...][:, 0]]

    return pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec((S, C), lambda: (0, 0)),
                  pl.BlockSpec((M, 1), lambda: (0, 0))],
        out_specs=pl.BlockSpec((M, C), lambda: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((M, C), state.dtype),
        interpret=interpret_mode(),
    )(state, idx2)


def gather_rows(state, idx):
    """``state[idx]`` with the state tile VMEM-resident.

    ``state``: (S,) or (S, C); ``idx``: (M,) int32 in [0, S). Bitwise-
    identical to the XLA gather (plain vectorized indexing of the same
    values)."""
    import jax.numpy as jnp
    squeeze = state.ndim == 1
    st = state[:, None] if squeeze else state
    out = _gather_call(st, idx.astype(jnp.int32)[:, None])
    return out[:, 0] if squeeze else out


# ---------------------------------------------------------------------------
# duplicate-safe scatter-add
# ---------------------------------------------------------------------------

def _scatter_call(state, idx2, upd):
    import jax
    import jax.numpy as jnp
    pl = _pl()
    S, C = state.shape
    M = idx2.shape[0]
    BS = min(_SLOT_BLOCK, S)
    Sp = -(-S // BS) * BS
    if Sp != S:                     # pad slots are never addressed
        state = jnp.concatenate(
            [state, jnp.zeros((Sp - S, C), state.dtype)])

    def kernel(idx_ref, upd_ref, st_ref, out_ref):
        b = pl.program_id(0)
        ids = (jax.lax.broadcasted_iota(jnp.int32, (BS, 1), 0)[:, 0]
               + b * BS)
        iv = idx_ref[...][:, 0]                       # (M,)
        u = upd_ref[...]                              # (M, C)

        def body(j, acc):
            # SELECT, not add: untouched slots keep their bits (adding
            # 0.0 would canonicalize -0.0), touched slots accumulate
            # fl(acc + u[j]) in update order — XLA's in-order
            # scatter-add semantics, hence the bitwise contract
            m = (iv[j] == ids)[:, None]
            return jnp.where(m, acc + u[j][None, :], acc)

        out_ref[...] = jax.lax.fori_loop(0, M, body, st_ref[...])

    out = pl.pallas_call(
        kernel,
        grid=(Sp // BS,),
        in_specs=[pl.BlockSpec((M, 1), lambda b: (0, 0)),
                  pl.BlockSpec((M, C), lambda b: (0, 0)),
                  pl.BlockSpec((BS, C), lambda b: (b, 0))],
        out_specs=pl.BlockSpec((BS, C), lambda b: (b, 0)),
        out_shape=jax.ShapeDtypeStruct((Sp, C), state.dtype),
        interpret=interpret_mode(),
    )(idx2, upd, state)
    return out[:S] if Sp != S else out


def scatter_add_rows(state, idx, upd):
    """``state.at[idx].add(upd)`` as a slot-blocked Pallas kernel.

    ``state``: (S,) or (S, C); ``idx``: (M,); ``upd``: (M,) or (M, C).
    Grid over contiguous slot blocks, each (z, n) tile VMEM-resident;
    duplicate indices accumulate in update order (duplicate-safe AND
    bitwise vs the XLA scatter-add, tests/test_kernels.py)."""
    import jax.numpy as jnp
    squeeze = state.ndim == 1
    st = state[:, None] if squeeze else state
    up = upd[:, None] if squeeze else upd
    out = _scatter_call(st, idx.astype(jnp.int32)[:, None], up)
    return out[:, 0] if squeeze else out


# ---------------------------------------------------------------------------
# chained-correction triangular matvec
# ---------------------------------------------------------------------------

def chained_corr(Mk, D, k: int):
    """``sum_{j<k} Mk[j] @ D[j]`` — the chained-correction matvec with
    the structurally-zero rows ``j >= k`` skipped.

    ``Mk``: (K, w, w) collision tensor row of sample ``k``; ``D``:
    (K, w, 2) stacked delta buffer; ``k`` static (the unrolled chunk
    position). The dense einsum the XLA path pays contracts all K rows;
    this kernel grids over exactly the ``k`` live ones, accumulating in
    full input precision (the ``Precision.HIGHEST`` contract — no MXU
    bf16 rounding of the f32/f64 deltas), so chained parity stays
    inside the pinned 1e-12 tolerance (association-only difference).
    """
    import jax
    import jax.numpy as jnp
    pl = _pl()
    K, w, _ = Mk.shape
    C = D.shape[2]
    if k == 0:
        return jnp.zeros((w, C), D.dtype)

    def kernel(m_ref, d_ref, out_ref):
        j = pl.program_id(0)

        @pl.when(j == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)

        out_ref[...] += jnp.dot(m_ref[...][0], d_ref[...][0],
                                preferred_element_type=out_ref.dtype)

    return pl.pallas_call(
        kernel,
        grid=(k,),                     # rows j >= k never enter the grid
        in_specs=[pl.BlockSpec((1, w, w), lambda j: (j, 0, 0)),
                  pl.BlockSpec((1, w, C), lambda j: (j, 0, 0))],
        out_specs=pl.BlockSpec((w, C), lambda j: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((w, C), D.dtype),
        interpret=interpret_mode(),
    )(Mk[:k], D[:k])


# ---------------------------------------------------------------------------
# eager probes (one per shape class per process)
# ---------------------------------------------------------------------------

def probe_scatter(S: int, C: int, dtype) -> bool:
    """Compile+run a gather+scatter instance at this state shape class
    — the ACTUAL state extent, not a capped stand-in — before the step
    program traces the kernels in; probe failure demotes (one-time
    warning) and the XLA path is chosen at trace time.

    Probing at the real ``S`` matters: ``gather_rows`` stages the
    whole (S, C) state tile in VMEM, so a large sharded model can
    overflow VMEM at exactly the shapes a smaller probe would pass —
    the hist.py precedent (probe per level-shape class). The probe
    state is zeros (one transient (S, C) allocation per shape class
    per process, memoized)."""
    dt = np.dtype(dtype)

    def probe():
        import jax.numpy as jnp
        st = jnp.zeros((S, C), dt)
        ix = jnp.zeros((8,), jnp.int32)
        np.asarray(_scatter_call(st, ix[:, None], jnp.zeros((8, C), dt)))
        np.asarray(_gather_call(st, ix[:, None]))

    return eager_probe("ftrl_scatter", ("zn", S, C, dt.name), probe)


def probe_chained(K: int, w: int, dtype) -> bool:
    dt = np.dtype(dtype)

    def probe():
        import jax.numpy as jnp
        np.asarray(chained_corr(jnp.zeros((K, w, w), dt),
                                jnp.zeros((K, w, 2), dt), max(K - 1, 1)))

    return eager_probe("ftrl_chained", ("corr", K, w, dt.name), probe)


# the chained kernel's availability probe runs at ONE canonical width:
# the chained checkpoint signature must describe the accumulation
# association the drain will ACTUALLY trace, and a per-batch-width
# probe could demote some widths and not others — leaving a snapshot
# whose signature misdescribes its arithmetic. Probing capability once
# per (K, dtype) keeps the link-time signature fold and the trace-time
# kernel selection deterministically identical; a genuinely
# width-specific compile failure (VMEM at extreme widths) then
# surfaces as a LOUD compile error instead of a silent mid-stream
# association change.
_CHAINED_PROBE_W = 8


def chained_kernel_available(K: int, dtype) -> bool:
    """Can the chained triangular kernel run at this (chunk length,
    dtype) on this backend? Memoized; the chained step factory AND the
    FTRL drain's checkpoint-signature fold both resolve through here,
    so they can never disagree."""
    return probe_chained(K, _CHAINED_PROBE_W, dtype)
