"""Stream source operators.

Re-design of operator/stream/source/ (MemSourceStreamOp, CsvSourceStreamOp,
LibSvmSourceStreamOp, TextSourceStreamOp, NumSeqSourceStreamOp,
RandomTableSourceStreamOp, TableSourceStreamOp): a bounded table is chopped
into timed micro-batches. ``batch_size`` controls the micro-batch size
(amortizes device dispatch); ``time_per_batch`` scales event time so
interval-based operators (windowed eval, FTRL snapshots) see simulated
seconds, matching the reference's processing-time windows.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ....common.mtable import MTable
from ....common.params import Params
from ....common.types import TableSchema
from ....io.csv import read_csv, read_libsvm
from ...base import BatchOperator, StreamOperator


class BoundedTableStreamSource(StreamOperator):
    """Base: replayable stream over a host table."""

    def __init__(self, params: Optional[Params] = None, batch_size: int = 256,
                 time_per_batch: float = 1.0, **kwargs):
        super().__init__(params, **kwargs)
        self.batch_size = int(batch_size)
        self.time_per_batch = float(time_per_batch)
        self._table: Optional[MTable] = None

    def _resolve(self) -> MTable:
        if self._table is None:
            raise RuntimeError(f"{type(self).__name__} has no table")
        return self._table

    def _set_table(self, table: MTable):
        self._table = table
        self._schema = table.schema

        def gen():
            t = self._resolve()
            n = t.num_rows
            b = max(1, self.batch_size)
            for k, start in enumerate(range(0, n, b)):
                yield (k * self.time_per_batch,
                       t.take_rows(np.arange(start, min(start + b, n))))

        self._stream_fn = gen
        return self

    def link_from(self, *inputs):
        raise RuntimeError(f"{type(self).__name__} is a source; it takes no inputs")


class MemSourceStreamOp(BoundedTableStreamSource):
    """reference: stream/source/MemSourceStreamOp."""

    def __init__(self, rows, schema=None, batch_size: int = 256,
                 time_per_batch: float = 1.0, params=None, **kwargs):
        super().__init__(params, batch_size, time_per_batch, **kwargs)
        table = rows if isinstance(rows, MTable) else MTable(rows, schema)
        self._set_table(table)


class TableSourceStreamOp(BoundedTableStreamSource):
    """Stream view of a batch table / operator (reference TableSourceStreamOp;
    also the batch→stream hand-off used all over the reference examples)."""

    def __init__(self, table, batch_size: int = 256, time_per_batch: float = 1.0,
                 params=None, **kwargs):
        super().__init__(params, batch_size, time_per_batch, **kwargs)
        if isinstance(table, BatchOperator):
            table = table.get_output_table()
        self._set_table(table)


class CsvSourceStreamOp(BoundedTableStreamSource):
    """reference: stream/source/CsvSourceStreamOp."""

    def __init__(self, file_path: str, schema_str: str, field_delimiter: str = ",",
                 batch_size: int = 256, time_per_batch: float = 1.0,
                 params=None, **kwargs):
        super().__init__(params, batch_size, time_per_batch, **kwargs)
        self._set_table(read_csv(file_path, TableSchema.parse(schema_str),
                                 field_delimiter))


class LibSvmSourceStreamOp(BoundedTableStreamSource):
    """reference: stream/source/LibSvmSourceStreamOp."""

    def __init__(self, file_path: str, batch_size: int = 256,
                 time_per_batch: float = 1.0, params=None, **kwargs):
        super().__init__(params, batch_size, time_per_batch, **kwargs)
        self._set_table(read_libsvm(file_path))


class TextSourceStreamOp(BoundedTableStreamSource):
    """reference: stream/source/TextSourceStreamOp (one 'text' column)."""

    def __init__(self, file_path: str, text_col: str = "text", batch_size: int = 256,
                 time_per_batch: float = 1.0, params=None, **kwargs):
        super().__init__(params, batch_size, time_per_batch, **kwargs)
        with open(file_path) as f:
            lines = [ln.rstrip("\n") for ln in f]
        self._set_table(MTable({text_col: lines}))


class NumSeqSourceStreamOp(BoundedTableStreamSource):
    """reference: stream/source/NumSeqSourceStreamOp."""

    def __init__(self, from_: int, to: int, col_name: str = "num",
                 batch_size: int = 256, time_per_batch: float = 1.0,
                 params=None, **kwargs):
        super().__init__(params, batch_size, time_per_batch, **kwargs)
        self._set_table(MTable({col_name: np.arange(from_, to + 1, dtype=np.int64)}))


class RandomTableSourceStreamOp(BoundedTableStreamSource):
    """reference: stream/source/RandomTableSourceStreamOp (numeric columns)."""

    def __init__(self, num_rows: int, num_cols: int, seed: int = 0,
                 batch_size: int = 256, time_per_batch: float = 1.0,
                 params=None, **kwargs):
        super().__init__(params, batch_size, time_per_batch, **kwargs)
        rng = np.random.default_rng(seed)
        cols = {f"col{i}": rng.random(num_rows) for i in range(num_cols)}
        self._set_table(MTable(cols))


# the reference's abstract base name for all stream sources
BaseSourceStreamOp = BoundedTableStreamSource


from ....io.db import HasDB as _HasDB
from ....io.db import HasMySqlDB as _HasMySqlDB
from ....common.params import ParamInfo as _ParamInfo


class DBSourceStreamOp(_HasDB, BoundedTableStreamSource):
    """Stream a DB table as micro-batches
    (reference: stream/source/DBSourceStreamOp.java)."""
    INPUT_TABLE_NAME = _ParamInfo("input_table_name", str, "table to read")
    QUERY = _ParamInfo("query", str, "free-form SELECT overriding table name")

    def _resolve(self) -> MTable:
        if self._table is None:
            q = self.params._m.get("query")
            db = self._db()
            table = (db.query(q) if q else
                     db.read_table(self.params._m["input_table_name"]))
            self._set_table(table)
        return self._table

    def timed_batches(self):
        self._resolve()
        return super().timed_batches()

    def get_schema(self):
        self._resolve()
        return super().get_schema()


class MySqlSourceStreamOp(_HasMySqlDB, DBSourceStreamOp):
    """reference: stream/source/MySqlSourceStreamOp.java"""
