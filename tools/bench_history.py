#!/usr/bin/env python
"""Render the BENCH_r01 -> rNN trajectory per workload.

One table: workload rows x bench-round columns (samples/sec/chip), an
ASCII sparkline of each workload's trajectory, and regression flags
where a workload dropped more than the threshold between adjacent
PRESENT rounds (a round that skipped the workload doesn't hide a drop
across it). The r01/r02 dumps predate ``workloads_sps_vs`` (r01 has only
the flagship metric; r02 carries a ``workloads`` detail map) — both are
handled.

Usage:
    python tools/bench_history.py                       # BENCH_r*.json in repo root
    python tools/bench_history.py r04.json r05.json r06.json
    python tools/bench_history.py --json [--threshold PCT]
                                  [--baseline-provenance]

``--threshold PCT`` exits 2 when any adjacent-round regression exceeds
PCT percent (the ``bench_compare --threshold`` contract).
``--baseline-provenance`` refuses (exit 3) a history whose adjacent
rounds carry DIFFERENT baseline fingerprints — cross-rig / re-pinned
captures make round-over-round ratios provenance artifacts, exactly the
``bench_compare --baseline-provenance`` rule; rounds without a
fingerprint (pre-r06) warn instead.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SPARK = "▁▂▃▄▅▆▇█"

# the flagship workload the r01 dump (final-line metric only) maps to
_FLAGSHIP = "logreg_criteo"


def load_round(path: str) -> Tuple[Dict[str, float], Optional[str], str]:
    """({workload: sps}, baseline_fp, mode) from any historical BENCH
    dump shape: the driver wrapper (``parsed``), ``workloads_sps_vs``
    (r03+), the r02 ``workloads`` detail map, or the bare r01 final
    line (flagship metric only)."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: not a bench dump")
    out: Dict[str, float] = {}
    wl = doc.get("workloads_sps_vs")
    if isinstance(wl, dict) and wl:
        for name, row in wl.items():
            sps = row[0] if isinstance(row, (list, tuple)) else row
            out[str(name)] = float(sps)
    elif isinstance(doc.get("workloads"), dict):
        for name, row in doc["workloads"].items():
            if isinstance(row, dict) \
                    and "samples_per_sec_per_chip" in row:
                out[str(name)] = float(row["samples_per_sec_per_chip"])
    elif doc.get("metric") and doc.get("value") is not None:
        out[_FLAGSHIP] = float(doc["value"])
    if not out:
        raise ValueError(f"{path}: no workload rates found "
                         f"(not a bench dump?)")
    fp = doc.get("baseline_fp")
    if fp is None and isinstance(doc.get("rig"), dict):
        fp = doc["rig"].get("baseline_fp")
    return out, (str(fp) if fp is not None else None), \
        str(doc.get("mode", "full"))


def default_rounds(directory: str) -> List[str]:
    """``BENCH_r*.json`` sorted by round number (r01 < r02 < ... <
    r10)."""
    def key(p: str):
        m = re.search(r"BENCH_r(\d+)", os.path.basename(p))
        return (int(m.group(1)) if m else 0, p)
    return sorted(glob.glob(os.path.join(directory, "BENCH_r*.json")),
                  key=key)


def _round_label(path: str) -> str:
    base = os.path.basename(path)
    m = re.search(r"BENCH_(r\d+)", base)
    return m.group(1) if m else base.replace(".json", "")


def sparkline(values: List[Optional[float]]) -> str:
    """Min-max normalized blocks; '·' for rounds the workload missed."""
    present = [v for v in values if v is not None]
    if not present:
        return ""
    lo, hi = min(present), max(present)
    span = hi - lo
    out = []
    for v in values:
        if v is None:
            out.append("·")
        elif span <= 0:
            out.append(_SPARK[-1])
        else:
            out.append(_SPARK[min(len(_SPARK) - 1,
                                  int((v - lo) / span * (len(_SPARK) - 1)
                                      + 0.5))])
    return "".join(out)


def build_history(paths: List[str]) -> Dict[str, Any]:
    """Unreadable rounds (e.g. the r03 dump whose final line arrived
    head-truncated: ``parsed: null``) are SKIPPED with a note, not
    fatal — one broken capture must not erase the whole trajectory."""
    rounds = []
    skipped = []
    series: Dict[str, List[Optional[float]]] = {}
    order: List[str] = []
    for p in paths:
        try:
            wl, fp, mode = load_round(p)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            skipped.append({"path": p, "label": _round_label(p),
                            "error": str(e)})
            continue
        i = len(rounds)
        rounds.append({"path": p, "label": _round_label(p),
                       "baseline_fp": fp, "mode": mode})
        for name, sps in wl.items():
            if name not in series:
                series[name] = [None] * i
                order.append(name)
            series[name].append(sps)
        for name in order:
            if len(series[name]) < i + 1:
                series[name].append(None)
    return {"rounds": rounds, "skipped": skipped,
            "workloads": {n: series[n] for n in order}}


def regressions(hist: Dict[str, Any],
                threshold_pct: float) -> List[Dict[str, Any]]:
    """Drops beyond the threshold between ADJACENT PRESENT rounds per
    workload: each round compares against the workload's last round
    that actually measured it, so a drop across a skipped round (r04 →
    missing → r06) is still flagged instead of silently vanishing."""
    out = []
    labels = [r["label"] for r in hist["rounds"]]
    for name, vals in hist["workloads"].items():
        last_v: Optional[float] = None
        last_i = -1
        for i, v in enumerate(vals):
            if v is None:
                continue
            if last_v is not None and last_v > 0:
                delta = 100.0 * (v - last_v) / last_v
                if delta < -abs(threshold_pct):
                    out.append({"workload": name,
                                "from": labels[last_i], "to": labels[i],
                                "old": last_v, "new": v,
                                "delta_pct": round(delta, 1)})
            last_v, last_i = v, i
    return out


def check_provenance(hist: Dict[str, Any]) -> Tuple[bool, List[str]]:
    """(ok, messages): ok=False means adjacent rounds carry DIFFERENT
    fingerprints — refuse, like ``bench_compare --baseline-provenance``.
    Fingerprint-less rounds produce warnings, never refusal."""
    msgs = []
    rounds = hist["rounds"]
    missing = [r["label"] for r in rounds if r["baseline_fp"] is None]
    if missing:
        msgs.append(f"WARNING: --baseline-provenance: no baseline "
                    f"fingerprint recorded in {', '.join(missing)} "
                    f"(pre-r06 capture?) — provenance not verifiable")
    ok = True
    # compare each fingerprinted round against the LAST KNOWN
    # fingerprint, not just the adjacent round — a fingerprint-less
    # round in between must not launder a rig change past the refusal
    last_fp: Optional[str] = None
    last_label: Optional[str] = None
    for r in rounds:
        fp = r["baseline_fp"]
        if fp is None:
            continue
        if last_fp is not None and fp != last_fp:
            ok = False
            msgs.append(
                f"REFUSING to compare {last_label} -> {r['label']}: "
                f"baseline fingerprints differ ({last_fp} vs {fp}) — "
                f"the captures ran against different rigs or a "
                f"re-pinned baseline, so round-over-round deltas would "
                f"be provenance artifacts, not code changes")
        last_fp, last_label = fp, r["label"]
    return ok, msgs


def _fmt(v: Optional[float]) -> str:
    return f"{v:,.0f}" if v is not None else "-"


def _display_name(name: str) -> str:
    """Serving rows measure requests, not samples: label them so the
    shared rate column stays readable (``serve_* (qps)``); the p99inv
    gate row is a reciprocal latency, called out explicitly."""
    if name.endswith("_p99inv"):
        return f"{name} (1/p99 s)"
    if name == "tuning_sweep":
        # the sweep row's rate is candidate points tuned per second
        # through the ASHA sweep engine (ISSUE 12)
        return f"{name} (points/s)"
    if name == "serve_fused":
        # whole-table scoring through the fused Pallas kernel, not the
        # micro-batcher: rows scored per second (ISSUE 13)
        return f"{name} (rows/s)"
    if name == "ftrl_pallas":
        # the Pallas-path staleness kernel rate — interpret-mode on CPU
        # rigs (the row's rig_note), native Mosaic on TPU (ISSUE 13)
        return f"{name} (samples/s, kernel tier)"
    if name.startswith("serve_") and name.endswith("_sharded"):
        # multi-chip serving rows report per-chip throughput at the
        # widest measured mesh (ISSUE 11)
        return f"{name} (qps/chip)"
    if name == "serve_chaos":
        # throughput DURING the scripted fault storm — degraded by
        # design; the SLO contract rides the row's own fields (ISSUE 14)
        return f"{name} (qps under storm)"
    if name == "serve_fleet":
        # steady-state multi-tenant throughput with cross-tenant batch
        # coalescing; the leak proof / eviction storm evidence rides the
        # row's own fields (ISSUE 17)
        return f"{name} (qps, multi-tenant)"
    if name == "serve_online_e2e":
        # the whole online-learning DAG's steady-state scoring rate;
        # the SLO verdicts / recovery evidence ride the row (ISSUE 15)
        return f"{name} (qps, whole-loop DAG)"
    if name.startswith("serve_"):
        return f"{name} (qps)"
    return name


def render(hist: Dict[str, Any], regs: List[Dict[str, Any]]) -> str:
    labels = [r["label"] for r in hist["rounds"]]
    out = ["bench history (samples/sec/chip)"]
    names = list(hist["workloads"])
    if not names:
        return out[0] + "\n  (no workloads)"
    wn = max(len("workload"), *(len(_display_name(n)) for n in names))
    cols = [max(len(l), *(len(_fmt(hist["workloads"][n][i]))
                          for n in names))
            for i, l in enumerate(labels)]
    sw = max(len("trend"), len(labels))
    head = ("  " + "workload".ljust(wn) + "  "
            + "  ".join(l.rjust(c) for l, c in zip(labels, cols))
            + "  " + "trend".ljust(sw))
    out.append(head)
    out.append("  " + "-" * (len(head) - 2))
    flagged = {(r["workload"], r["to"]) for r in regs}
    for n in names:
        vals = hist["workloads"][n]
        cells = []
        for i, v in enumerate(vals):
            cell = _fmt(v)
            if (n, labels[i]) in flagged:
                cell += "!"
            cells.append(cell.rjust(cols[i]))
        out.append("  " + _display_name(n).ljust(wn) + "  "
                   + "  ".join(cells) + "  " + sparkline(vals))
    if regs:
        out.append("")
        for r in regs:
            out.append(f"  REGRESSION {r['workload']}: {r['from']} -> "
                       f"{r['to']}  {_fmt(r['old'])} -> {_fmt(r['new'])} "
                       f"({r['delta_pct']:+.1f}%)")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_history.py", description=__doc__.splitlines()[0])
    ap.add_argument("files", nargs="*",
                    help="bench dumps in round order (default: "
                         "BENCH_r*.json in --dir, numerically sorted)")
    ap.add_argument("--dir", default=ROOT,
                    help="directory to glob BENCH_r*.json from "
                         "(default: repo root)")
    ap.add_argument("--threshold", type=float, metavar="PCT",
                    help="exit 2 when any adjacent-round regression "
                         "exceeds PCT percent")
    ap.add_argument("--json", action="store_true",
                    help="emit the history as JSON")
    ap.add_argument("--baseline-provenance", action="store_true",
                    help="refuse (exit 3) mixed-fingerprint round "
                         "sequences, like bench_compare")
    args = ap.parse_args(argv)
    paths = args.files or default_rounds(args.dir)
    if len(paths) < 2:
        print(f"bench_history.py: need at least two bench dumps, "
              f"found {len(paths)}", file=sys.stderr)
        return 1
    hist = build_history(paths)
    for s in hist["skipped"]:
        print(f"bench_history.py: skipping {s['label']}: {s['error']}",
              file=sys.stderr)
    if len(hist["rounds"]) < 2:
        print(f"bench_history.py: need at least two READABLE bench "
              f"dumps, got {len(hist['rounds'])}", file=sys.stderr)
        return 1
    if args.baseline_provenance:
        ok, msgs = check_provenance(hist)
        for m in msgs:
            print(f"bench_history.py: {m}", file=sys.stderr)
        if not ok:
            return 3
    modes = {r["mode"] for r in hist["rounds"]}
    if len(modes) > 1:
        print("WARNING: mixing quick and full captures — deltas "
              "reflect fixture sizes, not code changes", file=sys.stderr)
    regs = regressions(hist, args.threshold) \
        if args.threshold is not None else []
    if args.json:
        json.dump({"rounds": hist["rounds"],
                   "workloads": hist["workloads"],
                   "threshold_pct": args.threshold,
                   "regressions": regs}, sys.stdout, indent=1)
        sys.stdout.write("\n")
    else:
        print(render(hist, regs))
    return 2 if regs else 0


if __name__ == "__main__":
    raise SystemExit(main())
