"""Columnar prediction-detail column.

The predict -> eval hot path used to round-trip every row through JSON:
the mapper ``json.dumps``-ed one detail dict per row and the stream
evaluator ``json.loads``-ed them back (re-parsing the whole cumulative
span every window). This class keeps the per-class probabilities
columnar — ``(labels, probs (n, k))`` — and renders the EXACT
``json.dumps({str(label): float(p), ...})`` string only when a consumer
actually asks for a row (sinks, to_rows); ``parse_detail_probs``
recognizes it and reads the probability matrix zero-parse.
"""

from __future__ import annotations

import json
from typing import List, Sequence

import numpy as np

from ....common.columnar import ColumnarColumn


class PredictionDetailColumn(ColumnarColumn):
    """Columnar (labels, probs) details (protocol: common/columnar.py)."""

    __slots__ = ("labels", "probs")

    def __init__(self, labels: Sequence[str], probs: np.ndarray):
        assert probs.ndim == 2 and probs.shape[1] == len(labels)
        self.labels: List[str] = [str(l) for l in labels]
        self.probs = probs

    def __len__(self):
        return self.probs.shape[0]

    def _render_row(self, i: int) -> str:
        return json.dumps({l: float(p)
                           for l, p in zip(self.labels, self.probs[i])})

    def _subset(self, sel):
        return PredictionDetailColumn(self.labels, self.probs[sel])

    def copy(self) -> "PredictionDetailColumn":
        return PredictionDetailColumn(self.labels, self.probs.copy())

    def concat_same(self, other):
        if (isinstance(other, PredictionDetailColumn)
                and other.labels == self.labels):
            return PredictionDetailColumn(
                self.labels, np.concatenate([self.probs, other.probs]))
        return None
