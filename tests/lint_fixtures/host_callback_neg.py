"""HOST-CALLBACK-FREE negative: a compiled-path module with no host
callbacks; plain host-side printing outside jax.debug is fine."""
import jax.numpy as jnp


def stage(ctx):
    return jnp.sum(ctx)


def report(result):
    print("done", result)         # host code, not a jax callback
