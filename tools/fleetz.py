#!/usr/bin/env python
"""fleetz — scrape N alink_tpu admin endpoints into ONE fleet report
(ISSUE 16; the observation path ROADMAP item 5's multi-host workers and
item 2's multi-tenant fleets will ride).

Every long-lived alink_tpu process with ``ALINK_TPU_ADMIN_PORT`` armed
exposes the live operations plane (``alink_tpu/common/adminz.py``).
This tool fans a scrape out over a worker list, merges ``/varz`` +
``/statusz`` + the health verdicts, and renders one table — per-worker
columns plus a fleet aggregate — with the same table machinery
``run_report.py`` uses, so fleet output reads like every other report
in the repo.

    python tools/fleetz.py localhost:8321 localhost:8322 ...
    python tools/fleetz.py --json host:port ...       # machine-readable
    python tools/fleetz.py --snapshot DIR host:port   # archive scrapes

``--snapshot DIR`` writes each worker's raw ``varz.json`` /
``statusz.json`` / ``metrics.prom`` — plus ``tracez.json`` /
``requestz.json`` (the Layer-6 flight-recorder and request-timeline
views, ISSUE 18) and ``compilez.json`` (the Layer-7 compile ledger,
ISSUE 19) when the worker serves them — and the merged
``fleet.json``. The directory shape is what ``tools/doctor.py --url``
accepts as an offline input, so a fleet snapshot taken during an
incident replays through the verdict renderer later.

Unreachable workers are reported per worker (column ``DOWN``), not
fatal; the exit code is nonzero only when NO worker answered.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)


def _load_sibling_tool(name: str):
    """Import a sibling tools/*.py module (tools/ is not a package)."""
    import importlib.util
    p = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     f"{name}.py")
    spec = importlib.util.spec_from_file_location(
        f"alink_tpu_tool_{name}", p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def parse_prom_text(text: str) -> List[Tuple[str, Dict[str, str], float]]:
    """Parse Prometheus exposition text into ``(name, labels, value)``
    samples — enough of the format to round-trip what
    ``MetricsRegistry.render_text`` emits (and to prove a scraped
    ``/metrics`` body parses, which the smoke leg asserts)."""
    out: List[Tuple[str, Dict[str, str], float]] = []
    for ln in text.splitlines():
        ln = ln.strip()
        if not ln or ln.startswith("#"):
            continue
        head, _, val = ln.rpartition(" ")
        if not head:
            raise ValueError(f"malformed prom sample: {ln!r}")
        labels: Dict[str, str] = {}
        name = head
        if head.endswith("}"):
            name, _, body = head.partition("{")
            body = body[:-1]
            i = 0
            while i < len(body):
                eq = body.index("=", i)
                k = body[i:eq]
                if body[eq + 1] != '"':
                    raise ValueError(f"malformed labels in: {ln!r}")
                j = eq + 2
                buf = []
                while body[j] != '"':
                    if body[j] == "\\":
                        nxt = body[j + 1]
                        buf.append({"n": "\n"}.get(nxt, nxt))
                        j += 2
                    else:
                        buf.append(body[j])
                        j += 1
                labels[k] = "".join(buf)
                i = j + 1
                if i < len(body) and body[i] == ",":
                    i += 1
        out.append((name, labels, float(val)))
    return out


def _norm_url(worker: str) -> str:
    if "://" not in worker:
        worker = f"http://{worker}"
    return worker.rstrip("/")


def _get(url: str, timeout: float) -> Tuple[int, bytes]:
    """GET returning (status, body); admin verdict endpoints answer 503
    with a JSON body, which is a RESULT here, not an error."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def scrape_worker(worker: str, timeout: float = 5.0) -> Dict[str, Any]:
    """One worker's merged scrape: varz records, statusz doc, health/
    ready verdicts, raw prom text. ``error`` set (and the rest absent)
    when the endpoint did not answer."""
    url = _norm_url(worker)
    doc: Dict[str, Any] = {"worker": worker, "url": url}
    try:
        _, varz = _get(f"{url}/varz", timeout)
        doc["varz"] = json.loads(varz)
        _, statusz = _get(f"{url}/statusz", timeout)
        doc["statusz"] = json.loads(statusz)
        code, health = _get(f"{url}/healthz", timeout)
        doc["healthy"] = code == 200
        doc["health"] = json.loads(health)
        code, ready = _get(f"{url}/readyz", timeout)
        doc["ready"] = code == 200
        doc["readiness"] = json.loads(ready)
        _, prom = _get(f"{url}/metrics", timeout)
        doc["metrics_text"] = prom.decode("utf-8")
        doc["metrics_samples"] = len(parse_prom_text(doc["metrics_text"]))
        # the Layer-6 views (ISSUE 18) and the Layer-7 compile ledger
        # (ISSUE 19) — tolerant of 404 from workers predating them, so
        # a mixed-version fleet still scrapes clean
        for path in ("tracez", "requestz", "compilez"):
            try:
                code, body = _get(f"{url}/{path}", timeout)
                if code == 200:
                    doc[path] = json.loads(body)
            except Exception:
                pass
    except Exception as e:
        doc["error"] = f"{type(e).__name__}: {e}"
    return doc


def _series_value(varz: List[dict], name: str,
                  agg: str = "sum") -> Optional[float]:
    """Aggregate one metric family across its label sets (sum for
    counters, max for gauges where the worst series is the story).
    Histogram families contribute their ``sum`` (total seconds spent),
    which is the fleet-level story for e.g. compile wall time."""
    vals = [rec["value"] if "value" in rec else rec["sum"]
            for rec in varz
            if rec.get("name") == name
            and ("value" in rec or "sum" in rec)]
    if not vals:
        return None
    return max(vals) if agg == "max" else sum(vals)


#: the fleet table's metric rows: (label, family, per-worker agg,
#: fleet agg) — counters sum across the fleet, gauges take the worst
_METRIC_ROWS = [
    ("serve requests", "alink_serve_requests_total", "sum", "sum"),
    ("serve p99 (s)", "alink_serve_p99_seconds", "max", "max"),
    ("queue depth", "alink_serve_queue_depth", "max", "max"),
    ("shed", "alink_serve_shed_total", "sum", "sum"),
    ("breaker fallbacks", "alink_serve_breaker_fallback_total",
     "sum", "sum"),
    ("model swaps", "alink_serve_model_swaps_total", "sum", "sum"),
    ("fleet tenants", "alink_fleet_tenants", "max", "sum"),
    ("fleet evictions", "alink_fleet_evictions_total", "sum", "sum"),
    ("fleet readmissions", "alink_fleet_readmissions_total",
     "sum", "sum"),
    ("fleet coalesced", "alink_fleet_coalesced_batches_total",
     "sum", "sum"),
    ("compiles", "alink_compile_total", "sum", "sum"),
    ("compile disk hits", "alink_compile_disk_hits_total", "sum", "sum"),
    ("compile wall (s)", "alink_compile_seconds", "sum", "sum"),
    ("compile storms", "alink_compile_storms_total", "sum", "sum"),
    ("storm active", "alink_compile_storm_active", "max", "max"),
    ("slo breaches", "alink_slo_breaches_total", "sum", "sum"),
    ("slo burn (max)", "alink_slo_burn_rate", "max", "max"),
    ("slo alerts", "alink_slo_alerts_total", "sum", "sum"),
    ("admin scrapes", "alink_admin_requests_total", "sum", "sum"),
]


def _fmt(v: Optional[float]) -> str:
    if v is None:
        return "-"
    if v != v:  # NaN
        return "nan"
    if abs(v - round(v)) < 1e-9 and abs(v) < 1e15:
        return f"{int(round(v)):,}"
    return f"{v:.6g}"


def fleet_report(scrapes: List[Dict[str, Any]]) -> Dict[str, Any]:
    """The merged machine-readable fleet document (also what
    ``fleet.json`` archives)."""
    workers = []
    for s in scrapes:
        w: Dict[str, Any] = {"worker": s["worker"], "url": s["url"]}
        if "error" in s:
            w["error"] = s["error"]
        else:
            st = s.get("statusz") or {}
            w.update({
                "healthy": s["healthy"], "ready": s["ready"],
                "name": st.get("name"), "pid": st.get("pid"),
                "uptime_s": st.get("uptime_s"),
                "metrics_samples": s.get("metrics_samples"),
                "metrics": {fam: _series_value(s["varz"], fam, agg)
                            for _, fam, agg, _ in _METRIC_ROWS},
            })
        workers.append(w)
    up = [w for w in workers if "error" not in w]
    agg: Dict[str, Any] = {
        "workers": len(workers), "reachable": len(up),
        "healthy": sum(1 for w in up if w["healthy"]),
        "ready": sum(1 for w in up if w["ready"]),
    }
    for label, fam, _, fleet_agg in _METRIC_ROWS:
        vals = [w["metrics"][fam] for w in up
                if w["metrics"].get(fam) is not None]
        agg[fam] = (None if not vals
                    else (max(vals) if fleet_agg == "max" else sum(vals)))
    return {"workers": workers, "aggregate": agg}


def render_fleet(report: Dict[str, Any]) -> str:
    """Per-worker columns + one fleet aggregate column, through the
    run_report table renderer."""
    rr = _load_sibling_tool("run_report")
    workers = report["workers"]
    agg = report["aggregate"]
    headers = ["fleet"] + [w["worker"] for w in workers] + ["aggregate"]

    def col(w: Dict[str, Any], label: str, fam: Optional[str]) -> str:
        if "error" in w:
            return "DOWN"
        if fam is None:
            if label == "healthz":
                return "ok" if w["healthy"] else "503"
            if label == "readyz":
                return "ok" if w["ready"] else "503"
            if label == "uptime (s)":
                return _fmt(w.get("uptime_s"))
            return str(w.get("name") or "-")
        return _fmt(w["metrics"].get(fam))

    rows: List[List[str]] = []
    rows.append(["process"] + [col(w, "process", None) for w in workers]
                + [f"{agg['reachable']}/{agg['workers']} up"])
    rows.append(["healthz"] + [col(w, "healthz", None) for w in workers]
                + [f"{agg['healthy']}/{agg['reachable']} ok"])
    rows.append(["readyz"] + [col(w, "readyz", None) for w in workers]
                + [f"{agg['ready']}/{agg['reachable']} ok"])
    rows.append(["uptime (s)"] + [col(w, "uptime (s)", None)
                                  for w in workers] + ["-"])
    for label, fam, _, _fa in _METRIC_ROWS:
        rows.append([label] + [col(w, label, fam) for w in workers]
                    + [_fmt(agg.get(fam))])
    out = ["== fleet scrape =="]
    out.append(rr._table(headers, rows))
    down = [w for w in workers if "error" in w]
    for w in down:
        out.append(f"  DOWN {w['worker']}: {w['error']}")
    return "\n".join(out)


def write_snapshot(out_dir: str, scrapes: List[Dict[str, Any]],
                   report: Dict[str, Any]) -> None:
    """The offline archive: one subdir per worker with the raw scrape
    bodies, plus the merged fleet.json (the ``doctor.py --url DIR``
    input shape)."""
    os.makedirs(out_dir, exist_ok=True)
    for i, s in enumerate(scrapes):
        sub = os.path.join(out_dir, f"worker{i}_" +
                           s["worker"].replace("://", "_")
                           .replace("/", "_").replace(":", "_"))
        os.makedirs(sub, exist_ok=True)
        if "error" in s:
            with open(os.path.join(sub, "error.txt"), "w") as f:
                f.write(s["error"] + "\n")
            continue
        with open(os.path.join(sub, "varz.json"), "w") as f:
            json.dump(s["varz"], f)
        with open(os.path.join(sub, "statusz.json"), "w") as f:
            json.dump(s["statusz"], f)
        with open(os.path.join(sub, "metrics.prom"), "w") as f:
            f.write(s["metrics_text"])
        for path in ("tracez", "requestz", "compilez"):
            if s.get(path) is not None:
                with open(os.path.join(sub, f"{path}.json"), "w") as f:
                    json.dump(s[path], f)
    with open(os.path.join(out_dir, "fleet.json"), "w") as f:
        json.dump(report, f, indent=2)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Scrape N alink_tpu admin endpoints into one fleet "
                    "report")
    ap.add_argument("workers", nargs="+",
                    help="admin endpoints (host:port or http://host:port)")
    ap.add_argument("--timeout", type=float, default=5.0,
                    help="per-request scrape timeout seconds")
    ap.add_argument("--json", action="store_true",
                    help="print the merged fleet JSON instead of tables")
    ap.add_argument("--snapshot", metavar="DIR",
                    help="archive raw scrapes + fleet.json under DIR "
                         "(replayable via doctor.py --url DIR)")
    args = ap.parse_args(argv)

    scrapes = [scrape_worker(w, args.timeout) for w in args.workers]
    report = fleet_report(scrapes)
    if args.snapshot:
        write_snapshot(args.snapshot, scrapes, report)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(render_fleet(report))
        if args.snapshot:
            print(f"snapshot -> {args.snapshot}")
    return 0 if report["aggregate"]["reachable"] else 2


if __name__ == "__main__":
    sys.exit(main())
