"""ALS batch operators.

Re-design of batch/recommendation/ AlsTrainBatchOp, AlsPredictBatchOp,
AlsTopKPredictBatchOp + AlsModelDataConverter (common/recommendation/).
"""

from __future__ import annotations

import json
from typing import List, Optional

import numpy as np

from ....common.mtable import MTable
from ....common.params import ParamInfo, Params, RangeValidator
from ....common.types import AlinkTypes, TableSchema
from ....model.converters import (SimpleModelDataConverter, decode_array,
                                  encode_array)
from ....params.shared import HasPredictionCol, HasReservedCols, HasSeed
from ...base import BatchOperator
from ...common.recommendation.als import AlsTrainParams, als_train


class AlsModelData:
    def __init__(self, user_ids: List, item_ids: List, user_factors: np.ndarray,
                 item_factors: np.ndarray, user_col: str, item_col: str,
                 rate_col: str):
        self.user_ids = user_ids
        self.item_ids = item_ids
        self.user_factors = user_factors
        self.item_factors = item_factors
        self.user_col, self.item_col, self.rate_col = user_col, item_col, rate_col


class AlsModelDataConverter(SimpleModelDataConverter):
    """reference: common/recommendation/AlsModelDataConverter.java"""

    def serialize_model(self, m: AlsModelData):
        meta = Params({"user_col": m.user_col, "item_col": m.item_col,
                       "rate_col": m.rate_col,
                       "user_ids": [str(u) for u in m.user_ids],
                       "item_ids": [str(i) for i in m.item_ids]})
        return meta, [encode_array(m.user_factors), encode_array(m.item_factors)]

    def deserialize_model(self, meta, data):
        return AlsModelData(
            list(meta._m.get("user_ids", [])), list(meta._m.get("item_ids", [])),
            decode_array(data[0]), decode_array(data[1]),
            meta._m.get("user_col", "user"), meta._m.get("item_col", "item"),
            meta._m.get("rate_col", "rating"))


class AlsTrainBatchOp(BatchOperator, HasSeed):
    """reference: batch/recommendation/AlsTrainBatchOp.java"""
    USER_COL = ParamInfo("user_col", str, optional=False)
    ITEM_COL = ParamInfo("item_col", str, optional=False)
    RATE_COL = ParamInfo("rate_col", str, optional=False)
    RANK = ParamInfo("rank", int, default=10, validator=RangeValidator(1, None))
    NUM_ITER = ParamInfo("num_iter", int, default=10,
                         validator=RangeValidator(1, None))
    LAMBDA = ParamInfo("lambda_", float, default=0.1, aliases=("lambda",))
    IMPLICIT_PREFS = ParamInfo("implicit_prefs", bool, default=False)
    ALPHA = ParamInfo("alpha", float, default=40.0)
    NONNEGATIVE = ParamInfo("nonnegative", bool, default=False)
    SHARD_SOLVE = ParamInfo("shard_solve", bool, default=False,
                            description="shard the normal-equation "
                                        "accumulation + solve by id range "
                                        "(reduce_scatter) and all_gather "
                                        "only the solved factors")

    def link_from(self, in_op: BatchOperator) -> "AlsTrainBatchOp":
        t = in_op.get_output_table()
        uc, ic, rc = self.get_user_col(), self.get_item_col(), self.get_rate_col()
        users_raw = t.col(uc)
        items_raw = t.col(ic)
        user_ids = sorted({_c(v) for v in users_raw}, key=str)
        item_ids = sorted({_c(v) for v in items_raw}, key=str)
        u_lookup = {v: i for i, v in enumerate(user_ids)}
        i_lookup = {v: i for i, v in enumerate(item_ids)}
        users = np.asarray([u_lookup[_c(v)] for v in users_raw], np.int32)
        items = np.asarray([i_lookup[_c(v)] for v in items_raw], np.int32)
        ratings = np.asarray(t.col(rc), np.float64)
        p = AlsTrainParams(
            rank=self.get_rank(), num_iter=self.get_num_iter(),
            lambda_reg=self.get_lambda_(), implicit_prefs=self.get_implicit_prefs(),
            alpha=self.get_alpha(), nonnegative=self.get_nonnegative(),
            seed=self.get_seed(), shard_solve=self.get_shard_solve())
        uf, if_, curve = als_train(users, items, ratings, p,
                                   num_users=len(user_ids),
                                   num_items=len(item_ids))
        model = AlsModelData(user_ids, item_ids, np.asarray(uf, np.float64),
                             np.asarray(if_, np.float64), uc, ic, rc)
        self._output = AlsModelDataConverter().save_model(model)
        self._side_outputs = [MTable({"iter": np.arange(1, len(curve) + 1),
                                      "train_rmse": curve.astype(np.float64)})]
        return self


def _c(v):
    return v.item() if isinstance(v, np.generic) else v


def _id_index(ids) -> dict:
    """id -> row index under both the raw and the string form of the id."""
    lookup: dict = {}
    for i, v in enumerate(ids):
        lookup.setdefault(v, i)
        lookup.setdefault(str(v), i)
    return lookup


def _encode_ids(col, lookup: dict) -> np.ndarray:
    """id -> factor-row encode; -1 for unknown ids.

    The column collapses to its distinct values first (np.unique), so only
    O(distinct) Python-level dict probes run regardless of row count — the
    factor math afterwards is a single gather + einsum. Columns whose
    values don't sort (mixed types) fall back to a memoized row loop."""
    arr = np.asarray(col)
    try:
        uniq, inv = np.unique(arr, return_inverse=True)
    except TypeError:
        out = np.empty(len(col), np.int64)
        memo: dict = {}
        for r, v in enumerate(col):
            v = _c(v)
            j = memo.get(v)
            if j is None:
                j = lookup.get(str(v), lookup.get(v, -1))
                memo[v] = j
            out[r] = j
        return out
    codes = np.asarray([lookup.get(str(_c(v)), lookup.get(_c(v), -1))
                        for v in uniq], np.int64)
    return codes[inv.reshape(-1)]


class AlsRater:
    """Loaded ALS factors + id lookups, reusable across calls — the stream
    predict op loads this once and rates every micro-batch with it."""

    def __init__(self, model_table: MTable):
        self.m = AlsModelDataConverter().load_model(model_table)
        # ids round-trip to strings through the model table, so index both
        # the raw and the str form of every id
        self.u_lookup = _id_index(self.m.user_ids)
        self.i_lookup = _id_index(self.m.item_ids)

    def rate_table(self, t: MTable, user_col: str, item_col: str,
                   prediction_col: str, reserved_cols=None) -> MTable:
        m = self.m
        ui = _encode_ids(t.col(user_col), self.u_lookup)
        ii = _encode_ids(t.col(item_col), self.i_lookup)
        valid = (ui >= 0) & (ii >= 0)
        # one gather per side + a row-wise dot; unknown ids -> NaN
        preds = np.einsum("ij,ij->i", m.user_factors[np.maximum(ui, 0)],
                          m.item_factors[np.maximum(ii, 0)])
        preds = np.where(valid, preds, np.nan)
        from ....mapper.base import OutputColsHelper
        helper = OutputColsHelper(t.schema, [prediction_col],
                                  [AlinkTypes.DOUBLE], reserved_cols)
        return helper.build_output(t, [preds])


class AlsPredictBatchOp(BatchOperator, HasPredictionCol, HasReservedCols):
    """Predict the rating of (user, item) rows (reference AlsPredictBatchOp)."""
    USER_COL = ParamInfo("user_col", str, optional=False)
    ITEM_COL = ParamInfo("item_col", str, optional=False)

    def link_from(self, model_op: BatchOperator, data_op: BatchOperator):
        rater = AlsRater(model_op.get_output_table())
        self._output = rater.rate_table(
            data_op.get_output_table(), self.get_user_col(),
            self.get_item_col(), self.params._m.get("prediction_col", "pred"),
            self.params._m.get("reserved_cols"))
        return self


class AlsTopKPredictBatchOp(BatchOperator, HasPredictionCol):
    """Top-K item recommendations per user row (reference AlsTopKPredictBatchOp)."""
    USER_COL = ParamInfo("user_col", str, optional=False)
    TOP_K = ParamInfo("top_k", int, default=10)

    def link_from(self, model_op: BatchOperator, data_op: BatchOperator):
        m = AlsModelDataConverter().load_model(model_op.get_output_table())
        t = data_op.get_output_table()
        u_lookup = _id_index(m.user_ids)
        k = min(self.get_top_k(), len(m.item_ids))
        recs = np.empty(t.num_rows, object)
        # one matmul for all requested users (MXU-sized batch)
        uidx = _encode_ids(t.col(self.get_user_col()), u_lookup)
        valid = uidx >= 0
        scores = m.user_factors[np.maximum(uidx, 0)] @ m.item_factors.T
        top = np.argsort(-scores, axis=1)[:, :k]
        for r in range(t.num_rows):
            if not valid[r]:
                recs[r] = None
                continue
            recs[r] = json.dumps({
                "object": [str(m.item_ids[j]) for j in top[r]],
                "rate": [float(scores[r, j]) for j in top[r]]})
        from ....mapper.base import OutputColsHelper
        helper = OutputColsHelper(t.schema,
                                  [self.params._m.get("prediction_col",
                                                      "recommendations")],
                                  [AlinkTypes.STRING])
        self._output = helper.build_output(t, [recs])
        return self
