"""The PyAlink user contract: every operator / pipeline stage of the
reference inventory (SURVEY §2.5) is importable from the top-level
``alink_tpu`` namespace (the ``from pyalink.alink import *`` idiom,
reference README.md:49-58)."""

import alink_tpu

# Reference class names, grouped as in SURVEY §2.5.
REFERENCE_INVENTORY = [
    # classification
    "LogisticRegressionTrainBatchOp", "LogisticRegressionPredictBatchOp",
    "LinearSvmTrainBatchOp", "LinearSvmPredictBatchOp",
    "SoftmaxTrainBatchOp", "SoftmaxPredictBatchOp",
    "FmClassifierTrainBatchOp", "FmClassifierPredictBatchOp",
    "NaiveBayesTextTrainBatchOp", "NaiveBayesTextPredictBatchOp",
    "NaiveBayesTrainBatchOp", "NaiveBayesPredictBatchOp",
    "DecisionTreeTrainBatchOp", "DecisionTreePredictBatchOp",
    "RandomForestTrainBatchOp", "RandomForestPredictBatchOp",
    "GbdtTrainBatchOp", "GbdtPredictBatchOp",
    "MultilayerPerceptronTrainBatchOp", "MultilayerPerceptronPredictBatchOp",
    # regression
    "LinearRegTrainBatchOp", "LinearRegPredictBatchOp",
    "RidgeRegTrainBatchOp", "RidgeRegPredictBatchOp",
    "LassoRegTrainBatchOp", "LassoRegPredictBatchOp",
    "AftSurvivalRegTrainBatchOp", "AftSurvivalRegPredictBatchOp",
    "GlmTrainBatchOp", "GlmPredictBatchOp", "GlmEvaluationBatchOp",
    "IsotonicRegTrainBatchOp", "IsotonicRegPredictBatchOp",
    "DecisionTreeRegTrainBatchOp", "DecisionTreeRegPredictBatchOp",
    "RandomForestRegTrainBatchOp", "RandomForestRegPredictBatchOp",
    "GbdtRegTrainBatchOp", "GbdtRegPredictBatchOp",
    "FmRegressorTrainBatchOp", "FmRegressorPredictBatchOp",
    # clustering
    "KMeansTrainBatchOp", "KMeansPredictBatchOp",
    "BisectingKMeansTrainBatchOp", "BisectingKMeansPredictBatchOp",
    "GmmTrainBatchOp", "GmmPredictBatchOp",
    "LdaTrainBatchOp", "LdaPredictBatchOp",
    # recommendation
    "AlsTrainBatchOp", "AlsPredictBatchOp", "AlsTopKPredictBatchOp",
    # NLP
    "Word2VecTrainBatchOp", "Word2VecPredictBatchOp",
    "DocCountVectorizerTrainBatchOp", "DocCountVectorizerPredictBatchOp",
    "DocHashCountVectorizerTrainBatchOp", "DocHashCountVectorizerPredictBatchOp",
    "SegmentBatchOp", "TokenizerBatchOp", "RegexTokenizerBatchOp",
    "NGramBatchOp", "StopWordsRemoverBatchOp", "WordCountBatchOp",
    "StringSimilarityPairwiseBatchOp",
    "ApproxVectorSimilarityJoinLSHBatchOp", "ApproxVectorSimilarityTopNLSHBatchOp",
    # feature
    "OneHotTrainBatchOp", "OneHotPredictBatchOp",
    "QuantileDiscretizerTrainBatchOp", "QuantileDiscretizerPredictBatchOp",
    "BucketizerBatchOp", "BinarizerBatchOp", "FeatureHasherBatchOp",
    "ChiSqSelectorBatchOp", "PcaTrainBatchOp", "PcaPredictBatchOp",
    "DCTBatchOp", "VectorChiSqSelectorBatchOp",
    # dataproc
    "StandardScalerTrainBatchOp", "StandardScalerPredictBatchOp",
    "MinMaxScalerTrainBatchOp", "MinMaxScalerPredictBatchOp",
    "MaxAbsScalerTrainBatchOp", "MaxAbsScalerPredictBatchOp",
    "ImputerTrainBatchOp", "ImputerPredictBatchOp",
    "StringIndexerTrainBatchOp", "StringIndexerPredictBatchOp",
    "MultiStringIndexerTrainBatchOp", "MultiStringIndexerPredictBatchOp",
    "IndexToStringPredictBatchOp",
    "SampleBatchOp", "SampleWithSizeBatchOp", "WeightSampleBatchOp",
    "SplitBatchOp", "FirstNBatchOp", "AppendIdBatchOp",
    "NumericalTypeCastBatchOp", "JsonValueBatchOp",
    "VectorAssemblerBatchOp", "VectorSliceBatchOp", "VectorInteractionBatchOp",
    "VectorNormalizeBatchOp", "VectorElementwiseProductBatchOp",
    "VectorPolynomialExpandBatchOp", "VectorSizeHintBatchOp",
    "VectorStandardScalerTrainBatchOp", "VectorStandardScalerPredictBatchOp",
    "VectorMinMaxScalerTrainBatchOp", "VectorMinMaxScalerPredictBatchOp",
    "VectorMaxAbsScalerTrainBatchOp", "VectorMaxAbsScalerPredictBatchOp",
    "VectorImputerTrainBatchOp", "VectorImputerPredictBatchOp",
    # format conversion (sample of the 31-op matrix)
    "VectorToColumnsBatchOp", "ColumnsToVectorBatchOp", "KvToColumnsBatchOp",
    "ColumnsToKvBatchOp", "JsonToColumnsBatchOp", "ColumnsToJsonBatchOp",
    "CsvToColumnsBatchOp", "ColumnsToCsvBatchOp", "TripleToColumnsBatchOp",
    # statistics
    "SummarizerBatchOp", "VectorSummarizerBatchOp", "CorrelationBatchOp",
    "VectorCorrelationBatchOp", "ChiSquareTestBatchOp", "VectorChiSquareTestBatchOp",
    # evaluation
    "EvalBinaryClassBatchOp", "EvalMultiClassBatchOp",
    "EvalRegressionBatchOp", "EvalClusterBatchOp",
    # outlier / association rules
    "SosBatchOp", "FpGrowthBatchOp", "PrefixSpanBatchOp",
    # SQL
    "SelectBatchOp", "AsBatchOp", "WhereBatchOp", "FilterBatchOp",
    "GroupByBatchOp", "JoinBatchOp", "LeftOuterJoinBatchOp",
    "RightOuterJoinBatchOp", "FullOuterJoinBatchOp", "UnionBatchOp",
    "UnionAllBatchOp", "IntersectBatchOp", "IntersectAllBatchOp",
    "MinusBatchOp", "MinusAllBatchOp", "DistinctBatchOp", "OrderByBatchOp",
    # sources / sinks
    "CsvSourceBatchOp", "CsvSinkBatchOp", "LibSvmSourceBatchOp",
    "LibSvmSinkBatchOp", "TextSourceBatchOp", "TextSinkBatchOp",
    "MemSourceBatchOp", "NumSeqSourceBatchOp", "TableSourceBatchOp",
    "MySqlSourceBatchOp", "MySqlSinkBatchOp",
    # utils
    "UDFBatchOp", "UDTFBatchOp",
    # stream layer
    "MemSourceStreamOp", "CsvSourceStreamOp", "CsvSinkStreamOp",
    "LogisticRegressionPredictStreamOp", "KMeansPredictStreamOp",
    "EvalBinaryClassStreamOp", "EvalMultiClassStreamOp",
    "WindowGroupByStreamOp", "SelectStreamOp", "WhereStreamOp",
    "SampleStreamOp", "SplitStreamOp", "SegmentStreamOp",
    "FtrlTrainStreamOp", "FtrlPredictStreamOp",
    "KafkaSourceStreamOp", "KafkaSinkStreamOp",
    # pipeline stages
    "Pipeline", "PipelineModel", "LocalPredictor",
    "LogisticRegression", "LinearSvm", "Softmax", "LinearRegression",
    "RandomForestClassifier", "GbdtClassifier", "DecisionTreeClassifier",
    "KMeans", "BisectingKMeans", "GaussianMixture", "Lda",
    "NaiveBayesTextClassifier", "FmClassifier", "FmRegressor", "OneVsRest",
    "StandardScaler", "MinMaxScaler", "MaxAbsScaler", "Imputer",
    "OneHotEncoder", "QuantileDiscretizer", "Bucketizer", "Binarizer",
    "FeatureHasher", "VectorAssembler", "Pca", "Segment", "Word2Vec",
    "DocCountVectorizer", "ALS",
    # tuning
    "GridSearchCV", "GridSearchTVSplit", "ParamGrid",
    "BinaryClassificationTuningEvaluator", "MultiClassClassificationTuningEvaluator",
    "RegressionTuningEvaluator", "ClusterTuningEvaluator",
]


def test_reference_inventory_resolves_flat():
    missing = [n for n in REFERENCE_INVENTORY if not hasattr(alink_tpu, n)]
    assert not missing, f"{len(missing)} reference names missing: {missing}"


def test_flat_names_are_classes():
    assert isinstance(alink_tpu.LogisticRegressionTrainBatchOp, type)
    assert isinstance(alink_tpu.Pipeline, type)


def test_dir_exposes_flat_surface():
    d = dir(alink_tpu)
    assert "KMeansTrainBatchOp" in d and "FtrlTrainStreamOp" in d


def test_star_import_exports_inventory():
    ns = {}
    exec("from alink_tpu import *", ns)
    assert "KMeansTrainBatchOp" in ns and "Pipeline" in ns
    assert "FtrlTrainStreamOp" in ns
