"""alink-lint — compiled-program invariant analyzer for ``alink_tpu/``.

Every feature PR so far guarded its compiled-program invariants
("flag-off HLO byte-identical", "no host callbacks in compiled
programs", "collectives only via the manifest", "every env flag that
changes a trace folds into the cache key") with per-feature runtime
tests and reviewer vigilance. This package makes those invariants
**machine-checked on every run of the tier-1 suite**, anchored by the
declarative flag registry in ``alink_tpu/common/flags.py``.

Five rules (see ``tools/lint/rules.py`` for the precise semantics):

  ENV-KEY-FOLD       an env read reachable from a program/step factory
                     whose flag is not declared (in the registry) as
                     folding into that factory's cache-key dimension
                     and not declared key-neutral — the exact staleness
                     class PRs 4-6 each re-plumbed by hand;
  TRACED-CAPTURE     closure cells or globals captured by traced
                     functions (comqueue stage bodies, jitted/shard_map
                     callables) that hold device arrays or mutated
                     host containers — today only a runtime
                     RuntimeWarning in ``engine/comqueue.py``;
  DONATE-USE-AFTER   a name passed at a ``donate_argnums`` position and
                     read again before being rebound — the bug class
                     ``tests/test_overlap.py`` can only catch per-site;
  COLLECTIVE-SITE    raw ``lax.psum``/``all_gather``/... outside
                     ``engine/communication.py``, which silently escape
                     the collective manifest;
  HOST-CALLBACK-FREE ``io_callback``/``pure_callback``/
                     ``jax.debug.print`` inside compiled-path modules.

Pure ``ast`` — the analyzer never imports the analyzed package (and so
never imports jax); the flag registry is loaded standalone from its
file via importlib, which works because ``common/flags.py`` is
deliberately stdlib-only.

CLI:  ``python -m tools.lint [--strict] [--json] [--baseline FILE]``
Baseline workflow: a true positive that is *intentional* gets an entry
in ``tools/lint_baseline.json`` with a non-empty ``justification``
string; ``--strict`` additionally fails on stale (unmatched) baseline
entries so the allowlist can only shrink with the code.
"""

from .analyzer import (Finding, ModuleIndex, load_flag_registry,
                       repo_root)
from .rules import LintConfig, default_config, run_lint
from .baseline import Baseline, BaselineError, load_baseline

__all__ = [
    "Finding", "ModuleIndex", "LintConfig", "Baseline", "BaselineError",
    "default_config", "run_lint", "load_baseline", "load_flag_registry",
    "repo_root",
]
