"""ExecutionPlan — ONE frozen, hashable plan object behind every
compiled-program cache key (ROADMAP item 1; ISSUE 19 tentpole).

PRs 7-17 threaded cache-key dimensions by hand at ~15 sites: the engine
program cache (``engine/comqueue.py`` ckey), the 7 FTRL step-factory
lru keys, the chained-mode checkpoint signatures, the serving/fleet
program caches (``serving/plan.ServingPlan`` was the first slice of
this refactor), the sweep compile groups and the online-DAG stage
identities.  :class:`ExecutionPlan` collapses them into one shape —

    ExecutionPlan(subsystem, dims=((name, value), ...))

an ORDERED tuple of named dimensions.  Three contracts:

* **byte-identity** — every migrated cache derives its legacy key via
  :meth:`ExecutionPlan.legacy_key` (``tuple(value for name, value in
  dims)``), so the key tuples — and therefore hit/miss behavior and
  all lowered HLO — are byte-identical to the hand-threaded ones
  (pinned by ``tests/test_plan.py``, the PR-7 migration discipline);
* **canonical digest** — :meth:`ExecutionPlan.digest` is a blake2b
  over a canonical serialization of the dims: stable across processes
  for plans built from flags + mesh fingerprints + buckets (the
  ROADMAP item-3 AOT-persistent-cache precondition; Python's salted
  ``hash()`` is NOT);
* **named diffs** — :meth:`ExecutionPlan.diff` names exactly the
  dimensions that changed between two plans, so the compile ledger
  (``common/compileledger.py``) can answer "why did this recompile"
  with ``ALINK_TPU_SERVE_DTYPE f32->int8`` instead of "the key tuple
  differed".

Flag RESOLUTION lives here too: :func:`engine_flags`, :func:`ftrl_plan`
and :func:`sweep_plan` are the one place the key-folding flags are
latched into plan dimensions — alink-lint's ENV-KEY-FOLD rule checks
THESE functions (plus the serving-kernel resolution sites) instead of
every consumer of the values (``tools/lint/rules.py
default_config()``).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "ExecutionPlan", "engine_flags", "engine_plan",
    "engine_checkpoint_signature", "ftrl_plan",
    "ftrl_checkpoint_signature", "serving_event_plan", "sweep_plan",
    "legacy_sweep_program_key", "dag_stage_plan",
]


# ---------------------------------------------------------------------------
# canonical serialization (the digest substrate)
# ---------------------------------------------------------------------------

_SERVE_DTYPES = ("f32", "bf16", "int8")


def _canon(v: Any, out: List[bytes]) -> None:
    """Append a canonical, cross-process-stable token stream for ``v``.

    Covers the value vocabulary cache keys are actually built from:
    primitives, tuples/lists, dicts, ndarray-likes (content-digested)
    and jax ``Mesh`` objects (fingerprinted by axis names + shape +
    device strings — ``repr(mesh)`` would bake in object addresses).
    Anything else degrades to its ``repr`` WITHOUT stability claims;
    such dims still diff correctly, they just make the digest
    process-local (the engine's live-Mesh dim is the deliberate case:
    its digest-facing token is the fingerprint)."""
    if v is None or isinstance(v, (bool, int, float, str, bytes)):
        out.append(f"{type(v).__name__}:{v!r};".encode())
        return
    if isinstance(v, (tuple, list)):
        out.append(b"(")
        for x in v:
            _canon(x, out)
        out.append(b")")
        return
    if isinstance(v, dict):
        out.append(b"{")
        for k in sorted(v, key=lambda k: (type(k).__name__, repr(k))):
            _canon(k, out)
            _canon(v[k], out)
        out.append(b"}")
        return
    if hasattr(v, "devices") and hasattr(v, "axis_names"):
        # a jax Mesh: fingerprint, never repr (device objects carry
        # process-local identity)
        try:
            import numpy as _np
            devs = tuple(str(d) for d in _np.asarray(v.devices).flat)
            out.append(("mesh:" + repr((tuple(v.axis_names),
                                        tuple(v.devices.shape),
                                        devs)) + ";").encode())
            return
        except Exception:
            pass
    if hasattr(v, "shape") and hasattr(v, "dtype"):
        import numpy as _np
        a = _np.asarray(v)
        out.append(f"nd:{a.shape}:{a.dtype}:".encode())
        out.append(hashlib.blake2b(a.tobytes(), digest_size=16).digest())
        out.append(b";")
        return
    out.append(f"obj:{v!r};".encode())


def _fmt(v: Any) -> str:
    """Bounded human-readable rendering of a dim value for diffs and
    the /compilez ledger (a 4 MB stages digest must not ride a JSON
    response whole)."""
    s = repr(v)
    if len(s) > 120:
        return s[:117] + "..."
    return s


@dataclass(frozen=True)
class ExecutionPlan:
    """One compiled-program identity: an ordered tuple of named,
    already-resolved dimensions.  Frozen + hashable (every value a
    cache key could hold already is); see the module docstring for the
    byte-identity / digest / diff contracts."""

    subsystem: str
    dims: Tuple[Tuple[str, Any], ...]

    def __post_init__(self):
        object.__setattr__(self, "dims",
                           tuple((str(n), v) for n, v in self.dims))

    # -- accessors ------------------------------------------------------
    def get(self, name: str, default: Any = None) -> Any:
        for n, v in self.dims:
            if n == name:
                return v
        return default

    def dim_names(self) -> Tuple[str, ...]:
        return tuple(n for n, _ in self.dims)

    def extend(self, *extra: Tuple[str, Any]) -> "ExecutionPlan":
        """A new plan with ``extra`` dims appended (per-call dimensions
        layered over a per-drain base plan)."""
        return ExecutionPlan(self.subsystem, self.dims + tuple(extra))

    # -- the three contracts --------------------------------------------
    def legacy_key(self) -> Tuple:
        """The hand-threaded key tuple this plan replaces: the dim
        VALUES in declaration order.  Byte-identity of every migrated
        cache key reduces to byte-identity of this tuple."""
        return tuple(v for _, v in self.dims)

    def digest(self) -> str:
        """Canonical blake2b hex digest of (subsystem, dims) — stable
        across processes for plans built from flags, mesh fingerprints
        and buckets (``tests/test_plan.py`` pins it in a fresh
        interpreter)."""
        out: List[bytes] = [f"plan:{self.subsystem};".encode()]
        for n, v in self.dims:
            out.append(f"dim:{n}=".encode())
            _canon(v, out)
        return hashlib.blake2b(b"".join(out), digest_size=16).hexdigest()

    def diff(self, prev: Optional["ExecutionPlan"]
             ) -> List[Dict[str, str]]:
        """The named dimensions on which ``self`` differs from ``prev``
        — the ledger's "why did this recompile" answer.  ``prev=None``
        (a cache's first program) diffs as a single ``cold-start``
        entry."""
        if prev is None:
            return [{"dim": "cold-start", "old": "-", "new": "-"}]
        mine = dict(self.dims)
        theirs = dict(prev.dims)
        out: List[Dict[str, str]] = []
        for n, _ in self.dims:
            if n not in theirs:
                out.append({"dim": n, "old": "<absent>",
                            "new": _fmt(mine[n])})
            elif mine[n] != theirs[n] or type(mine[n]) is not type(theirs[n]):
                out.append({"dim": n, "old": _fmt(theirs[n]),
                            "new": _fmt(mine[n])})
        for n, v in prev.dims:
            if n not in mine:
                out.append({"dim": n, "old": _fmt(v), "new": "<absent>"})
        return out


# ---------------------------------------------------------------------------
# engine (comqueue program cache + recovery signature)
# ---------------------------------------------------------------------------

def engine_flags() -> Tuple[Tuple[str, Any], ...]:
    """The engine's key-folding flag dims, latched ONCE per exec — the
    single derivation site ENV-KEY-FOLD checks for the engine cache.

    Order is load-bearing: these four occupy positions 7-10 of the
    legacy ckey tuple (after ``criterion``), so ``engine_plan`` splices
    them verbatim and ``legacy_key()`` stays byte-identical."""
    from ..common.health import health_enabled
    from ..common.profiling import step_log_enabled
    from ..engine.communication import fusion_enabled
    from ..engine.comqueue import donation_enabled
    return (("ALINK_TPU_STEP_LOG", step_log_enabled()),
            ("ALINK_TPU_HEALTH", health_enabled()),
            ("ALINK_TPU_DONATE", donation_enabled()),
            ("ALINK_TPU_FUSE_COLLECTIVES", fusion_enabled()))


def engine_plan(*, program_key: Any, stages_digest: Any, mesh: Any,
                num_workers: int, max_iter: int, seed: int,
                has_criterion: bool,
                flags: Sequence[Tuple[str, Any]],
                part_names: Tuple[str, ...],
                bcast_names: Tuple[str, ...]) -> ExecutionPlan:
    """The engine program-cache plan.  ``legacy_key()`` reproduces the
    historical 13-tuple EXACTLY (order pinned by
    ``tests/test_plan.py``):

        (program_key, stages_digest, mesh, nw, max_iter, seed,
         criterion?, step_log, probes, donate, fuse,
         sorted(parts), sorted(bcast))
    """
    flags = tuple(flags)
    step_log = flags[0]
    rest = flags[1:]
    return ExecutionPlan("engine", (
        ("program_key", program_key),
        ("stages", stages_digest),
        ("mesh", mesh),
        ("num_workers", int(num_workers)),
        ("max_iter", int(max_iter)),
        ("seed", int(seed)),
        ("criterion", bool(has_criterion)),
        step_log) + rest + (
        ("parts", tuple(part_names)),
        ("bcast", tuple(bcast_names)),
    ))


def engine_checkpoint_signature(plan: ExecutionPlan, *, part_sig: Tuple,
                                data_token: Any) -> Dict[str, Any]:
    """The engine's durable-run signature, derived from the plan dims
    (content identical to the historical direct
    ``recovery.program_signature`` call — old snapshots stay
    resumable)."""
    from ..engine import recovery
    return recovery.program_signature(
        num_workers=plan.get("num_workers"),
        max_iter=plan.get("max_iter"), seed=plan.get("seed"),
        part_sig=part_sig, bcast_names=plan.get("bcast"),
        stages_digest=plan.get("stages"), data_token=data_token,
        probes_on=plan.get("ALINK_TPU_HEALTH"),
        fuse_collectives=plan.get("ALINK_TPU_FUSE_COLLECTIVES"))


# ---------------------------------------------------------------------------
# FTRL (step-factory lru keys + stream checkpoint signature)
# ---------------------------------------------------------------------------

def ftrl_plan(*, mesh: Any, alpha: float, beta: float, l1: float,
              l2: float, dim: int, dim_pad: int, update_mode: str,
              staleness: int, chunk_size: int, has_intercept: bool,
              warm_fp: str) -> ExecutionPlan:
    """The FTRL drain's plan: hyperparameters + geometry + the resolved
    key-folding flags (``ALINK_TPU_FTRL_KERNEL`` mode,
    ``ALINK_TPU_DONATE``, chained-mode ``ALINK_TPU_FUSE_COLLECTIVES``),
    latched ONCE per drain at this single ENV-KEY-FOLD-checked site.

    ``kernel_resolved`` is the availability-probed tier the chained
    signature folds ("pallas" only when the triangular kernel can
    actually run at this chunk length/dtype — the probe-demoted drain
    keeps the flag-off signature, same numbers, interchangeable
    snapshots)."""
    from ..engine.communication import fusion_enabled
    from ..engine.comqueue import donation_enabled
    from ..kernels.ftrl import chained_kernel_available, ftrl_kernel_mode

    chained = update_mode == "chained"
    kern = ftrl_kernel_mode()
    resolved = "off"
    if chained and kern == "pallas":
        import jax as _jx
        import numpy as _np
        if chained_kernel_available(
                int(chunk_size),
                _np.float64 if _jx.config.jax_enable_x64
                else _np.float32):
            resolved = "pallas"
    return ExecutionPlan("ftrl", (
        ("mesh", mesh),
        ("alpha", alpha), ("beta", beta), ("l1", l1), ("l2", l2),
        ("dim", int(dim)), ("dim_pad", int(dim_pad)),
        ("update_mode", str(update_mode)),
        ("staleness", int(staleness)
         if update_mode == "staleness" else None),
        ("chunk_size", int(chunk_size) if chained else None),
        ("has_intercept", bool(has_intercept)),
        ("warm_coef_blake2b", str(warm_fp)),
        ("ALINK_TPU_FTRL_KERNEL", kern),
        ("kernel_resolved", resolved),
        ("ALINK_TPU_DONATE", donation_enabled()),
        ("ALINK_TPU_FUSE_COLLECTIVES",
         fusion_enabled() if chained else False),
    ))


def ftrl_checkpoint_signature(plan: ExecutionPlan) -> Dict[str, Any]:
    """The FTRL stream's resume signature, derived from the plan —
    content IDENTICAL to the historical hand-built ``ck_signature``
    dict, including the conditional keys (chained-only ``chunk_size`` /
    ``ftrl_kernel`` / ``fuse_collectives``), so every pre-existing
    snapshot keeps its exact signature and stays resumable."""
    sig: Dict[str, Any] = {
        "kind": "ftrl_state",
        "alpha": plan.get("alpha"), "beta": plan.get("beta"),
        "l1": plan.get("l1"), "l2": plan.get("l2"),
        "dim": plan.get("dim"), "dim_pad": plan.get("dim_pad"),
        "update_mode": plan.get("update_mode"),
        "staleness": plan.get("staleness"),
        "has_intercept": plan.get("has_intercept"),
        "warm_coef_blake2b": plan.get("warm_coef_blake2b"),
    }
    if plan.get("update_mode") == "chained":
        sig["chunk_size"] = plan.get("chunk_size")
        if plan.get("kernel_resolved") == "pallas":
            sig["ftrl_kernel"] = "pallas"
        if plan.get("ALINK_TPU_FUSE_COLLECTIVES"):
            sig["fuse_collectives"] = True
    return sig


# ---------------------------------------------------------------------------
# serving / fleet (ledger-facing event plans over ServingPlan)
# ---------------------------------------------------------------------------

def serving_event_plan(serving_plan, *, signature: Optional[Tuple] = None,
                       sharded: Optional[bool] = None, kind: str = "",
                       bucket: int = 0, trailing: Tuple = (),
                       lanes: Optional[int] = None) -> ExecutionPlan:
    """One compiled serving program's identity as named dims.

    ``serving/plan.ServingPlan`` (PR 17) stays the serving tier's key
    object — its ``program_key`` tuples are untouched — this view
    names the dimensions so ledger diffs read ``ALINK_TPU_SERVE_DTYPE
    f32->int8`` / ``bucket 128->512`` instead of "tuple changed".  The
    kernel-signature tail convention (resolved serve dtype at [-2],
    fused mode at [-1] — ``operator/common/linear/mapper.py``) is
    decomposed when present."""
    sig = tuple(serving_plan.signature if signature is None
                else signature)
    sh = serving_plan.sharded if sharded is None else bool(sharded)
    dims: List[Tuple[str, Any]] = []
    if (len(sig) >= 2 and sig[-2] in _SERVE_DTYPES
            and isinstance(sig[-1], bool)):
        dims += [("geometry", sig[:-2]),
                 ("ALINK_TPU_SERVE_DTYPE", sig[-2]),
                 ("ALINK_TPU_SERVE_FUSED", sig[-1])]
    else:
        dims.append(("geometry", sig))
    dims += [("kind", str(kind)), ("bucket", int(bucket)),
             ("trailing", tuple(trailing)),
             ("buckets", tuple(serving_plan.buckets)),
             ("lanes", None if lanes is None else int(lanes)),
             ("sharded", sh),
             ("mesh", serving_plan.mesh_fp if sh else None)]
    return ExecutionPlan("serving", tuple(dims))


# ---------------------------------------------------------------------------
# tuning sweep (compile groups riding the engine cache)
# ---------------------------------------------------------------------------

def sweep_plan(kind: str, key_tail: Tuple) -> ExecutionPlan:
    """The sweep compile group's plan.  ``legacy_sweep_program_key()``
    reproduces the historical ``set_program_key`` tuple exactly:
    ``("sweep", kind, ALINK_TPU_SWEEP) + key_tail``."""
    from .flags import flag_value
    return ExecutionPlan("sweep", (
        ("family", "sweep"),
        ("sweep_kind", str(kind)),
        ("ALINK_TPU_SWEEP", bool(flag_value("ALINK_TPU_SWEEP", False))),
        ("key_tail", tuple(key_tail)),
    ))


def legacy_sweep_program_key(plan: ExecutionPlan) -> Tuple:
    """The byte-identical legacy sweep program key (the ``key_tail``
    dim splices back, unlike ``legacy_key()``'s value-per-dim shape)."""
    return ((plan.get("family"), plan.get("sweep_kind"),
             plan.get("ALINK_TPU_SWEEP")) + tuple(plan.get("key_tail")))


# ---------------------------------------------------------------------------
# online DAG (stage identities for cold-start attribution)
# ---------------------------------------------------------------------------

def dag_stage_plan(stage: str, config: Any) -> ExecutionPlan:
    """One DAG stage's identity: the stage name + a frozen token of the
    configuration its compiled programs depend on (the engine's
    ``freeze_config`` canonicalization).  Registered with the compile
    ledger so a restart's cold-start report names which stage's
    programs were re-paid."""
    from ..engine.comqueue import freeze_config
    return ExecutionPlan("dag", (
        ("stage", str(stage)),
        ("config", freeze_config(config)),
    ))
