"""CSV read/write utilities.

Re-design of common/io/csv/ (CsvUtil, CsvParser, CsvFormatter): schema-aware
CSV <-> MTable with the reference's "col TYPE, col TYPE" schema strings.
"""

from __future__ import annotations

import csv
import io
import os
from typing import List, Optional, Sequence
from urllib.request import urlopen

import numpy as np

from ..common.mtable import MTable
from ..common.types import AlinkTypes, TableSchema
from ..common.vector import VectorUtil


def _parse_cell(s: str, type_: str):
    if s is None or s == "":
        return None
    t = type_.upper()
    if t in (AlinkTypes.DOUBLE, AlinkTypes.FLOAT):
        return float(s)
    if t in (AlinkTypes.LONG, AlinkTypes.INT):
        return int(float(s))
    if t == AlinkTypes.BOOLEAN:
        return s.strip().lower() in ("true", "1", "t")
    if AlinkTypes.is_vector(t):
        return VectorUtil.parse(s)
    return s


def _csv_bytes_native(data: bytes, schema: TableSchema, field_delimiter: str,
                      quote_char: str):
    """Numeric-only fast path through the native parser (parser.cpp
    csv_dims/csv_fill). Returns an MTable or None to fall back."""
    if len(field_delimiter) != 1:
        return None
    num = {AlinkTypes.DOUBLE, AlinkTypes.FLOAT, AlinkTypes.LONG, AlinkTypes.INT}
    if not all(t.upper() in num for t in schema.types):
        return None
    from ..native import parse_numeric_csv_bytes
    if quote_char.encode() in data:
        return None
    m = parse_numeric_csv_bytes(data, field_delimiter)
    if m is None or m.shape[1] != len(schema.names) or np.isnan(m).any():
        return None  # missing cells need the None-aware python path
    cols = {}
    for j, (n, t) in enumerate(zip(schema.names, schema.types)):
        c = m[:, j]
        if t.upper() in (AlinkTypes.LONG, AlinkTypes.INT):
            c = c.astype(np.int64)
        cols[n] = c
    return MTable(cols, schema)


def _csv_bytes(data: bytes, schema: TableSchema, field_delimiter: str,
               quote_char: str, skip_blank: bool) -> MTable:
    fast = _csv_bytes_native(data, schema, field_delimiter, quote_char)
    if fast is not None:
        return fast
    reader = csv.reader(io.StringIO(data.decode("utf-8")),
                        delimiter=field_delimiter, quotechar=quote_char)
    rows = []
    for rec in reader:
        if skip_blank and not rec:
            continue
        vals = [_parse_cell(rec[j] if j < len(rec) else None, t)
                for j, t in enumerate(schema.types)]
        rows.append(tuple(vals))
    return MTable(rows, schema)


def _load_line_bytes(path: str, ignore_first_line: bool,
                     shard=None, quote_char: str = '"') -> bytes:
    """Bytes of ``path``'s lines for this reader.

    ``shard=(i, n)`` selects the per-host slice (SURVEY §7 sharded sources):
    glob paths shard round-robin by file; single files shard by
    newline-aligned byte range (io/sharding.py). Header dropping happens
    per-file for globs, on shard 0 for byte ranges.
    """
    from .sharding import read_file_shard, shard_paths

    q = quote_char.encode("utf-8") if quote_char else None

    def drop_header(b: bytes) -> bytes:
        # quote-aware: a header record containing a quoted embedded newline
        # spans physical lines — skip newlines until quotes are balanced.
        # A stray unbalanced quote must not silently swallow data: the
        # continuation scan is capped, and past the cap the input is
        # rejected (a >64-line header is malformation, not a header).
        first_nl = b.find(b"\n")
        if first_nl < 0:
            return b""
        if q is None:
            return b[first_nl + 1:]
        pos, quotes = 0, 0
        for _ in range(64):
            nl = b.find(b"\n", pos)
            if nl < 0:
                return b[first_nl + 1:]
            quotes += b.count(q, pos, nl)
            if quotes % 2 == 0:
                return b[nl + 1:]
            pos = nl + 1
        raise ValueError(
            "header record spans >64 physical lines (unbalanced quote?); "
            "refusing to guess where the header ends")

    if path.startswith(("http://", "https://")):
        if shard is not None and shard[1] > 1:
            raise ValueError("sharded reads of http sources are unsupported")
        data = urlopen(path).read()  # pragma: no cover - no egress in CI
        return drop_header(data) if ignore_first_line else data
    if shard is None:
        with open(path, "rb") as f:
            data = f.read()
        return drop_header(data) if ignore_first_line else data
    files = shard_paths(path, *shard)
    if files is not None:
        parts = []
        for p in files:
            with open(p, "rb") as f:
                b = f.read()
            if ignore_first_line:
                b = drop_header(b)
            if b and not b.endswith(b"\n"):
                b += b"\n"
            parts.append(b)
        return b"".join(parts)
    data = read_file_shard(path, *shard)
    if ignore_first_line and shard[0] == 0:
        data = drop_header(data)
    return data


def read_csv(path: str, schema: TableSchema, field_delimiter: str = ",",
             quote_char: str = '"', skip_blank: bool = True,
             ignore_first_line: bool = False, shard=None) -> MTable:
    data = _load_line_bytes(path, ignore_first_line, shard, quote_char)
    return _csv_bytes(data, schema, field_delimiter, quote_char, skip_blank)


def write_csv(table: MTable, path: str, field_delimiter: str = ",",
              quote_char: str = '"', with_header: bool = False):
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w", newline="", encoding="utf-8") as f:
        writer = csv.writer(f, delimiter=field_delimiter, quotechar=quote_char)
        if with_header:
            writer.writerow(table.col_names)
        for row in table.rows():
            out = []
            for v, t in zip(row, table.schema.types):
                if v is None:
                    out.append("")
                elif AlinkTypes.is_vector(t):
                    out.append(VectorUtil.to_string(VectorUtil.parse(v)))
                else:
                    out.append(v)
            writer.writerow(out)


def format_csv_rows(table: MTable, field_delimiter: str = ",",
                    quote_char: str = '"') -> str:
    """CSV-encode a table to a string (stream sinks append per micro-batch)."""
    buf = io.StringIO()
    writer = csv.writer(buf, delimiter=field_delimiter, quotechar=quote_char)
    for row in table.rows():
        out = []
        for v, t in zip(row, table.schema.types):
            if v is None:
                out.append("")
            elif AlinkTypes.is_vector(t):
                out.append(VectorUtil.to_string(VectorUtil.parse(v)))
            else:
                out.append(v)
        writer.writerow(out)
    return buf.getvalue()


def format_libsvm_rows(table: MTable, label_col: str, vector_col: str,
                       start_index: int = 1) -> str:
    from ..common.vector import DenseVector
    lines = []
    for lbl, vec in zip(table.col(label_col), table.col(vector_col)):
        v = VectorUtil.parse(vec)
        if isinstance(v, DenseVector):
            pairs = [(i, x) for i, x in enumerate(v.data) if x != 0]
        else:
            pairs = list(zip(v.indices, v.values))
        body = " ".join(f"{int(i) + start_index}:{x}" for i, x in pairs)
        lines.append(f"{lbl} {body}\n")
    return "".join(lines)


def read_libsvm(path: str, start_index: int = 1, shard=None,
                vector_size=None) -> MTable:
    """LibSVM format -> (label DOUBLE, features SPARSE_VECTOR)
    (reference common/io/LibSvmSourceBatchOp).

    Parses through the native C++ two-pass parser
    (alink_tpu/native/parser.cpp svm_count/svm_fill) when available;
    falls back to the pure-Python loop.

    Sharded reads should pass ``vector_size``: the per-shard max-index
    inference would otherwise give different hosts different widths for
    the same dataset.
    """
    from ..common.vector import SparseVector
    from ..native import get_lib, parse_libsvm_bytes_parallel
    data = _load_line_bytes(path, ignore_first_line=False, shard=shard)
    if get_lib() is not None:
        # chunked multi-core parse (the C calls release the GIL)
        labels_a, indptr, indices, values = parse_libsvm_bytes_parallel(
            data, start_index)
        max_idx = (int(vector_size) if vector_size is not None else
                   (int(indices.max()) + 1 if indices.size else 0))
        if vector_size is not None and max_idx <= 0:
            raise ValueError(f"vector_size must be positive, got {vector_size}")
        col = [SparseVector(max_idx, indices[indptr[i]:indptr[i + 1]],
                            values[indptr[i]:indptr[i + 1]])
               for i in range(len(labels_a))]
        return MTable({"label": labels_a, "features": col},
                      TableSchema(["label", "features"],
                                  [AlinkTypes.DOUBLE,
                                   AlinkTypes.SPARSE_VECTOR]))
    # pure-Python fallback
    labels: List[float] = []
    vecs = []
    max_idx = 0
    for line in io.StringIO(data.decode("utf-8")):
        parts = line.strip().split()
        if not parts:
            continue
        labels.append(float(parts[0]))
        idx, val = [], []
        for p in parts[1:]:
            k, v = p.split(":")
            idx.append(int(k) - start_index)
            val.append(float(v))
        if idx:
            max_idx = max(max_idx, max(idx) + 1)
        vecs.append((idx, val))
    if vector_size is not None:
        max_idx = int(vector_size)
        if max_idx <= 0:
            raise ValueError(f"vector_size must be positive, got {vector_size}")
    col = [SparseVector(max_idx, i, v) for i, v in vecs]
    return MTable({"label": np.asarray(labels), "features": col},
                  TableSchema(["label", "features"],
                              [AlinkTypes.DOUBLE, AlinkTypes.SPARSE_VECTOR]))


def write_libsvm(table: MTable, path: str, label_col: str, vector_col: str,
                 start_index: int = 1):
    with open(path, "w", encoding="utf-8") as f:
        for lbl, vec in zip(table.col(label_col), table.col(vector_col)):
            v = VectorUtil.parse(vec)
            from ..common.vector import DenseVector
            if isinstance(v, DenseVector):
                pairs = [(i, x) for i, x in enumerate(v.data) if x != 0]
            else:
                pairs = list(zip(v.indices, v.values))
            body = " ".join(f"{int(i) + start_index}:{x}" for i, x in pairs)
            f.write(f"{lbl} {body}\n")
