"""LDA batch operators.

Re-design of operator/batch/clustering/LdaTrainBatchOp.java /
LdaPredictBatchOp.java with model schema per
operator/common/clustering/LdaModelData.java (gamma word-topic count
matrix incl. trailing topic-total row, alpha/beta vectors, vocab list)
and params per params/clustering/LdaTrainParams.java.

Training pipeline mirrors the reference linkFrom: build a
DocCountVectorizer vocabulary from the selected text column
(LdaTrainBatchOp.java:88-99), encode docs as padded bag-of-words arrays,
then dispatch on method EM | Online (:100-110) to the TPU kernels in
``operator/common/clustering/lda.py``.
"""

from __future__ import annotations

import json
from typing import List

import numpy as np

from ....common.mtable import MTable
from ....common.params import ParamInfo, Params, RangeValidator
from ....common.types import AlinkTypes, TableSchema
from ....mapper.base import ModelMapper, OutputColsHelper
from ....model.converters import (SimpleModelDataConverter, decode_array,
                                  encode_array)
from ....params.shared import (HasPredictionCol, HasPredictionDetailCol,
                               HasReservedCols, HasSeed, HasSelectedCol)
from ...base import BatchOperator
from ...common.clustering.lda import (em_lda_train, encode_corpus,
                                      gibbs_lda_train, lda_infer,
                                      online_lda_train)
from ...common.nlp.vectorizer import (DocCountVectorizerModelConverter,
                                      train_doc_count_vectorizer)
from ..utils.model_map import ModelMapBatchOp


class LdaModelData:
    """reference: operator/common/clustering/LdaModelData.java"""

    def __init__(self, topic_num: int, vocab: List[str], gamma: np.ndarray,
                 alpha: np.ndarray, beta: float, method: str,
                 log_likelihood: float = 0.0, log_perplexity: float = 0.0):
        self.topic_num = topic_num
        self.vocab = vocab
        self.gamma = gamma            # (V+1, k): word-topic counts + topic totals
        self.alpha = np.atleast_1d(np.asarray(alpha, np.float64))
        self.beta = float(beta)
        self.method = method
        self.log_likelihood = log_likelihood
        self.log_perplexity = log_perplexity

    def word_topic_probs(self) -> np.ndarray:
        """(V, k) p(w|z) (LdaModelMapper.java:96-121).

        EM stores raw expected counts -> smooth with beta, exactly the
        beta_hat used during training. Online stores the variational
        lambda, which already contains the beta prior from the
        natural-gradient update — adding it again would double-count.
        """
        V = len(self.vocab)
        wt, tot = self.gamma[:V], self.gamma[V]
        b = 0.0 if self.method == "online" else self.beta
        return (wt + b) / (tot[None, :] + V * b)


class LdaModelDataConverter(SimpleModelDataConverter):
    def serialize_model(self, m: LdaModelData):
        meta = Params({"topic_num": m.topic_num, "method": m.method,
                       "beta": m.beta, "alpha": list(map(float, m.alpha)),
                       "log_likelihood": m.log_likelihood,
                       "log_perplexity": m.log_perplexity})
        return meta, [encode_array(m.gamma), json.dumps(m.vocab)]

    def deserialize_model(self, meta: Params, data):
        return LdaModelData(
            int(meta._m["topic_num"]), json.loads(data[1]),
            decode_array(data[0]), np.asarray(meta._m["alpha"]),
            float(meta._m["beta"]), meta._m.get("method", "em"),
            float(meta._m.get("log_likelihood", 0.0)),
            float(meta._m.get("log_perplexity", 0.0)))


class _LdaTrainParams(HasSelectedCol, HasSeed):
    """params/clustering/LdaTrainParams.java"""
    TOPIC_NUM = ParamInfo("topic_num", int, "number of topics", optional=False,
                          validator=RangeValidator(1, None))
    NUM_ITER = ParamInfo("num_iter", int, "iterations", default=10)
    ALPHA = ParamInfo("alpha", float, "doc-topic Dirichlet prior (-1=auto)",
                      default=-1.0)
    BETA = ParamInfo("beta", float, "topic-word Dirichlet prior (-1=auto)",
                     default=-1.0)
    METHOD = ParamInfo("method", str,
                       "optimizer: em | em_gibbs (alias: gibbs) | online",
                       default="em",
                       aliases=("optimizer",))
    VOCAB_SIZE = ParamInfo("vocab_size", int, "max vocabulary size",
                           default=1 << 18)
    ONLINE_LEARNING_OFFSET = ParamInfo("online_learning_offset", float,
                                       "tau0 downweighting early steps",
                                       default=1024.0)
    LEARNING_DECAY = ParamInfo("learning_decay", float,
                               "kappa in rho_t=(tau0+t)^-kappa", default=0.51)
    SUBSAMPLING_RATE = ParamInfo("subsampling_rate", float,
                                 "minibatch fraction per online step",
                                 default=0.05)
    OPTIMIZE_DOC_CONCENTRATION = ParamInfo(
        "optimize_doc_concentration", bool,
        "learn alpha during online training", default=True)


class LdaTrainBatchOp(BatchOperator, _LdaTrainParams):
    """reference: operator/batch/clustering/LdaTrainBatchOp.java"""

    def link_from(self, in_op: BatchOperator) -> "LdaTrainBatchOp":
        t = in_op.get_output_table()
        col = self.get_selected_col()
        k = self.get_topic_num()
        method = str(self.get_method()).lower()
        seed = self.get_seed()
        vocab_table = train_doc_count_vectorizer(
            t, col, vocab_size=self.get_vocab_size())
        dcv = DocCountVectorizerModelConverter().load_model(vocab_table)
        V = len(dcv.vocab)
        if V == 0:
            raise ValueError("LDA: empty vocabulary")
        ids, cnts = encode_corpus(t.col(col), dcv.index)
        alpha, beta = self.get_alpha(), self.get_beta()
        if method == "online":
            lam, avec, ll, perp = online_lda_train(
                ids, cnts, k, V, num_iter=self.get_num_iter(),
                alpha=alpha, beta=beta,
                tau0=self.get_online_learning_offset(),
                kappa=self.get_learning_decay(),
                subsample=self.get_subsampling_rate(),
                optimize_alpha=self.get_optimize_doc_concentration(),
                seed=seed)
            # lambda is the (k, V) variational word-topic pseudo-count matrix;
            # store in the common gamma layout (BuildOnlineLdaModel.java)
            gamma = np.concatenate([lam.T, lam.sum(1)[None, :]], axis=0)
            beta_out = beta if beta > 0 else 1.0 / k
            model = LdaModelData(k, dcv.vocab, gamma, avec, beta_out,
                                 "online", ll, perp)
        elif method in ("em", "gibbs", "em_gibbs"):
            # em = batched variational EM; em_gibbs = the AD-LDA sampler
            # twin of the reference's collapsed Gibbs (EmCorpusStep.java).
            # Both produce the same (V, k)+totals count-matrix model, so
            # they share the model construction. gibbs_lda_train's
            # DEFAULTS already include the reference's +1 prior shift for
            # the collapsed predictive rule (LdaTrainBatchOp.java:118-124);
            # explicitly-set alpha/beta are used as given.
            train_fn = em_lda_train if method == "em" else gibbs_lda_train
            wt, tot, a, b, ll, perp = train_fn(
                ids, cnts, k, V, num_iter=self.get_num_iter(),
                alpha=alpha, beta=beta, seed=seed)
            gamma = np.concatenate([wt, tot[None, :]], axis=0)
            model = LdaModelData(k, dcv.vocab, gamma, np.full((k,), a),
                                 b, "em", ll, perp)
        else:
            raise ValueError(
                f"LDA method must be em|em_gibbs|online, got {method}")
        self.set_output_table(LdaModelDataConverter().save_model(model))
        return self


class LdaModelMapper(ModelMapper):
    """reference: operator/common/clustering/LdaModelMapper.java"""

    def __init__(self, model_schema, data_schema, params=None, **kwargs):
        super().__init__(model_schema, data_schema, params, **kwargs)
        self.model: LdaModelData = None

    def load_model(self, model_table: MTable):
        self.model = LdaModelDataConverter().load_model(model_table)
        self._wt = self.model.word_topic_probs()
        self._index = {w: i for i, w in enumerate(self.model.vocab)}

    def _cols(self):
        p = self.params._m
        out = [p["prediction_col"]]
        types = [AlinkTypes.LONG]
        if p.get("prediction_detail_col"):
            out.append(p["prediction_detail_col"])
            types.append(AlinkTypes.STRING)
        return out, types

    def get_output_schema(self) -> TableSchema:
        out, types = self._cols()
        return OutputColsHelper(self.data_schema, out, types,
                                self.params._m.get("reserved_cols")
                                ).get_output_schema()

    def map_table(self, data: MTable) -> MTable:
        col = self.params._m["selected_col"]
        ids, cnts = encode_corpus(data.col(col), self._index)
        theta = lda_infer(ids, cnts, self._wt, self.model.alpha)
        pred = theta.argmax(1).astype(np.int64)
        out, types = self._cols()
        cols = [pred]
        if len(out) > 1:
            cols.append([json.dumps([round(float(v), 6) for v in row])
                         for row in theta])
        helper = OutputColsHelper(self.data_schema, out, types,
                                  self.params._m.get("reserved_cols"))
        return helper.build_output(data, cols)


class LdaPredictBatchOp(ModelMapBatchOp, HasSelectedCol, HasPredictionCol,
                        HasPredictionDetailCol, HasReservedCols):
    """reference: operator/batch/clustering/LdaPredictBatchOp.java"""
    MAPPER_CLS = LdaModelMapper
