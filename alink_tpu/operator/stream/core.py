"""Stream runtime core — per-batch transforms and event-time merging.

The Flink DataStream substrate (reference stream/StreamOperator.java and the
per-op RichFlatMap/CoFlatMap functions) is replaced by lazy generators of
``(event_time, MTable)``. Multi-input operators merge their inputs in
event-time order (``merge_timed``), which is what Flink's arrival-order
co-processing gives the reference's FtrlPredictStreamOp / windowed eval.
"""

from __future__ import annotations

import heapq
import time
from typing import Callable, Iterable, Iterator, Optional, Tuple

from ...common.metrics import get_registry, metrics_enabled
from ...common.mtable import MTable
from ...common.tracing import trace_complete
from ...common.types import TableSchema
from ..base import StreamOperator

TimedBatch = Tuple[float, MTable]


def merge_timed(*streams: Iterable[TimedBatch]) -> Iterator[Tuple[float, int, MTable]]:
    """Merge timed streams in event-time order; yields (time, stream_idx, table).

    Ties break by stream index (earlier input wins), matching the reference's
    model-stream-then-data convention for co-flat-map operators.
    """
    def tag(i, s):
        for t, mt in s:
            yield (t, i, mt)

    return heapq.merge(*[tag(i, s) for i, s in enumerate(streams)],
                       key=lambda x: (x[0], x[1]))


# sentinel a _transform may return to end the drain early (FirstN etc.)
STOP = object()


class BaseStreamTransformOp(StreamOperator):
    """Single-input, per-batch stream transform.

    Subclasses implement ``_open(in_schema) -> out_schema`` (schema + state
    init per drain) and ``_transform(mt) -> MTable | None | STOP``. Each
    drain of the DAG replays the stream from the source; per-drain state set
    in ``_open`` lives on a shallow *copy* of the operator, so concurrent
    drains of the same instance (diamond DAGs, side streams) don't share
    mutable state.
    """

    def _open(self, in_schema: TableSchema) -> TableSchema:
        return in_schema

    def _transform(self, mt: MTable) -> Optional[MTable]:  # pragma: no cover
        raise NotImplementedError

    def _close(self):
        """Yielded-after-input-end hook; return iterable of MTable or None."""
        return None

    def link_from(self, in_op: StreamOperator) -> "BaseStreamTransformOp":
        try:
            self._schema = self._open(in_op.get_schema())
        except RuntimeError:
            self._schema = None  # upstream schema data-dependent; resolve on first batch

        def gen():
            import copy
            worker = copy.copy(self)  # per-drain mutable state lives here
            opened = False
            last_t = 0.0
            # per-drain telemetry: micro-batch count/rows and per-batch
            # transform latency, labelled by op class. Resolved once per
            # drain so the per-batch cost is one time.perf_counter pair.
            mx = metrics_enabled()
            reg = get_registry() if mx else None
            lbl = {"op": type(self).__name__}
            for t, mt in in_op.timed_batches():
                if not opened:
                    self._schema = worker._open(mt.schema)
                    opened = True
                last_t = t
                t0 = time.perf_counter()
                out = worker._transform(mt)
                dt = time.perf_counter() - t0
                # retroactive span (trace_complete, not a ``with`` block):
                # this generator body suspends at ``yield`` in the
                # CALLER's context, so an open span held across the yield
                # would adopt unrelated downstream spans as children
                trace_complete(f"stream:{type(self).__name__}", dt,
                               cat="stream",
                               args={"rows": mt.num_rows,
                                     "event_time": t})
                if mx:
                    reg.observe("alink_stream_batch_seconds", dt, lbl)
                    reg.inc("alink_stream_batches_total", 1, lbl)
                    reg.inc("alink_stream_rows_total", mt.num_rows, lbl)
                if out is STOP:
                    break
                if out is not None and out.num_rows > 0:
                    yield (t, out)
            tail = worker._close()
            if tail:
                for out in tail:
                    if out is not None and out.num_rows > 0:
                        yield (last_t, out)

        self._stream_fn = gen
        return self


class BatchApplyStreamOp(BaseStreamTransformOp):
    """Apply a stateless batch op class to every micro-batch.

    The class comes either from a subclass overriding ``_batch_cls`` or
    from the ``batch_cls=`` constructor argument (the same injection
    pattern as ModelMapStreamOp's ``mapper_cls=``).
    """

    def __init__(self, params=None, batch_cls=None, **kwargs):
        super().__init__(params, **kwargs)
        if batch_cls is not None:
            self._injected_batch_cls = batch_cls

    def _batch_cls(self):
        cls = getattr(self, "_injected_batch_cls", None)
        if cls is None:
            raise NotImplementedError(
                f"{type(self).__name__}: override _batch_cls or pass batch_cls=")
        return cls

    def _open(self, in_schema):
        from ..base import BatchOperator
        probe = self._batch_cls()(self.params.clone())
        probe.link_from(BatchOperator.from_table(MTable([], in_schema)))
        return probe.get_schema()

    def _transform(self, mt):
        from ..base import BatchOperator
        op = self._batch_cls()(self.params.clone())
        op.link_from(BatchOperator.from_table(mt))
        return op.get_output_table()


class FnStreamOp(BaseStreamTransformOp):
    """Ad-hoc per-batch function stream op (UDF-style, reference
    stream/utils UDF ops)."""

    def __init__(self, fn: Callable[[MTable], Optional[MTable]],
                 schema_fn: Optional[Callable[[TableSchema], TableSchema]] = None,
                 params=None, **kwargs):
        super().__init__(params, **kwargs)
        self._fn = fn
        self._schema_fn = schema_fn

    def _open(self, in_schema):
        return self._schema_fn(in_schema) if self._schema_fn else in_schema

    def _transform(self, mt):
        return self._fn(mt)
