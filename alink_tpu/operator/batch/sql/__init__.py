"""SQL-style batch operators.

Re-design of operator/batch/sql/ (18 ops: Select/As/Where/Filter/GroupBy/
Join x5/Union[All]/Intersect[All]/Minus[All]/Distinct/OrderBy, delegating to
Flink Table in the reference — here to the host columnar engine, with a
small safe expression evaluator instead of Calcite SQL).

Expression language: python-syntax expressions over column names
(e.g. "sepal_length > 5.0 and species != 'setosa'"); select supports
"col", "expr as alias", "*".
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional

import numpy as np

from ....common.mtable import MTable
from ....common.params import ParamInfo, Params
from ....common.types import AlinkTypes, TableSchema
from ...base import BatchOperator

_CLAUSE = ParamInfo("clause", str, "expression clause", optional=False)

_ALLOWED_FUNCS = {
    "abs": np.abs, "sqrt": np.sqrt, "exp": np.exp, "log": np.log, "log2": np.log2,
    "log10": np.log10, "sin": np.sin, "cos": np.cos, "tan": np.tan,
    "floor": np.floor, "ceil": np.ceil, "round": np.round, "sign": np.sign,
    "pow": np.power, "power": np.power, "minimum": np.minimum, "maximum": np.maximum,
    "upper": lambda c: _str_map(c, str.upper), "lower": lambda c: _str_map(c, str.lower),
    "cast_double": lambda c: np.asarray(c, np.float64),
    "cast_long": lambda c: np.asarray(c, np.int64),
    "cast_string": lambda c: _str_map(c, str),
    "concat": lambda *cs: _concat_str(cs),
}


def _str_map(col, fn):
    out = np.empty(len(col), object)
    out[:] = [None if v is None else fn(str(v)) for v in col]
    return out


def _concat_str(cols):
    n = len(cols[0])
    out = np.empty(n, object)
    out[:] = ["".join(str(c[i]) for c in cols) for i in range(n)]
    return out


class _SafeEval(ast.NodeVisitor):
    """Whitelisted expression evaluator over table columns."""

    ALLOWED = (ast.Expression, ast.BoolOp, ast.BinOp, ast.UnaryOp, ast.Compare,
               ast.Call, ast.Name, ast.Constant, ast.And, ast.Or, ast.Not,
               ast.Add, ast.Sub, ast.Mult, ast.Div, ast.Mod, ast.Pow,
               ast.FloorDiv, ast.USub, ast.UAdd, ast.Eq, ast.NotEq, ast.Lt,
               ast.LtE, ast.Gt, ast.GtE, ast.In, ast.NotIn, ast.Load,
               ast.Tuple, ast.List, ast.IfExp, ast.Subscript, ast.Index, ast.Slice)

    def __init__(self, cols: Dict[str, np.ndarray]):
        self.cols = cols

    def run(self, expr: str):
        tree = ast.parse(expr, mode="eval")
        for node in ast.walk(tree):
            if not isinstance(node, self.ALLOWED):
                raise ValueError(f"unsupported syntax {type(node).__name__!r} in {expr!r}")
        return self._eval(tree.body)

    def _eval(self, node):
        if isinstance(node, ast.Constant):
            return node.value
        if isinstance(node, ast.Name):
            if node.id in self.cols:
                return self.cols[node.id]
            if node.id.lower() in ("true", "false"):
                return node.id.lower() == "true"
            if node.id.lower() in ("null", "none"):
                return None
            raise KeyError(f"unknown column {node.id!r}; have {sorted(self.cols)}")
        if isinstance(node, ast.BoolOp):
            vals = [_as_bool(self._eval(v)) for v in node.values]
            out = vals[0]
            for v in vals[1:]:
                out = out & v if isinstance(node.op, ast.And) else out | v
            return out
        if isinstance(node, ast.UnaryOp):
            v = self._eval(node.operand)
            if isinstance(node.op, ast.Not):
                return ~_as_bool(v)
            return -v if isinstance(node.op, ast.USub) else +v
        if isinstance(node, ast.BinOp):
            a, b = self._eval(node.left), self._eval(node.right)
            ops = {ast.Add: np.add, ast.Sub: np.subtract, ast.Mult: np.multiply,
                   ast.Div: np.divide, ast.Mod: np.mod, ast.Pow: np.power,
                   ast.FloorDiv: np.floor_divide}
            return ops[type(node.op)](a, b)
        if isinstance(node, ast.Compare):
            left = self._eval(node.left)
            out = None
            for op, comp in zip(node.ops, node.comparators):
                right = self._eval(comp)
                res = _compare(left, op, right)
                out = res if out is None else (out & res)
                left = right
            return out
        if isinstance(node, ast.Call):
            fname = node.func.id if isinstance(node.func, ast.Name) else None
            if fname is None or fname.lower() not in _ALLOWED_FUNCS:
                raise ValueError(f"unknown function in expression: {ast.dump(node.func)}")
            args = [self._eval(a) for a in node.args]
            return _ALLOWED_FUNCS[fname.lower()](*args)
        if isinstance(node, ast.IfExp):
            c = _as_bool(self._eval(node.test))
            return np.where(c, self._eval(node.body), self._eval(node.orelse))
        if isinstance(node, (ast.Tuple, ast.List)):
            return [self._eval(e) for e in node.elts]
        raise ValueError(f"unsupported node {type(node).__name__}")


def _as_bool(v):
    if isinstance(v, np.ndarray) and v.dtype == object:
        return np.asarray([bool(x) for x in v])
    return np.asarray(v, bool)


def _compare(a, op, b):
    if isinstance(op, (ast.In, ast.NotIn)):
        vals = set(b if isinstance(b, (list, tuple)) else [b])
        res = np.asarray([x in vals for x in np.asarray(a, object)])
        return ~res if isinstance(op, ast.NotIn) else res
    if isinstance(a, np.ndarray) and a.dtype == object:
        a2 = np.asarray([str(x) if x is not None else None for x in a], object)
        b2 = str(b) if not isinstance(b, np.ndarray) else b
        ops = {ast.Eq: lambda: a2 == b2, ast.NotEq: lambda: a2 != b2,
               ast.Lt: lambda: a2 < b2, ast.LtE: lambda: a2 <= b2,
               ast.Gt: lambda: a2 > b2, ast.GtE: lambda: a2 >= b2}
        return np.asarray(ops[type(op)](), bool)
    ops = {ast.Eq: np.equal, ast.NotEq: np.not_equal, ast.Lt: np.less,
           ast.LtE: np.less_equal, ast.Gt: np.greater, ast.GtE: np.greater_equal}
    return ops[type(op)](a, b)


def evaluate_expr(table: MTable, expr: str):
    return _SafeEval({n: table.col(n) for n in table.col_names}).run(expr)


def _split_top_level(s: str, sep: str = ",") -> List[str]:
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch in "([":
            depth += 1
        elif ch in ")]":
            depth -= 1
        if ch == sep and depth == 0:
            parts.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    if cur:
        parts.append("".join(cur).strip())
    return [p for p in parts if p]


class BaseSqlApiBatchOp(BatchOperator):
    """Base of the SQL-clause operators (reference
    batch/sql/BaseSqlApiBatchOp.java)."""


class SelectBatchOp(BaseSqlApiBatchOp):
    """reference: batch/sql/SelectBatchOp — "a, b*2 as c, *"."""
    CLAUSE = _CLAUSE

    def link_from(self, in_op: BatchOperator) -> "SelectBatchOp":
        t = in_op.get_output_table()
        cols: Dict[str, np.ndarray] = {}
        types: Dict[str, str] = {}
        for item in _split_top_level(self.get_clause()):
            if item == "*":
                for n in t.col_names:
                    cols[n] = t.col(n)
                    types[n] = t.schema.type_of(n)
                continue
            m = re.match(r"^(.*?)\s+[aA][sS]\s+(\w+)$", item)
            expr, name = (m.group(1), m.group(2)) if m else (item, None)
            expr = expr.strip()
            if re.fullmatch(r"\w+", expr) and expr in t.col_names:
                val = t.col(expr)
                vtype = t.schema.type_of(expr)
                name = name or expr
            else:
                val = evaluate_expr(t, expr)
                if not isinstance(val, np.ndarray):
                    val = np.full(t.num_rows, val)
                vtype = AlinkTypes.from_numpy_dtype(val.dtype) \
                    if val.dtype != object else AlinkTypes.STRING
                name = name or re.sub(r"\W+", "_", expr)
            cols[name] = val
            types[name] = vtype
        self._output = MTable(cols, TableSchema(list(cols), [types[n] for n in cols]))
        return self


class AsBatchOp(BaseSqlApiBatchOp):
    """Rename all columns (reference AsBatchOp)."""
    CLAUSE = _CLAUSE

    def link_from(self, in_op: BatchOperator) -> "AsBatchOp":
        names = [n.strip() for n in self.get_clause().split(",")]
        self._output = in_op.get_output_table().rename(names)
        return self


class WhereBatchOp(BaseSqlApiBatchOp):
    CLAUSE = _CLAUSE

    def link_from(self, in_op: BatchOperator) -> "WhereBatchOp":
        t = in_op.get_output_table()
        self._output = t.filter_mask(_as_bool(evaluate_expr(t, self.get_clause())))
        return self


class FilterBatchOp(WhereBatchOp):
    pass


class DistinctBatchOp(BaseSqlApiBatchOp):
    def link_from(self, in_op: BatchOperator) -> "DistinctBatchOp":
        self._output = in_op.get_output_table().distinct()
        return self


class OrderByBatchOp(BaseSqlApiBatchOp):
    CLAUSE = _CLAUSE
    LIMIT = ParamInfo("limit", int, "top-n limit")
    ASCENDING = ParamInfo("ascending", bool, default=True)

    def link_from(self, in_op: BatchOperator) -> "OrderByBatchOp":
        t = in_op.get_output_table()
        self._output = t.order_by(self.get_clause().strip(),
                                  ascending=bool(self.get_ascending()),
                                  limit=self.params._m.get("limit"))
        return self


_AGGS = {
    "sum": np.sum, "avg": np.mean, "mean": np.mean, "min": np.min, "max": np.max,
    "count": len, "stddev": lambda v: float(np.std(v, ddof=1)) if len(v) > 1 else 0.0,
    "variance": lambda v: float(np.var(v, ddof=1)) if len(v) > 1 else 0.0,
    "first": lambda v: v[0], "last": lambda v: v[-1],
}


class GroupByBatchOp(BaseSqlApiBatchOp):
    """reference: batch/sql/GroupByBatchOp — group cols + "key, agg(col) as name"."""
    GROUP_BY_PREDICATE = ParamInfo("group_by_predicate", str, optional=False)
    SELECT_CLAUSE = ParamInfo("select_clause", str, optional=False)

    def link_from(self, in_op: BatchOperator) -> "GroupByBatchOp":
        t = in_op.get_output_table()
        by = [c.strip() for c in self.get_group_by_predicate().split(",")]
        groups = t.group_indices(by)
        items = _split_top_level(self.get_select_clause())
        out_cols: Dict[str, List] = {}
        order: List[str] = []
        for key, idx in sorted(groups.items(), key=lambda kv: tuple(map(str, kv[0]))):
            sub = t.take_rows(idx)
            for item in items:
                m = re.match(r"^(.*?)\s+[aA][sS]\s+(\w+)$", item)
                expr, name = (m.group(1).strip(), m.group(2)) if m \
                    else (item.strip(), None)
                fm = re.match(r"^(\w+)\((\*|\w+)\)$", expr)
                if fm:
                    fn, col = fm.group(1).lower(), fm.group(2)
                    name = name or f"{fn}_{col}" if col != "*" else (name or fn)
                    vals = (np.arange(len(idx)) if col == "*"
                            else np.asarray(sub.col(col)))
                    if fn not in _AGGS:
                        raise ValueError(f"unknown aggregate {fn}")
                    v = _AGGS[fn](vals) if fn != "count" else len(idx)
                elif expr in by:
                    name = name or expr
                    v = key[by.index(expr)]
                else:
                    raise ValueError(f"non-aggregate column {expr!r} not in group by")
                if name not in out_cols:
                    out_cols[name] = []
                    order.append(name)
                out_cols[name].append(v)
        self._output = MTable({n: out_cols[n] for n in order})
        return self


class UnionAllBatchOp(BaseSqlApiBatchOp):
    def link_from(self, *inputs: BatchOperator) -> "UnionAllBatchOp":
        t = inputs[0].get_output_table()
        for other in inputs[1:]:
            t = t.concat_rows(other.get_output_table())
        self._output = t
        return self


class UnionBatchOp(BaseSqlApiBatchOp):
    def link_from(self, *inputs: BatchOperator) -> "UnionBatchOp":
        t = UnionAllBatchOp().link_from(*inputs).get_output_table()
        self._output = t.distinct()
        return self


class IntersectBatchOp(BaseSqlApiBatchOp):
    _ALL = False

    def link_from(self, a: BatchOperator, b: BatchOperator):
        ta, tb = a.get_output_table(), b.get_output_table()
        from ....common.mtable import _hashable
        bset = {}
        for r in tb.rows():
            k = tuple(_hashable(v) for v in r)
            bset[k] = bset.get(k, 0) + 1
        keep = []
        for i, r in enumerate(ta.rows()):
            k = tuple(_hashable(v) for v in r)
            if bset.get(k, 0) > 0:
                keep.append(i)
                if not self._ALL:
                    bset[k] = 0
        self._output = ta.take_rows(keep)
        if not self._ALL:
            self._output = self._output.distinct()
        return self


class IntersectAllBatchOp(IntersectBatchOp):
    _ALL = True


class MinusBatchOp(BaseSqlApiBatchOp):
    _ALL = False

    def link_from(self, a: BatchOperator, b: BatchOperator):
        ta, tb = a.get_output_table(), b.get_output_table()
        from ....common.mtable import _hashable
        bset = {}
        for r in tb.rows():
            k = tuple(_hashable(v) for v in r)
            bset[k] = bset.get(k, 0) + 1
        keep = []
        for i, r in enumerate(ta.rows()):
            k = tuple(_hashable(v) for v in r)
            if self._ALL:
                # multiset semantics: consume one b-occurrence per match
                if bset.get(k, 0) > 0:
                    bset[k] -= 1
                    continue
            elif k in bset:
                continue
            keep.append(i)
        self._output = ta.take_rows(keep)
        if not self._ALL:
            self._output = self._output.distinct()
        return self


class MinusAllBatchOp(MinusBatchOp):
    _ALL = True


class JoinBatchOp(BaseSqlApiBatchOp):
    """reference: batch/sql/JoinBatchOp (+Left/Right/Full/Cross variants)."""
    JOIN_PREDICATE = ParamInfo("join_predicate", str, "a.col = b.col [and ...]",
                               optional=False)
    SELECT_CLAUSE = ParamInfo("select_clause", str, default="*")
    TYPE = ParamInfo("type", str, default="join",
                     aliases=("join_type",))

    def link_from(self, a: BatchOperator, b: BatchOperator) -> "JoinBatchOp":
        ta, tb = a.get_output_table(), b.get_output_table()
        pred = self.get_join_predicate()
        pairs = []
        for part in re.split(r"\s+and\s+", pred, flags=re.I):
            m = re.match(r"^\s*a\.(\w+)\s*=+\s*b\.(\w+)\s*$", part.strip(), re.I)
            if not m:
                m2 = re.match(r"^\s*(\w+)\s*=+\s*(\w+)\s*$", part.strip())
                if not m2:
                    raise ValueError(f"unsupported join predicate {part!r}")
                pairs.append((m2.group(1), m2.group(2)))
            else:
                pairs.append((m.group(1), m.group(2)))
        jtype = (self.get_type() or "join").lower()
        self._output = _hash_join(ta, tb, pairs, jtype)
        sel = self.get_select_clause()
        if sel and sel != "*":
            self._output = SelectBatchOp(clause=sel).link_from(
                BatchOperator.from_table(self._output)).get_output_table()
        return self


class LeftOuterJoinBatchOp(JoinBatchOp):
    TYPE = ParamInfo("type", str, default="leftOuterJoin")


class RightOuterJoinBatchOp(JoinBatchOp):
    TYPE = ParamInfo("type", str, default="rightOuterJoin")


class FullOuterJoinBatchOp(JoinBatchOp):
    TYPE = ParamInfo("type", str, default="fullOuterJoin")


class CrossBatchOp(BaseSqlApiBatchOp):
    def link_from(self, a: BatchOperator, b: BatchOperator) -> "CrossBatchOp":
        ta, tb = a.get_output_table(), b.get_output_table()
        na, nb = ta.num_rows, tb.num_rows
        ia = np.repeat(np.arange(na), nb)
        ib = np.tile(np.arange(nb), na)
        left = ta.take_rows(ia)
        right = tb.take_rows(ib)
        cols = {n: left.col(n) for n in left.col_names}
        for n in right.col_names:
            cols[n if n not in cols else n + "_r"] = right.col(n)
        self._output = MTable(cols)
        return self


def _hash_join(ta: MTable, tb: MTable, pairs, jtype: str) -> MTable:
    from ....common.mtable import _hashable
    la = [p[0] for p in pairs]
    lb = [p[1] for p in pairs]
    index: Dict[tuple, List[int]] = {}
    bcols = [tb.col(c) for c in lb]
    for j in range(tb.num_rows):
        k = tuple(_hashable(c[j]) for c in bcols)
        index.setdefault(k, []).append(j)
    acols = [ta.col(c) for c in la]
    ia, ib = [], []
    matched_b = set()
    for i in range(ta.num_rows):
        k = tuple(_hashable(c[i]) for c in acols)
        js = index.get(k, [])
        for j in js:
            ia.append(i)
            ib.append(j)
            matched_b.add(j)
        if not js and jtype in ("leftouterjoin", "fullouterjoin"):
            ia.append(i)
            ib.append(-1)
    if jtype in ("rightouterjoin", "fullouterjoin"):
        for j in range(tb.num_rows):
            if j not in matched_b:
                ia.append(-1)
                ib.append(j)
    bname_map = {n: (n if n not in set(ta.col_names) else n + "_r")
                 for n in tb.col_names}
    cols: Dict[str, List] = {n: [] for n in ta.col_names}
    cols.update({bname_map[n]: [] for n in tb.col_names})
    for i, j in zip(ia, ib):
        ra = ta.row(i) if i >= 0 else (None,) * len(ta.col_names)
        rb = tb.row(j) if j >= 0 else (None,) * len(tb.col_names)
        for n, v in zip(ta.col_names, ra):
            cols[n].append(v)
        for n, v in zip(tb.col_names, rb):
            cols[bname_map[n]].append(v)
    return MTable(cols)
