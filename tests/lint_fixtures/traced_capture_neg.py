"""TRACED-CAPTURE negative: the stage's only free names are an int
constant (host scalar, hashable by the cache guard) and a dict that is
never mutated after construction; the jitted fn captures nothing."""
import jax

SCALE = 4
config = {"mode": "fast"}


def stage(ctx):
    return ctx * SCALE + len(config)


def register(queue):
    queue.add(stage)


def make_step(fn):
    return jax.jit(fn)
