"""ComQueue superstep recovery — durable snapshots + resumable runs.

The reference's ``IterativeComQueue`` is fault-tolerant because it compiles
to a Flink iterative dataflow and Flink checkpoints it; a preempted
TaskManager restarts from the last completed checkpoint and the BSP loop
continues. The TPU rebuild compiles the whole superstep loop into ONE XLA
program (engine/comqueue.py), which is the fast path and also the
durability problem: a preempted host loses every superstep since launch.

This module restores the Flink property without giving up the compiled
loop. With ``checkpoint_every=N`` the engine runs the SAME superstep body
through a *chunked* while-loop whose upper bound is a **traced scalar**
(one compiled program serves every chunk), and between chunks — on the
host, outside the compiled program — the stacked carry is fetched and
persisted through ``common/checkpoint.py``. ``resume_from=`` loads the
newest valid snapshot, validates it against the program's signature, and
re-enters the loop mid-run; because the snapshot round-trips bitwise and
the chunk program is deterministic, the resumed run's final state is
bit-identical to the uninterrupted one (tests/test_checkpoint.py proves
this for L-BFGS and KMeans).

What checkpointing costs: one device->host fetch of the carry every N
supersteps plus the file writes — and nothing inside the compiled
program. The lowered chunk programs contain no host callbacks and exactly
the collectives of the unchunked program (asserted by a lowered-HLO test,
the same discipline as the collective-manifest accounting).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from ..common.checkpoint import load_latest_validated, save_checkpoint
from ..common.faults import maybe_crash
from ..common.tracing import trace_span

__all__ = ["CheckpointConfig", "program_signature", "resume_state", "drive"]

SCOPE = "comqueue"
SITE = "comqueue.superstep"


@dataclass(frozen=True)
class CheckpointConfig:
    """Engine checkpoint knobs (``IterativeComQueue.set_checkpoint``).

    ``every``      — persist the carry at every superstep boundary that is
                     a multiple of this (and at the final state);
    ``directory``  — snapshot root (one ``ckpt-<step>`` dir per snapshot);
    ``keep_last``  — bounded retention, pruned after each publish;
    ``resume_from``— directory to resume from (usually == ``directory``);
                     the newest VALID snapshot wins; a signature mismatch
                     fails loudly instead of resuming the wrong program.
    """
    directory: str
    every: int = 1
    keep_last: int = 3
    resume_from: Optional[str] = None

    def __post_init__(self):
        if int(self.every) < 1:
            raise ValueError(f"checkpoint_every must be >= 1, "
                             f"got {self.every}")
        if int(self.keep_last) < 1:
            # fail at construction, not mid-training from inside the
            # first snapshot's prune
            raise ValueError(f"checkpoint_keep must be >= 1, "
                             f"got {self.keep_last}")


def program_signature(*, num_workers: int, max_iter: int, seed: int,
                      part_sig: Tuple, bcast_names: Tuple,
                      stages_digest: Any,
                      data_token: Any = None,
                      probes_on: bool = False) -> Dict[str, Any]:
    """JSON identity of the compiled superstep program a snapshot belongs
    to. A resume target must match exactly: same worker count, same input
    geometry, same stage structure — otherwise the carry pytree would be
    fed to a different program and the 'bitwise-identical' contract would
    silently turn into garbage.

    ``data_token`` additionally fingerprints the training DATA (content
    hash for host arrays; shape/dtype only for already-device-resident
    inputs, where a content hash would round-trip device memory): without
    it, a finished run's final snapshot would be silently 'resumed' as
    already-done for a *different* dataset of the same geometry."""
    import hashlib
    stages = hashlib.blake2b(repr(stages_digest).encode(),
                             digest_size=12).hexdigest()
    sig = {"kind": "comqueue_carry", "num_workers": int(num_workers),
           "max_iter": int(max_iter), "seed": int(seed),
           "parts": [list(map(str, item)) for item in part_sig],
           "bcast": [str(n) for n in bcast_names],
           "stages_blake2b": stages}
    if probes_on:
        # health probes add stacked carry entries: a probe-less snapshot
        # must not resume a probed program (and vice versa). Emitted only
        # when on, so pre-health snapshots stay resumable unchanged.
        sig["health_probes"] = True
    if data_token is not None:
        sig["data_blake2b"] = hashlib.blake2b(
            repr(data_token).encode(), digest_size=12).hexdigest()
    return sig


def _next_limit(step: int, every: int, max_iter: int) -> int:
    """Next checkpoint boundary after ``step`` (multiples of ``every``,
    capped at ``max_iter``)."""
    return min(max_iter, (step // every + 1) * every)


def resume_state(config: CheckpointConfig,
                 signature: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Load the newest valid snapshot from ``config.resume_from`` and
    check it against ``signature``; returns the host carry (stacked
    layout) or None when there is nothing to resume from."""
    if not config.resume_from:
        return None
    got = load_latest_validated(config.resume_from, signature, scope=SCOPE,
                                what="program")
    return None if got is None else got[0]


def drive(config: CheckpointConfig, *,
          first: Callable, cont: Callable,
          parts: Dict[str, Any], bcast: Dict[str, Any],
          max_iter: int, signature: Dict[str, Any],
          resumed: Optional[Dict[str, Any]] = None,
          on_snapshot: Optional[Callable] = None
          ) -> Tuple[Any, Dict[str, Any]]:
    """Run the chunked superstep loop with host-side persistence.

    ``first(parts, bcast, limit)`` runs the init pass + loop to ``limit``;
    ``cont(parts, bcast, carry, limit)`` continues a stacked carry.
    ``resumed`` is a host carry from :func:`resume_state` (skips
    ``first``). ``on_snapshot(host_carry, step)`` — if given — fires
    right after each snapshot publishes, with the host carry the save
    already fetched (the health monitor's mid-run hook; it may raise to
    abort the run, and because the snapshot is already on disk the
    aborted run stays resumable). Returns ``(stacked_carry, info)``
    where ``info`` carries the superstep accounting the metrics tail
    needs (``steps_executed``, ``init_ran``, ``resumed_at``).
    """
    import jax.numpy as jnp

    every = int(config.every)
    max_iter = int(max_iter)

    def boundary(stacked):
        # worker 0's copy — __step/__stop are replicated by construction
        step = int(np.asarray(stacked["__step"])[0])
        stop = bool(np.asarray(stacked["__stop"])[0])
        return step, stop

    def chunk(fn, args, from_step, limit):
        """One compiled-chunk pass: dispatch + the boundary sync that
        flushes it. The span tree (exec -> execute -> chunk ->
        superstep.sync) is what lets a trace answer 'which chunk of
        which exec was slow' — the aggregate metrics cannot."""
        with trace_span("comqueue.chunk", cat="engine") as sp:
            out = fn(*args, jnp.asarray(limit, jnp.int32))
            # the device work materializes at this host fetch — timed as
            # its own phase span so dispatch vs sync split is visible
            with trace_span("superstep.sync", cat="engine"):
                step, stop = boundary(out)
            sp.set(from_step=from_step, limit=limit, step=step)
        return out, step, stop

    info: Dict[str, Any] = {"init_ran": resumed is None, "resumed_at": None}
    if resumed is None:
        stacked, step, stop = chunk(first, (parts, bcast), 1,
                                    _next_limit(1, every, max_iter))
        start_step = 0
    else:
        stacked = resumed
        step, stop = boundary(stacked)
        start_step = step
        info["resumed_at"] = start_step
    last_saved = start_step if resumed is not None else None
    while True:
        # the injected-preemption point: BEFORE the snapshot publish, so a
        # killed run genuinely loses the work since the last checkpoint
        # and the resume has supersteps to re-execute
        maybe_crash(SITE, step)
        if step != last_saved:
            host = _to_host(stacked)
            save_checkpoint(config.directory, step, host,
                            meta={"signature": signature, "step": step,
                                  "stopped": stop or step >= max_iter},
                            scope=SCOPE, keep_last=config.keep_last)
            last_saved = step
            if on_snapshot is not None:
                on_snapshot(host, step)
        if stop or step >= max_iter:
            break
        stacked, step, stop = chunk(cont, (parts, bcast, stacked), step,
                                    _next_limit(step, every, max_iter))
    info["steps_executed"] = step - start_step
    return stacked, info


def _to_host(stacked) -> Dict[str, Any]:
    """Fetch every carry leaf to host numpy (the persistence payload)."""
    import jax
    return jax.tree_util.tree_map(np.asarray, dict(stacked))
