"""alink_tpu — a TPU-native distributed ML platform.

A ground-up JAX/XLA re-design of the capabilities of ZhangYuef/Alink
(Alibaba PAI's Flink-based ML platform): operator DAGs, sklearn-style
pipelines, a BSP iterative-compute engine with XLA collectives, ~full
classical-ML algorithm coverage, online learning, and evaluation —
with Flink task slots replaced by a `jax.sharding.Mesh` of TPU chips.
"""

__version__ = "0.1.0"

from .common import (Params, ParamInfo, WithParams, AlinkTypes, TableSchema,
                     DenseVector, SparseVector, VectorUtil, SparseBatch, DenseMatrix,
                     MTable, MLEnvironment, MLEnvironmentFactory, use_local_env)
from .engine import (IterativeComQueue, ComContext, ComputeFunction, AllReduce,
                     AllGather, BroadcastFromWorker0)
