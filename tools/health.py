#!/usr/bin/env python
"""Render an alink_tpu HealthReport (training-health) JSON.

Usage:
    python tools/health.py HEALTH.json             # summary tables
    python tools/health.py HEALTH.json --series loss   # sparkline one series
    python tools/health.py HEALTH.json --json      # normalized JSON

The input is a ``HealthMonitor.save_report()`` file
(``alink_tpu_health_v1``): alert list + probe series recorded by the
engine probe channel (``ctx.probe``), the optimizers' default probes, or
the FTRL progressive-validation path — see docs/observability.md
"Layer 2 — training health".

Exit code: 0 when the report is healthy (nothing above ``info``),
1 otherwise — so a CI step can gate on training health directly.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from alink_tpu.common.health import (HEALTH_FORMAT,  # noqa: E402
                                     HealthMonitor, _jsonify, sparkline)


def _table(headers: List[str], rows: List[List[str]],
           align_right=None) -> str:
    if not rows:
        return "  (none)"
    ar = align_right or [False] + [True] * (len(headers) - 1)
    widths = [max(len(str(headers[i])), *(len(str(r[i])) for r in rows))
              for i in range(len(headers))]

    def fmt(cells):
        return "  " + "  ".join(
            str(c).rjust(widths[i]) if ar[i] else str(c).ljust(widths[i])
            for i, c in enumerate(cells)).rstrip()

    sep = "  " + "  ".join("-" * w for w in widths)
    return "\n".join([fmt(headers), sep] + [fmt(r) for r in rows])


def _fmt(v) -> str:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return str(v)
    if f != f:
        return "NaN"
    return f"{f:.6g}"


def render(doc: dict, series_name=None) -> str:
    out: List[str] = []
    alerts = doc.get("alerts") or []
    series = doc.get("series") or {}

    out.append("== Health summary ==")
    by_sev = {}
    for a in alerts:
        by_sev[a["severity"]] = by_sev.get(a["severity"], 0) + 1
    rows = [["source", doc.get("source", "?")],
            ["healthy", "yes" if doc.get("healthy") else "NO"],
            ["worst severity", doc.get("worst_severity") or "-"],
            ["alerts", f"{len(alerts):,}"
             + (" (" + ", ".join(f"{k}={v}" for k, v in
                                 sorted(by_sev.items())) + ")"
                if by_sev else "")],
            ["probe series", f"{len(series):,}"],
            ["rules", ", ".join(r.get("rule", "?")
                                for r in doc.get("rules", [])) or "-"]]
    out.append(_table(["field", "value"], rows,
                      align_right=[False, False]))

    out.append("\n== Alerts ==")
    arows = [[a["severity"], a["rule"], a["series"], f"{a['step']:,}",
              _fmt(a["value"]), a["message"]] for a in alerts]
    out.append(_table(["severity", "rule", "series", "step", "value",
                       "message"], arows,
                      align_right=[False, False, False, True, True, False]))

    out.append("\n== Probe series ==")
    srows = []
    for name in sorted(series):
        vals = [v for v in series[name]["values"]]
        fv = [v for v in vals if isinstance(v, (int, float)) and v == v]
        srows.append([name, f"{len(vals):,}",
                      _fmt(vals[0]) if vals else "-",
                      _fmt(vals[-1]) if vals else "-",
                      _fmt(min(fv)) if fv else "-",
                      _fmt(max(fv)) if fv else "-"])
    out.append(_table(["series", "points", "first", "last", "min", "max"],
                      srows))

    # sparkline: the requested series, else the conventional objective
    # ("loss", "inertia", or the first pv loss), else the first series
    cand = [series_name] if series_name else \
        ["loss", "inertia"] + [n for n in sorted(series) if "logloss" in n] \
        + sorted(series)
    pick = next((n for n in cand if n in series), None)
    if series_name and pick is None:
        raise SystemExit(f"health.py: no series {series_name!r}; "
                         f"have {sorted(series)}")
    if pick is not None:
        vals = series[pick]["values"]
        steps = series[pick]["steps"]
        out.append(f"\n== {pick} ==")
        if not vals:
            out.append("  (empty series)")
        else:
            out.append("  " + sparkline(vals))
            fv = [v for v in vals
                  if isinstance(v, (int, float)) and v == v]
            out.append(f"  steps {steps[0]}..{steps[-1]}"
                       + (f"  first {_fmt(vals[0])}  last {_fmt(vals[-1])}"
                          f"  min {_fmt(min(fv))}  max {_fmt(max(fv))}"
                          if fv else "  (no finite values)")
                       + ("  (! = non-finite)"
                          if len(fv) != len(vals) else ""))
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="health.py", description=__doc__.splitlines()[0])
    ap.add_argument("report", help=f"path to a {HEALTH_FORMAT} JSON "
                                   f"(HealthMonitor.save_report)")
    ap.add_argument("--series", metavar="NAME",
                    help="sparkline this probe series")
    ap.add_argument("--json", action="store_true",
                    help="emit the normalized report JSON instead of tables")
    args = ap.parse_args(argv)
    doc = HealthMonitor.load_report(args.report)
    if args.json:
        # same strict-JSON encoding save_report uses (non-finite floats
        # as strings), so the output round-trips through load_report
        json.dump(_jsonify(doc), sys.stdout, indent=1, allow_nan=False)
        sys.stdout.write("\n")
    else:
        print(render(doc, series_name=args.series))
    return 0 if doc.get("healthy") else 1


if __name__ == "__main__":
    raise SystemExit(main())
