"""KMeans internals — TPU-native.

Re-design of common/clustering/kmeans/ (call stack SURVEY §3.3):
  KMeansPreallocateCentroid  -> init centroids (host k-means++ / random)
  KMeansAssignCluster        -> distances as ONE matmul on the MXU
                                (||x||^2 - 2 x.c + ||c||^2), argmin, and the
                                k x (d+1) sum/weight buffer built with a
                                one-hot scatter-add matmul (replaces
                                KMeansUtil.updateSumMatrix's per-point loop,
                                KMeansAssignCluster.java:60-64)
  AllReduce(centroidAllReduce) -> lax.psum
  KMeansUpdateCentroids      -> sums / weights (KMeansUpdateCentroids.java:53-71)
  KMeansIterTermination      -> centroid movement < tol carry bit
Supports EUCLIDEAN and COSINE distances (reference FastDistance pre-norms).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ....common.mlenv import MLEnvironment
from ....engine import AllReduce, IterativeComQueue


def kmeans_plus_plus_init(X: np.ndarray, k: int, seed: int,
                          sample_cap: int = 4096) -> np.ndarray:
    """k-means++ seeding on a bounded host sample (reference KMeansInitCentroids
    K-MEANS|| has the same role: good seeds without a full device pass)."""
    rng = np.random.RandomState(seed)
    n = X.shape[0]
    if n > sample_cap:
        X = X[rng.choice(n, sample_cap, replace=False)]
        n = sample_cap
    cents = [X[rng.randint(n)]]
    d2 = ((X - cents[0]) ** 2).sum(1)
    for _ in range(1, k):
        tot = d2.sum()
        if tot <= 0:  # fewer distinct points than k: fall back to uniform
            cents.append(X[rng.randint(n)])
            continue
        cents.append(X[rng.choice(n, p=d2 / tot)])
        d2 = np.minimum(d2, ((X - cents[-1]) ** 2).sum(1))
    return np.stack(cents)


def random_init(X: np.ndarray, k: int, seed: int) -> np.ndarray:
    rng = np.random.RandomState(seed)
    return X[rng.choice(X.shape[0], k, replace=X.shape[0] < k)]


def _distances(X, C, distance_type: str):
    """(n, k) distance matrix as one MXU matmul."""
    if distance_type == "COSINE":
        Xn = X / jnp.maximum(jnp.linalg.norm(X, axis=1, keepdims=True), 1e-12)
        Cn = C / jnp.maximum(jnp.linalg.norm(C, axis=1, keepdims=True), 1e-12)
        return 1.0 - Xn @ Cn.T
    x2 = (X ** 2).sum(1, keepdims=True)
    c2 = (C ** 2).sum(1)
    return x2 - 2.0 * (X @ C.T) + c2


def assign_clusters(X, C, distance_type: str = "EUCLIDEAN"):
    """Nearest centroid ids + distances for a block."""
    D = _distances(X, C, distance_type)
    ids = jnp.argmin(D, axis=1)
    return ids, jnp.take_along_axis(D, ids[:, None], 1)[:, 0]


def kmeans_train(X: np.ndarray, k: int, max_iter: int = 50, tol: float = 1e-4,
                 distance_type: str = "EUCLIDEAN", init: str = "K_MEANS_PARALLEL",
                 seed: int = 0, env: Optional[MLEnvironment] = None,
                 sample_weight: Optional[np.ndarray] = None
                 ) -> Tuple[np.ndarray, np.ndarray, int]:
    """Returns (centroids (k,d), cluster_weights (k,), num_steps)."""
    X = np.asarray(X)
    n, d = X.shape
    w = np.ones(n, X.dtype) if sample_weight is None else np.asarray(sample_weight, X.dtype)
    init_c = (kmeans_plus_plus_init(X, k, seed) if init.upper() != "RANDOM"
              else random_init(X, k, seed)).astype(X.dtype)
    data = np.concatenate([X, w[:, None]], axis=1)
    dt = X.dtype

    def assign(ctx):
        if ctx.is_init_step:
            ctx.put_obj("centroids", ctx.get_obj("init_centroids"))
            ctx.put_obj("movement", jnp.asarray(jnp.inf, dt))
        block = ctx.get_obj("data")
        Xb, wb = block[:, :d], block[:, d]
        C = ctx.get_obj("centroids")
        ids, _ = assign_clusters(Xb, C, distance_type)
        onehot = jax.nn.one_hot(ids, k, dtype=dt) * wb[:, None]   # (n, k), weighted
        sums = onehot.T @ Xb                                      # (k, d) on MXU
        cnts = onehot.sum(0)                                      # (k,)
        ctx.put_obj("buf", jnp.concatenate([sums, cnts[:, None]], 1))

    def update(ctx):
        buf = ctx.get_obj("buf")
        C = ctx.get_obj("centroids")
        sums, cnts = buf[:, :d], buf[:, d]
        newC = jnp.where(cnts[:, None] > 0, sums / jnp.maximum(cnts[:, None], 1e-12), C)
        ctx.put_obj("movement", jnp.sqrt(((newC - C) ** 2).sum(1)).max())
        ctx.put_obj("centroids", newC)
        ctx.put_obj("cluster_weights", cnts)

    result = (IterativeComQueue(env=env, max_iter=max_iter, seed=seed)
              .init_with_partitioned_data("data", data)
              .init_with_broadcast_data("init_centroids", init_c)
              .add(assign)
              .add(AllReduce("buf"))
              .add(update)
              .set_compare_criterion(lambda ctx: ctx.get_obj("movement") < tol)
              .exec())
    return (result.get("centroids"), result.get("cluster_weights"),
            result.step_count)
