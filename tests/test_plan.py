"""ExecutionPlan contracts (ISSUE 19 tentpole): byte-identity of every
migrated cache key against the hand-threaded legacy tuples, canonical
cross-process digest stability, and named single-dimension diffs.

The migration discipline is the PR-7 one: the plan must be a pure
REFACTOR of key derivation — ``legacy_key()`` reproduces the exact
historical tuples, the checkpoint signatures are content-identical
dicts, lowered HLO is byte-identical with the ledger on or off, and
hit/miss behavior never moves.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from alink_tpu.common import compileledger
from alink_tpu.common import plan as planlib
from alink_tpu.common.plan import ExecutionPlan

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# digest: canonical + cross-process stable
# ---------------------------------------------------------------------------

_DIGEST_DIMS = (
    ("ALINK_TPU_SERVE_DTYPE", "f32"),
    ("bucket", 128),
    ("buckets", (1, 4, 128)),
    ("flags", {"donate": True, "fuse": False}),
    ("seed", 7),
    ("nothing", None),
)

_CHILD = """
import sys
sys.path.insert(0, {root!r})
from alink_tpu.common.plan import ExecutionPlan
p = ExecutionPlan("test", {dims!r})
print(p.digest())
"""


class TestDigest:
    def test_stable_within_process(self):
        a = ExecutionPlan("test", _DIGEST_DIMS)
        b = ExecutionPlan("test", _DIGEST_DIMS)
        assert a.digest() == b.digest()
        assert a == b
        # hashability holds for the tuple-of-primitives dims real cache
        # keys are built from (the dict dim above exercises _canon only)
        h = ExecutionPlan("test", _DIGEST_DIMS[:3])
        assert hash(h) == hash(ExecutionPlan("test", _DIGEST_DIMS[:3]))

    def test_stable_across_processes(self):
        """Python's builtin hash() is salted per process; the plan
        digest must NOT be — a fresh interpreter building the same
        flags+buckets plan prints the same digest (the AOT-persistent-
        cache precondition, ROADMAP item 3)."""
        here = ExecutionPlan("test", _DIGEST_DIMS).digest()
        src = _CHILD.format(root=ROOT, dims=_DIGEST_DIMS)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        outs = {subprocess.run([sys.executable, "-c", src], env=env,
                               capture_output=True, text=True,
                               timeout=120, check=True).stdout.strip()
                for _ in range(2)}
        assert outs == {here}

    def test_single_dimension_change_moves_digest_and_names_diff(self):
        base = ExecutionPlan("test", _DIGEST_DIMS)
        for i, (name, old) in enumerate(_DIGEST_DIMS):
            changed = list(_DIGEST_DIMS)
            changed[i] = (name, "CHANGED" if old != "CHANGED" else "X")
            other = ExecutionPlan("test", tuple(changed))
            assert other.digest() != base.digest(), name
            d = other.diff(base)
            assert [e["dim"] for e in d] == [name]
            assert d[0]["old"] == repr(old)

    def test_type_sensitive_diff(self):
        """1 vs True must diff (they key differently in some legacy
        tuples even though == holds)."""
        a = ExecutionPlan("t", (("x", 1),))
        b = ExecutionPlan("t", (("x", True),))
        assert a.diff(b) and a.diff(b)[0]["dim"] == "x"
        assert a.digest() != b.digest()

    def test_cold_start_diff(self):
        p = ExecutionPlan("t", (("x", 1),))
        assert p.diff(None) == [{"dim": "cold-start",
                                 "old": "-", "new": "-"}]

    def test_mesh_digest_uses_fingerprint(self):
        """A live jax Mesh dim digests by fingerprint (axis names +
        shape + device strings), not repr — two Mesh objects over the
        same devices digest identically."""
        import jax
        from jax.sharding import Mesh
        devs = np.array(jax.devices()[:1])
        m1 = Mesh(devs, ("w",))
        m2 = Mesh(devs, ("w",))
        assert ExecutionPlan("t", (("mesh", m1),)).digest() \
            == ExecutionPlan("t", (("mesh", m2),)).digest()


# ---------------------------------------------------------------------------
# engine: legacy ckey byte-identity + checkpoint signature content
# ---------------------------------------------------------------------------

class TestEnginePlan:
    def test_legacy_key_reproduces_historical_13_tuple(self):
        """The exact pre-ISSUE-19 ckey shape, order and values:

            (program_key, stages_dig, mesh, nw, max_iter, seed,
             criterion?, step_log, probes, donate, fuse,
             sorted(parts), sorted(bcast))
        """
        flags = (("ALINK_TPU_STEP_LOG", False),
                 ("ALINK_TPU_HEALTH", True),
                 ("ALINK_TPU_DONATE", True),
                 ("ALINK_TPU_FUSE_COLLECTIVES", False))
        mesh = object()   # identity-keyed, exactly like the legacy tuple
        p = planlib.engine_plan(
            program_key=("lr", 5), stages_digest="digest123", mesh=mesh,
            num_workers=4, max_iter=10, seed=7, has_criterion=True,
            flags=flags, part_names=("a", "train"), bcast_names=("b0",))
        assert p.legacy_key() == (
            ("lr", 5), "digest123", mesh, 4, 10, 7,
            True, False, True, True, False, ("a", "train"), ("b0",))

    def test_live_flags_match_accessors(self):
        from alink_tpu.common.health import health_enabled
        from alink_tpu.common.profiling import step_log_enabled
        from alink_tpu.engine.communication import fusion_enabled
        from alink_tpu.engine.comqueue import donation_enabled
        flags = dict(planlib.engine_flags())
        assert flags == {
            "ALINK_TPU_STEP_LOG": step_log_enabled(),
            "ALINK_TPU_HEALTH": health_enabled(),
            "ALINK_TPU_DONATE": donation_enabled(),
            "ALINK_TPU_FUSE_COLLECTIVES": fusion_enabled(),
        }

    def test_checkpoint_signature_content_identical(self):
        from alink_tpu.engine import recovery
        flags = (("ALINK_TPU_STEP_LOG", False),
                 ("ALINK_TPU_HEALTH", True),
                 ("ALINK_TPU_DONATE", False),
                 ("ALINK_TPU_FUSE_COLLECTIVES", True))
        p = planlib.engine_plan(
            program_key=None, stages_digest="sd", mesh=None,
            num_workers=2, max_iter=3, seed=9, has_criterion=False,
            flags=flags, part_names=("train",), bcast_names=("w",))
        got = planlib.engine_checkpoint_signature(
            p, part_sig=(("train", (8, 2)),), data_token="tok")
        want = recovery.program_signature(
            num_workers=2, max_iter=3, seed=9,
            part_sig=(("train", (8, 2)),), bcast_names=("w",),
            stages_digest="sd", data_token="tok",
            probes_on=True, fuse_collectives=True)
        assert got == want


# ---------------------------------------------------------------------------
# FTRL: checkpoint-signature content identity (incl. conditional keys)
# ---------------------------------------------------------------------------

def _legacy_ftrl_signature(*, alpha, beta, l1, l2, dim, dim_pad,
                           update_mode, staleness, chunk_size,
                           has_icpt, warm_fp, kern_resolved_pallas,
                           fuse):
    """The pre-ISSUE-19 hand-built ck_signature, verbatim."""
    sig = {"kind": "ftrl_state", "alpha": alpha, "beta": beta,
           "l1": l1, "l2": l2, "dim": dim, "dim_pad": dim_pad,
           "update_mode": update_mode,
           "staleness": (staleness
                         if update_mode == "staleness" else None),
           "has_intercept": bool(has_icpt),
           "warm_coef_blake2b": warm_fp}
    if update_mode == "chained":
        sig["chunk_size"] = chunk_size
        if kern_resolved_pallas:
            sig["ftrl_kernel"] = "pallas"
        if fuse:
            sig["fuse_collectives"] = True
    return sig


class TestFtrlPlan:
    @pytest.mark.parametrize("mode", ["dense", "staleness", "chained"])
    def test_signature_content_identical(self, mode, monkeypatch):
        monkeypatch.delenv("ALINK_TPU_FTRL_KERNEL", raising=False)
        monkeypatch.delenv("ALINK_TPU_FUSE_COLLECTIVES", raising=False)
        kw = dict(alpha=0.1, beta=1.0, l1=0.01, l2=0.05, dim=33,
                  dim_pad=64, update_mode=mode, staleness=4,
                  chunk_size=128)
        p = planlib.ftrl_plan(mesh=None, has_intercept=True,
                              warm_fp="abc123", **kw)
        want = _legacy_ftrl_signature(
            has_icpt=True, warm_fp="abc123",
            kern_resolved_pallas=False, fuse=False, **kw)
        assert planlib.ftrl_checkpoint_signature(p) == want

    def test_chained_fuse_folds_conditionally(self, monkeypatch):
        monkeypatch.delenv("ALINK_TPU_FTRL_KERNEL", raising=False)
        monkeypatch.setenv("ALINK_TPU_FUSE_COLLECTIVES", "1")
        kw = dict(mesh=None, alpha=0.1, beta=1.0, l1=0.0, l2=0.0,
                  dim=8, dim_pad=8, staleness=0, chunk_size=64,
                  has_intercept=False, warm_fp="x")
        chained = planlib.ftrl_plan(update_mode="chained", **kw)
        assert planlib.ftrl_checkpoint_signature(
            chained).get("fuse_collectives") is True
        dense = planlib.ftrl_plan(update_mode="dense", **kw)
        assert "fuse_collectives" not in \
            planlib.ftrl_checkpoint_signature(dense)
        assert "chunk_size" not in \
            planlib.ftrl_checkpoint_signature(dense)


# ---------------------------------------------------------------------------
# sweep + serving views
# ---------------------------------------------------------------------------

class TestSweepPlan:
    def test_legacy_program_key_byte_identity(self, monkeypatch):
        monkeypatch.delenv("ALINK_TPU_SWEEP", raising=False)
        p = planlib.sweep_plan("ftrl", ("a", 1))
        assert planlib.legacy_sweep_program_key(p) == \
            ("sweep", "ftrl", False, "a", 1)
        monkeypatch.setenv("ALINK_TPU_SWEEP", "1")
        p2 = planlib.sweep_plan("ftrl", ("a", 1))
        assert planlib.legacy_sweep_program_key(p2) == \
            ("sweep", "ftrl", True, "a", 1)
        d = p2.diff(p)
        assert [e["dim"] for e in d] == ["ALINK_TPU_SWEEP"]


class TestServingEventPlan:
    def _splan(self, sig):
        from alink_tpu.serving.plan import ServingPlan
        return ServingPlan(signature=tuple(sig), buckets=(1, 4, 16),
                           sharded=False, mesh_fp=None)

    def test_signature_tail_decomposes_into_flag_dims(self):
        sp = self._splan(("linear", 8, "f32", False))
        p = planlib.serving_event_plan(sp, kind="dense", bucket=16,
                                       trailing=((8,),))
        assert p.get("ALINK_TPU_SERVE_DTYPE") == "f32"
        assert p.get("ALINK_TPU_SERVE_FUSED") is False
        assert p.get("geometry") == ("linear", 8)
        assert p.get("bucket") == 16

    def test_dtype_flip_diffs_exactly_the_flag(self):
        a = planlib.serving_event_plan(
            self._splan(("linear", 8, "f32", False)), kind="dense",
            bucket=16, trailing=((8,),))
        b = planlib.serving_event_plan(
            self._splan(("linear", 8, "int8", False)), kind="dense",
            bucket=16, trailing=((8,),))
        d = b.diff(a)
        assert [e["dim"] for e in d] == ["ALINK_TPU_SERVE_DTYPE"]
        assert d[0]["old"] == "'f32'" and d[0]["new"] == "'int8'"

    def test_bucket_change_diffs_bucket(self):
        a = planlib.serving_event_plan(
            self._splan(("linear", 8, "f32", False)), kind="dense",
            bucket=128, trailing=())
        b = planlib.serving_event_plan(
            self._splan(("linear", 8, "f32", False)), kind="dense",
            bucket=512, trailing=())
        assert [e["dim"] for e in b.diff(a)] == ["bucket"]


# ---------------------------------------------------------------------------
# the no-op proof: ledger on/off — identical keys, hit/miss, HLO
# ---------------------------------------------------------------------------

class TestLedgerIsKeyNeutral:
    def _run_queue(self, seed):
        import jax.numpy as jnp
        from alink_tpu.engine import AllReduce, IterativeComQueue

        def stage(ctx):
            if ctx.is_init_step:
                ctx.put_obj("acc", jnp.zeros(()))
            ctx.put_obj("local", jnp.ones(()))

        def fold(ctx):
            ctx.put_obj("acc", ctx.get_obj("acc") + ctx.get_obj("local"))

        q = (IterativeComQueue(max_iter=3, seed=seed)
             .add(stage).add(AllReduce("local")).add(fold))
        q.set_program_key(("plan_test", seed))
        return q.exec()

    def test_engine_cache_keys_and_hits_identical(self, monkeypatch):
        """Same program run twice under ledger ON and ledger OFF: the
        program-cache key set and the hit/miss deltas are identical —
        the ledger observes the cache, it is not part of the key."""
        from alink_tpu.engine import comqueue

        def deltas():
            comqueue.clear_program_cache()
            compileledger.reset()
            h0 = dict(comqueue._PROGRAM_CACHE_STATS)
            self._run_queue(3)
            self._run_queue(3)
            h1 = comqueue._PROGRAM_CACHE_STATS
            return (set(comqueue._PROGRAM_CACHE),
                    h1["hits"] - h0["hits"],
                    h1["misses"] - h0["misses"])

        monkeypatch.setenv("ALINK_TPU_COMPILE_LEDGER", "0")
        keys_off, hits_off, miss_off = deltas()
        assert not compileledger.compilez_doc()["caches"]
        monkeypatch.setenv("ALINK_TPU_COMPILE_LEDGER", "1")
        keys_on, hits_on, miss_on = deltas()
        assert keys_on == keys_off
        assert (hits_on, miss_on) == (hits_off, miss_off)
        row = compileledger.compilez_doc()["caches"]["engine.program"]
        assert row["misses"] == miss_on and row["hits"] == hits_on

    def test_serving_lowered_hlo_byte_identical(self, monkeypatch):
        """The serving score program lowers to byte-identical text with
        the ledger on or off (the ledger records AROUND the compile; it
        must never reach the traced computation)."""
        import jax
        import jax.numpy as jnp
        from alink_tpu.common.compat import lowered_text
        from alink_tpu.common.mtable import MTable
        from alink_tpu.common.params import Params
        from alink_tpu.common.vector import DenseVector
        from alink_tpu.operator.batch.classification.linear import (
            LogisticRegressionTrainBatchOp)
        from alink_tpu.operator.batch.source.sources import (
            MemSourceBatchOp)
        from alink_tpu.operator.common.linear.mapper import (
            LinearModelMapper)

        rng = np.random.RandomState(3)
        X = rng.randn(32, 6)
        y = (X @ rng.randn(6) > 0).astype(np.int64)
        vecs = np.empty(32, object)
        vecs[:] = [DenseVector(X[i]) for i in range(32)]
        tbl = MTable({"vec": vecs, "label": y},
                     "vec VECTOR, label LONG")
        warm = LogisticRegressionTrainBatchOp(
            vector_col="vec", label_col="label", max_iter=2).link_from(
            MemSourceBatchOp(tbl))
        mapper = LinearModelMapper(
            warm.get_output_table().schema,
            tbl.select(["vec"]).schema,
            Params({"prediction_col": "pred", "vector_col": "vec"}))
        mapper.load_model(warm.get_output_table())

        def lowered():
            k = mapper.serving_kernel()
            mdl = tuple(jnp.asarray(a) for a in k.model_arrays)
            kind, arrs = k.encode(tbl.select(["vec"]).first_n(4), 8)
            return k.signature, lowered_text(
                jax.jit(k.device_fns[kind]).lower(mdl, *arrs))

        monkeypatch.setenv("ALINK_TPU_COMPILE_LEDGER", "0")
        sig_off, hlo_off = lowered()
        monkeypatch.setenv("ALINK_TPU_COMPILE_LEDGER", "1")
        sig_on, hlo_on = lowered()
        assert sig_on == sig_off
        assert hlo_on == hlo_off

    def test_ledger_flags_registered_key_neutral(self):
        from alink_tpu.common.flags import FLAGS
        for name in ("ALINK_TPU_COMPILE_LEDGER", "ALINK_TPU_COMPILE_RING"):
            f = FLAGS.get(name)
            assert f is not None and f.key_neutral, name
            assert not f.folds_into
