"""SweepPlan — hyperparameter classification + compile-group planning.

A swept parameter is either:

* **carry-resident** — its value enters the compiled program as DATA
  (a ``(points,)`` broadcast lane read by the per-point kernel): step
  size, regularization strength, convergence tolerance, the SGD
  mini-batch fraction, the k-means init seed (which only shapes the
  host-computed stacked init centroids). Any number of points sweep
  these inside ONE program; changing the values never recompiles.
* **trace-shaping** — its value changes program GEOMETRY or the traced
  op graph: the optimizer method (LBFGS's ring buffers vs SGD's
  sampling), ``max_iter`` (preallocated curve length), the engine seed,
  k / distance metric for k-means. Points that differ in a
  trace-shaping parameter land in separate **compile groups**, one
  compiled program per group.

The compiled-program count of a sweep therefore equals the number of
trace-shaping groups — independent of population size and of the ASHA
rung schedule (the acceptance invariant of ISSUE 12).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["CARRY_RESIDENT", "TRACE_SHAPING", "AshaConfig", "SweepPlan",
           "classify_param"]

# Per-trainer parameter classification. "optimizer" covers the five
# iterative trainers behind OptimParams (LBFGS/OWLQN/GD/SGD/Newton);
# "kmeans" covers kmeans_train; "ftrl" covers the online FTRL staleness
# kernel (sweep_ftrl — ISSUE 13 satellite, the ROADMAP item 3
# leftover). Names are the OptimParams / kmeans_train /
# FtrlTrainStreamOp keyword names (l1/l2 ride the objective in the
# serial path but sweep as per-point lanes through the parameterized
# kernels; FTRL's alpha/beta/l1/l2 enter the weights closed form as
# pure data).
CARRY_RESIDENT: Dict[str, frozenset] = {
    "optimizer": frozenset({"learning_rate", "epsilon", "l1", "l2",
                            "mini_batch_fraction"}),
    "kmeans": frozenset({"tol", "seed"}),
    "ftrl": frozenset({"alpha", "beta", "l1", "l2"}),
}

TRACE_SHAPING: Dict[str, frozenset] = {
    "optimizer": frozenset({"method", "max_iter", "seed"}),
    "kmeans": frozenset({"k", "distance_type", "init", "max_iter"}),
    # the staleness bound is the scan chunk length — program geometry
    "ftrl": frozenset({"staleness", "update_mode"}),
}


def classify_param(trainer: str, name: str) -> str:
    """``"carry"`` or ``"trace"`` for a swept parameter; raises KeyError
    for a name the sweep engine does not understand (callers must fall
    back to the serial loop, recorded — never guess)."""
    if trainer not in CARRY_RESIDENT:
        raise KeyError(f"unknown sweep trainer {trainer!r}; "
                       f"have {sorted(CARRY_RESIDENT)}")
    if name in CARRY_RESIDENT[trainer]:
        return "carry"
    if name in TRACE_SHAPING[trainer]:
        return "trace"
    raise KeyError(f"{trainer}: unknown sweep parameter {name!r} "
                   f"(carry-resident: {sorted(CARRY_RESIDENT[trainer])}; "
                   f"trace-shaping: {sorted(TRACE_SHAPING[trainer])})")


@dataclass(frozen=True)
class AshaConfig:
    """ASHA successive halving (Li et al., "A System for Massively
    Parallel Hyperparameter Tuning", MLSys 2020; generalizing Hyperband,
    Li et al., JMLR 2018) mapped onto the engine's chunk boundaries.

    ``rung``       — supersteps between rungs; each rung is a chunk
                     boundary of the compiled while-loop (where
                     checkpoints already exist, PR 2), so pruning reads
                     the per-point probe lanes with ZERO new host
                     callbacks inside the program;
    ``eta``        — keep the top ``ceil(alive/eta)`` points per rung;
    ``min_points`` — never prune below this many live points.

    Pruning flips a carry-resident boolean lane; the program never
    recompiles as the population shrinks, and the decision is
    deterministic and seed-free: points rank by (loss, point index) —
    NaN losses sort last — so the same grid always yields the same
    survivors.
    """
    rung: int
    eta: int = 3
    min_points: int = 1

    def __post_init__(self):
        if int(self.rung) < 1:
            raise ValueError(f"AshaConfig.rung must be >= 1, got {self.rung}")
        if int(self.eta) < 2:
            raise ValueError(f"AshaConfig.eta must be >= 2, got {self.eta}")
        if int(self.min_points) < 1:
            raise ValueError(f"AshaConfig.min_points must be >= 1, "
                             f"got {self.min_points}")


@dataclass
class SweepPlan:
    """A validated sweep: trainer family + per-point override dicts.

    ``points`` are ``{param_name: value}`` overrides on top of the
    caller's base configuration; every name must classify (carry or
    trace) for ``trainer``. :meth:`groups` partitions the points into
    compile groups keyed by their trace-shaping values, preserving
    point order inside each group (the deterministic tie-break relies
    on stable point indices).
    """
    trainer: str
    points: List[Dict[str, Any]]
    base: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        if not self.points:
            raise ValueError("SweepPlan needs at least one point")
        for i, pt in enumerate(self.points):
            for name in pt:
                classify_param(self.trainer, name)   # raises on unknown

    # ------------------------------------------------------------------
    def carry_axes(self) -> List[str]:
        names = set()
        for pt in self.points:
            names.update(n for n in pt
                         if n in CARRY_RESIDENT[self.trainer])
        return sorted(names)

    def trace_axes(self) -> List[str]:
        names = set()
        for pt in self.points:
            names.update(n for n in pt
                         if n in TRACE_SHAPING[self.trainer])
        return sorted(names)

    def _trace_key(self, pt: Dict[str, Any]) -> Tuple:
        """The compile-group identity of one point: its resolved
        trace-shaping values (base-filled, so an explicit override equal
        to the base value lands in the base group, not a duplicate)."""
        return tuple(
            (n, pt.get(n, self.base.get(n)))
            for n in sorted(TRACE_SHAPING[self.trainer]))

    def groups(self) -> List[Tuple[Tuple, List[int]]]:
        """``[(trace_key, [point indices])]`` in first-seen order.

        len(groups()) is the number of compiled sweep programs this
        plan needs — the acceptance invariant: independent of the
        population size and of any ASHA schedule.
        """
        order: List[Tuple] = []
        members: Dict[Tuple, List[int]] = {}
        for i, pt in enumerate(self.points):
            k = self._trace_key(pt)
            if k not in members:
                members[k] = []
                order.append(k)
            members[k].append(i)
        return [(k, members[k]) for k in order]

    @property
    def num_points(self) -> int:
        return len(self.points)
