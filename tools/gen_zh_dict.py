# -*- coding: utf-8 -*-
"""Generate alink_tpu's Mandarin frequency dictionary (zh_dict.txt).

The reference bundles jieba's ~350k-entry dictionary plus a 676K HMM
emission table (jiebasegment/WordDictionary.java, viterbi/FinalSeg.java).
This repo may not copy those resources, and the build has no network
egress — so the dictionary is COMPILED here, deterministically, from:

  1. a hand-authored core vocabulary (common words across POS classes,
     written for this project);
  2. compositional expansion over real components:
     - numerals (一百, 三千五, 第十二, 百分之三十 ...),
     - dates/times (三月, 十五日, 星期四, 二零二四年 ...),
     - full person names = real surname inventory x common given-name
       characters (王伟, 李秀英 ... — the reference dictionary likewise
       carries bulk name entries),
     - place names = province/city stems x administrative suffixes
       (北京市, 广东省, 朝阳区 ...),
     - measure-word phrases (一个, 两张, 几次 ...),
     - verb reduplication and V一V (看看, 想一想 ...),
     - common affixed forms (老师们, 科学家, 现代化 ...).

Frequencies are band-based: hand-authored core words carry corpus-scale
bands by class; generated items carry low bands (they exist so the DAG
*can* take them, and so OOV Viterbi sees realistic B/E char statistics —
exact counts matter far less than relative magnitude).

Run:  python tools/gen_zh_dict.py   (rewrites
      alink_tpu/operator/common/nlp/zh_dict.txt deterministically)
"""

import os
import sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from tools.zh_core_vocab import CORE_VOCAB  # noqa: E402
from tools.zh_vocab_extended import EXTENDED_VOCAB  # noqa: E402
from tools.zh_vocab_r5 import R5_BLOCKS  # noqa: E402
from tools.zh_vocab_r6 import (R6_COMPLEMENTS, R6_CURATED,  # noqa: E402
                               R6_NOUN_STEMS, R6_PREFIXES, R6_SUFFIXES,
                               R6_V2_SUFFIXES, R6_VERBS_1, R6_VERBS_2)

OUT = os.path.join(os.path.dirname(__file__), "..", "alink_tpu",
                   "operator", "common", "nlp", "zh_dict.txt")

# ---------------------------------------------------------------------------
# component inventories (real items, hand-authored)
# ---------------------------------------------------------------------------

DIGITS = "一二三四五六七八九"
SMALL_UNITS = ["十", "百", "千"]
BIG_UNITS = ["万", "亿"]

SURNAMES = (
    "王李张刘陈杨黄赵吴周徐孙马朱胡郭何高林罗郑梁谢宋唐许韩冯邓曹彭曾肖田董袁潘于蒋蔡余杜叶程苏魏吕丁任沈姚卢姜崔钟谭陆汪范金石廖贾夏韦付方白邹孟熊秦邱江尹薛闫段雷侯龙史陶黎贺顾毛郝龚邵万钱严覃武戴莫孔向汤"
)
DOUBLE_SURNAMES = ["欧阳", "司马", "上官", "诸葛", "东方", "皇甫", "尉迟",
                   "司徒", "长孙", "慕容"]
GIVEN_CHARS = (
    "伟芳娜敏静丽强磊军洋勇艳杰娟涛明超秀霞平刚桂英华玉萍红娥玲芬燕彬鹏浩凯秀兰珍莉斌宇浩然博文昊轩子涵雨欣怡梓晨思宇佳琪志国建军建华国强国华志强志明海燕海燕春梅春花秋月冬梅雪梅丹凤霞云龙凤鑫淼森晶磊鑫焱垚嘉琪欣怡雅婷婷玥璐瑶倩颖莹洁慧巧美惠珠翠雅芝妍茜秋珊莎锦黛青倩婷姣婉娴瑾颖露瑶怡婵雁蓓纨仪荷丹蓉眉君琴蕊薇菁梦岚苑婕馨瑗琰韵融园艺咏卿聪澜纯毓悦昭冰爽琬茗羽希宁欣飘育滢馥筠柔竹霭凝晓欢霄枫芸菲寒伊亚宜可姬舒影荔枝思丽"
)

PROVINCES = ["北京", "天津", "上海", "重庆", "河北", "山西", "辽宁", "吉林",
             "黑龙江", "江苏", "浙江", "安徽", "福建", "江西", "山东", "河南",
             "湖北", "湖南", "广东", "海南", "四川", "贵州", "云南", "陕西",
             "甘肃", "青海", "台湾", "内蒙古", "广西", "西藏", "宁夏", "新疆",
             "香港", "澳门"]
CITIES = ["广州", "深圳", "杭州", "南京", "苏州", "成都", "武汉", "西安",
          "郑州", "长沙", "沈阳", "青岛", "大连", "厦门", "宁波", "无锡",
          "佛山", "东莞", "泉州", "南通", "合肥", "福州", "济南", "昆明",
          "哈尔滨", "长春", "石家庄", "太原", "南昌", "贵阳", "南宁", "兰州",
          "乌鲁木齐", "呼和浩特", "银川", "西宁", "拉萨", "海口", "三亚",
          "珠海", "中山", "惠州", "嘉兴", "温州", "绍兴", "台州", "金华",
          "徐州", "常州", "扬州", "烟台", "潍坊", "临沂", "洛阳", "开封",
          "襄阳", "宜昌", "岳阳", "衡阳", "桂林", "柳州", "遵义", "绵阳",
          "唐山", "保定", "邯郸", "秦皇岛", "包头", "鞍山", "抚顺", "吉林",
          "齐齐哈尔", "大庆", "牡丹江", "镇江", "泰州", "盐城", "淮安",
          "连云港", "湖州", "芜湖", "蚌埠", "安庆", "漳州", "莆田", "九江",
          "赣州", "淄博", "济宁", "威海", "日照", "新乡", "安阳", "焦作",
          "黄石", "十堰", "荆州", "株洲", "湘潭", "常德", "汕头", "湛江",
          "茂名", "肇庆", "江门", "北海", "攀枝花", "泸州", "德阳", "南充",
          "宜宾", "曲靖", "大理", "宝鸡", "咸阳", "延安", "天水", "克拉玛依"]
DISTRICTS = ["朝阳", "海淀", "东城", "西城", "丰台", "石景山", "浦东",
             "黄浦", "徐汇", "长宁", "静安", "虹口", "杨浦", "闵行", "宝山",
             "天河", "越秀", "荔湾", "白云", "番禺", "南山", "福田", "罗湖",
             "宝安", "龙岗", "西湖", "滨江", "余杭", "萧山", "鼓楼", "玄武",
             "秦淮", "武侯", "锦江", "青羊", "金牛", "洪山", "武昌", "汉阳",
             "雁塔", "碑林", "未央", "岳麓", "芙蓉", "天心"]
COUNTRIES = ["中国", "美国", "日本", "韩国", "英国", "法国", "德国", "俄罗斯",
             "印度", "巴西", "加拿大", "澳大利亚", "意大利", "西班牙",
             "葡萄牙", "荷兰", "瑞士", "瑞典", "挪威", "丹麦", "芬兰",
             "波兰", "希腊", "土耳其", "埃及", "南非", "墨西哥", "阿根廷",
             "智利", "泰国", "越南", "新加坡", "马来西亚", "印度尼西亚",
             "菲律宾", "缅甸", "柬埔寨", "老挝", "蒙古", "朝鲜", "巴基斯坦",
             "孟加拉", "伊朗", "伊拉克", "沙特", "以色列", "乌克兰",
             "比利时", "奥地利", "爱尔兰", "新西兰", "捷克", "匈牙利"]

MEASURES = "个只条张件套名位本台辆艘间家场次回顿番趟遍层排行组队双对副幅座栋棵株朵粒颗滴块段节届期封笔门科岁年月日天周"
MEASURE_NUMS = ["一", "两", "三", "四", "五", "六", "七", "八", "九", "十",
                "几", "每", "半", "数", "这", "那", "上", "下", "首", "同"]

REDUP_VERBS = ["看", "听", "想", "说", "走", "坐", "玩", "试", "问", "读",
               "写", "聊", "歇", "逛", "查", "算", "等", "找", "摸", "尝",
               "谈", "转", "动", "笑", "练", "比", "猜", "数", "擦", "洗"]

PERSON_SUFFIX = ["们", "家", "者", "员", "长", "手", "师", "士", "生", "工"]
ABSTRACT_SUFFIX = ["化", "性", "度", "率", "力", "感", "观", "界", "论",
                   "学", "法", "式", "型", "类", "版", "期", "区", "部",
                   "所", "站", "厅", "馆", "院", "局", "处", "科"]
STEMS_FOR_SUFFIX = ["现代", "全球", "信息", "工业", "城市", "市场", "科学",
                    "自动", "数字", "智能", "网络", "标准", "规范", "多样",
                    "合理", "可能", "重要", "安全", "稳定", "可靠", "敏感",
                    "责任", "荣誉", "幸福", "满意", "成功", "效率", "增长",
                    "利用", "覆盖", "就业", "入学", "合格", "优秀", "道德",
                    "价值", "人生", "世界", "历史", "艺术", "文学", "哲学",
                    "经济", "社会", "自然", "语言", "心理", "物理", "化学",
                    "生物", "地理", "教育", "管理", "金融", "法律", "医学",
                    "工程", "环境", "能源", "材料", "生活", "工作", "学习",
                    "研究", "发展", "建设", "服务", "生产", "消费", "投资"]


def number_words():
    """Real numeral words: 十五, 三百, 五千二, 第十二, 百分之三十 ..."""
    words = set()
    # 11..99 (十一..九十九)
    for t in [""] + list(DIGITS):
        for o in [""] + list(DIGITS):
            if t == "" and o == "":
                continue
            w = (t + "十" + o) if (t or o != "") else ""
            if t == "" and o:
                w = "十" + o          # 十一..十九
            elif t and o == "":
                w = t + "十"          # 二十..九十
            elif t and o:
                w = t + "十" + o      # 二十一..
            if w:
                words.add(w)
    # D百 / D千 / D万 / D亿 (+一位 tail: 三百五, 两千八)
    for d in list(DIGITS) + ["两", "几", "数"]:
        for u in SMALL_UNITS + BIG_UNITS:
            words.add(d + u)
            for tail in DIGITS:
                words.add(d + u + tail)
    # 第N (ordinals)
    for d in list(DIGITS) + ["十", "百"]:
        words.add("第" + d)
    for t in DIGITS:
        words.add("第十" + t)
        words.add("第" + t + "十")
    # percent 百分之N
    for d in list(DIGITS) + ["十", "百"]:
        words.add("百分之" + d)
    for t in DIGITS:
        words.add("百分之十" + t)
        words.add("百分之" + t + "十")
    return sorted(words)


def date_words():
    words = set()
    months = ["一", "二", "三", "四", "五", "六", "七", "八", "九", "十",
              "十一", "十二"]
    for m in months:
        words.add(m + "月")
        words.add(m + "月份")
    days = months + ["十三", "十四", "十五", "十六", "十七", "十八", "十九",
                     "二十", "二十一", "二十二", "二十三", "二十四", "二十五",
                     "二十六", "二十七", "二十八", "二十九", "三十", "三十一"]
    for d in days:
        words.add(d + "日")
        words.add(d + "号")
    for w in ["一", "二", "三", "四", "五", "六", "日", "天"]:
        words.add("星期" + w)
        words.add("周" + w)
        words.add("礼拜" + w)
    for h in days[:24]:
        words.add(h + "点")
        words.add(h + "点钟")
    for d in DIGITS + "零":
        words.add(d + "年")
    return sorted(words)


def person_names():
    """Full names: top surname inventory x given-name characters.

    Two-char names (王伟) from every (surname, given) pair; three-char
    names (王秀英) from a deterministic subsample of given-char pairs —
    the full cross product would be ~900k entries, far beyond need."""
    names = []
    gc = sorted(set(GIVEN_CHARS))
    for s in SURNAMES:
        for g in gc:
            names.append(s + g)
    # deterministic 3-char subsample: per-surname cross product of two
    # disjoint-stride slices of the given-char inventory (~325/surname —
    # the full cross product would be ~2.9M entries; this matches the
    # name density a corpus-derived dictionary would carry)
    for si, s in enumerate(SURNAMES):
        aset = gc[si % 13::13]
        bset = gc[(si * 3) % 7::7]
        for a in aset:
            for b in bset:
                names.append(s + a + b)
    n = len(gc)
    for s in DOUBLE_SURNAMES:
        for k in range(40):
            names.append(s + gc[(k * 17) % n])
        for a in gc[3::23]:
            for b in gc[5::11]:
                names.append(s + a + b)
    return names


def place_names():
    words = set()
    for p in PROVINCES:
        words.add(p)
        words.add(p + ("市" if p in ("北京", "天津", "上海", "重庆") else "省"))
        words.add(p + "人")
    for c in CITIES:
        words.add(c)
        words.add(c + "市")
        words.add(c + "人")
    for d in DISTRICTS:
        words.add(d)
        words.add(d + "区")
    for c in COUNTRIES:
        words.add(c)
        words.add(c + "人")
        words.add(c + "语")
    return sorted(words)


def measure_phrases():
    words = set()
    for n in MEASURE_NUMS:
        for m in MEASURES:
            words.add(n + m)
    return sorted(words)


def redup_words():
    words = set()
    for v in REDUP_VERBS:
        words.add(v + v)
        words.add(v + "一" + v)
        words.add(v + "了" + v)
    return sorted(words)


def affixed_words():
    words = set()
    for s in STEMS_FOR_SUFFIX:
        for suf in ABSTRACT_SUFFIX:
            words.add(s + suf)
    people = ["工人", "农民", "学生", "老师", "医生", "护士", "司机",
              "记者", "作家", "画家", "歌手", "演员", "律师", "法官",
              "警察", "士兵", "科学家", "工程师", "设计师", "教授",
              "专家", "学者", "读者", "观众", "听众", "用户", "客户",
              "选手", "球员", "教练", "裁判", "厨师", "服务员", "经理",
              "职员", "会计", "秘书", "助理", "主任", "主席", "部长",
              "市长", "省长", "校长", "院长", "馆长", "团长", "队长",
              "班长", "组长", "社长", "店长", "厂长", "船长", "机长"]
    for p in people:
        words.add(p)
        words.add(p + "们")
    # demonyms and language names — real derived lexemes over the real
    # place/country inventories (北京人, 美国人, 法语, 德文 ...)
    for place in PROVINCES + CITIES + COUNTRIES:
        words.add(place + "人")
    for c in ["英", "法", "德", "俄", "日", "韩", "西班牙", "葡萄牙",
              "意大利", "阿拉伯", "希腊", "越南", "泰", "缅甸", "印地",
              "蒙古", "朝鲜", "马来", "荷兰", "瑞典", "芬兰", "波兰",
              "土耳其", "波斯", "拉丁"]:
        words.add(c + "语")
    for c in ["英", "法", "德", "俄", "日", "韩", "中", "外"]:
        words.add(c + "文")
    # AABB adjective reduplications from real AB bases
    aabb = ["高兴", "快乐", "干净", "整齐", "认真", "仔细", "清楚",
            "明白", "漂亮", "大方", "老实", "规矩", "安静", "热闹",
            "辛苦", "快活", "舒服", "松散", "零碎", "琐碎", "叮当",
            "吞吐", "来往", "进出", "上下", "反复", "日夜", "风雨",
            "躲闪", "摇晃", "哭啼", "吵闹", "拉扯", "敲打", "修补",
            "缝补", "洗刷", "收拾", "打扫", "挑选", "说笑", "蹦跳",
            "指点", "评说", "商量", "思念", "痛快", "和气", "客气",
            "仓促", "匆忙", "勤恳", "踏实", "结实", "地道", "利落",
            "爽快", "直爽", "活泼", "斯文", "文静", "秀气", "实在"]
    for ab in aabb:
        words.add(ab[0] * 2 + ab[1] * 2)
    return sorted(words)


# frequency bands (log-ish spacing; core classes set in zh_core_vocab)
BANDS = {
    "number": 800, "date": 900, "measure": 1500, "redup": 300,
    "affix": 400, "place": 600, "country": 1200, "name3": 25, "name2": 60,
    "deriv": 150,
}


def derived_words():
    """Round-6 single-char affix derivation over real stems (ISSUE 15
    satellite): noun stem x bound suffix (安全性, 市场化), bound prefix
    x noun stem (非正式, 超高速), single-char verb x resultative
    complement (打开, 看完, 听懂), two-char verb x nominalizer
    (管理者, 研究员). Single-char BOUND affixes only — a derived word
    can never merge two adjacent free gold tokens, which is what rules
    out composing 2-char+2-char compounds here."""
    words = set()
    for s in R6_NOUN_STEMS:
        for suf in R6_SUFFIXES:
            words.add(s + suf)
        for pre in R6_PREFIXES:
            words.add(pre + s)
    for v in R6_VERBS_1:
        for c in R6_COMPLEMENTS:
            if c != v:
                words.add(v + c)
    for v in R6_VERBS_2:
        for suf in R6_V2_SUFFIXES:
            words.add(v + suf)
    return sorted(words)


def main():
    entries = {}
    category = {}   # word -> first-assigned category (stats only)

    def put(w, f, cat="general"):
        if len(w) < 1 or " " in w:
            return
        if w not in entries:
            category[w] = cat
        entries[w] = max(entries.get(w, 0), f)

    for w, f in CORE_VOCAB:
        put(w, f)
    for w, f in EXTENDED_VOCAB:
        put(w, f)
    # round-5 domain vocabulary (medicine/law/IT/daily life/geo/mind) plus
    # enumerated verb-complement compounds; each block maps a frequency
    # band to its whitespace-separated words (tools/zh_vocab_r5.py)
    for block in R5_BLOCKS:
        for band, text in sorted(block.items()):
            for w in text.split():
                put(w, band, "r5")
    # round-2's hand-tuned 1.1k list rides along as a base layer (it is
    # equally original and already covers the segmenter's fixture set)
    base = os.path.join(os.path.dirname(__file__), "zh_base_vocab.txt")
    with open(base, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                w, _, c = line.partition(" ")
                put(w, int(c))
    for w in number_words():
        put(w, BANDS["number"], "number")
    for w in date_words():
        put(w, BANDS["date"], "date")
    for w in measure_phrases():
        put(w, BANDS["measure"], "measure")
    for w in redup_words():
        put(w, BANDS["redup"], "redup")
    for w in affixed_words():
        put(w, BANDS["affix"], "affix")
    for w in place_names():
        put(w, BANDS["place"], "place")
    for w in person_names():
        put(w, BANDS["name2"] if len(w) == 2 else BANDS["name3"], "name")
    # round-6 general expansion (ISSUE 15 satellite): curated real
    # words + single-char-affix derivation over real stems, so the
    # GENERAL (non-name/non-compositional-class) inventory clears 50k
    for band, text in sorted(R6_CURATED.items()):
        for w in text.split():
            put(w, band, "r6")
    for w in derived_words():
        put(w, BANDS["deriv"], "deriv")

    from collections import Counter
    stats = Counter(category.values())
    out = os.path.abspath(OUT)
    with open(out, "w", encoding="utf-8") as f:
        f.write("# Mandarin frequency dictionary for alink_tpu — GENERATED\n"
                "# by tools/gen_zh_dict.py (deterministic). Original\n"
                "# compilation: hand-authored core vocabulary + composed\n"
                "# real items (numerals, dates, full names, places,\n"
                "# measures). NOT derived from the reference's resources.\n")
        f.write("# category-stats: " + " ".join(
            f"{k}={v}" for k, v in sorted(stats.items())) + "\n")
        for w in sorted(entries, key=lambda w: (-entries[w], w)):
            f.write(f"{w} {entries[w]}\n")
    print(f"{len(entries)} entries -> {out}")
    print("category stats:", dict(sorted(stats.items())))


if __name__ == "__main__":
    main()
