"""OnlineDag — the whole online-learning loop as ONE supervised,
fault-tolerant program (ISSUE 15; ROADMAP item 5; the reference's
``FTRLExample.java`` DAG upgraded to serving-tier traffic).

One :class:`OnlineDag` wires the stages every prior PR hardened in
isolation into a single runtime with per-stage restart policy and
end-to-end SLO enforcement::

    ingest (resumable, replayable source)
      ├─> scoring/eval leg: rows served through PredictServer
      │     (deadlines + circuit breaker armed) -> windowed stream
      │     eval (AUC/logloss per window, durable journal) -> SLO +
      │     health/drift alerts
      └─> train leg: FtrlTrainStreamOp (checkpointed) -> model-snapshot
            stream -> supervised feeder -> hot swap into serving

Restart policies (typed, per stage — the DAG supervisor is the
in-process stand-in for the cluster manager that would restart a dead
task, which is WHY it may catch :class:`~alink_tpu.common.faults.
FaultInjected` that generic handlers must not):

* **trainer — restart-from-last-checkpoint.** A crashed drain rebuilds
  the trainer with ``resume=True``; the FTRL checkpoint machinery
  restores (z, n) bitwise and SKIPS the committed replay prefix
  pre-encode, so a micro-batch is never silently dropped or
  double-applied (PR 2's contract, now supervised).
* **feeders / serving — respawn-with-last-good-model.** The serving
  tier keeps answering from the last successfully swapped model while
  the train leg restarts (the PR 14 last-good guarantee); crashed
  serving loops quarantine their in-flight batch with a typed error
  and respawn (request quarantine — never silence).
* **ingest — resume-at-offset.** The scoring leg's source iterator
  rebuilds the replayable source and fast-skips the already-delivered
  prefix; a batch whose delivery crashed is REDELIVERED (at-least-once
  into the idempotent eval journal, exactly-once into the windows).

**Deterministic pacing** (default): the scoring leg scores micro-batch
``k+1`` only after the trainer committed batch ``k``, and the trainer
holds batch ``k+1`` until batch ``k+1`` was scored (the FTRL
``set_batch_hook`` gate). Every score is then produced by the model
from the last emission boundary at or before ``k`` — a pure function
of the stream — so eval windows (and their score digests) are
BITWISE-resumable across kills and restarts. ``pacing="throughput"``
frees both legs for steady-state QPS measurement.

Artifacts (all under ``artifacts_dir``): ``ckpt/`` (trainer
checkpoints), ``eval/windows.jsonl`` (the durable window journal —
each closed window with AUC/logloss and a sha256 digest of its raw
scores), ``serving/last_good.json`` (the last successfully swapped
model table, restored into serving at DAG restart).
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..common.adminz import acquire_admin, release_admin
from ..common.faults import FaultInjected, maybe_crash
from ..common.flags import flag_value
from ..common.metrics import get_registry, metrics_enabled
from ..common.mtable import MTable
from ..common.tracing import trace_instant
from ..common.types import TableSchema
from .slo import (SloBurnRate, SloContract, SloVerdict,
                  SwapStalenessTracker, e2e_dag_enabled, e2e_deadline_s)

__all__ = ["OnlineDag", "DagReport", "DagFailed", "RESTART_POLICIES",
           "e2e_max_restarts", "e2e_pacing"]

#: the typed per-stage restart policies (ISSUE 15)
RESTART_POLICIES = {
    "train": "restart-from-last-checkpoint",
    "feed": "respawn-with-last-good-model",
    "serve": "respawn-with-last-good-model",
    "ingest": "resume-at-offset",
}

#: the quality anchor the bench row must clear or explain (VERDICT #7)
AUC_ANCHOR = 0.75


def e2e_max_restarts() -> int:
    """``ALINK_TPU_E2E_MAX_RESTARTS``: per-stage restart budget."""
    return int(flag_value("ALINK_TPU_E2E_MAX_RESTARTS"))


def e2e_pacing() -> str:
    """``ALINK_TPU_E2E_PACING``: deterministic | throughput."""
    return str(flag_value("ALINK_TPU_E2E_PACING"))


class DagFailed(RuntimeError):
    """A stage exhausted its restart budget (or hit a non-restartable
    error); carries the stage name and the last cause."""

    def __init__(self, stage: str, cause: BaseException):
        super().__init__(f"online DAG stage {stage!r} failed "
                         f"({type(cause).__name__}: {cause})")
        self.stage = stage
        self.cause = cause


class _Pacer:
    """The deterministic-interleave gate between the scoring and train
    legs, plus the committed-batch watermark both modes use for restart
    recovery timing. All waits are condition-variable based with an
    abort channel so a dead stage can never hang its peer."""

    def __init__(self, deterministic: bool, timeout_s: float = 600.0):
        self.deterministic = deterministic
        self.timeout_s = timeout_s
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self.scored = 0          # scoring-leg watermark (batch seq)
        self.committed = 0       # trainer watermark (batches committed)
        self.train_done = False
        self._abort: Optional[DagFailed] = None
        self._pending_recovery: List[Tuple[int, float, dict]] = []

    # -- the trainer-side hook (FtrlTrainStreamOp.set_batch_hook) --------
    def hook(self, phase: str, batch: int, t: float) -> None:
        with self._cond:
            # BOTH pacing modes: a dead scoring leg must stop the
            # trainer too — in throughput mode nothing below blocks, so
            # without this check the drain would keep training (and
            # mutating the already-returned report + last-good
            # artifact) after run() gave up
            if self._abort is not None:
                raise self._abort
        if phase == "pre":
            # a resumed trainer's first pre-batch call implies every
            # earlier batch is committed (restored from the checkpoint)
            # — jump the watermark BEFORE blocking, or a scoring leg
            # replaying its own skip-prefix deadlocks against us
            with self._cond:
                if batch - 1 > self.committed:
                    self.committed = batch - 1
                    self._cond.notify_all()
            if self.deterministic:
                self._wait(lambda: self.scored >= batch,
                           f"scoring leg to reach batch {batch}")
            return
        with self._cond:
            if batch > self.committed:
                self.committed = batch
                now = time.perf_counter()
                for c0, t_crash, rec in list(self._pending_recovery):
                    if self.committed > c0:
                        rec["recovery_s"] = round(now - t_crash, 4)
                        self._pending_recovery.remove((c0, t_crash, rec))
            self._cond.notify_all()

    # -- the scoring-leg side --------------------------------------------
    def on_scored(self, seq: int) -> None:
        with self._cond:
            if seq > self.scored:
                self.scored = seq
            self._cond.notify_all()

    def wait_committed(self, seq: int) -> None:
        if not self.deterministic:
            # throughput mode never blocks, but a dead train stage must
            # still stop the scoring leg — a journal written past the
            # crash would not be a bitwise prefix of the golden run
            with self._cond:
                if self._abort is not None:
                    raise self._abort
            return
        self._wait(lambda: self.committed >= seq or self.train_done,
                   f"trainer to commit batch {seq}")

    # -- supervision ------------------------------------------------------
    def training_done(self) -> None:
        with self._cond:
            self.train_done = True
            self._cond.notify_all()

    def abort(self, stage: str, cause: BaseException) -> None:
        with self._cond:
            first = self._abort is None
            if first:
                self._abort = DagFailed(stage, cause)
            self.train_done = True
            self._cond.notify_all()
        if first:
            # the FIRST stage abort is the incident (later aborts are
            # the shutdown cascade it causes): capture a post-mortem
            # bundle while the rings still hold the failing stage's
            # evidence (ISSUE 18; debounced, off without
            # ALINK_TPU_POSTMORTEM_DIR)
            from ..common import postmortem
            postmortem.maybe_bundle(
                "stage_abort",
                f"online DAG stage {stage!r} aborted "
                f"({type(cause).__name__}: {cause})",
                extra={"stage": stage,
                       "cause": type(cause).__name__})

    @property
    def aborted(self) -> Optional[DagFailed]:
        return self._abort

    def note_recovery(self, rec: dict) -> None:
        """Fill ``rec["recovery_s"]`` when the NEXT batch beyond the
        crash-time watermark commits (crash -> productive again)."""
        with self._cond:
            self._pending_recovery.append(
                (self.committed, time.perf_counter(), rec))

    def _wait(self, pred: Callable[[], bool], what: str) -> None:
        deadline = time.monotonic() + self.timeout_s
        with self._cond:
            while True:
                # abort wins even when the predicate holds: train_done
                # is set on abort too (to wake waiters), and a scoring
                # leg that kept going past a dead trainer would journal
                # scores the golden run produces with a NEWER model
                if self._abort is not None:
                    raise self._abort
                if pred():
                    return
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"online DAG pacing wait timed out ({what}; "
                        f"{self.timeout_s}s)")
                self._cond.wait(min(remaining, 0.5))


# ---------------------------------------------------------------------------
# durable artifacts: model table persist + eval window journal
# ---------------------------------------------------------------------------

def _json_safe(v: Any) -> Any:
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, np.bool_):
        return bool(v)
    return v


def save_model_table(path: str, version: int, table: MTable) -> None:
    """Atomically persist a model table (the serving tier's last-good
    artifact): write-tmp-then-rename + dir fsync, the checkpoint
    store's durability discipline."""
    doc = {"format": "alink_tpu_last_good_v1", "version": int(version),
           "names": list(table.schema.names),
           "types": [str(t) for t in table.schema.types],
           "rows": [[_json_safe(v) for v in table.row(i)]
                    for i in range(table.num_rows)]}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def load_model_table(path: str) -> Optional[Tuple[int, MTable]]:
    """The persisted last-good model, or ``None`` when absent/corrupt
    (a torn artifact must not block a restart — the warm-start model
    still serves; the corruption is surfaced as a warning)."""
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
        table = MTable([tuple(r) for r in doc["rows"]],
                       TableSchema(doc["names"], doc["types"]))
        return int(doc["version"]), table
    except (ValueError, KeyError, TypeError) as e:
        import warnings
        warnings.warn(f"online DAG: last-good model artifact {path} is "
                      f"unreadable ({type(e).__name__}: {e}); serving "
                      f"restarts from the warm-start model",
                      RuntimeWarning)
        return None


def _journal_records(path: str) -> List[dict]:
    """Parse a JSONL journal tolerating a TORN FINAL line — the one
    tear the fsync-per-line append contract allows (a kill/power loss
    mid-write). The torn tail is truncated off so the append handle
    continues a valid journal, and a complete final record missing its
    newline gets one appended (the next record must not concatenate
    onto it). An unparsable NON-final line is real corruption, not a
    torn tail, and refuses loudly."""
    if not os.path.exists(path):
        return []
    with open(path, "rb") as f:
        data = f.read()
    recs: List[dict] = []
    offset = good_end = 0
    for line in data.splitlines(keepends=True):
        end = offset + len(line)
        s = line.strip()
        if s:
            try:
                recs.append(json.loads(s))
            except ValueError:
                if end < len(data):
                    raise ValueError(
                        f"corrupt journal line at byte {offset} of "
                        f"{path} (mid-file — not a torn tail; the "
                        f"artifact needs manual repair)")
                with open(path, "r+b") as tf:
                    tf.truncate(good_end)
                    tf.flush()
                    os.fsync(tf.fileno())
                return recs
        good_end = end
        offset = end
    if data and not data.endswith(b"\n"):
        with open(path, "ab") as af:
            af.write(b"\n")
            af.flush()
            os.fsync(af.fileno())
    return recs


def _window_auc(y: np.ndarray, p: np.ndarray) -> Optional[float]:
    """Rank-statistic AUC with tie-averaged ranks (the evaluation
    tier's formulation); ``None`` for a single-class window."""
    pos = y > 0.5
    n_pos, n_neg = int(pos.sum()), int((~pos).sum())
    if n_pos == 0 or n_neg == 0:
        return None
    order = np.argsort(p, kind="mergesort")
    ranks = np.empty(len(p), np.float64)
    sp = p[order]
    i = 0
    while i < len(sp):
        j = i
        while j + 1 < len(sp) and sp[j + 1] == sp[i]:
            j += 1
        ranks[order[i:j + 1]] = 0.5 * (i + j) + 1.0
        i = j + 1
    return float((ranks[pos].sum() - n_pos * (n_pos + 1) / 2.0)
                 / (n_pos * n_neg))


def _window_logloss(y: np.ndarray, p: np.ndarray) -> float:
    pc = np.clip(p, 1e-15, 1.0 - 1e-15)
    return float(-np.mean(y * np.log(pc) + (1.0 - y) * np.log(1.0 - pc)))


class _EvalWindowLog:
    """Windowed stream eval over a durable per-batch prediction log.

    Two artifacts side by side:

    * ``scores.jsonl`` — ONE line per scored micro-batch: seq, event
      time, and the raw (label, score) float64 values (json floats
      round-trip float64 exactly). This is the classic serving-tier
      prediction log, and it is what makes eval windows
      bitwise-RESUMABLE: the trainer's checkpoint cadence is batch-
      count-based while windows close on event time, so a per-window
      journal could lag the checkpoint and lose the scores needed to
      re-derive a partial window. The per-batch log always covers the
      scoring watermark, which deterministic pacing keeps AHEAD of the
      trainer's committed watermark.
    * ``windows.jsonl`` — one line per CLOSED event-time window
      (``window_end = (floor(t/interval)+1)*interval``, empty windows
      never fire — the stream-eval operators' contract) carrying
      AUC/logloss, the covered batch range, and a sha256 digest over
      the window's raw score bytes: the bitwise-continuation evidence
      the kill-and-resume tests pin.

    On restart the scores log is re-folded through the same window
    machinery (pure function), closed windows are re-derived in memory
    (NOT re-appended — the windows file continues where it left off),
    and scoring resumes at the first unlogged batch."""

    def __init__(self, scores_path: str, windows_path: str,
                 window_s: float, dag: str = "online"):
        self.scores_path = scores_path
        self.windows_path = windows_path
        self.window_s = float(window_s)
        self.dag = dag
        self.windows: List[dict] = []
        self.resume_seq = 0
        self._y: List[float] = []
        self._p: List[float] = []
        self._first_seq: Optional[int] = None
        self._last_seq = 0
        self._window_end: Optional[float] = None
        os.makedirs(os.path.dirname(scores_path), exist_ok=True)
        self._windows_on_disk = len(_journal_records(windows_path))
        for rec in _journal_records(scores_path):
            self._fold(int(rec["seq"]), float(rec["t"]),
                       np.asarray(rec["y"], np.float64),
                       np.asarray(rec["p"], np.float64),
                       replay=True)
        self.resume_seq = self._last_seq
        self._sf = open(scores_path, "a")
        self._wf = open(windows_path, "a")
        if len(self.windows) > self._windows_on_disk:
            # a crash landed between a batch's scores-log fsync and its
            # window close: the re-derivation regenerates the missing
            # window line(s) — the scores log is the source of truth
            for w in self.windows[self._windows_on_disk:]:
                self._wf.write(json.dumps(w, sort_keys=True) + "\n")
            self._wf.flush()
            os.fsync(self._wf.fileno())
            self._windows_on_disk = len(self.windows)

    def add_batch(self, seq: int, t: float, y: np.ndarray,
                  p: np.ndarray) -> List[dict]:
        """Durably log one scored batch, then fold it; returns any
        windows the fold closed (already journaled)."""
        self._sf.write(json.dumps(
            {"seq": int(seq), "t": float(t),
             "y": [float(v) for v in y],
             "p": [float(v) for v in p]}) + "\n")
        self._sf.flush()
        os.fsync(self._sf.fileno())
        return self._fold(seq, t, y, p)

    def _fold(self, seq: int, t: float, y: np.ndarray, p: np.ndarray,
              replay: bool = False) -> List[dict]:
        closed: List[dict] = []
        if self._window_end is None:
            self._window_end = (math.floor(t / self.window_s) + 1) \
                * self.window_s
        while t >= self._window_end:
            w = self._close(self._window_end, replay=replay)
            if w is not None:
                closed.append(w)
            self._window_end += self.window_s
        if self._first_seq is None:
            self._first_seq = seq
        self._y.extend(float(v) for v in y)
        self._p.extend(float(v) for v in p)
        self._last_seq = seq
        return closed

    def flush_final(self) -> Optional[dict]:
        """End-of-stream: close the trailing partial window (the eval
        stream op's final emission)."""
        if not self._y:
            return None
        return self._close(self._window_end
                           if self._window_end is not None
                           else self.window_s)

    def _close(self, end_t: float, replay: bool = False
               ) -> Optional[dict]:
        if not self._y:
            return None
        y = np.asarray(self._y, np.float64)
        p = np.asarray(self._p, np.float64)
        digest = hashlib.sha256(y.tobytes() + p.tobytes()).hexdigest()
        w = {"w": len(self.windows) + 1, "end_t": float(end_t),
             "first_seq": int(self._first_seq or 0),
             "last_seq": int(self._last_seq), "n": int(len(y)),
             "auc": _window_auc(y, p),
             "logloss": round(_window_logloss(y, p), 12),
             "digest": digest}
        self.windows.append(w)
        self._y, self._p, self._first_seq = [], [], None
        if replay:
            return w          # re-derived from the scores log: already
                              # on disk (or lost with its partial tail
                              # — re-derivation regenerates it below)
        if len(self.windows) > self._windows_on_disk:
            self._wf.write(json.dumps(w, sort_keys=True) + "\n")
            self._wf.flush()
            os.fsync(self._wf.fileno())
            self._windows_on_disk = len(self.windows)
        trace_instant("e2e.window", cat="e2e",
                      args={"w": w["w"], "n": w["n"], "auc": w["auc"]})
        if metrics_enabled():
            reg = get_registry()
            reg.inc("alink_e2e_windows_total", 1, {"dag": self.dag})
            if w["auc"] is not None:
                reg.set_gauge("alink_e2e_window_auc", w["auc"],
                              {"dag": self.dag})
        return w

    def close(self) -> None:
        self._sf.close()
        self._wf.close()


class _ResumableIngest:
    """The ingest stage: iterate a REPLAYABLE source with the
    resume-at-offset restart policy — on a crashed delivery the source
    rebuilds and the already-delivered prefix is fast-skipped (no
    re-scoring), the crashed batch is redelivered. The fault site
    ``ingest.batch`` is auto-indexed, so bounded kill windows clear
    across redeliveries."""

    def __init__(self, source_fn: Callable[[], Any], max_restarts: int,
                 report: "DagReport",
                 on_stage_event: Optional[Callable] = None):
        self.source_fn = source_fn
        self.max_restarts = max_restarts
        self.report = report
        self.on_stage_event = on_stage_event

    def batches(self):
        delivered = 0
        attempts = 0
        pending_rec: Optional[Tuple[float, dict]] = None
        while True:
            src = self.source_fn()
            try:
                seq = 0
                for t, mt in src.timed_batches():
                    if mt.num_rows == 0:
                        continue        # the trainer's raw_batches skips
                    seq += 1            # these too — keep seq aligned
                    if seq <= delivered:
                        continue        # resume-at-offset fast skip
                    maybe_crash("ingest.batch")
                    delivered = seq
                    if pending_rec is not None:
                        t_crash, rec = pending_rec
                        rec["recovery_s"] = round(
                            time.perf_counter() - t_crash, 4)
                        pending_rec = None
                    yield (seq, t, mt)
                return
            except GeneratorExit:
                raise
            except Exception as e:       # incl. FaultInjected: the
                attempts += 1            # supervisor IS the restart
                rec = {"stage": "ingest",
                       "policy": RESTART_POLICIES["ingest"],
                       "error": type(e).__name__,
                       "site": getattr(e, "site", None),
                       "offset": delivered, "recovery_s": None}
                self.report.restarts.append(rec)
                trace_instant("e2e.restart", cat="e2e", args=dict(rec))
                if metrics_enabled():
                    get_registry().inc("alink_e2e_restarts_total", 1,
                                       {"stage": "ingest"})
                if self.on_stage_event is not None:
                    try:
                        self.on_stage_event("ingest", e)
                    except BaseException:
                        pass   # a raising observer must not turn a
                        # supervised restart into an unhandled crash
                if attempts > self.max_restarts:
                    raise DagFailed("ingest", e)
                pending_rec = (time.perf_counter(), rec)


class _EmissionTap:
    """Wraps the trainer's snapshot stream so the DAG can timestamp
    each emission (the swap-staleness clock starts when the snapshot
    leaves the trainer, not when the feeder gets around to it)."""

    def __init__(self, op, tracker: SwapStalenessTracker):
        self.op = op
        self.tracker = tracker

    def timed_batches(self):
        for t, mt in self.op.timed_batches():
            self.tracker.mark_emitted()
            yield (t, mt)


@dataclass
class DagReport:
    """The whole-run verdict: eval windows, SLO verdicts (typed),
    restart records per stage, and the serving-tier counters."""
    windows: List[dict] = field(default_factory=list)
    final_window_auc: Optional[float] = None
    auc_note: Optional[str] = None
    slo: List[SloVerdict] = field(default_factory=list)
    breaches: List[SloVerdict] = field(default_factory=list)
    restarts: List[dict] = field(default_factory=list)
    swaps: int = 0
    swap_staleness_max_s: Optional[float] = None
    swap_staleness_mean_s: Optional[float] = None
    scored_rows: int = 0
    batches_scored: int = 0
    eval_retries: int = 0
    shed_requests: int = 0
    typed_rejections: int = 0
    silent_drops: int = 0
    feeder_skipped: int = 0
    feeder_retried: int = 0
    server_stats: Dict[str, Any] = field(default_factory=dict)
    wall_s: float = 0.0
    qps: float = 0.0
    p99_s: Optional[float] = None
    failed: Optional[str] = None

    def restart_count(self, stage: Optional[str] = None) -> int:
        return sum(1 for r in self.restarts
                   if stage is None or r["stage"] == stage)

    def slo_ok(self) -> bool:
        return all(v.ok for v in self.slo)

    def to_dict(self) -> dict:
        d = dict(self.__dict__)
        d["slo"] = [v.to_dict() for v in self.slo]
        d["breaches"] = [v.to_dict() for v in self.breaches]
        return d


class OnlineDag:
    """The supervised online-learning DAG (see module docstring).

    ``source_fn`` must build a fresh, REPLAYABLE stream of identical
    timed micro-batches each call (the reference's replayed-source
    resume assumption, docs/checkpointing.md) carrying the feature
    column(s)/vector AND the label column; ``warm_model`` is the
    batch-trained initial linear model every FTRL run warm-starts from.
    """

    def __init__(self, source_fn: Callable[[], Any], warm_model,
                 artifacts_dir: str, label_col: str,
                 vector_col: Optional[str] = None,
                 feature_cols: Optional[List[str]] = None,
                 alpha: float = 0.1, beta: float = 1.0,
                 l1: float = 0.0, l2: float = 0.0,
                 update_mode: str = "batch", staleness: int = 32,
                 time_interval: float = 1.0,
                 checkpoint_every: int = 4, checkpoint_keep: int = 3,
                 window_s: Optional[float] = None,
                 pacing: Optional[str] = None,
                 slo: Optional[SloContract] = None,
                 health=None,
                 deadline_s: Optional[float] = None,
                 max_restarts: Optional[int] = None,
                 buckets=None, min_fill=None,
                 request_timeout_s: float = 60.0,
                 score_retry_limit: int = 120,
                 name: str = "online",
                 on_stage_event: Optional[Callable] = None):
        if vector_col is None and not feature_cols:
            raise ValueError("OnlineDag needs vector_col or feature_cols")
        self.source_fn = source_fn
        self.warm_model = warm_model
        self.artifacts_dir = artifacts_dir
        self.label_col = label_col
        self.vector_col = vector_col
        self.feature_cols = list(feature_cols) if feature_cols else None
        self.alpha, self.beta, self.l1, self.l2 = alpha, beta, l1, l2
        self.update_mode = update_mode
        self.staleness = staleness
        self.time_interval = float(time_interval)
        self.checkpoint_every = int(checkpoint_every)
        self.checkpoint_keep = int(checkpoint_keep)
        self.window_s = float(window_s) if window_s else self.time_interval
        self.pacing = pacing or e2e_pacing()
        armed_defaults = e2e_dag_enabled()
        self.slo = slo if slo is not None else (
            SloContract.from_flags(name) if armed_defaults
            else SloContract(name=name))
        self.health = health
        self.deadline_s = deadline_s if deadline_s is not None else (
            e2e_deadline_s() if armed_defaults else None)
        self.max_restarts = (e2e_max_restarts() if max_restarts is None
                             else int(max_restarts))
        self.buckets = buckets
        self.min_fill = min_fill
        self.request_timeout_s = float(request_timeout_s)
        self.score_retry_limit = int(score_retry_limit)
        self.name = name
        self.on_stage_event = on_stage_event

        self.ckpt_dir = os.path.join(artifacts_dir, "ckpt")
        self.eval_path = os.path.join(artifacts_dir, "eval",
                                      "windows.jsonl")
        self.scores_path = os.path.join(artifacts_dir, "eval",
                                        "scores.jsonl")
        self.last_good_path = os.path.join(artifacts_dir, "serving",
                                           "last_good.json")
        os.makedirs(self.ckpt_dir, exist_ok=True)
        os.makedirs(os.path.dirname(self.last_good_path), exist_ok=True)
        # a stage-abort post-mortem bundle must name the restart point
        # (ISSUE 18): point the bundle context at this DAG's durable
        # artifacts (checkpoints + last-good serving model)
        from ..common import postmortem
        postmortem.set_context("checkpoint", self.ckpt_dir)
        postmortem.set_context("last_good_model", self.last_good_path)

        # resolved at run()
        self.server = None
        self.predictor = None
        self.report = DagReport()
        self._versions: List[Tuple[int, MTable]] = []   # active-model set
        self._pacer: Optional[_Pacer] = None
        self._tracker: Optional[SwapStalenessTracker] = None
        self._live_feeder = None
        self._warm_table = None
        self._pos_label: Optional[str] = None
        # live operations plane (ISSUE 16): the DAG registers its
        # readiness + status on the shared admin endpoint for run()'s
        # duration; _swap_log is the /statusz "last N model swaps" ring
        self._admin = None
        self._burn: Optional[SloBurnRate] = None
        self._swap_log: List[dict] = []

    # -- stage builders ----------------------------------------------------
    def _build_serving(self):
        from ..common.params import Params
        from ..operator.common.linear.mapper import LinearModelMapper
        from ..serving.predictor import CompiledPredictor
        from ..serving.server import PredictServer
        warm_table = self.warm_model.get_output_table()
        self._warm_table = warm_table
        probe = self.source_fn()
        src_schema = probe.get_schema()
        feat_names = [self.vector_col] if self.vector_col \
            else self.feature_cols
        idx = [src_schema.names.index(c) for c in feat_names]
        data_schema = TableSchema([src_schema.names[i] for i in idx],
                                  [src_schema.types[i] for i in idx])
        pp = {"prediction_col": "pred", "prediction_detail_col": "detail"}
        if self.vector_col:
            pp["vector_col"] = self.vector_col
        else:
            pp["feature_cols"] = self.feature_cols
        mapper = LinearModelMapper(warm_table.schema, data_schema,
                                   Params(pp))
        # restore serving from the persisted last-good model when one
        # exists (respawn-with-last-good-model across DAG restarts);
        # the warm-start model otherwise
        restored = load_model_table(self.last_good_path)
        serve_table = restored[1] if restored is not None else warm_table
        mapper.load_model(serve_table)
        self.predictor = CompiledPredictor(mapper, buckets=self.buckets,
                                           name=self.name)
        # compile-plane ledger (ISSUE 19): the serving stage's program
        # identity, so a restart's cold-start report names which stage
        # re-paid compiles
        from ..common import compileledger
        from ..common.plan import dag_stage_plan
        compileledger.subsystem_start("dag")
        compileledger.register_stage(
            "dag", "serving",
            dag_stage_plan("serving", {"name": self.name,
                                       "buckets": self.predictor.buckets,
                                       "min_fill": self.min_fill}))
        self.server = PredictServer(self.predictor, name=self.name,
                                    min_fill=self.min_fill)
        self._versions.append((self.predictor.model_version, serve_table))
        self._feat_idx = idx
        self._label_idx = src_schema.names.index(self.label_col)

    def _build_trainer(self):
        from ..operator.stream.onlinelearning.ftrl import FtrlTrainStreamOp
        kw = dict(label_col=self.label_col, alpha=self.alpha,
                  beta=self.beta, l1=self.l1, l2=self.l2,
                  update_mode=self.update_mode, staleness=self.staleness,
                  time_interval=self.time_interval,
                  checkpoint_dir=self.ckpt_dir,
                  checkpoint_every_batches=self.checkpoint_every,
                  checkpoint_keep=self.checkpoint_keep, resume=True)
        if self.vector_col:
            kw["vector_col"] = self.vector_col
        else:
            kw["feature_cols"] = self.feature_cols
        if self.health is not None:
            kw["health"] = self.health
        from ..common import compileledger
        from ..common.plan import dag_stage_plan
        compileledger.register_stage(
            "dag", "trainer",
            dag_stage_plan("trainer", {"update_mode": self.update_mode,
                                       "staleness": self.staleness,
                                       "alpha": self.alpha,
                                       "beta": self.beta,
                                       "l1": self.l1, "l2": self.l2}))
        op = FtrlTrainStreamOp(self.warm_model, **kw).link_from(
            self.source_fn())
        op.set_batch_hook(self._pacer.hook)
        return op

    def _on_swap(self, version: int, model_table: MTable) -> None:
        staleness_s = self._tracker.mark_installed(version)
        self._versions.append((version, model_table))
        self.report.swaps += 1
        self._swap_log.append({"version": int(version),
                               "unix": time.time(),
                               "staleness_s": staleness_s})
        del self._swap_log[:-32]
        save_model_table(self.last_good_path, version, model_table)

    def _build_feeder(self, op):
        from ..serving.server import ModelStreamFeeder
        return ModelStreamFeeder(self.server,
                                 _EmissionTap(op, self._tracker),
                                 on_swap=self._on_swap)

    # -- the supervised train+feed stage ----------------------------------
    def _train_stage(self) -> None:
        attempts = 0
        while True:
            feeder = None
            try:
                op = self._build_trainer()
                feeder = self._build_feeder(op)
                self._live_feeder = feeder
                feeder.run()
                self.report.feeder_skipped += feeder.skipped
                self.report.feeder_retried += feeder.retried
                self._pacer.training_done()
                return
            except BaseException as e:
                if feeder is not None:
                    self.report.feeder_skipped += feeder.skipped
                    self.report.feeder_retried += feeder.retried
                if isinstance(e, DagFailed):
                    # the OTHER side already failed (driver abort
                    # surfacing through the pacing hook) — not a
                    # trainer crash, nothing to restart
                    self._pacer.abort(e.stage, e.cause)
                    return
                site = getattr(e, "site", None)
                policy = (RESTART_POLICIES["feed"]
                          if site in ("feeder.snapshot", "serve.swap")
                          else RESTART_POLICIES["train"])
                rec = {"stage": "train", "policy": policy,
                       "error": type(e).__name__, "site": site,
                       "at_batch": self._pacer.committed,
                       "recovery_s": None}
                self.report.restarts.append(rec)
                trace_instant("e2e.restart", cat="e2e", args=dict(rec))
                if metrics_enabled():
                    get_registry().inc("alink_e2e_restarts_total", 1,
                                       {"stage": "train"})
                if self.on_stage_event is not None:
                    try:
                        self.on_stage_event("train", e)
                    except BaseException:
                        pass
                attempts += 1
                if not isinstance(e, Exception):
                    self._pacer.abort("train", e)   # interrupt: abort,
                    raise                           # never restart
                if attempts > self.max_restarts:
                    self._pacer.abort("train", e)
                    return
                self._pacer.note_recovery(rec)

    # -- the scoring/eval leg ---------------------------------------------
    def _request_rows(self, mt: MTable) -> List[Tuple]:
        cols = [mt.col(mt.schema.names[i]) for i in self._feat_idx]
        return [tuple(c[i] for c in cols) for i in range(mt.num_rows)]

    def _score_rows(self, rows: List[Tuple]) -> List[Tuple]:
        """Serve every row, retrying typed rejections (eval traffic is
        the ground truth — a shed/failed row is retried, never dropped;
        storms clear deterministically so the retry loop terminates).
        A future that resolves to NEITHER a result nor a typed error is
        a silent drop and fails the DAG loudly."""
        out: List[Optional[Tuple]] = [None] * len(rows)
        pending = list(range(len(rows)))
        attempt = 0
        while pending:
            futs = [(i, self.server.submit(rows[i],
                                           deadline_s=self.deadline_s))
                    for i in pending]
            failed: List[int] = []
            for i, f in futs:
                try:
                    out[i] = tuple(f.result(self.request_timeout_s))
                except TimeoutError:
                    self.report.silent_drops += 1
                    raise DagFailed("serve", RuntimeError(
                        "SILENT drop: a scoring future resolved to "
                        "neither a result nor a typed rejection"))
                except Exception:
                    self.report.typed_rejections += 1
                    failed.append(i)
            if failed:
                attempt += 1
                self.report.eval_retries += len(failed)
                if attempt > self.score_retry_limit:
                    raise DagFailed("serve", RuntimeError(
                        f"{len(failed)} eval rows still rejected after "
                        f"{attempt} retry rounds"))
                time.sleep(min(0.1, 0.005 * attempt))
            pending = failed
        return out  # type: ignore[return-value]

    # -- run ---------------------------------------------------------------
    def run(self, max_batches: Optional[int] = None) -> DagReport:
        """Execute the DAG to end of stream; returns the
        :class:`DagReport` (``report.failed`` set — and the report
        still rendered — when a stage exhausted its restart budget)."""
        t_run0 = time.perf_counter()
        self.report = DagReport()
        self._versions = []
        self._swap_log = []
        self._pacer = _Pacer(self.pacing == "deterministic")
        self._tracker = SwapStalenessTracker(self.slo, self.name)
        self._burn = SloBurnRate(self.slo, name=self.name)
        self._build_serving()
        # live operations plane (ISSUE 16): while armed, this run is
        # inspectable — /healthz|/readyz fold in the DAG's supervisor
        # state and the burn monitor (a critical fast-window burn reads
        # unready), /statusz shows swaps/clauses/restarts live
        self._admin = acquire_admin(self.name)
        if self._admin is not None:
            self._admin.add_source(f"dag:{self.name}", self._readiness)
            self._admin.add_source(f"slo:{self.name}",
                                   self._burn.readiness)
            self._admin.add_status(f"dag:{self.name}", self._statusz_doc)
        # positive label: the trainer's convention (label_values[0])
        self._pos_label = self._positive_label()
        eval_log = _EvalWindowLog(self.scores_path, self.eval_path,
                                  self.window_s, self.name)
        ingest = _ResumableIngest(self.source_fn, self.max_restarts,
                                  self.report, self.on_stage_event)
        det_idx: Optional[int] = None
        train_th = threading.Thread(target=self._train_stage,
                                    daemon=True,
                                    name=f"alink-e2e-{self.name}-train")
        train_th.start()
        t_score = 0.0
        try:
            for seq, t, mt in ingest.batches():
                if max_batches is not None and seq > max_batches:
                    break
                if seq <= eval_log.resume_seq:
                    # journaled pre-crash: replay-prefix skip on the
                    # EVAL side (the train side has its own)
                    self._pacer.on_scored(seq)
                    self._pacer.wait_committed(seq)
                    continue
                t0 = time.perf_counter()
                rows = self._request_rows(mt)
                if det_idx is None:
                    det_idx = list(
                        self.predictor.output_schema.names).index("detail")
                resp = self._score_rows(rows)
                t_score += time.perf_counter() - t0
                pos = self._pos_label
                p = np.asarray(
                    [float(json.loads(r[det_idx]).get(pos, 0.0))
                     for r in resp], np.float64)
                labels = mt.col(self.label_col)
                y = np.asarray([1.0 if str(v) == pos else 0.0
                                for v in labels], np.float64)
                self.report.scored_rows += len(rows)
                self.report.batches_scored += 1
                if metrics_enabled():
                    get_registry().inc("alink_e2e_scored_rows_total",
                                       len(rows), {"dag": self.name})
                for w in eval_log.add_batch(seq, t, y, p):
                    self._on_window_closed(w)
                self._pacer.on_scored(seq)
                self._pacer.wait_committed(seq)
            # stream ended: let the trainer finish its drain
            self._pacer.on_scored(10 ** 12)
            train_th.join(timeout=self._pacer.timeout_s)
            w = eval_log.flush_final()
            if w is not None:
                self._on_window_closed(w)
        except DagFailed as e:
            self.report.failed = str(e)
            self._pacer.abort(e.stage, e.cause)
        except BaseException as e:
            # any OTHER scoring-leg failure (a health watchdog abort
            # propagating out of _on_window_closed, a bug) must still
            # stop the trainer before the finally unblocks its gate —
            # an un-aborted train thread would keep training and
            # hot-swapping into the just-closed server after this
            # raises
            self._pacer.abort("serve", e)
            raise
        finally:
            self._pacer.on_scored(10 ** 12)   # never strand the hook
            train_th.join(timeout=10.0)
            stats = self.server.stats() if self.server else {}
            self.server.close()
            eval_log.close()
            if self._admin is not None:
                self._admin.remove_source(f"dag:{self.name}")
                self._admin.remove_source(f"slo:{self.name}")
                self._admin.remove_status(f"dag:{self.name}")
                self._admin = None
                release_admin()
        if self._pacer.aborted is not None and self.report.failed is None:
            self.report.failed = str(self._pacer.aborted)
        # -- the report --------------------------------------------------
        rep = self.report
        rep.windows = eval_log.windows
        aucs = [w["auc"] for w in rep.windows if w["auc"] is not None]
        rep.final_window_auc = aucs[-1] if aucs else None
        rep.auc_note = self._auc_note(rep)
        rep.swap_staleness_max_s = self._tracker.max_s
        rep.swap_staleness_mean_s = self._tracker.mean_s
        rep.server_stats = stats
        rep.shed_requests = int(stats.get("shed", 0))
        rep.p99_s = stats.get("p99_s")
        rep.breaches = list(self.slo.breaches)
        rep.slo = self.slo.final(rep.p99_s, rep.swap_staleness_max_s,
                                 rep.final_window_auc)
        rep.wall_s = time.perf_counter() - t_run0
        rep.qps = (rep.scored_rows / t_score) if t_score > 0 else 0.0
        return rep

    # -- helpers -----------------------------------------------------------
    def _positive_label(self) -> str:
        from ..operator.common.linear.base import LinearModelDataConverter
        data = LinearModelDataConverter.load_table(self._warm_table)
        return str(data.label_values[0])

    # -- admin-plane sources (ISSUE 16) ------------------------------------
    def _readiness(self) -> dict:
        """ReadinessSource: the DAG is ready while no stage aborted;
        stage restart counts and feeder liveness ride as detail."""
        pacer = self._pacer
        aborted = pacer.aborted if pacer is not None else None
        restarts: Dict[str, int] = {}
        for rec in self.report.restarts:
            stage = rec.get("stage", "?")
            restarts[stage] = restarts.get(stage, 0) + 1
        doc = {"ready": aborted is None, "healthy": aborted is None,
               "stage_restarts": restarts,
               "committed_batches": (pacer.committed
                                     if pacer is not None else 0),
               "swaps": self.report.swaps}
        feeder = self._live_feeder
        if feeder is not None:
            doc["feeder"] = {
                "versions": getattr(feeder, "versions", None),
                "skipped": getattr(feeder, "skipped", 0),
                "retried": getattr(feeder, "retried", 0),
            }
        if aborted is not None:
            doc["aborted"] = str(aborted)
        return doc

    def _statusz_doc(self) -> dict:
        """/statusz section: swap history, staleness, live SLO clause +
        burn states, program-cache sizes, restart log."""
        doc: Dict[str, Any] = {
            "swaps": list(self._swap_log),
            "staleness": {
                "max_s": self._tracker.max_s if self._tracker else None,
                "mean_s": (self._tracker.mean_s
                           if self._tracker else None),
            },
            "slo_clauses": self.slo.clause_states(),
            "restarts": [dict(r) for r in self.report.restarts],
        }
        if self._burn is not None:
            doc["burn"] = self._burn.state()
        if self.predictor is not None:
            doc["program_cache"] = self.predictor.cache_stats()
        return doc

    def _on_window_closed(self, w: dict) -> None:
        stats = self.server.stats()
        self.slo.observe_p99(stats.get("p99_s"), w["w"])
        self.slo.observe_auc(w["auc"], w["w"])
        if self.health is not None:
            # drift/health alerting over the eval trajectory (the
            # monitor's own rules decide; a raise_on watchdog abort
            # propagates out of the scoring leg)
            if w["auc"] is not None:
                self.health.record("e2e.window_auc", w["w"], w["auc"])
            self.health.record("e2e.window_logloss", w["w"],
                               w["logloss"])
            self.health.evaluate()

    def _auc_note(self, rep: DagReport) -> Optional[str]:
        """The VERDICT #7 quality anchor: a final-window AUC below the
        0.75 anchor must carry a self-explaining convergence note
        (window trajectory + why), never a bare chance-level number."""
        floor = self.slo.final_window_auc or AUC_ANCHOR
        auc = rep.final_window_auc
        if auc is not None and auc >= floor:
            return None
        traj = [round(w["auc"], 4) for w in rep.windows
                if w["auc"] is not None]
        if not traj:
            return ("no two-class eval window closed — the drain is "
                    "shorter than one eval window or the label stream "
                    "is single-class; lengthen the stream or shrink "
                    "window_s")
        rising = len(traj) >= 2 and traj[-1] > traj[0] + 0.01
        why = ("AUC still rising across windows: the drain ended before "
               "convergence — lengthen the stream, warm-start on more "
               "rows, or raise time_interval so more batches fold into "
               "each emitted model"
               if rising else
               "AUC flat near chance: the model is not learning this "
               "stream — check feature hashing width (vector_size), "
               "label parsing (positive label "
               f"{self._pos_label!r}), and the warm start")
        return (f"final-window AUC {auc if auc is not None else 'n/a'} "
                f"is below the {floor} anchor; window trajectory "
                f"{traj}; {why}")
