"""ALS matrix factorization — TPU-native.

Re-design of common/recommendation/AlsTrain.java (587 LoC; SURVEY §2.3
"block/graph parallelism"): the reference groups ratings into user/item
blocks, exchanges factor request/response messages over Flink coGroups
(AlsTrain.java:266-335), and solves per-block normal equations with a
Cholesky (NormalEquation, :493) inside a Flink loop of
numIters*numMiniBatches*2 supersteps.

TPU-first shape: factors live as device arrays sharded over the data axis;
the request/response gather becomes ONE ``lax.all_gather`` of the opposing
factor block per half-step (the "factor all-gather" north star), and all
per-row normal equations are solved with ``jnp.linalg.solve`` batched over
rows — MXU-batched Cholesky solves instead of per-block Java loops.

Accumulating the per-row (A, b) sums is the hot spot: a scatter-add of
nnz x rank^2 outer products serializes on TPU (~120 ms per side at
MovieLens-1M scale). Instead each worker's rating rows are pre-sorted by
the side's id (host-side, once — the ids never change), so every id owns a
CONTIGUOUS run and its sum is a difference of two prefix sums. The prefix
is two-level: f32 cumsums WITHIN 512-row blocks (error bounded by the
block length, ~512*eps, independent of the global magnitude) plus an f64
cumsum over only the ~nnz/512 block sums — a single global f32 prefix
would lose ~nnz*eps of every short run, and a full f64 cumsum is slow
(f64 is emulated on TPU; measured slower than the scatter it replaces).
Two tiny per-id gathers then replace the million-row scatter.

Ratings rows carry weight-0 padding. Implicit feedback (implicitprefs)
follows the reference's confidence weighting c = 1 + alpha*|r|.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ....common.mlenv import MLEnvironment, MLEnvironmentFactory
from ....engine import IterativeComQueue


def batched_nnls(A, b, x0=None, num_iter: int = 80):
    """Batched nonnegative least squares: min_x>=0  1/2 x^T A x - b^T x.

    The reference's NNLSSolver (Scala, projected-gradient NNLS used by ALS
    nonnegative mode) becomes accelerated projected gradient (FISTA) with a
    per-row Lipschitz bound L = trace(A) (valid since A is PSD), batched
    over the leading axis and fully traceable — a fixed-trip-count
    ``lax.fori_loop`` instead of the reference's per-block CPU iterations.

    ``A``: (n, r, r) PSD normal matrices, ``b``: (n, r). ``x0`` optional
    warm start (defaults to the clipped unconstrained solution's role —
    zeros if omitted).
    """
    L = jnp.maximum(jnp.trace(A, axis1=-2, axis2=-1), 1e-12)[:, None]
    x = jnp.zeros_like(b) if x0 is None else x0
    state = (x, x, jnp.asarray(1.0, b.dtype))

    def body(_, st):
        x, yv, t = st
        grad = jnp.einsum("nij,nj->ni", A, yv) - b
        x_new = jnp.maximum(yv - grad / L, 0.0)
        t_new = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
        y_new = x_new + ((t - 1.0) / t_new) * (x_new - x)
        return (x_new, y_new, t_new)

    x, _, _ = jax.lax.fori_loop(0, num_iter, body, state)
    return x


@dataclass
class AlsTrainParams:
    rank: int = 10
    num_iter: int = 10
    lambda_reg: float = 0.1
    implicit_prefs: bool = False
    alpha: float = 40.0
    nonnegative: bool = False
    seed: int = 0


def _sorted_side(block: np.ndarray, col: int):
    """Sort one worker's rating rows by the side's id column and emit the
    per-id run boundaries. Returns (sorted_block, (ids, starts, ends))."""
    order = np.argsort(block[:, col], kind="stable")
    sb = block[order]
    ids, starts, counts = np.unique(sb[:, col].astype(np.int64),
                                    return_index=True, return_counts=True)
    return sb, np.stack([ids, starts, starts + counts], 1).astype(np.int32)


def als_train(users: np.ndarray, items: np.ndarray, ratings: np.ndarray,
              p: AlsTrainParams, env: Optional[MLEnvironment] = None,
              num_users: Optional[int] = None, num_items: Optional[int] = None
              ) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (user_factors (U, rank), item_factors (I, rank))."""
    env = env or MLEnvironmentFactory.get_default()
    users = np.asarray(users, np.int32)
    items = np.asarray(items, np.int32)
    ratings = np.asarray(ratings, np.float32)
    U = int(num_users if num_users is not None else users.max() + 1)
    I = int(num_items if num_items is not None else items.max() + 1)
    rank = p.rank
    rng = np.random.RandomState(p.seed)
    uf0 = (rng.rand(U, rank).astype(np.float32) / np.sqrt(rank))
    if0 = (rng.rand(I, rank).astype(np.float32) / np.sqrt(rank))
    nw = env.num_workers
    # ratings partitioned by row over workers; factor matrices sharded by
    # padding U/I to a multiple of the worker count
    Upad = -(-U // nw) * nw
    Ipad = -(-I // nw) * nw
    uf0 = np.concatenate([uf0, np.zeros((Upad - U, rank), np.float32)])
    if0 = np.concatenate([if0, np.zeros((Ipad - I, rank), np.float32)])
    nnz = len(ratings)
    L = -(-max(nnz, 1) // nw)
    data = np.zeros((nw * L, 4), np.float32)      # weight-0 padding rows
    data[:nnz] = np.stack([users.astype(np.float32),
                           items.astype(np.float32),
                           ratings, np.ones(nnz, np.float32)], axis=1)
    # per-worker side-sorted copies + run boundaries (the ids are static,
    # so this host pass happens once per training, not per iteration)
    blkU, blkI, planU, planI = [], [], [], []
    for wkr in range(nw):
        chunk = data[wkr * L:(wkr + 1) * L]
        sbU, plU = _sorted_side(chunk, 0)
        sbI, plI = _sorted_side(chunk, 1)
        blkU.append(sbU)
        blkI.append(sbI)
        planU.append(plU)
        planI.append(plI)
    Nu = max(pl.shape[0] for pl in planU)
    Ni = max(pl.shape[0] for pl in planI)
    # zero-length (id=0, start=end=0) slots pad to a uniform worker shape
    planU = np.stack([np.concatenate(
        [pl, np.zeros((Nu - pl.shape[0], 3), np.int32)]) for pl in planU])
    planI = np.stack([np.concatenate(
        [pl, np.zeros((Ni - pl.shape[0], 3), np.int32)]) for pl in planI])
    lam = p.lambda_reg
    eye = np.eye(rank, dtype=np.float32)

    def solve_side(block, plan, other_col, other_factors, n_rows):
        """Per-id normal equations from this worker's rows, which are
        pre-sorted by the side's id: contribution sums are prefix-sum
        differences over the contiguous runs (see module docstring), then
        psum across workers (the reference's request/response
        accumulation) and one batched Cholesky-style solve."""
        ids = plan[:, 0]
        starts = plan[:, 1]
        ends = plan[:, 2]
        r = block[:, 2]
        w = block[:, 3]
        x = other_factors[block[:, other_col].astype(jnp.int32)]  # (L, rank)
        if p.implicit_prefs:
            c = 1.0 + p.alpha * jnp.abs(r)
            pref = (r > 0).astype(x.dtype)
            ww = c * w
            bval = c * pref * w
        else:
            ww = w
            bval = r * w
        contrib = jnp.concatenate(
            [ww[:, None] * (x[:, :, None] * x[:, None, :]).reshape(-1, rank * rank),
             bval[:, None] * x, w[:, None]], axis=1)          # (L, r^2+r+1)
        # Two-level prefix: a single global f32 prefix grows to O(nnz)
        # magnitude and differencing it loses ~nnz*eps of every short run,
        # while a full f64 cumsum is slow (f64 is emulated on TPU). So:
        # f32 prefixes WITHIN 512-row blocks (error bounded by the block
        # length, not the global magnitude) and an f64 cumsum over only
        # the ~L/512 block sums (x64 stays off globally).
        K = contrib.shape[1]
        Lr = contrib.shape[0]
        C = 512
        Lb = -(-Lr // C)
        pad = Lb * C - Lr
        cpad = jnp.concatenate(
            [contrib, jnp.zeros((pad, K), contrib.dtype)], axis=0)
        intra = jnp.cumsum(cpad.reshape(Lb, C, K), axis=1)    # f32, in-block
        with jax.enable_x64(True):
            bsums = intra[:, -1, :].astype(jnp.float64)
            inter = jnp.concatenate(
                [jnp.zeros((1, K), jnp.float64),
                 jnp.cumsum(bsums, axis=0)], axis=0)          # exclusive

            def prefix(t):                                    # t: (N,) positions
                bi = t // C
                ri = t % C
                part = jnp.where((ri > 0)[:, None],
                                 intra[bi, ri - 1], 0.0)
                return inter[bi] + part.astype(jnp.float64)

            slot = (prefix(ends) - prefix(starts)).astype(x.dtype)
        A = jnp.zeros((n_rows, rank * rank), x.dtype).at[ids].add(
            slot[:, :rank * rank])
        b = jnp.zeros((n_rows, rank), x.dtype).at[ids].add(
            slot[:, rank * rank:rank * rank + rank])
        cnt = jnp.zeros((n_rows,), x.dtype).at[ids].add(slot[:, -1])
        A = jax.lax.psum(A, "d").reshape(n_rows, rank, rank)
        b = jax.lax.psum(b, "d")
        cnt = jax.lax.psum(cnt, "d")
        A = A + lam * jnp.maximum(cnt, 1.0)[:, None, None] * eye
        sol = jnp.linalg.solve(A, b[..., None])[..., 0]
        if p.nonnegative:
            sol = batched_nnls(A, b, x0=jnp.maximum(sol, 0.0))
        return jnp.where(cnt[:, None] > 0, sol, 0.0)

    def step(ctx):
        if ctx.is_init_step:
            tid0 = ctx.task_id
            ctx.put_obj("uf", ctx.get_obj("uf0")[tid0])   # (Upad/nw, rank)
            ctx.put_obj("if_", ctx.get_obj("if0")[tid0])
            ctx.put_obj("rmse_curve", jnp.zeros((p.num_iter,), jnp.float32))
        bU = ctx.get_obj("blkU")
        bI = ctx.get_obj("blkI")
        plU = ctx.get_obj("planU")
        plI = ctx.get_obj("planI")
        # ---- update user factors: gather ALL item factors (all_gather) ----
        item_full = jax.lax.all_gather(ctx.get_obj("if_"), "d", axis=0,
                                       tiled=True)
        uf_full = solve_side(bU, plU, 1, item_full, Upad)
        tid = ctx.task_id
        shard = Upad // nw
        ctx.put_obj("uf", jax.lax.dynamic_slice_in_dim(uf_full, tid * shard,
                                                       shard, 0))
        # ---- update item factors ----
        user_full = jax.lax.all_gather(ctx.get_obj("uf"), "d", axis=0, tiled=True)
        if_full = solve_side(bI, plI, 0, user_full, Ipad)
        ishard = Ipad // nw
        ctx.put_obj("if_", jax.lax.dynamic_slice_in_dim(if_full, tid * ishard,
                                                        ishard, 0))
        # rmse for the curve (over the user-sorted copy; order is irrelevant)
        uid = bU[:, 0].astype(jnp.int32)
        iid = bU[:, 1].astype(jnp.int32)
        r = bU[:, 2]
        w = bU[:, 3]
        uf_now = jax.lax.all_gather(ctx.get_obj("uf"), "d", axis=0, tiled=True)
        pred = (uf_now[uid] * if_full[iid]).sum(-1)
        se = jax.lax.psum(jnp.stack([(w * (pred - r) ** 2).sum(), w.sum()]), "d")
        ctx.put_obj("rmse_curve", jax.lax.dynamic_update_index_in_dim(
            ctx.get_obj("rmse_curve"),
            jnp.sqrt(se[0] / jnp.maximum(se[1], 1e-12)).astype(jnp.float32),
            ctx.step_no - 1, 0))

    queue = (IterativeComQueue(env=env, max_iter=p.num_iter, seed=p.seed)
             .init_with_partitioned_data("blkU", np.concatenate(blkU))
             .init_with_partitioned_data("blkI", np.concatenate(blkI))
             .init_with_partitioned_data("planU", planU.reshape(-1, 3))
             .init_with_partitioned_data("planI", planI.reshape(-1, 3))
             .init_with_broadcast_data("uf0", uf0.reshape(nw, -1, rank))
             .init_with_broadcast_data("if0", if0.reshape(nw, -1, rank))
             .add(step))
    res = queue.exec()
    uf = res.concat("uf", total=Upad)[:U]
    if_ = res.concat("if_", total=Ipad)[:I]
    return uf, if_, np.asarray(res.get("rmse_curve"))
