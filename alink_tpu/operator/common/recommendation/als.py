"""ALS matrix factorization — TPU-native.

Re-design of common/recommendation/AlsTrain.java (587 LoC; SURVEY §2.3
"block/graph parallelism"): the reference groups ratings into user/item
blocks, exchanges factor request/response messages over Flink coGroups
(AlsTrain.java:266-335), and solves per-block normal equations with a
Cholesky (NormalEquation, :493) inside a Flink loop of
numIters*numMiniBatches*2 supersteps.

TPU-first shape: factors live as device arrays sharded over the data axis;
the request/response gather becomes ONE ``lax.all_gather`` of the opposing
factor block per half-step (the "factor all-gather" north star), and all
per-row normal equations are built with one batched segment-sum of
x x^T outer products and solved with ``jnp.linalg.solve`` batched over
rows — MXU-batched Cholesky solves instead of per-block Java loops.

Ratings are a padded COO block per user-shard: (user_local, item, rating)
with weight-0 padding. Implicit feedback (implicitprefs) follows the
reference's confidence weighting c = 1 + alpha*|r|.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ....common.mlenv import MLEnvironment, MLEnvironmentFactory
from ....engine import IterativeComQueue


def batched_nnls(A, b, x0=None, num_iter: int = 80):
    """Batched nonnegative least squares: min_x>=0  1/2 x^T A x - b^T x.

    The reference's NNLSSolver (Scala, projected-gradient NNLS used by ALS
    nonnegative mode) becomes accelerated projected gradient (FISTA) with a
    per-row Lipschitz bound L = trace(A) (valid since A is PSD), batched
    over the leading axis and fully traceable — a fixed-trip-count
    ``lax.fori_loop`` instead of the reference's per-block CPU iterations.

    ``A``: (n, r, r) PSD normal matrices, ``b``: (n, r). ``x0`` optional
    warm start (defaults to the clipped unconstrained solution's role —
    zeros if omitted).
    """
    L = jnp.maximum(jnp.trace(A, axis1=-2, axis2=-1), 1e-12)[:, None]
    x = jnp.zeros_like(b) if x0 is None else x0
    state = (x, x, jnp.asarray(1.0, b.dtype))

    def body(_, st):
        x, yv, t = st
        grad = jnp.einsum("nij,nj->ni", A, yv) - b
        x_new = jnp.maximum(yv - grad / L, 0.0)
        t_new = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
        y_new = x_new + ((t - 1.0) / t_new) * (x_new - x)
        return (x_new, y_new, t_new)

    x, _, _ = jax.lax.fori_loop(0, num_iter, body, state)
    return x


@dataclass
class AlsTrainParams:
    rank: int = 10
    num_iter: int = 10
    lambda_reg: float = 0.1
    implicit_prefs: bool = False
    alpha: float = 40.0
    nonnegative: bool = False
    seed: int = 0


def als_train(users: np.ndarray, items: np.ndarray, ratings: np.ndarray,
              p: AlsTrainParams, env: Optional[MLEnvironment] = None,
              num_users: Optional[int] = None, num_items: Optional[int] = None
              ) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (user_factors (U, rank), item_factors (I, rank))."""
    env = env or MLEnvironmentFactory.get_default()
    users = np.asarray(users, np.int32)
    items = np.asarray(items, np.int32)
    ratings = np.asarray(ratings, np.float32)
    U = int(num_users if num_users is not None else users.max() + 1)
    I = int(num_items if num_items is not None else items.max() + 1)
    rank = p.rank
    rng = np.random.RandomState(p.seed)
    uf0 = (rng.rand(U, rank).astype(np.float32) / np.sqrt(rank))
    if0 = (rng.rand(I, rank).astype(np.float32) / np.sqrt(rank))
    nw = env.num_workers
    # ratings partitioned by row over workers; factor matrices sharded by
    # padding U/I to a multiple of the worker count
    Upad = -(-U // nw) * nw
    Ipad = -(-I // nw) * nw
    uf0 = np.concatenate([uf0, np.zeros((Upad - U, rank), np.float32)])
    if0 = np.concatenate([if0, np.zeros((Ipad - I, rank), np.float32)])
    data = np.stack([users.astype(np.float32), items.astype(np.float32),
                     ratings, np.ones(len(ratings), np.float32)], axis=1)
    lam = p.lambda_reg
    eye = np.eye(rank, dtype=np.float32)

    def solve_side(ids, other_ids, r, w, other_factors, n_rows):
        """Normal equations for each of n_rows ids given gathered opposing
        factors: batched segment-sum of local contributions, psum of (A, b)
        across workers (the reference's request/response accumulation), then
        one batched Cholesky-style solve."""
        x = other_factors[other_ids]                     # (nnz, rank)
        if p.implicit_prefs:
            c = 1.0 + p.alpha * jnp.abs(r)
            pref = (r > 0).astype(x.dtype)
            A_contrib = (c * w)[:, None, None] * (x[:, :, None] * x[:, None, :])
            b_contrib = (c * pref * w)[:, None] * x
        else:
            A_contrib = w[:, None, None] * (x[:, :, None] * x[:, None, :])
            b_contrib = (r * w)[:, None] * x
        A = jnp.zeros((n_rows, rank, rank), x.dtype).at[ids].add(A_contrib)
        b = jnp.zeros((n_rows, rank), x.dtype).at[ids].add(b_contrib)
        cnt = jnp.zeros((n_rows,), x.dtype).at[ids].add(w)
        A = jax.lax.psum(A, "d")
        b = jax.lax.psum(b, "d")
        cnt = jax.lax.psum(cnt, "d")
        A = A + lam * jnp.maximum(cnt, 1.0)[:, None, None] * eye
        sol = jnp.linalg.solve(A, b[..., None])[..., 0]
        if p.nonnegative:
            sol = batched_nnls(A, b, x0=jnp.maximum(sol, 0.0))
        return jnp.where(cnt[:, None] > 0, sol, 0.0)

    def step(ctx):
        if ctx.is_init_step:
            tid0 = ctx.task_id
            ctx.put_obj("uf", ctx.get_obj("uf0")[tid0])   # (Upad/nw, rank)
            ctx.put_obj("if_", ctx.get_obj("if0")[tid0])
            ctx.put_obj("rmse_curve", jnp.zeros((p.num_iter,), jnp.float32))
        block = ctx.get_obj("ratings")
        uid = block[:, 0].astype(jnp.int32)
        iid = block[:, 1].astype(jnp.int32)
        r = block[:, 2]
        w = block[:, 3]
        # ---- update user factors: gather ALL item factors (all_gather) ----
        item_full = jax.lax.all_gather(ctx.get_obj("if_"), "d", axis=0,
                                       tiled=True)
        uf_full = solve_side(uid, iid, r, w, item_full, Upad)
        tid = ctx.task_id
        shard = Upad // nw
        ctx.put_obj("uf", jax.lax.dynamic_slice_in_dim(uf_full, tid * shard,
                                                       shard, 0))
        # ---- update item factors ----
        user_full = jax.lax.all_gather(ctx.get_obj("uf"), "d", axis=0, tiled=True)
        if_full = solve_side(iid, uid, r, w, user_full, Ipad)
        ishard = Ipad // nw
        ctx.put_obj("if_", jax.lax.dynamic_slice_in_dim(if_full, tid * ishard,
                                                        ishard, 0))
        # rmse for the curve
        uf_now = jax.lax.all_gather(ctx.get_obj("uf"), "d", axis=0, tiled=True)
        pred = (uf_now[uid] * if_full[iid]).sum(-1)
        se = jax.lax.psum(jnp.stack([(w * (pred - r) ** 2).sum(), w.sum()]), "d")
        ctx.put_obj("rmse_curve", jax.lax.dynamic_update_index_in_dim(
            ctx.get_obj("rmse_curve"),
            jnp.sqrt(se[0] / jnp.maximum(se[1], 1e-12)).astype(jnp.float32),
            ctx.step_no - 1, 0))

    queue = (IterativeComQueue(env=env, max_iter=p.num_iter, seed=p.seed)
             .init_with_partitioned_data("ratings", data)
             .init_with_broadcast_data("uf0", uf0.reshape(nw, -1, rank))
             .init_with_broadcast_data("if0", if0.reshape(nw, -1, rank))
             .add(step))
    res = queue.exec()
    uf = res.concat("uf", total=Upad)[:U]
    if_ = res.concat("if_", total=Ipad)[:I]
    return uf, if_, np.asarray(res.get("rmse_curve"))
