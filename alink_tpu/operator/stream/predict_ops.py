"""Stream predict variants of every model-backed batch operator.

The reference ships a ``*PredictStreamOp`` next to nearly every
``*PredictBatchOp`` (operator/stream/{classification,regression,clustering,
dataproc,feature}/...StreamOp.java); all of them are the same shape — load
the (batch-trained) model once, map the stream through the model mapper
(stream/utils/ModelMapStreamOp). Here they are derived mechanically from
the batch predict classes: same mapper kernel, same params, applied per
micro-batch.
"""

from __future__ import annotations

import sys

from .utils import ModelMapStreamOp

_BATCH_PREDICT_OPS = {
    # classification
    "LogisticRegressionPredictStreamOp": ("..batch.classification.linear", "LogisticRegressionPredictBatchOp"),
    "LinearSvmPredictStreamOp": ("..batch.classification.linear", "LinearSvmPredictBatchOp"),
    "SoftmaxPredictStreamOp": ("..batch.classification.linear", "SoftmaxPredictBatchOp"),
    "PerceptronPredictStreamOp": ("..batch.classification.linear", "PerceptronPredictBatchOp"),
    "NaiveBayesTextPredictStreamOp": ("..batch.classification.naive_bayes", "NaiveBayesTextPredictBatchOp"),
    "NaiveBayesPredictStreamOp": ("..batch.classification.naive_bayes", "NaiveBayesPredictBatchOp"),
    "FmPredictStreamOp": ("..batch.classification.fm_ops", "FmPredictBatchOp"),
    "MultilayerPerceptronPredictStreamOp": ("..batch.classification.mlpc_ops", "MultilayerPerceptronPredictBatchOp"),
    "GbdtPredictStreamOp": ("..batch.classification.tree_ops", "GbdtPredictBatchOp"),
    "GbdtRegPredictStreamOp": ("..batch.classification.tree_ops", "GbdtRegPredictBatchOp"),
    "RandomForestPredictStreamOp": ("..batch.classification.tree_ops", "RandomForestPredictBatchOp"),
    "RandomForestRegPredictStreamOp": ("..batch.classification.tree_ops", "RandomForestRegPredictBatchOp"),
    "DecisionTreePredictStreamOp": ("..batch.classification.tree_ops", "DecisionTreePredictBatchOp"),
    "DecisionTreeRegPredictStreamOp": ("..batch.classification.tree_ops", "DecisionTreeRegPredictBatchOp"),
    # regression
    "LinearRegPredictStreamOp": ("..batch.regression.linear", "LinearRegPredictBatchOp"),
    "RidgeRegPredictStreamOp": ("..batch.regression.linear", "RidgeRegPredictBatchOp"),
    "LassoRegPredictStreamOp": ("..batch.regression.linear", "LassoRegPredictBatchOp"),
    "LinearSvrPredictStreamOp": ("..batch.regression.linear", "LinearSvrPredictBatchOp"),
    "GlmPredictStreamOp": ("..batch.regression.glm_ops", "GlmPredictBatchOp"),
    "IsotonicRegPredictStreamOp": ("..batch.regression.glm_ops", "IsotonicRegPredictBatchOp"),
    "AftSurvivalRegPredictStreamOp": ("..batch.regression.glm_ops", "AftSurvivalRegPredictBatchOp"),
    # clustering
    "KMeansPredictStreamOp": ("..batch.clustering.kmeans_ops", "KMeansPredictBatchOp"),
    "GmmPredictStreamOp": ("..batch.clustering.gmm_bisecting", "GmmPredictBatchOp"),
    "BisectingKMeansPredictStreamOp": ("..batch.clustering.gmm_bisecting", "BisectingKMeansPredictBatchOp"),
    # dataproc / feature
    "StandardScalerPredictStreamOp": ("..batch.dataproc.scalers", "StandardScalerPredictBatchOp"),
    "MinMaxScalerPredictStreamOp": ("..batch.dataproc.scalers", "MinMaxScalerPredictBatchOp"),
    "MaxAbsScalerPredictStreamOp": ("..batch.dataproc.scalers", "MaxAbsScalerPredictBatchOp"),
    "ImputerPredictStreamOp": ("..batch.dataproc.scalers", "ImputerPredictBatchOp"),
    "VectorStandardScalerPredictStreamOp": ("..batch.dataproc.vector_ops", "VectorStandardScalerPredictBatchOp"),
    "VectorImputerPredictStreamOp": ("..batch.dataproc.vector_ops", "VectorImputerPredictBatchOp"),
    "VectorMinMaxScalerPredictStreamOp": ("..batch.dataproc.vector_ops", "VectorMinMaxScalerPredictBatchOp"),
    "VectorMaxAbsScalerPredictStreamOp": ("..batch.dataproc.vector_ops", "VectorMaxAbsScalerPredictBatchOp"),
    "StringIndexerPredictStreamOp": ("..batch.dataproc.indexers", "StringIndexerPredictBatchOp"),
    "MultiStringIndexerPredictStreamOp": ("..batch.dataproc.indexers", "MultiStringIndexerPredictBatchOp"),
    "IndexToStringPredictStreamOp": ("..batch.dataproc.indexers", "IndexToStringPredictBatchOp"),
    "OneHotPredictStreamOp": ("..batch.feature.feature_ops", "OneHotPredictBatchOp"),
    "QuantileDiscretizerPredictStreamOp": ("..batch.feature.feature_ops", "QuantileDiscretizerPredictBatchOp"),
    "PcaPredictStreamOp": ("..batch.feature.feature_ops", "PcaPredictBatchOp"),
    # nlp
    "DocCountVectorizerPredictStreamOp": ("..batch.nlp", "DocCountVectorizerPredictBatchOp"),
    "DocHashCountVectorizerPredictStreamOp": ("..batch.nlp", "DocHashCountVectorizerPredictBatchOp"),
    "Word2VecPredictStreamOp": ("..batch.nlp", "Word2VecPredictBatchOp"),
}

__all__ = sorted(_BATCH_PREDICT_OPS)


def _build():
    import importlib

    from ...common.params import WithParams
    from ..base import AlgoOperator
    mod = sys.modules[__name__]
    for name, (batch_module, batch_name) in _BATCH_PREDICT_OPS.items():
        bm = importlib.import_module(batch_module, package=__name__.rsplit(".", 1)[0])
        batch_cls = getattr(bm, batch_name)
        # carry over the pure param mixins (Has*) but nothing operator-typed:
        # mixins are plain classes harvested by the WithParams metaclass
        bases = tuple(b for b in batch_cls.__mro__
                      if not issubclass(b, WithParams) and b is not object)
        cls = type(name, (ModelMapStreamOp,) + bases, {
            "MAPPER_CLS": batch_cls.MAPPER_CLS,
            "__doc__": f"Stream variant of {batch_name} "
                       f"(reference stream predict op of the same family).",
            "__module__": __name__,
        })
        setattr(mod, name, cls)


_build()
