"""TPU-native compute kernels (the framework's "BLAS layer").

The reference dispatches its hot loops to native BLAS through JNI
(common/linalg/BLAS.java:10-26) and hand-written Java inner loops
(per-sample gradient loops in common/optim/subfunc/CalcGradient.java:27-54).
On TPU the equivalents are XLA programs shaped for the MXU plus Pallas
kernels where XLA's default lowering is wrong for the access pattern —
most importantly random gather/scatter, which XLA serializes on TPU.

`fieldblock` implements the field-blocked sparse format and its
factored-one-hot matvec/rmatvec — the TPU answer to the reference's
SparseVector dot/axpy hot loops.
"""

from .fieldblock import (FieldBlockMeta, detect_fieldblock, fb_fused_grad,
                         fb_fused_grad_pallas, fb_matvec, fb_matvec_pallas,
                         fb_pallas_ok, fb_rmatvec, fb_to_flat_indices,
                         flat_to_fb_indices, hash_to_fields)

__all__ = [
    "FieldBlockMeta", "detect_fieldblock", "fb_matvec", "fb_rmatvec",
    "fb_fused_grad", "fb_matvec_pallas", "fb_pallas_ok",
    "fb_fused_grad_pallas", "fb_to_flat_indices", "flat_to_fb_indices",
    "hash_to_fields",
]
