"""Zero-copy / overlapped-execution tests (tier-1, JAX_PLATFORMS=cpu).

Covers the r06 device-residency + overlap pass end to end:

  * buffer donation (``ALINK_TPU_DONATE``): the lowered cont-chunk
    program carries input->output aliasing, its collective set is
    byte-identical to the non-donated program, checkpointed training is
    bitwise identical either way, and a donated buffer reused after the
    call raises cleanly;
  * the async snapshot writer (``ALINK_TPU_ASYNC_SNAPSHOT``): on-disk
    artifacts and kill-and-resume parity (superstep kill AND an injected
    ``ckpt.save`` fault while the next chunk is in flight) match the
    synchronous path bitwise; the final barrier holds;
  * the ordered multi-worker prefetch pool (``ALINK_TPU_STREAM_WORKERS``):
    no reordering at workers > 1, error delivery at the failing item's
    position, stop-aware producer wakeup, named threads;
  * batched host fetches: a multi-leaf ``ComQueueResult`` read issues ONE
    ``jax.device_get``.
"""

import os
import threading
import time

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from alink_tpu.common.faults import FAULT_ENV, FaultInjected
from alink_tpu.engine import AllReduce, IterativeComQueue
from alink_tpu.engine.comqueue import clear_program_cache, donation_enabled


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _queue(max_iter=8, ckpt=None, **ck):
    """A small allreduce queue with a multi-leaf carry (scalar acc + a
    vector state) — enough structure for aliasing/fetch assertions."""
    def stage(ctx):
        if ctx.is_init_step:
            ctx.put_obj("acc", jnp.zeros(()))
            ctx.put_obj("state", jnp.zeros(16))
        ctx.put_obj("v", jnp.ones(()))
        ctx.put_obj("acc", ctx.get_obj("acc") + ctx.get_obj("v"))
        ctx.put_obj("state", ctx.get_obj("state") * 0.5
                    + ctx.get_obj("acc"))
    q = IterativeComQueue(max_iter=max_iter).add(stage).add(AllReduce("v"))
    if ckpt is not None:
        q.set_checkpoint(ckpt, **ck)
    return q


def _lr_fixture(n=256, d=6, seed=3):
    r = np.random.RandomState(seed)
    X = r.randn(n, d).astype(np.float32)
    y = (X @ r.randn(d) > 0).astype(np.float32) * 2 - 1
    return {"X": X, "y": y, "w": np.ones(n, np.float32)}


def _lbfgs(data, **ck):
    from alink_tpu.operator.common.optim.objfunc import (LogLossFunc,
                                                         UnaryLossObjFunc)
    from alink_tpu.operator.common.optim.optimizers import (OptimParams,
                                                            optimize)
    obj = UnaryLossObjFunc(LogLossFunc(), dim=data["X"].shape[1])
    params = OptimParams(method="LBFGS", max_iter=12, epsilon=0.0, **ck)
    return optimize(obj, data, params)


# ---------------------------------------------------------------------------
# donation: lowered-HLO aliasing + collective-set identity
# ---------------------------------------------------------------------------

class TestDonationHLO:
    def test_default_on(self, monkeypatch):
        monkeypatch.delenv("ALINK_TPU_DONATE", raising=False)
        assert donation_enabled()
        monkeypatch.setenv("ALINK_TPU_DONATE", "0")
        assert not donation_enabled()

    def test_cont_program_aliases_carry_and_keeps_collectives(
            self, monkeypatch):
        """ISSUE acceptance: donation introduces input->output aliasing
        in the cont chunk program and changes NOTHING about the compiled
        collective set; the first program (no carry input) is identical
        either way."""
        monkeypatch.setenv("ALINK_TPU_DONATE", "1")
        first_d, cont_d = _queue(ckpt="/tmp/unused-ovl", every=2
                                 ).lowered_chunked()
        monkeypatch.setenv("ALINK_TPU_DONATE", "0")
        first_p, cont_p = _queue(ckpt="/tmp/unused-ovl", every=2
                                 ).lowered_chunked()
        txt_d, txt_p = cont_d.as_text(), cont_p.as_text()
        # jax marks a donated StableHLO argument tf.aliasing_output when
        # the input->output pairing is static, jax.buffer_donor when the
        # compiler picks the pairing (the multi-device case) — either
        # way the aliasing is IN the lowered program
        assert "aliasing_output" in txt_d or "buffer_donor" in txt_d
        assert "aliasing_output" not in txt_p \
            and "buffer_donor" not in txt_p
        # zero change to the compiled collectives (and still no host
        # callbacks — donation is an aliasing annotation, not an op)
        for op in ("all_reduce", "all_gather", "collective_permute",
                   "reduce_scatter", "custom_call", "outfeed", "infeed"):
            assert txt_d.lower().count(op) == txt_p.lower().count(op), op
        assert first_d.as_text() == first_p.as_text()

    def test_donate_rides_program_cache_key(self, monkeypatch):
        """Toggling ALINK_TPU_DONATE must MISS the compiled-program
        cache, never alias-through a cached non-donated program."""
        from alink_tpu.engine.comqueue import program_cache_stats
        clear_program_cache()

        def run():
            return (_queue(max_iter=4)
                    .set_program_key(("ovl_donate_key",))
                    .exec())
        monkeypatch.setenv("ALINK_TPU_DONATE", "1")
        run()
        monkeypatch.setenv("ALINK_TPU_DONATE", "0")
        before = program_cache_stats()
        run()
        after = program_cache_stats()
        assert after["misses"] == before["misses"] + 1

    def test_donated_buffer_reuse_raises_cleanly(self):
        """The donation contract's failure mode is LOUD: touching a
        buffer that was donated into an FTRL step raises, it never
        serves stale bytes. A single-device mesh: that is where the CPU
        backend actually performs donation (multi-device host platforms
        defer the aliasing to the compiler and may skip it; TPU donates
        in both layouts)."""
        from jax.sharding import Mesh
        from alink_tpu.operator.stream.onlinelearning.ftrl import (
            _ftrl_sparse_batch_step_factory)
        mesh = Mesh(np.array(jax.devices()[:1]), ("d",))
        step = _ftrl_sparse_batch_step_factory(mesh, 0.1, 1.0, 0.0, 0.0,
                                               donate=True)
        dim = 32
        idx = jnp.zeros((4, 8), jnp.int32)
        val = jnp.ones((4, 8))
        y = jnp.ones((4,))
        z0 = jnp.zeros(dim)
        n0 = jnp.zeros(dim)
        z1, n1, _ = step(idx, val, y, z0, n0)
        np.asarray(z1)                         # outputs are live
        with pytest.raises((RuntimeError, ValueError),
                           match="delet|donat"):
            np.asarray(z0) + 0                 # donated input is dead

    def test_ftrl_drain_bitwise_identical_donate_on_off(self, monkeypatch):
        """Donation changes buffer ownership, not math: the trained FTRL
        model is bitwise identical with the switch on and off."""
        from alink_tpu.common.mtable import MTable
        from alink_tpu.operator.batch.classification.linear import (
            LogisticRegressionTrainBatchOp)
        from alink_tpu.operator.batch.source.sources import MemSourceBatchOp
        from alink_tpu.operator.common.linear.base import (
            LinearModelDataConverter)
        from alink_tpu.operator.stream.onlinelearning.ftrl import (
            FtrlTrainStreamOp)
        from alink_tpu.operator.stream.source.sources import (
            MemSourceStreamOp)
        r = np.random.RandomState(0)
        n, d = 192, 8
        X = r.randn(n, d).astype(np.float64)
        yv = (X @ r.randn(d) > 0).astype(np.int64)
        cols = {**{f"f{i}": X[:, i] for i in range(d)}, "label": yv}
        schema = ", ".join(f"f{i} DOUBLE" for i in range(d)) \
            + ", label LONG"
        table = MTable(cols, schema)
        feats = [f"f{i}" for i in range(d)]
        warm = LogisticRegressionTrainBatchOp(
            feature_cols=feats, label_col="label", max_iter=3).link_from(
            MemSourceBatchOp(table.first_n(64)))

        def run():
            ftrl = FtrlTrainStreamOp(
                warm, feature_cols=feats, label_col="label", alpha=0.5,
                time_interval=1e9).link_from(
                MemSourceStreamOp(table, batch_size=64))
            final = list(ftrl.micro_batches())[-1]
            lt = final.schema.types[2]
            return LinearModelDataConverter(lt).load_model(final).coef
        monkeypatch.setenv("ALINK_TPU_DONATE", "1")
        coef_on = run()
        monkeypatch.setenv("ALINK_TPU_DONATE", "0")
        coef_off = run()
        assert np.asarray(coef_on).tobytes() == np.asarray(coef_off).tobytes()


# ---------------------------------------------------------------------------
# async snapshot writer
# ---------------------------------------------------------------------------

class TestAsyncSnapshot:
    def test_artifacts_match_sync_bitwise(self, tmp_path, monkeypatch):
        """Same snapshots on disk (tags, payload bytes) and same final
        result, async vs sync — the writer only moves work off the
        critical path."""
        from alink_tpu.common.checkpoint import (list_checkpoints,
                                                 load_checkpoint)
        data = _lr_fixture()
        monkeypatch.setenv("ALINK_TPU_ASYNC_SNAPSHOT", "0")
        d_sync = str(tmp_path / "sync")
        coef_s, curve_s, _ = _lbfgs(data, checkpoint_dir=d_sync,
                                    checkpoint_every=4)
        monkeypatch.setenv("ALINK_TPU_ASYNC_SNAPSHOT", "1")
        d_async = str(tmp_path / "async")
        coef_a, curve_a, _ = _lbfgs(data, checkpoint_dir=d_async,
                                    checkpoint_every=4)
        assert np.asarray(coef_a).tobytes() == np.asarray(coef_s).tobytes()
        tags_s = [os.path.basename(p) for p in list_checkpoints(d_sync)]
        tags_a = [os.path.basename(p) for p in list_checkpoints(d_async)]
        # final barrier: every boundary is on disk when the fit returns
        assert tags_a == tags_s and tags_a
        for ts, ta in zip(list_checkpoints(d_sync),
                          list_checkpoints(d_async)):
            ps, _ = load_checkpoint(ts)
            pa, _ = load_checkpoint(ta)
            assert sorted(ps) == sorted(pa)
            for k in ps:
                assert np.asarray(ps[k]).tobytes() == \
                    np.asarray(pa[k]).tobytes(), k

    def test_superstep_kill_and_resume_bitwise(self, tmp_path, monkeypatch):
        """The PR4-era kill-and-resume guarantee, now with the async
        writer AND donation on (the defaults)."""
        from alink_tpu.common.checkpoint import list_checkpoints
        data = _lr_fixture()
        d_full = str(tmp_path / "full")
        coef_full, curve_full, steps_full = _lbfgs(
            data, checkpoint_dir=d_full, checkpoint_every=4)
        d_kill = str(tmp_path / "kill")
        monkeypatch.setenv(FAULT_ENV, "comqueue.superstep:8")
        with pytest.raises(FaultInjected):
            _lbfgs(data, checkpoint_dir=d_kill, checkpoint_every=4)
        monkeypatch.delenv(FAULT_ENV)
        # the boundary-4 write raced the killed chunk — the shutdown path
        # must still have committed it (durability of the last boundary)
        assert [os.path.basename(p) for p in list_checkpoints(d_kill)] \
            == ["ckpt-000000000004"]
        coef_res, curve_res, steps_res = _lbfgs(
            data, checkpoint_dir=d_kill, checkpoint_every=4,
            resume_from=d_kill)
        assert steps_res == steps_full
        assert np.asarray(coef_res).tobytes() == \
            np.asarray(coef_full).tobytes()
        assert np.asarray(curve_res).tobytes() == \
            np.asarray(curve_full).tobytes()

    def test_ckpt_save_fault_while_chunk_in_flight(self, tmp_path,
                                                   monkeypatch):
        """ISSUE acceptance: inject a ckpt.save fault (it fires inside
        the background writer, while chunk t+1 is already dispatched);
        the failure surfaces on the main thread as FaultInjected, the
        poisoned snapshot is invisible, and the resume is bitwise."""
        from alink_tpu.common.checkpoint import list_checkpoints
        data = _lr_fixture()
        d_full = str(tmp_path / "full")
        coef_full, _, steps_full = _lbfgs(
            data, checkpoint_dir=d_full, checkpoint_every=4)
        d_kill = str(tmp_path / "kill")
        # ckpt.save uses a per-process auto counter; reset_faults() zeros
        # it so the threshold means "the 2nd save of THIS run" regardless
        # of which tests armed the site earlier (ISSUE 14 satellite: the
        # exported fixture hook, replacing ad-hoc _AUTO_INDEX pokes)
        from alink_tpu.common.faults import reset_faults
        reset_faults()
        monkeypatch.setenv(FAULT_ENV, "ckpt.save:2")
        with pytest.raises(FaultInjected):
            _lbfgs(data, checkpoint_dir=d_kill, checkpoint_every=4)
        monkeypatch.delenv(FAULT_ENV)
        # save #1 (boundary 4) committed; save #2 (boundary 8) died
        # mid-write -> no visible snapshot, no .tmp debris that listing
        # would surface
        assert [os.path.basename(p) for p in list_checkpoints(d_kill)] \
            == ["ckpt-000000000004"]
        coef_res, _, steps_res = _lbfgs(
            data, checkpoint_dir=d_kill, checkpoint_every=4,
            resume_from=d_kill)
        assert steps_res == steps_full
        assert np.asarray(coef_res).tobytes() == \
            np.asarray(coef_full).tobytes()

    def test_overlap_metrics_emitted(self, tmp_path):
        from alink_tpu.common.metrics import MetricsRegistry, set_registry
        reg = MetricsRegistry()
        prev = set_registry(reg)
        try:
            _queue(ckpt=str(tmp_path), every=2).exec()
        finally:
            set_registry(prev)
        assert reg.value("alink_overlap_snapshot_writes_total",
                         {"scope": "comqueue"}) >= 3
        fam = reg.histogram("alink_overlap_submit_wait_seconds")
        assert any(s.count > 0 for _, s in fam.series())


# ---------------------------------------------------------------------------
# ordered multi-worker prefetch pool
# ---------------------------------------------------------------------------

class TestPrefetchPool:
    def test_no_reordering_at_workers_gt_1(self):
        """ISSUE acceptance: adversarially jittered work, 4 workers, the
        output order is exactly the input order."""
        import random
        from alink_tpu.operator.stream.prefetch import prefetch_map
        rng = random.Random(7)

        def jittered(x):
            time.sleep(rng.random() * 0.005)
            return x * 3
        out = list(prefetch_map(iter(range(300)), jittered,
                                workers=4, depth=3))
        assert out == [x * 3 for x in range(300)]

    def test_env_worker_default(self, monkeypatch):
        from alink_tpu.operator.stream.prefetch import stream_workers
        monkeypatch.delenv("ALINK_TPU_STREAM_WORKERS", raising=False)
        assert stream_workers() == 1
        monkeypatch.setenv("ALINK_TPU_STREAM_WORKERS", "6")
        assert stream_workers() == 6

    def test_error_delivered_at_position(self):
        from alink_tpu.operator.stream.prefetch import prefetch_map

        def boom(x):
            if x == 23:
                raise ValueError("item-23")
            return x
        got = []
        with pytest.raises(ValueError, match="item-23"):
            for v in prefetch_map(iter(range(100)), boom,
                                  workers=4, depth=2):
                got.append(v)
        assert got == list(range(23))

    def test_worker_threads_are_named(self):
        from alink_tpu.operator.stream.prefetch import prefetch_map
        seen = set()

        def spy(x):
            seen.add(threading.current_thread().name)
            return x
        assert list(prefetch_map(iter(range(40)), spy,
                                 workers=3, depth=2)) == list(range(40))
        assert {f"alink-prefetch-{i}" for i in range(3)} <= seen

    def test_abandonment_wakes_blocked_producer_fast(self):
        """The old put() polled queue.Full every 0.1 s; the stop-aware
        channel must release an abandoned producer immediately."""
        from alink_tpu.operator.stream.prefetch import prefetch
        released = threading.Event()

        def src():
            try:
                for i in range(10**6):
                    yield i
            finally:
                released.set()
        it = prefetch(src(), depth=2)
        assert next(it) == 0
        t0 = time.perf_counter()
        it.close()                  # consumer abandons mid-stream
        assert released.wait(timeout=2.0)
        assert time.perf_counter() - t0 < 2.0

    def test_ftrl_model_identical_across_worker_counts(self, monkeypatch):
        """The pool preserves the drain's semantics: 3-worker encode
        produces the bit-identical model to the single-thread path."""
        from alink_tpu.common.mtable import MTable
        from alink_tpu.operator.batch.classification.linear import (
            LogisticRegressionTrainBatchOp)
        from alink_tpu.operator.batch.source.sources import MemSourceBatchOp
        from alink_tpu.operator.common.linear.base import (
            LinearModelDataConverter)
        from alink_tpu.operator.stream.onlinelearning.ftrl import (
            FtrlTrainStreamOp)
        from alink_tpu.operator.stream.source.sources import (
            MemSourceStreamOp)
        r = np.random.RandomState(5)
        n, dim, nnz = 256, 24, 5
        w_true = r.randn(dim)
        vecs, ys = [], []
        for _ in range(n):
            ii = np.sort(r.choice(dim, nnz, replace=False))
            vv = r.randn(nnz)
            ys.append(int(vv @ w_true[ii] > 0))
            vecs.append("$%d$" % dim + " ".join(
                f"{i}:{v:.6f}" for i, v in zip(ii, vv)))
        table = MTable({"vec": np.asarray(vecs, object),
                        "label": np.asarray(ys, np.int64)})
        warm = LogisticRegressionTrainBatchOp(
            vector_col="vec", label_col="label", max_iter=3).link_from(
            MemSourceBatchOp(table.first_n(64)))

        def run():
            ftrl = FtrlTrainStreamOp(
                warm, vector_col="vec", label_col="label", alpha=0.5,
                time_interval=1e9).link_from(
                MemSourceStreamOp(table, batch_size=32))
            final = list(ftrl.micro_batches())[-1]
            lt = final.schema.types[2]
            return LinearModelDataConverter(lt).load_model(final).coef
        monkeypatch.setenv("ALINK_TPU_STREAM_WORKERS", "1")
        coef_1 = run()
        monkeypatch.setenv("ALINK_TPU_STREAM_WORKERS", "3")
        coef_3 = run()
        assert np.asarray(coef_1).tobytes() == np.asarray(coef_3).tobytes()


# ---------------------------------------------------------------------------
# batched host fetches
# ---------------------------------------------------------------------------

class TestBatchedFetch:
    def test_multi_leaf_result_single_device_get(self, monkeypatch):
        """ISSUE acceptance: shards()/get() on a multi-leaf carry object
        collect the leaves and fetch them in ONE jax.device_get; the
        read-only memo contract is unchanged."""
        def stage(ctx):
            if ctx.is_init_step:
                ctx.put_obj("pair", (jnp.zeros(4), jnp.ones(3)))
            a, b = ctx.get_obj("pair")
            ctx.put_obj("pair", (a + 1.0, b * 2.0))
        res = IterativeComQueue(max_iter=3).add(stage).exec()
        calls = []
        real = jax.device_get

        def counting(x):
            calls.append(x)
            return real(x)
        monkeypatch.setattr(jax, "device_get", counting)
        got = res.shards("pair")
        assert len(calls) == 1, "multi-leaf shards() must batch-fetch"
        assert isinstance(got, tuple) and len(got) == 2
        for leaf in got:
            assert not leaf.flags.writeable
            with pytest.raises(ValueError):
                leaf[...] = 0
        calls.clear()
        g = res.get("pair")
        assert calls == []          # served by slicing the shards memo
        assert np.asarray(g[0]).shape == (4,)

    def test_probes_batch_fetch(self, monkeypatch):
        from alink_tpu.common.health import health_enabled
        if not health_enabled():
            pytest.skip("ALINK_TPU_HEALTH off")

        def stage(ctx):
            if ctx.is_init_step:
                ctx.put_obj("acc", jnp.zeros(()))
            ctx.put_obj("acc", ctx.get_obj("acc") + 1.0)
            ctx.probe("a", ctx.get_obj("acc"))
            ctx.probe("b", -ctx.get_obj("acc"))
            ctx.probe("c", 2.0 * ctx.get_obj("acc"))
        res = IterativeComQueue(max_iter=4).add(stage).exec()
        calls = []
        real = jax.device_get

        def counting(x):
            calls.append(x)
            return real(x)
        monkeypatch.setattr(jax, "device_get", counting)
        got = res.probes()
        assert set(got) == {"a", "b", "c"}
        assert len(calls) == 1, "probes() must batch all series into " \
                                "one device_get"
        np.testing.assert_allclose(got["a"], [1, 2, 3, 4])
