"""ENV-KEY-FOLD positive: a program factory reads (a) a flag declared
as folding only into step_lru — the wrong dimension for this factory —
and (b) an undeclared flag, via a transitively-called helper."""
import os

UNDECLARED = "ALINK_TPU_UNDECLARED"


def helper():
    # undeclared flag, reached through the factory's call chain
    return os.environ.get(UNDECLARED)


def make_program(stages):
    wrong_dim = os.environ.get("ALINK_TPU_BAD")     # declares step_lru only
    extra = helper()
    # os.getenv is the same read as os.environ.get and must not slip past
    alt_spelling = os.getenv("ALINK_TPU_UNDECLARED_GETENV")
    return (stages, wrong_dim, extra, alt_spelling)
