"""Per-host sharded file reading (SURVEY §7 "scaling 8->128 chips":
input pipelines must shard at the source — Criteo-1TB cannot funnel
through one host).

Two mechanisms, chosen by the path:

- **glob patterns** (`part-*.csv`): the sorted file list is partitioned
  round-robin across shards — the natural fit for pre-split datasets;
- **single file**: byte-range sharding with newline alignment — shard i
  owns every line whose first byte lies in ``[size*i//n, size*(i+1)//n)``,
  so shards are disjoint, complete, and each host reads only ~1/n of the
  file.

The default shard topology is the JAX process grid
(``jax.process_index()/process_count()``), so a multi-host session
(``use_remote_env``) gets per-host input sharding with no extra
configuration.

This module also hosts the DEVICE-side partition-rule machinery
(:func:`match_partition_rules` / :func:`state_sharding` /
:func:`device_put_state`): regex-over-name rules that place model-state
pytrees on the session mesh (FTRL's feature-sharded (z, n), replicated
coefficients), the SNIPPETS.md [1] idiom.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import List, Optional, Tuple

_GLOB_CHARS = ("*", "?", "[")


def resolve_shard(shard_index: Optional[int] = None,
                  num_shards: Optional[int] = None) -> Tuple[int, int]:
    """(shard_index, num_shards), defaulting to the JAX process topology."""
    if num_shards is None:
        if shard_index is not None:
            raise ValueError("shard_index given without num_shards")
        import jax
        return jax.process_index(), jax.process_count()
    if shard_index is None:
        # num_shards alone means "shard by host": defaulting to 0 would make
        # every host read the same 1/n slice and silently drop the rest.
        import jax
        if num_shards != jax.process_count():
            raise ValueError(
                f"num_shards={num_shards} without shard_index only makes "
                f"sense when it equals the process count "
                f"({jax.process_count()}); pass shard_index explicitly")
        shard_index = jax.process_index()
    if not 0 <= shard_index < num_shards:
        raise ValueError(f"shard_index {shard_index} not in [0, {num_shards})")
    return shard_index, num_shards


def expand_paths(pattern: str) -> Optional[List[str]]:
    """Sorted glob expansion, or None when the path has no glob magic."""
    if not any(c in pattern for c in _GLOB_CHARS):
        return None
    if os.path.exists(pattern):  # literal filename containing glob chars
        return None
    paths = sorted(_glob.glob(pattern))
    if not paths:
        raise FileNotFoundError(f"no files match {pattern!r}")
    return paths


def shard_paths(pattern: str, shard_index: int, num_shards: int
                ) -> Optional[List[str]]:
    """This shard's round-robin slice of a glob expansion (None: no glob)."""
    paths = expand_paths(pattern)
    if paths is None:
        return None
    return paths[shard_index::num_shards]


def read_file_shard(path: str, shard_index: int, num_shards: int) -> bytes:
    """Newline-aligned byte-range shard of one file.

    Shard i owns every line whose first byte falls in
    ``[size*i//n, size*(i+1)//n)``; a line straddling a boundary belongs to
    the shard where it starts. Reads only this shard's range (+ the tail of
    its last line), never the whole file.
    """
    size = os.path.getsize(path)
    start = size * shard_index // num_shards
    end = size * (shard_index + 1) // num_shards
    with open(path, "rb") as f:
        if start > 0:
            # the line containing byte start-1 belongs to the previous shard
            f.seek(start - 1)
            prev = f.read(1)
            if prev != b"\n":
                _scan_to_newline(f)
        data_start = f.tell()
        if data_start >= end:
            return b""
        buf = f.read(end - data_start)
        if not buf.endswith(b"\n") and f.tell() < size:
            buf += _scan_to_newline(f)  # finish the straddling line
    return buf


def _scan_to_newline(f, chunk: int = 1 << 16) -> bytes:
    """Read up to and including the next newline (or EOF)."""
    out = b""
    while True:
        c = f.read(chunk)
        if not c:
            return out
        j = c.find(b"\n")
        if j >= 0:
            out += c[:j + 1]
            f.seek(f.tell() - (len(c) - j - 1))
            return out
        out += c


# -- model-state partition rules (SNIPPETS.md [1] match_partition_rules) ----
# Regex-over-leaf-path rules mapping a named state pytree to
# PartitionSpecs: the declarative form of "which axis of which state
# array lives on which mesh axis". FTRL shards its (z, n) state across
# the feature axis exactly the way the reference splits it across
# workers (getSplitInfo ranges, FtrlTrainStreamOp.java:74-87); model
# coefficients and other replicated state fall through to P().

def _leaf_path_name(path) -> str:
    """'/'-joined human key path of a pytree leaf (dict keys, sequence
    indices, attribute names)."""
    parts = []
    for p in path:
        key = getattr(p, "key", None)
        if key is None:
            key = getattr(p, "idx", None)
        if key is None:
            key = getattr(p, "name", None)
        parts.append(str(key) if key is not None else str(p))
    return "/".join(parts)


def match_partition_rules(rules, tree, default=None):
    """Pytree of ``PartitionSpec`` built by regex-matching each leaf's
    '/'-joined key path against ``rules`` (``[(pattern, spec), ...]``,
    first match wins — the match_partition_rules idiom of SNIPPETS.md
    [1]). Scalar (0-d) leaves are never partitioned. ``default`` is the
    spec for unmatched leaves; None means unmatched leaves RAISE, so a
    new state entry cannot silently default to the wrong placement."""
    import re

    import jax
    from jax.sharding import PartitionSpec as P

    def spec(path, leaf):
        name = _leaf_path_name(path)
        if getattr(leaf, "ndim", None) == 0 or not getattr(
                leaf, "shape", ()):  # scalars replicate
            return P()
        for pattern, ps in rules:
            if re.search(pattern, name) is not None:
                return ps
        if default is not None:
            return default
        raise ValueError(
            f"match_partition_rules: no rule matches state leaf {name!r} "
            f"(rules: {[p for p, _ in rules]!r}); add a rule or pass "
            f"default=P()")

    return jax.tree_util.tree_map_with_path(spec, tree)


def state_sharding(mesh, rules, tree, default=None):
    """``NamedSharding`` pytree for ``tree`` under ``rules`` on ``mesh``
    — feed each leaf to ``jax.device_put``."""
    import jax
    from jax.sharding import NamedSharding

    specs = match_partition_rules(rules, tree, default=default)
    return jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), specs)


def device_put_state(tree, mesh, rules, default=None):
    """Place a named state pytree on ``mesh`` according to ``rules`` (one
    ``jax.device_put`` per leaf, each with its matched NamedSharding)."""
    import jax

    shardings = state_sharding(mesh, rules, tree, default=default)
    return jax.tree_util.tree_map(jax.device_put, tree, shardings)


def parallel_shard_map(fn, n: int, max_workers: Optional[int] = None) -> list:
    """``[fn(0), ..., fn(n-1)]`` computed on a thread pool, in shard order.

    File reads and the native C parsers (ctypes CDLL calls) release the
    GIL, so shard read+parse work runs truly concurrently — the fix for
    the serial drain that capped the source layer at one core
    (VERDICT r3 #3). Exceptions propagate from the failing shard.
    """
    if n <= 1:
        return [fn(i) for i in range(n)]
    from concurrent.futures import ThreadPoolExecutor
    workers = max_workers or min(n, os.cpu_count() or 4)
    with ThreadPoolExecutor(workers) as ex:
        return list(ex.map(fn, range(n)))
