"""Pipeline wrappers — clustering (reference pipeline/clustering/)."""

from ..operator.batch.clustering.kmeans_ops import (KMeansModelMapper,
                                                    KMeansPredictBatchOp,
                                                    KMeansTrainBatchOp,
                                                    _KMeansParams)
from ..params.shared import HasPredictionCol, HasReservedCols
from .base import MapModel, Trainer


class KMeansModel(MapModel, HasPredictionCol, HasReservedCols):
    MAPPER_CLS = KMeansModelMapper
    PREDICTION_DISTANCE_COL = KMeansPredictBatchOp.PREDICTION_DISTANCE_COL


class KMeans(Trainer, _KMeansParams, HasPredictionCol, HasReservedCols):
    TRAIN_OP_CLS = KMeansTrainBatchOp
    MODEL_CLS = KMeansModel
    PREDICTION_DISTANCE_COL = KMeansPredictBatchOp.PREDICTION_DISTANCE_COL


from ..operator.batch.clustering.lda_ops import (LdaModelMapper,  # noqa: E402
                                                 LdaTrainBatchOp, _LdaTrainParams)
from ..params.shared import HasPredictionDetailCol  # noqa: E402


class LdaModel(MapModel, HasPredictionCol, HasPredictionDetailCol,
               HasReservedCols):
    """reference: pipeline/clustering/LdaModel.java"""
    MAPPER_CLS = LdaModelMapper


class Lda(Trainer, _LdaTrainParams, HasPredictionCol, HasPredictionDetailCol,
          HasReservedCols):
    """reference: pipeline/clustering/Lda.java"""
    TRAIN_OP_CLS = LdaTrainBatchOp
    MODEL_CLS = LdaModel
