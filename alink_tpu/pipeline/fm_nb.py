"""Pipeline wrappers — FM + NaiveBayes + OneVsRest
(reference pipeline/classification/FmClassifier, NaiveBayes, OneVsRest)."""

from __future__ import annotations

import json
from typing import List, Optional

import numpy as np

from ..common.mtable import MTable
from ..common.types import AlinkTypes
from ..mapper.base import OutputColsHelper
from ..operator.base import BatchOperator, TableSourceBatchOp
from ..operator.batch.classification.fm_ops import (FmClassifierTrainBatchOp,
                                                    FmModelMapper,
                                                    FmRegressorTrainBatchOp)
from ..operator.batch.classification.naive_bayes import (
    NaiveBayesModelMapper, NaiveBayesTextModelMapper,
    NaiveBayesTextTrainBatchOp, NaiveBayesTrainBatchOp)
from ..operator.batch.evaluation.eval_ops import parse_detail_probs
from .base import Estimator, MapModel, Model, Trainer, Transformer, _as_op


def _wrap(name, train_op, mapper):
    from .base import caller_module
    mod = caller_module()
    model_cls = type(name + "Model", (MapModel,),
                     {"MAPPER_CLS": mapper, "__module__": mod})
    cls = type(name, (Trainer,), {"TRAIN_OP_CLS": train_op,
                                  "MODEL_CLS": model_cls, "__module__": mod})
    from ..params.shared import (HasPredictionCol, HasPredictionDetailCol,
                                 HasReservedCols)
    extra = {i.name: i for i in (HasPredictionCol.PREDICTION_COL,
                                 HasPredictionDetailCol.PREDICTION_DETAIL_COL,
                                 HasReservedCols.RESERVED_COLS)}
    cls._PARAM_INFOS = {**train_op._PARAM_INFOS, **extra, **cls._PARAM_INFOS}
    model_cls._PARAM_INFOS = dict(cls._PARAM_INFOS)
    return cls, model_cls


FmClassifier, FmClassifierModel = _wrap("FmClassifier", FmClassifierTrainBatchOp,
                                        FmModelMapper)
FmRegressor, FmRegressorModel = _wrap("FmRegressor", FmRegressorTrainBatchOp,
                                      FmModelMapper)
NaiveBayesTextClassifier, NaiveBayesTextModel = _wrap(
    "NaiveBayesTextClassifier", NaiveBayesTextTrainBatchOp, NaiveBayesTextModelMapper)
NaiveBayes, NaiveBayesModel = _wrap("NaiveBayes", NaiveBayesTrainBatchOp,
                                    NaiveBayesModelMapper)


from ..params.shared import (HasLabelCol, HasPredictionCol,
                             HasPredictionDetailCol, HasReservedCols)


class OneVsRestModel(Model, HasPredictionCol, HasPredictionDetailCol,
                     HasReservedCols):
    """reference: common/classification/OneVsRestModelMapper."""

    def __init__(self, models: Optional[List[Model]] = None,
                 labels: Optional[List] = None, params=None, **kwargs):
        super().__init__(params, **kwargs)
        self.models = models or []
        self.labels = labels or []

    def transform(self, in_op) -> BatchOperator:
        in_op = _as_op(in_op)
        data = in_op.get_output_table()
        probs = np.zeros((data.num_rows, len(self.models)))
        for j, sub in enumerate(self.models):
            sub_params = sub.params.clone()
            sub_params.set("prediction_col", "__ovr_pred")
            sub_params.set("prediction_detail_col", "__ovr_detail")
            sub2 = type(sub)(sub_params)
            sub2.set_model_data(sub.get_model_data())
            out = sub2.transform(in_op).get_output_table()
            _, p = parse_detail_probs(out.col("__ovr_detail"), "__positive__")
            probs[:, j] = p
        pick = probs.argmax(1)
        norm = probs / np.maximum(probs.sum(1, keepdims=True), 1e-12)
        preds = np.empty(data.num_rows, object)
        preds[:] = [self.labels[i] for i in pick]
        pred_col = self.params._m.get("prediction_col", "pred")
        detail_col = self.params._m.get("prediction_detail_col")
        label_type = self.params._m.get("label_type", AlinkTypes.STRING)
        cols, types, vals = [pred_col], [label_type], [preds]
        if detail_col:
            details = np.asarray(
                [json.dumps({str(l): float(p) for l, p in zip(self.labels, row)})
                 for row in norm], object)
            cols.append(detail_col)
            types.append(AlinkTypes.STRING)
            vals.append(details)
        helper = OutputColsHelper(data.schema, cols, types,
                                  self.params._m.get("reserved_cols"))
        return TableSourceBatchOp(helper.build_output(data, vals))


class OneVsRest(Estimator, HasPredictionCol, HasPredictionDetailCol,
                HasReservedCols):
    """Meta-estimator over any binary classifier (reference pipeline/classification/OneVsRest)."""
    LABEL_COL = HasLabelCol.LABEL_COL

    def __init__(self, classifier: Optional[Estimator] = None, params=None, **kwargs):
        super().__init__(params, **kwargs)
        self.classifier = classifier

    def fit(self, in_op) -> OneVsRestModel:
        in_op = _as_op(in_op)
        data = in_op.get_output_table()
        label_col = (self.params._m.get("label_col")
                     or self.classifier.params._m.get("label_col"))
        raw = data.col(label_col)
        labels = sorted({_canon(v) for v in raw}, key=str)
        models = []
        for c in labels:
            relabeled = data.add_column(
                label_col,
                np.asarray(["__positive__" if _canon(v) == c else "__rest__"
                            for v in raw], object),
                AlinkTypes.STRING)
            sub = self.classifier.clone()
            sub.params.set("positive_label_value_string", "__positive__")
            models.append(sub.fit(TableSourceBatchOp(relabeled)))
        model = OneVsRestModel(models, labels, self.params.clone())
        model.params.set("label_type", data.schema.type_of(label_col))
        if not model.params._m.get("prediction_col"):
            model.params.set("prediction_col",
                             self.classifier.params._m.get("prediction_col", "pred"))
        if self.classifier.params._m.get("prediction_detail_col") and \
                not model.params._m.get("prediction_detail_col"):
            model.params.set("prediction_detail_col",
                             self.classifier.params._m["prediction_detail_col"])
        return model


def _canon(v):
    return v.item() if isinstance(v, np.generic) else v
