"""Pipeline wrappers — clustering (reference pipeline/clustering/)."""

from ..operator.batch.clustering.kmeans_ops import (KMeansModelMapper,
                                                    KMeansPredictBatchOp,
                                                    KMeansTrainBatchOp,
                                                    _KMeansParams)
from ..params.shared import HasPredictionCol, HasReservedCols
from .base import MapModel, Trainer


class KMeansModel(MapModel, HasPredictionCol, HasReservedCols):
    MAPPER_CLS = KMeansModelMapper
    PREDICTION_DISTANCE_COL = KMeansPredictBatchOp.PREDICTION_DISTANCE_COL


class KMeans(Trainer, _KMeansParams, HasPredictionCol, HasReservedCols):
    TRAIN_OP_CLS = KMeansTrainBatchOp
    MODEL_CLS = KMeansModel
    PREDICTION_DISTANCE_COL = KMeansPredictBatchOp.PREDICTION_DISTANCE_COL
