"""AST index, import resolution, and the pragmatic reachability engine
the alink-lint rules share.

Design constraints:

  * **never import the analyzed code** — everything is ``ast``; the
    flag registry (the one piece of *data* the rules need) is loaded
    standalone from ``alink_tpu/common/flags.py`` via importlib, which
    is safe because that module is deliberately stdlib-only;
  * **total** — unresolvable names/calls degrade to "skip", never to a
    crash: the analyzer runs in the tier-1 gate, so a parse-level
    surprise must surface as a finding or a skip, not a traceback;
  * **over-approximate reachability** — scanning a function scans its
    whole lexical subtree (nested defs included) and follows calls it
    can resolve by name (same module, ``self.``-methods, and
    ``from``/``import`` targets inside the package). Dynamic dispatch
    (``stage.calc``) is out of reach by construction; the rules that
    care (TRACED-CAPTURE) find stage bodies at their registration
    sites (``.add(fn)``, ``jax.jit(fn)``, ``shard_map(fn)``) instead.
"""

from __future__ import annotations

import ast
import builtins
import importlib.util
import os
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

_BUILTINS = frozenset(dir(builtins))


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def load_flag_registry(path: Optional[str] = None):
    """The :data:`FLAGS` registry, loaded standalone (no alink_tpu /
    jax import) from ``alink_tpu/common/flags.py``."""
    if path is None:
        path = os.path.join(repo_root(), "alink_tpu", "common", "flags.py")
    spec = importlib.util.spec_from_file_location("_alink_lint_flags", path)
    mod = importlib.util.module_from_spec(spec)
    # dataclasses resolves string annotations through sys.modules at
    # class-creation time — the module must be registered before exec
    import sys
    sys.modules[spec.name] = mod
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.modules.pop(spec.name, None)
    return mod.FLAGS


@dataclass(frozen=True)
class Finding:
    """One rule violation. ``ident`` is the stable baseline-matching
    token (never a line number, so baselines survive reformatting)."""
    rule: str
    file: str          # repo-relative posix path
    line: int
    ident: str
    message: str
    flag: str = ""

    @property
    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.file, self.ident)

    def render(self) -> str:
        return f"{self.file}:{self.line}: {self.rule} [{self.ident}] " \
               f"{self.message}"

    def to_json(self) -> Dict[str, Any]:
        return {"rule": self.rule, "file": self.file, "line": self.line,
                "ident": self.ident, "flag": self.flag,
                "message": self.message}


# ---------------------------------------------------------------------------
# module index
# ---------------------------------------------------------------------------

@dataclass
class FunctionInfo:
    qualname: str                  # "fn" | "Class.method"
    node: ast.AST                  # FunctionDef / AsyncFunctionDef
    module: "ModuleInfo" = field(repr=False, default=None)


@dataclass
class ModuleInfo:
    path: str                      # repo-relative posix
    modname: str                   # "alink_tpu.engine.comqueue"
    tree: ast.Module
    functions: Dict[str, FunctionInfo] = field(default_factory=dict)
    # local binding name -> fully qualified target ("jax.numpy",
    # "alink_tpu.common.metrics.env_flag", ...)
    imports: Dict[str, str] = field(default_factory=dict)
    classes: Dict[str, ast.ClassDef] = field(default_factory=dict)
    # module-level NAME = "string literal" constants (FAULT_ENV = "...")
    str_constants: Dict[str, str] = field(default_factory=dict)


def _module_name(relpath: str) -> str:
    mod = relpath[:-3] if relpath.endswith(".py") else relpath
    mod = mod.replace("/", ".").replace("\\", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def _resolve_relative(modname: str, level: int, target: Optional[str],
                      is_package: bool) -> str:
    """Absolute module for a ``from ...x import y`` node."""
    parts = modname.split(".")
    # a non-package module's level-1 import resolves to its parent pkg
    cut = len(parts) - (level - (1 if is_package else 0))
    base = parts[:max(cut, 0)]
    if target:
        base = base + target.split(".")
    return ".".join(base)


class ModuleIndex:
    """Parsed ``*.py`` files under one or more roots, with per-module
    function tables and import maps."""

    def __init__(self):
        self.modules: Dict[str, ModuleInfo] = {}      # modname -> info
        self.by_path: Dict[str, ModuleInfo] = {}      # relpath -> info
        # files that failed to parse, surfaced as PARSE-ERROR findings
        # by run_lint — the analyzer's "total" contract: a broken file
        # in the gate must be a diagnostic, never a traceback
        self.parse_errors: List[Finding] = []

    @classmethod
    def build(cls, root: str, package_dirs: Sequence[str]) -> "ModuleIndex":
        idx = cls()
        for pkg in package_dirs:
            base = os.path.join(root, pkg)
            if os.path.isfile(base) and base.endswith(".py"):
                idx.add_file(root, os.path.relpath(base, root))
                continue
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"
                               and not d.startswith(".")]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        rel = os.path.relpath(os.path.join(dirpath, fn), root)
                        idx.add_file(root, rel)
        return idx

    def add_file(self, root: str, relpath: str) -> Optional[ModuleInfo]:
        relpath = relpath.replace(os.sep, "/")
        with open(os.path.join(root, relpath), "r") as f:
            src = f.read()
        try:
            tree = ast.parse(src, filename=relpath)
        except (SyntaxError, ValueError) as e:
            line = getattr(e, "lineno", None) or 1
            self.parse_errors.append(Finding(
                "PARSE-ERROR", relpath, line, "syntax",
                f"file does not parse ({e.msg if isinstance(e, SyntaxError) else e}) — "
                f"no rule can analyze it"))
            return None
        info = ModuleInfo(path=relpath, modname=_module_name(relpath),
                          tree=tree)
        is_pkg = relpath.endswith("__init__.py")
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    info.imports[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
                    if a.asname:
                        info.imports[a.asname] = a.name
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    base = _resolve_relative(info.modname, node.level,
                                             node.module, is_pkg)
                for a in node.names:
                    if a.name == "*":
                        continue
                    info.imports[a.asname or a.name] = \
                        f"{base}.{a.name}" if base else a.name
        for node in tree.body:
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Constant) and isinstance(
                    node.value.value, str):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        info.str_constants[t.id] = node.value.value
            elif isinstance(node, ast.AnnAssign) and isinstance(
                    node.target, ast.Name) and isinstance(
                    node.value, ast.Constant) and isinstance(
                    node.value.value, str):
                info.str_constants[node.target.id] = node.value.value
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info.functions[node.name] = FunctionInfo(node.name, node, info)
            elif isinstance(node, ast.ClassDef):
                info.classes[node.name] = node
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        q = f"{node.name}.{sub.name}"
                        info.functions[q] = FunctionInfo(q, sub, info)
        self.modules[info.modname] = info
        self.by_path[relpath] = info
        return info

    # -- resolution --------------------------------------------------------
    def resolve_symbol(self, fq: str) -> Optional[FunctionInfo]:
        """``alink_tpu.engine.recovery.drive`` -> FunctionInfo, by the
        longest known module prefix."""
        parts = fq.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            mod = self.modules.get(".".join(parts[:cut]))
            if mod is not None:
                qual = ".".join(parts[cut:])
                return mod.functions.get(qual)
        return None

    def resolve_call(self, call: ast.Call, mod: ModuleInfo,
                     cls_name: str = "") -> Optional[FunctionInfo]:
        """Best-effort: Name() in same module / imported; self.m();
        imported_module.fn()."""
        fn = call.func
        if isinstance(fn, ast.Name):
            got = mod.functions.get(fn.id)
            if got is not None:
                return got
            fq = mod.imports.get(fn.id)
            if fq is not None:
                return self.resolve_symbol(fq)
            return None
        if isinstance(fn, ast.Attribute):
            v = fn.value
            if isinstance(v, ast.Name):
                if v.id in ("self", "cls") and cls_name:
                    return mod.functions.get(f"{cls_name}.{fn.attr}")
                fq = mod.imports.get(v.id)
                if fq is not None:
                    return self.resolve_symbol(f"{fq}.{fn.attr}")
        return None


# ---------------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------------

def dotted_name(node: ast.AST) -> str:
    """"jax.lax.psum" for an Attribute/Name chain; "" when not a plain
    chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


@dataclass(frozen=True)
class EnvRead:
    """One env-var read site."""
    name: str            # flag name, or "<dynamic>"
    line: int
    how: str             # "os.environ" | "env_flag" | "flag_value" | ...


_FLAG_READERS = {
    # resolved fq name -> takes flag name as first positional arg
    "alink_tpu.common.flags.env_flag",
    "alink_tpu.common.flags.flag_value",
    "alink_tpu.common.flags.flag_raw",
    "alink_tpu.common.metrics.env_flag",
}


def _env_name_arg(node: ast.AST, mod: ModuleInfo,
                  index: Optional["ModuleIndex"]) -> Optional[str]:
    """The flag name of an env-read argument: a string literal, a
    module-level string constant (``FAULT_ENV``), or a constant imported
    from another indexed module."""
    got = const_str(node)
    if got is not None:
        return got
    if isinstance(node, ast.Name):
        got = mod.str_constants.get(node.id)
        if got is not None:
            return got
        fq = mod.imports.get(node.id)
        if fq is not None and index is not None and "." in fq:
            owner, attr = fq.rsplit(".", 1)
            src = index.modules.get(owner)
            if src is not None:
                return src.str_constants.get(attr)
    return None


def env_reads_in(node: ast.AST, mod: ModuleInfo,
                 index: Optional[ModuleIndex] = None) -> List[EnvRead]:
    """Every env read lexically inside ``node``: ``os.environ`` get/
    subscript/contains, plus calls to the registry accessors
    (``env_flag``/``flag_value``/``flag_raw``) resolved through the
    module's imports. Name arguments resolve through module-level
    string constants (``FAULT_ENV = "..."``) before degrading to
    ``<dynamic>``."""
    reads: List[EnvRead] = []
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            dn = dotted_name(n.func)
            if dn.endswith("environ.get") and "environ" in dn:
                nm = _env_name_arg(n.args[0], mod, index) if n.args else None
                reads.append(EnvRead(nm or "<dynamic>", n.lineno,
                                     "os.environ"))
                continue
            # os.getenv — the standard alternative spelling, under any
            # import alias (import os as _o / from os import getenv)
            parts = dn.split(".")
            if parts[-1] == "getenv" and (
                    (len(parts) == 1
                     and mod.imports.get(dn) == "os.getenv")
                    or (len(parts) > 1
                        and mod.imports.get(parts[0]) == "os")):
                nm = _env_name_arg(n.args[0], mod, index) if n.args else None
                reads.append(EnvRead(nm or "<dynamic>", n.lineno,
                                     "os.getenv"))
                continue
            # env_flag("X") / flag_value("X") / flag_raw("X"), under
            # whatever local alias the import bound
            target = None
            if isinstance(n.func, ast.Name):
                target = mod.imports.get(n.func.id)
                if target is None and n.func.id in ("env_flag",
                                                    "flag_value",
                                                    "flag_raw"):
                    target = f"alink_tpu.common.flags.{n.func.id}"
            elif isinstance(n.func, ast.Attribute):
                base = dotted_name(n.func.value)
                if base:
                    root_alias = base.split(".")[0]
                    fq_base = mod.imports.get(root_alias)
                    if fq_base:
                        target = fq_base + base[len(root_alias):] \
                            + "." + n.func.attr
            if target in _FLAG_READERS or (
                    target and (target.endswith(".env_flag")
                                or target.endswith(".flag_value")
                                or target.endswith(".flag_raw"))
                    and target.startswith("alink_tpu.")):
                nm = _env_name_arg(n.args[0], mod, index) if n.args else None
                reads.append(EnvRead(nm or "<dynamic>", n.lineno,
                                     target.rsplit(".", 1)[-1]))
        elif isinstance(n, ast.Subscript):
            if dotted_name(n.value).endswith("environ"):
                nm = None if isinstance(n.slice, ast.Tuple) \
                    else _env_name_arg(n.slice, mod, index)
                if isinstance(n.ctx, ast.Load):
                    reads.append(EnvRead(nm or "<dynamic>", n.lineno,
                                         "os.environ"))
    return reads


@dataclass
class Reached:
    """One function reached from a factory root, with the call chain."""
    fn: FunctionInfo
    chain: Tuple[str, ...]


def reachable_functions(index: ModuleIndex, root: FunctionInfo,
                        max_depth: int = 10) -> List[Reached]:
    """Transitive closure of name-resolvable calls starting at ``root``
    (the root itself included). Each function's whole lexical subtree
    counts as scanned, so nested defs ride along for free."""
    seen: Set[Tuple[str, str]] = set()
    out: List[Reached] = []
    stack: List[Tuple[FunctionInfo, Tuple[str, ...], int]] = [
        (root, (root.qualname,), 0)]
    while stack:
        fi, chain, depth = stack.pop()
        key = (fi.module.modname, fi.qualname)
        if key in seen:
            continue
        seen.add(key)
        out.append(Reached(fi, chain))
        if depth >= max_depth:
            continue
        cls = fi.qualname.split(".")[0] if "." in fi.qualname else ""
        for n in ast.walk(fi.node):
            if isinstance(n, ast.Call):
                got = index.resolve_call(n, fi.module, cls_name=cls)
                if got is not None:
                    stack.append((got, chain + (got.qualname,), depth + 1))
    return out


# -- scope / capture analysis (TRACED-CAPTURE, DONATE-USE-AFTER) ------------

def bound_names(fnode: ast.AST) -> Set[str]:
    """Every name BOUND anywhere in ``fnode``'s subtree: params (of any
    nested def/lambda too), assignment/for/with/except targets,
    imports, def/class names, comprehension targets."""
    bound: Set[str] = set()
    outward: Set[str] = set()      # global/nonlocal declarations
    for n in ast.walk(fnode):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            a = n.args
            for p in (list(a.posonlyargs) + list(a.args)
                      + list(a.kwonlyargs)):
                bound.add(p.arg)
            if a.vararg:
                bound.add(a.vararg.arg)
            if a.kwarg:
                bound.add(a.kwarg.arg)
            if not isinstance(n, ast.Lambda):
                bound.add(n.name)
        elif isinstance(n, ast.ClassDef):
            bound.add(n.name)
        elif isinstance(n, ast.Name) and isinstance(
                n.ctx, (ast.Store, ast.Del)):
            bound.add(n.id)
        elif isinstance(n, (ast.Import, ast.ImportFrom)):
            for al in n.names:
                bound.add((al.asname or al.name).split(".")[0])
        elif isinstance(n, ast.ExceptHandler) and n.name:
            bound.add(n.name)
        elif isinstance(n, (ast.Global, ast.Nonlocal)):
            # declared names bind OUTSIDE this scope
            outward.update(n.names)
    return bound - outward


def free_names(fnode: ast.AST) -> Set[str]:
    """Names loaded in ``fnode``'s subtree but bound nowhere inside it
    (and not builtins) — closure captures or module globals."""
    bound = bound_names(fnode)
    free: Set[str] = set()
    for n in ast.walk(fnode):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
            if n.id not in bound and n.id not in _BUILTINS:
                free.add(n.id)
    return free


def iter_statements(body: Iterable[ast.stmt]):
    """Flatten a statement list in source order, descending into
    compound statements' bodies (If/For/While/With/Try) but NOT into
    nested function/class definitions."""
    for stmt in body:
        yield stmt
        for sub in ast.iter_child_nodes(stmt):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                continue
            if isinstance(sub, ast.stmt):
                yield from iter_statements([sub])
            elif isinstance(sub, ast.ExceptHandler):
                yield from iter_statements(sub.body)
