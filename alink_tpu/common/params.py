"""Typed parameter system.

TPU-native re-design of the reference's params stack:
  - ``Params``      <- org/apache/flink/ml/api/misc/param/Params.java:19-90
                       (a JSON-serializable string->value map with typed access)
  - ``ParamInfo``   <- ParamInfo/ParamInfoFactory (name, description, optional,
                       default, aliases, validator)
  - ``WithParams``  <- WithParams + the 433 ``Has*`` mixin interfaces
                       (e.g. params/shared/iter/HasMaxIterDefaultAs100.java:11-26).

Design notes (not a port):
  - ``Has*`` mixins are plain Python classes holding ``ParamInfo`` class
    attributes; a metaclass scans the MRO and generates fluent
    ``set_<name>/get_<name>`` methods (both snake_case and camelCase
    spellings are accepted as aliases, mirroring the reference's alias
    machinery).
  - Values are stored as plain Python objects and serialized with json;
    the reference stores JSON strings per key (Params.java:19-33) which we
    keep only at the (de)serialization boundary.
"""

from __future__ import annotations

import json
import re
from typing import Any, Callable, Dict, Iterable, Optional, Sequence


def _snake(name: str) -> str:
    s = re.sub(r"(.)([A-Z][a-z]+)", r"\1_\2", name)
    s = re.sub(r"([a-z0-9])([A-Z])", r"\1_\2", s)
    return s.lower()


def _camel(name: str) -> str:
    parts = name.split("_")
    return parts[0] + "".join(p.title() for p in parts[1:])


class ParamValidator:
    """Validator contract (reference: params/validators/ParamValidator)."""

    def validate(self, value) -> bool:  # pragma: no cover - interface
        return True

    def describe(self) -> str:
        return ""


class RangeValidator(ParamValidator):
    """Closed/open range check (reference: params/validators/RangeValidator.java)."""

    def __init__(self, min_val=None, max_val=None, left_inclusive=True, right_inclusive=True):
        self.min_val, self.max_val = min_val, max_val
        self.left_inclusive, self.right_inclusive = left_inclusive, right_inclusive

    def validate(self, value) -> bool:
        if value is None:
            return True
        if self.min_val is not None:
            if self.left_inclusive and value < self.min_val:
                return False
            if not self.left_inclusive and value <= self.min_val:
                return False
        if self.max_val is not None:
            if self.right_inclusive and value > self.max_val:
                return False
            if not self.right_inclusive and value >= self.max_val:
                return False
        return True

    def describe(self) -> str:
        lo = "[" if self.left_inclusive else "("
        hi = "]" if self.right_inclusive else ")"
        return f"{lo}{self.min_val}, {self.max_val}{hi}"


class InValidator(ParamValidator):
    def __init__(self, allowed: Sequence[Any]):
        self.allowed = list(allowed)

    def validate(self, value) -> bool:
        return value is None or value in self.allowed

    def describe(self) -> str:
        return f"in {self.allowed}"


class MinValidator(RangeValidator):
    def __init__(self, min_val, inclusive=True):
        super().__init__(min_val=min_val, left_inclusive=inclusive)


class ParamInfo:
    """Descriptor for one typed parameter (reference ParamInfoFactory builder)."""

    __slots__ = ("name", "type", "description", "optional", "has_default",
                 "default", "aliases", "validator")

    def __init__(self, name: str, type_: type = object, description: str = "",
                 optional: bool = True, has_default: bool = False, default: Any = None,
                 aliases: Sequence[str] = (), validator: Optional[ParamValidator] = None):
        self.name = _snake(name)
        self.type = type_
        self.description = description
        self.optional = optional
        # mirror ParamInfoFactory: setting a default implies having one
        self.has_default = has_default or default is not None
        self.default = default
        base_aliases = {self.name, _camel(self.name), name}
        base_aliases.update(aliases)
        base_aliases.update(_camel(a) if "_" in a else _snake(a) for a in tuple(aliases))
        self.aliases = tuple(sorted(base_aliases))
        self.validator = validator

    def __repr__(self):
        return f"ParamInfo({self.name!r}, {getattr(self.type, '__name__', self.type)})"

    def check(self, value):
        if self.validator is not None and not self.validator.validate(value):
            raise ValueError(
                f"param {self.name}={value!r} fails validation {self.validator.describe()}")
        return value


class Params:
    """JSON-round-trippable parameter map with typed access.

    Mirrors the observable behavior of the reference ``Params``
    (get with default fallback / required-missing error, contains, remove,
    merge, clone, to/from json) without its string-per-key storage.
    """

    def __init__(self, init: Optional[Dict[str, Any]] = None):
        self._m: Dict[str, Any] = {}
        if init:
            for k, v in init.items():
                self._m[_snake(k)] = v

    # -- primitive access ------------------------------------------------
    def set(self, info, value) -> "Params":
        if isinstance(info, ParamInfo):
            info.check(value)
            self._m[info.name] = value
        else:
            self._m[_snake(str(info))] = value
        return self

    def get(self, info: "ParamInfo"):
        for a in info.aliases:
            key = _snake(a)
            if key in self._m:
                return self._m[key]
        if info.has_default:
            return info.default
        if info.optional:
            return None
        raise KeyError(f"required param '{info.name}' is not set and has no default")

    def contains(self, info) -> bool:
        if isinstance(info, ParamInfo):
            return any(_snake(a) in self._m for a in info.aliases)
        return _snake(str(info)) in self._m

    def remove(self, info) -> "Params":
        if isinstance(info, ParamInfo):
            for a in info.aliases:
                self._m.pop(_snake(a), None)
        else:
            self._m.pop(_snake(str(info)), None)
        return self

    def merge(self, other: Optional["Params"]) -> "Params":
        if other is not None:
            self._m.update(other._m)
        return self

    def clone(self) -> "Params":
        p = Params()
        p._m = dict(self._m)
        return p

    def keys(self):
        return self._m.keys()

    def items(self):
        return self._m.items()

    def size(self) -> int:
        return len(self._m)

    def is_empty(self) -> bool:
        return not self._m

    def clear(self):
        self._m.clear()

    # -- serialization ---------------------------------------------------
    def to_json(self) -> str:
        return json.dumps(self._m, sort_keys=True, default=_json_default)

    @staticmethod
    def from_json(s: str) -> "Params":
        return Params(json.loads(s) if s else {})

    def __eq__(self, other):
        return isinstance(other, Params) and self._m == other._m

    def __repr__(self):
        return f"Params({self._m})"


def _json_default(o):
    try:
        import numpy as np
        if isinstance(o, np.generic):
            return o.item()
        if isinstance(o, np.ndarray):
            return o.tolist()
    except ImportError:  # pragma: no cover
        pass
    return str(o)


class _WithParamsMeta(type):
    """Generates fluent setters/getters for every ParamInfo found in the MRO."""

    def __new__(mcls, name, bases, ns):
        cls = super().__new__(mcls, name, bases, ns)
        infos = {}
        # inherit param maps assigned post-hoc on bases (the _trainer /
        # `_PARAM_INFOS = SomeBatchOp._PARAM_INFOS` delegation patterns)
        for klass in reversed(cls.__mro__[1:]):
            base_infos = klass.__dict__.get("_PARAM_INFOS")
            if isinstance(base_infos, dict):
                infos.update(base_infos)
        for klass in reversed(cls.__mro__):
            for k, v in vars(klass).items():
                if isinstance(v, ParamInfo):
                    infos[v.name] = v
        declared = ns.get("_PARAM_INFOS")
        if isinstance(declared, dict):
            infos.update(declared)
        cls._PARAM_INFOS = infos
        for pname, info in infos.items():
            setter = f"set_{pname}"
            getter = f"get_{pname}"
            # regenerate inherited accessors so a subclass overriding a
            # ParamInfo (the Has*DefaultAsN pattern) binds its own info;
            # hand-written methods (no _param_info tag) always win.
            for attr, make in ((setter, mcls._make_setter), (getter, mcls._make_getter)):
                if attr in ns:
                    continue
                existing = getattr(cls, attr, None)
                existing_info = getattr(existing, "_param_info", None)
                if existing is None or (existing_info is not None
                                        and existing_info is not info):
                    setattr(cls, attr, make(info))
        return cls

    @staticmethod
    def _make_setter(info):
        def _set(self, value):
            self.params.set(info, value)
            return self
        _set.__name__ = f"set_{info.name}"
        _set.__doc__ = info.description
        _set._param_info = info
        return _set

    @staticmethod
    def _make_getter(info):
        def _get(self):
            return self.params.get(info)
        _get.__name__ = f"get_{info.name}"
        _get.__doc__ = info.description
        _get._param_info = info
        return _get


class WithParams(metaclass=_WithParamsMeta):
    """Base for anything carrying a Params bag with fluent accessors."""

    def __init__(self, params: Optional[Params] = None, **kwargs):
        self.params = params.clone() if params is not None else Params()
        unknown = []
        for k, v in kwargs.items():
            key = _snake(k)
            info = self._PARAM_INFOS.get(key)
            if info is not None:
                self.params.set(info, v)
            else:
                # accept aliases of any declared info
                for cand in self._PARAM_INFOS.values():
                    if key in (_snake(a) for a in cand.aliases):
                        self.params.set(cand, v)
                        break
                else:
                    unknown.append(k)
        if unknown:
            raise TypeError(f"{type(self).__name__}: unknown params {unknown}; "
                            f"known: {sorted(self._PARAM_INFOS)}")

    @classmethod
    def param_infos(cls) -> Dict[str, ParamInfo]:
        return dict(cls._PARAM_INFOS)

    def get_params(self) -> Params:
        return self.params
