"""Multi-chip serving: mesh-sharded bucket programs (ISSUE 11 tentpole).

PR 10's serving programs were single-device: a feature-sharded FTRL
model had to gather to one chip before it could serve, and QPS was
capped at one chip no matter how wide ``ALINK_TPU_MESH_DEVICES`` made
the session mesh. This module is where the serving tier meets the
sharded execution path (PR 9):

* :func:`serving_mesh` — the 1-D ``('d',)`` serving mesh over the
  session's devices (the same devices the engine's BSP programs span);
* :func:`make_linear_sharded_fns` — the linear score kernel as a
  ``shard_map`` program: the model's feature axis is partitioned
  ``P('d')`` (the ``io/sharding.py`` placement the FTRL trainer already
  uses for its (z, n) state), each shard reduces its own feature slice,
  and ONE :func:`~alink_tpu.engine.communication.manifest_psum` per
  dispatch combines the partial sums — through the manifest wrappers,
  so the collective manifest (and fusion accounting) sees serving
  traffic exactly like training traffic;
* :func:`seq_chunk_sum` / :func:`lane_partials` — the canonical
  fixed-order reductions every serving kernel builds on.

**The mesh-size-invariance contract.** Serving results must not depend
on how many chips the mesh has — a fleet mixing 1-, 4- and 8-chip
replica groups must answer bitwise-identically. Plain "reduce locally,
psum the partials" breaks that: float addition is non-associative, so a
4-way split rounds differently from an 8-way split. The sharded kernels
therefore reduce in a FIXED lane structure independent of the mesh:

1. the (padded) feature axis splits into ``SERVE_LANES`` (= 8)
   contiguous lanes — a constant, NOT the shard count;
2. each lane reduces strictly left-to-right (:func:`seq_chunk_sum`) on
   whichever shard owns it (shard counts must divide ``SERVE_LANES``,
   so every lane lives whole on exactly one shard);
3. the per-lane partials cross shards as ONE psum of a ``(rows,
   SERVE_LANES)`` buffer in which each lane is non-zero on exactly one
   shard — adding zeros is exact, so the psum reconstructs every lane
   partial bitwise no matter the shard count or reduction order
   (a ``+ 0.0`` canonicalization pins the one IEEE edge, ``-0.0``);
4. every shard then reduces the 8 lane partials in the same strict
   left-to-right order.

Steps 1-4 are literally the same arithmetic at mesh size 1, 2, 4 and 8,
which is what `tests/test_serving_sharded.py` pins bitwise.

The sparse kernel uses the same trick one level down: each gathered
``val * w[idx]`` term is owned by exactly one shard (the one holding
that feature), the ``(rows, width)`` term buffer psums exactly, and the
width-axis reduction runs identically everywhere.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

# The serving reduction granule: feature axes pad to multiples of
# SERVE_CHUNK and reduce CHUNK terms per scan step in strict
# left-to-right order (the PR-10 bucket-invariance contract).
SERVE_CHUNK = 8
# Fixed lane count of the mesh-size-invariant blocked reduction: shard
# counts must divide it (1/2/4/8 — the host-platform mesh sizes the
# scaling evidence runs). Feature axes of SHARDED kernels pad to
# multiples of SERVE_LANES * SERVE_CHUNK so every lane is a whole
# number of scan chunks.
SERVE_LANES = 8
LANE_PAD = SERVE_LANES * SERVE_CHUNK


def serve_sharded_enabled() -> bool:
    """``ALINK_TPU_SERVE_SHARDED``: compile serving bucket programs under
    the session mesh's partition rules (feature-sharded model state, one
    psum per dispatch). Default off — single-device programs."""
    from ..common.flags import flag_value
    return bool(flag_value("ALINK_TPU_SERVE_SHARDED", False))


def serve_replicas() -> int:
    """``ALINK_TPU_SERVE_REPLICAS``: serving-loop replica count of
    :class:`~alink_tpu.serving.server.PredictServer` (data-parallel
    dispatch fan-out across the session mesh's chips). 0 = one replica
    per mesh device; default 1 = the historical single loop.

    Every replica loop runs SUPERVISED (ISSUE 14): a crash — anything
    escaping the per-batch failure handling, e.g. an injected
    ``serve.dispatch`` kill or an admission-channel fault — quarantines
    the replica's in-flight batch (typed ``ReplicaCrashed`` through
    each unresolved future, never silence) and respawns the loop, so
    one bad replica degrades capacity instead of stranding requests
    (``alink_serve_loop_respawns_total``)."""
    from ..common.flags import flag_value
    return int(flag_value("ALINK_TPU_SERVE_REPLICAS", 1))


def serving_mesh(devices: Optional[Sequence] = None):
    """The 1-D ``('d',)`` serving mesh.

    Defaults to the session's devices (``MLEnvironmentFactory.
    get_default()``, sized by ``ALINK_TPU_MESH_DEVICES``) flattened to
    one data axis: serving shards the model's FEATURE axis over 'd',
    the placement :func:`~alink_tpu.operator.stream.onlinelearning.ftrl.
    ftrl_state_rules` already uses for the trainer's (z, n) state, so a
    feature-sharded model swaps in place with no re-layout."""
    import numpy as np
    from jax.sharding import Mesh
    if devices is None:
        from ..common.mlenv import MLEnvironmentFactory
        env = MLEnvironmentFactory.get_default()
        devices = list(env.mesh.devices.reshape(-1))
    return Mesh(np.asarray(devices), ("d",))


def mesh_fingerprint(mesh) -> Optional[Tuple]:
    """Hashable mesh identity for the serving program-cache key: device
    ids + axis names. A different mesh (or sharded-vs-unsharded) can
    therefore never reuse a stale compiled serving program — the fold
    the ``ALINK_TPU_SERVE_SHARDED`` registry entry points at."""
    if mesh is None:
        return None
    return (tuple(int(d.id) for d in mesh.devices.reshape(-1)),
            tuple(mesh.axis_names))


# -- canonical fixed-order reductions ---------------------------------------

def seq_chunk_sum(terms, axis: int):
    """Sum ``terms`` over ``axis`` in a FIXED left-to-right order
    (chunked ``lax.scan`` of elementwise adds): unlike ``jnp.sum`` /
    ``@``, the float rounding cannot depend on the other dimensions'
    sizes, which is what makes serving buckets numerical no-ops. Extents
    beyond the unroll threshold must be a multiple of ``SERVE_CHUNK``
    (encoders pad)."""
    import jax
    import jax.numpy as jnp
    t = jnp.moveaxis(terms, axis, 0)
    ext = t.shape[0]
    acc0 = jnp.zeros(t.shape[1:], t.dtype)
    if ext <= 16 * SERVE_CHUNK:
        # small extents unroll in-trace: same strict order, none of the
        # scan loop's per-step dispatch overhead (the serial bucket-1
        # program's latency lives here)
        acc = acc0
        for j in range(ext):
            acc = acc + t[j]
        return acc
    m = ext // SERVE_CHUNK
    t = t.reshape((m, SERVE_CHUNK) + t.shape[1:])

    def body(acc, chunk):
        for k in range(SERVE_CHUNK):
            acc = acc + chunk[k]
        return acc, None

    acc, _ = jax.lax.scan(body, acc0, t)
    return acc


def scan_sum(terms, axis: int):
    """Strict left-to-right sum over ``axis`` as a ``lax.scan`` with the
    term buffer as xs — ALWAYS the loop form, never unrolled.

    The while-loop boundary keeps the producer multiply out of the add
    chain (XLA does not fuse across it), so every term rounds before it
    is added and the chain is pure float adds — deterministic under any
    vectorization. This is the reduction the tree/FM serving kernels
    use: it makes their device scores bitwise-reproducible across shape
    buckets AND bitwise-equal to a host numpy loop that adds the same
    rounded products in the same order."""
    import jax
    import jax.numpy as jnp
    t = jnp.moveaxis(terms, axis, 0)

    def body(acc, x):
        return acc + x, None

    acc, _ = jax.lax.scan(body, jnp.zeros(t.shape[1:], t.dtype), t)
    return acc


def lane_partials(terms, lanes: int):
    """Per-lane strict left-to-right partial sums: ``terms`` ``(rows,
    ext)`` split into ``lanes`` contiguous blocks, each reduced to one
    partial -> ``(rows, lanes)``.

    The reduction is a ``lax.scan`` whose xs are the MATERIALIZED term
    buffer, on purpose: an inline/unrolled add chain lets the backend
    contract the producer multiply into the adds as FMA, and whether it
    does depends on the operand shapes — measured on CPU, the same lane
    then rounds ONE ULP differently on a 1-device and an 8-device mesh
    (``optimization_barrier`` does not survive to codegen, so it cannot
    fence this). XLA never fuses across a while-loop boundary, so the
    scan body sees already-rounded terms and is a pure float-add chain
    — deterministic under any vectorization, hence bitwise identical at
    every mesh size."""
    import jax
    import jax.numpy as jnp
    rows, ext_total = terms.shape
    ext = ext_total // lanes
    t = terms.reshape(rows, lanes, ext)
    t = jnp.moveaxis(t, 2, 0)                  # (ext, rows, lanes)

    def body(acc, x):
        return acc + x, None

    acc, _ = jax.lax.scan(body, jnp.zeros((rows, lanes), terms.dtype), t)
    return acc


def ordered_lane_reduce(lanes_arr):
    """Strict left-to-right reduce of the ``(rows, L)`` lane partials —
    step 4 of the invariance contract, identical at every mesh size."""
    acc = lanes_arr[:, 0]
    for j in range(1, lanes_arr.shape[1]):
        acc = acc + lanes_arr[:, j]
    return acc


# -- the linear family's sharded score programs -----------------------------

def linear_partition_rules():
    """Partition rules (the ``io/sharding.py`` ``match_partition_rules``
    idiom) for the linear serving kernel's model arrays: the weight
    vector shards over the mesh feature axis 'd' — the serving-side twin
    of ``ftrl_state_rules()`` — and everything else (intercept)
    replicates."""
    from jax.sharding import PartitionSpec as P
    return ((r"^w$", P("d")),)


def linear_input_specs(kind: str):
    """PartitionSpecs of the ENCODED request arrays: the dense design
    matrix shards its feature axis alongside the weights; the sparse
    (idx, val) pair replicates (each shard masks to the features it
    owns)."""
    from jax.sharding import PartitionSpec as P
    if kind == "dense":
        return (P(None, "d"),)
    return (P(), P())


def make_linear_device_fns(mesh) -> Dict[str, callable]:
    """The binary/regression linear score kernel as mesh-sharded
    programs: ``{kind: fn(model_arrays, *encoded)}``, drop-in twins of
    the single-device ``device_fns`` the predictor jits per bucket.

    One ``manifest_psum`` per dispatch crosses the feature-axis partial
    sums between shards; results are bitwise-identical at every mesh
    size dividing ``SERVE_LANES`` (module docstring contract).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..common.compat import shard_map
    from ..engine.communication import manifest_psum

    n_shards = int(mesh.devices.size)
    if SERVE_LANES % n_shards:
        raise ValueError(
            f"serving mesh has {n_shards} devices, which does not divide "
            f"SERVE_LANES={SERVE_LANES}; the lane-blocked reduction "
            f"cannot keep results mesh-size-invariant")
    lanes_local = SERVE_LANES // n_shards

    def _dense_local(w_loc, X_loc):
        # this shard's contiguous feature slice: lanes
        # [idx*lanes_local, (idx+1)*lanes_local)
        part = lane_partials(X_loc * w_loc[None, :], lanes_local)
        lanes = jnp.zeros((X_loc.shape[0], SERVE_LANES), part.dtype)
        idx = jax.lax.axis_index("d")
        lanes = jax.lax.dynamic_update_slice(
            lanes, part, (jnp.zeros((), idx.dtype), idx * lanes_local))
        # each lane non-zero on exactly one shard -> the psum is exact
        lanes = manifest_psum(lanes, "d", name="serve_dense_lanes",
                              num_workers=n_shards)
        # canonicalize -0.0 lane partials (x + 0.0) so a lane that
        # psummed against zeros (mesh > 1) and one that did not
        # (mesh 1) agree bitwise even on signed zeros
        return ordered_lane_reduce(lanes + 0.0)

    def _dense(mdl, X):
        w, b = mdl
        score = shard_map(_dense_local, mesh=mesh,
                          in_specs=(P("d"), P(None, "d")),
                          out_specs=P())(w, X)
        return score + b

    def _sparse_local(w_loc, idx, val):
        block = w_loc.shape[0]
        off = jax.lax.axis_index("d") * block
        loc = idx - off
        mine = (loc >= 0) & (loc < block)
        g = jnp.where(mine, val * w_loc[jnp.clip(loc, 0, block - 1)], 0.0)
        # every (row, slot) term is owned by exactly one shard: the term
        # buffer psums exactly, then reduces in the same strict order
        # at every mesh size
        g = manifest_psum(g, "d", name="serve_sparse_terms",
                          num_workers=n_shards)
        return seq_chunk_sum(g + 0.0, axis=1)

    def _sparse(mdl, idx, val):
        w, b = mdl
        score = shard_map(_sparse_local, mesh=mesh,
                          in_specs=(P("d"), P(), P()),
                          out_specs=P())(w, idx, val)
        return score + b

    return {"dense": _dense, "sparse": _sparse}


def make_linear_fleet_fns() -> Dict[str, callable]:
    """The binary/regression linear score kernel as TENANT-LANE-stacked
    programs (ISSUE 17): ``{kind: fn(stacked_model_arrays, lane,
    *encoded)}`` where each model array gained a leading tenant-lane
    axis — ``W (L, dim8)``, ``b (L,)`` — and ``lane`` is the per-row
    int32 tenant->lane index (the tuning ``(points,)`` carry-lane idiom
    applied to serving weights).

    Bitwise contract (the fleet's coalescing proof): per request row,
    ``(X * W[lane])[i] == X[i] * w_tenant`` elementwise,
    :func:`seq_chunk_sum` reduces the feature axis in the SAME strict
    left-to-right order regardless of what the other rows of the batch
    hold, and ``+ b[lane]`` is the same scalar add — so a row served in
    a coalesced cross-tenant batch is bitwise-identical to the same row
    served through its tenant's own single-model bucket program
    (tests/test_fleet.py pins it). Padding lanes (zero weights) are
    gathered only by padding rows, which are sliced off at decode.
    """

    def _dense(mdls, lane, X):
        W, b = mdls                       # (L, dim8), (L,)
        return seq_chunk_sum(X * W[lane], axis=1) + b[lane]

    def _sparse(mdls, lane, idx, val):
        W, b = mdls
        # per-row two-level gather: row i reads its own tenant's weight
        # slots — value-identical to the single-model w[idx] gather
        return seq_chunk_sum(val * W[lane[:, None], idx], axis=1) + b[lane]

    return {"dense": _dense, "sparse": _sparse}
