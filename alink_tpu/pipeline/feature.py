"""Pipeline wrappers — feature engineering + dataproc scalers
(reference pipeline/feature/ and pipeline/dataproc/)."""

from __future__ import annotations

from typing import Optional, Type

from ..operator.base import BatchOperator
from ..operator.batch.dataproc.indexers import (IndexToStringPredictBatchOp,
                                                StringIndexerPredictBatchOp,
                                                StringIndexerTrainBatchOp)
from ..operator.batch.dataproc.scalers import (
    ImputerPredictBatchOp, ImputerTrainBatchOp, MaxAbsScalerPredictBatchOp,
    MaxAbsScalerTrainBatchOp, MinMaxScalerPredictBatchOp,
    MinMaxScalerTrainBatchOp, StandardScalerPredictBatchOp,
    StandardScalerTrainBatchOp, _ColScalerMapper)
from ..operator.batch.dataproc.vector_ops import (
    VectorAssemblerBatchOp, VectorMaxAbsScalerTrainBatchOp,
    VectorMinMaxScalerTrainBatchOp, VectorNormalizeBatchOp,
    VectorScalerModelMapper, VectorStandardScalerTrainBatchOp)
from ..operator.batch.feature.feature_ops import (
    BinarizerBatchOp, BucketizerBatchOp, DCTBatchOp, FeatureHasherBatchOp,
    OneHotModelMapper, OneHotPredictBatchOp, OneHotTrainBatchOp,
    PcaModelMapper, PcaPredictBatchOp, PcaTrainBatchOp, _BucketMapperBase,
    QuantileDiscretizerTrainBatchOp)
from ..operator.batch.dataproc.indexers import StringIndexerModelMapper
from .base import Estimator, MapModel, Model, Trainer, Transformer, _as_op


class BatchOpTransformer(Transformer):
    """Stateless transformer backed by a batch op (reference MapTransformer)."""

    OP_CLS: Optional[Type[BatchOperator]] = None

    def transform(self, in_op) -> BatchOperator:
        return self.OP_CLS(self.params.clone()).link_from(_as_op(in_op))


def _trainer(name, train_op, mapper, extra_bases=()):
    from .base import caller_module
    mod = caller_module()
    model_cls = type(name + "Model", (MapModel,) + tuple(extra_bases),
                     {"MAPPER_CLS": mapper, "__module__": mod})
    cls = type(name, (Trainer,) + tuple(extra_bases),
               {"TRAIN_OP_CLS": train_op, "MODEL_CLS": model_cls,
                "__module__": mod})
    # inherit train-op + mapper params for kwargs validation
    mapper_infos = getattr(mapper, "_PARAM_INFOS", {})
    cls._PARAM_INFOS = {**train_op._PARAM_INFOS, **mapper_infos,
                        **cls._PARAM_INFOS}
    model_cls._PARAM_INFOS = {**train_op._PARAM_INFOS, **mapper_infos,
                              **model_cls._PARAM_INFOS}
    return cls, model_cls


StandardScaler, StandardScalerModel = _trainer(
    "StandardScaler", StandardScalerTrainBatchOp, _ColScalerMapper)
MinMaxScaler, MinMaxScalerModel = _trainer(
    "MinMaxScaler", MinMaxScalerTrainBatchOp, _ColScalerMapper)
MaxAbsScaler, MaxAbsScalerModel = _trainer(
    "MaxAbsScaler", MaxAbsScalerTrainBatchOp, _ColScalerMapper)
Imputer, ImputerModel = _trainer("Imputer", ImputerTrainBatchOp, _ColScalerMapper)
OneHotEncoder, OneHotEncoderModel = _trainer(
    "OneHotEncoder", OneHotTrainBatchOp, OneHotModelMapper)
QuantileDiscretizer, QuantileDiscretizerModel = _trainer(
    "QuantileDiscretizer", QuantileDiscretizerTrainBatchOp, _BucketMapperBase)
StringIndexer, StringIndexerModel = _trainer(
    "StringIndexer", StringIndexerTrainBatchOp, StringIndexerModelMapper)
Pca, PcaModel = _trainer("Pca", PcaTrainBatchOp, PcaModelMapper)
VectorStandardScaler, VectorStandardScalerModel = _trainer(
    "VectorStandardScaler", VectorStandardScalerTrainBatchOp, VectorScalerModelMapper)
VectorMinMaxScaler, VectorMinMaxScalerModel = _trainer(
    "VectorMinMaxScaler", VectorMinMaxScalerTrainBatchOp, VectorScalerModelMapper)
VectorMaxAbsScaler, VectorMaxAbsScalerModel = _trainer(
    "VectorMaxAbsScaler", VectorMaxAbsScalerTrainBatchOp, VectorScalerModelMapper)

# kwargs validation needs predict params too (output_col etc.)
for _cls in (StringIndexer, StringIndexerModel):
    _cls._PARAM_INFOS = {**_cls._PARAM_INFOS,
                         **StringIndexerPredictBatchOp._PARAM_INFOS}
for _cls in (OneHotEncoder, OneHotEncoderModel, Pca, PcaModel,
             QuantileDiscretizer, QuantileDiscretizerModel,
             StandardScaler, StandardScalerModel,
             VectorStandardScaler, VectorStandardScalerModel):
    from ..params.shared import HasOutputCol, HasOutputCols, HasReservedCols
    _cls._PARAM_INFOS = {**_cls._PARAM_INFOS,
                         **{i.name: i for i in (HasOutputCol.OUTPUT_COL,
                                                HasOutputCols.OUTPUT_COLS,
                                                HasReservedCols.RESERVED_COLS)}}
for _cls in (Pca, PcaModel):
    _cls._PARAM_INFOS = {**_cls._PARAM_INFOS,
                         "prediction_col": PcaPredictBatchOp.PREDICTION_COL}


class Binarizer(BatchOpTransformer):
    OP_CLS = BinarizerBatchOp
    _PARAM_INFOS = BinarizerBatchOp._PARAM_INFOS


class Bucketizer(BatchOpTransformer):
    OP_CLS = BucketizerBatchOp
    _PARAM_INFOS = BucketizerBatchOp._PARAM_INFOS


class FeatureHasher(BatchOpTransformer):
    OP_CLS = FeatureHasherBatchOp
    _PARAM_INFOS = FeatureHasherBatchOp._PARAM_INFOS


class VectorAssembler(BatchOpTransformer):
    OP_CLS = VectorAssemblerBatchOp
    _PARAM_INFOS = VectorAssemblerBatchOp._PARAM_INFOS


class VectorNormalizer(BatchOpTransformer):
    OP_CLS = VectorNormalizeBatchOp
    _PARAM_INFOS = VectorNormalizeBatchOp._PARAM_INFOS


class DCT(BatchOpTransformer):
    OP_CLS = DCTBatchOp
    _PARAM_INFOS = DCTBatchOp._PARAM_INFOS
