"""tools/bench_compare.py — bench regression gate on synthetic dumps.

No jax needed: the tool is pure-host JSON diffing. Covers both accepted
file shapes (driver dump with ``parsed``, bare final-line object), the
newest-pair discovery, per-workload deltas incl. appear/disappear, the
``--threshold`` exit-code gate, and the ``--json`` machine output.
"""

import importlib.util
import json
import os
import time

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def cli():
    spec = importlib.util.spec_from_file_location(
        "bench_compare_cli", os.path.join(ROOT, "tools", "bench_compare.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _driver_dump(path, workloads, n=1):
    """The BENCH_r*.json driver shape (final line under 'parsed')."""
    doc = {"n": n, "cmd": "python bench.py", "rc": 0, "tail": "...",
           "parsed": {"metric": "m", "value": 1.0, "unit": "sps",
                      "workloads_sps_vs": {
                          k: [v, 1.0, 0.5] for k, v in workloads.items()}}}
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def _bare_dump(path, workloads):
    with open(path, "w") as f:
        json.dump({"workloads_sps_vs":
                   {k: [v, 2.0, 0.1] for k, v in workloads.items()}}, f)
    return path


class TestLoadAndCompare:
    def test_both_shapes_load(self, cli, tmp_path):
        a = _driver_dump(str(tmp_path / "a.json"), {"x": 100.0})
        b = _bare_dump(str(tmp_path / "b.json"), {"x": 50.0})
        # unmarked dumps (everything pre --quick) load as mode "full"
        # with no baseline fingerprint (pre-r06)
        assert cli.load_workloads(a) == ({"x": 100.0}, "full", None)
        assert cli.load_workloads(b) == ({"x": 50.0}, "full", None)

    def test_quick_mode_marker_and_mismatch_warning(self, cli, tmp_path,
                                                    capsys):
        a = _bare_dump(str(tmp_path / "full.json"), {"x": 100.0})
        q = str(tmp_path / "quick.json")
        with open(q, "w") as f:
            json.dump({"mode": "quick",
                       "workloads_sps_vs": {"x": [10.0, 1.0, 0.0]}}, f)
        assert cli.load_workloads(q) == ({"x": 10.0}, "quick", None)
        # cross-mode diff: reported, but loudly flagged as fixture-size
        assert cli.main([a, q]) == 0
        err = capsys.readouterr().err
        assert "WARNING" in err and "quick" in err
        # same-mode diff: no warning
        q2 = str(tmp_path / "quick2.json")
        with open(q2, "w") as f:
            json.dump({"mode": "quick",
                       "workloads_sps_vs": {"x": [11.0, 1.0, 0.0]}}, f)
        assert cli.main([q, q2]) == 0
        assert "WARNING" not in capsys.readouterr().err

    def test_not_a_bench_dump(self, cli, tmp_path):
        p = str(tmp_path / "junk.json")
        with open(p, "w") as f:
            json.dump({"hello": 1}, f)
        with pytest.raises(ValueError, match="workloads_sps_vs"):
            cli.load_workloads(p)

    def test_compare_deltas_and_membership(self, cli):
        rows = cli.compare({"a": 100.0, "gone": 5.0},
                           {"a": 110.0, "fresh": 7.0})
        by = {r["workload"]: r for r in rows}
        assert by["a"]["delta_pct"] == pytest.approx(10.0)
        assert by["gone"]["new"] is None and by["gone"]["delta_pct"] is None
        assert by["fresh"]["old"] is None and by["fresh"]["delta_pct"] is None

    def test_regressions_threshold(self, cli):
        rows = cli.compare({"a": 100.0, "b": 100.0}, {"a": 80.0, "b": 95.0})
        assert [r["workload"] for r in cli.regressions(rows, 10.0)] == ["a"]
        assert cli.regressions(rows, 25.0) == []

    def test_zero_old_rate_is_na_not_gone(self, cli, tmp_path, capsys):
        """A failed/zeroed old run has no percentage baseline: the
        workload must render as n/a (present in both), never 'gone'."""
        rows = cli.compare({"a": 0.0}, {"a": 500.0})
        assert rows[0]["old"] == 0.0 and rows[0]["new"] == 500.0
        assert rows[0]["delta_pct"] is None
        old = _driver_dump(str(tmp_path / "o.json"), {"a": 0.0})
        new = _driver_dump(str(tmp_path / "n.json"), {"a": 500.0})
        assert cli.main([old, new, "--threshold", "10"]) == 0
        out = capsys.readouterr().out
        assert "n/a" in out and "gone" not in out

    def test_newest_pair_by_mtime(self, cli, tmp_path):
        p1 = _driver_dump(str(tmp_path / "BENCH_r01.json"), {"x": 1.0})
        p2 = _driver_dump(str(tmp_path / "BENCH_r02.json"), {"x": 2.0})
        p3 = _driver_dump(str(tmp_path / "BENCH_full.json"), {"x": 9.0})
        now = time.time()
        os.utime(p1, (now - 20, now - 20))
        os.utime(p2, (now - 10, now - 10))
        os.utime(p3, (now, now))          # per-run detail: never selected
        # quick smoke dumps are excluded too: auto-pairing one against a
        # full capture would gate on fixture-size deltas
        p4 = _driver_dump(str(tmp_path / "BENCH_quick.json"), {"x": 0.1})
        os.utime(p4, (now + 5, now + 5))
        old, new = cli.newest_pair(str(tmp_path))
        assert os.path.basename(old) == "BENCH_r01.json"
        assert os.path.basename(new) == "BENCH_r02.json"
        with pytest.raises(ValueError, match="at least two"):
            cli.newest_pair(str(tmp_path / "empty"))


class TestCli:
    def test_ok_and_gate(self, cli, tmp_path, capsys):
        old = _driver_dump(str(tmp_path / "old.json"),
                           {"a": 100.0, "b": 200.0})
        new = _driver_dump(str(tmp_path / "new.json"),
                           {"a": 80.0, "b": 210.0})
        # report-only: exit 0 even with the regression visible
        assert cli.main([old, new]) == 0
        out = capsys.readouterr().out
        assert "-20.0%" in out and "+5.0%" in out
        # gated: exit 2 past the threshold, 0 within it
        assert cli.main([old, new, "--threshold", "10"]) == 2
        assert "REGRESSION" in capsys.readouterr().out
        assert cli.main([old, new, "--threshold", "30"]) == 0
        assert "ok:" in capsys.readouterr().out

    def test_newest_pair_mode_and_json(self, cli, tmp_path, capsys):
        p1 = _driver_dump(str(tmp_path / "BENCH_r01.json"), {"a": 100.0})
        p2 = _driver_dump(str(tmp_path / "BENCH_r02.json"), {"a": 50.0})
        now = time.time()
        os.utime(p1, (now - 10, now - 10))
        os.utime(p2, (now, now))
        rc = cli.main(["--dir", str(tmp_path), "--threshold", "25",
                       "--json"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 2
        assert doc["regressions"] == ["a"]
        assert doc["workloads"][0]["delta_pct"] == pytest.approx(-50.0)
        assert os.path.basename(doc["old"]) == "BENCH_r01.json"

    def test_error_paths(self, cli, tmp_path, capsys):
        assert cli.main([str(tmp_path / "nope.json"),
                         str(tmp_path / "nope2.json")]) == 1
        assert "bench_compare.py:" in capsys.readouterr().err
        assert cli.main(["--dir", str(tmp_path)]) == 1

    def test_real_repo_dumps_if_present(self, cli, capsys):
        """The recorded BENCH_r*.json dumps in the repo root must parse."""
        import glob
        dumps = sorted(glob.glob(os.path.join(ROOT, "BENCH_r*.json")))
        if len(dumps) < 2:
            pytest.skip("fewer than two recorded dumps")
        assert cli.main([dumps[-2], dumps[-1]]) == 0
        assert "bench compare" in capsys.readouterr().out
