"""User-function and utility operators.

Re-design of batch/utils/{UDFBatchOp, UDTFBatchOp, FlatMapBatchOp,
PrintBatchOp, DataSetWrapperBatchOp}.java. The reference registers Flink
ScalarFunction/TableFunction objects into the table environment and
generates a SQL clause (UDFBatchOp.java:50-67); here the function is a
plain Python callable applied host-side over the columnar table — the
same selectedCols/outputCol(s)/reservedCols contract, no SQL detour.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import numpy as np

from ....common.mtable import MTable
from ....common.params import ParamInfo, Params
from ....common.types import AlinkTypes, TableSchema
from ....params.shared import (HasOutputCol, HasOutputCols, HasReservedCols,
                               HasSelectedCols)
from ...base import BatchOperator, TableSourceBatchOp

__all__ = ["UDFBatchOp", "UDTFBatchOp", "FlatMapBatchOp", "PrintBatchOp",
           "DataSetWrapperBatchOp"]


def _reserved(t: MTable, reserved: Optional[Sequence[str]], out_cols: Sequence[str]):
    cols = list(t.col_names) if reserved is None else list(reserved)
    return [c for c in cols if c not in out_cols]


class UDFBatchOp(BatchOperator, HasSelectedCols, HasOutputCol, HasReservedCols):
    """Scalar user function over selected columns (reference
    batch/utils/UDFBatchOp.java:50-67).

    ``func(*selected_values) -> value`` per row; ``output_col`` may collide
    with selected/reserved names, in which case it replaces them — the same
    column-collision contract the reference documents.
    """

    RESULT_TYPE = ParamInfo("result_type", str, default=AlinkTypes.DOUBLE)

    def __init__(self, params: Optional[Params] = None, func: Optional[Callable] = None,
                 **kwargs):
        super().__init__(params, **kwargs)
        self.func = func

    def set_func(self, func: Callable) -> "UDFBatchOp":
        self.func = func
        return self

    def link_from(self, in_op: BatchOperator) -> "UDFBatchOp":
        if self.func is None:
            raise ValueError("a function must be set with set_func")
        t = in_op.get_output_table()
        sel = self.get_selected_cols()
        out_col = self.params._m["output_col"]
        data = [t.col(c) for c in sel]
        out = np.empty(t.num_rows, object)
        out[:] = [self.func(*vals) for vals in zip(*data)] if sel else \
            [self.func() for _ in range(t.num_rows)]
        keep = _reserved(t, self.params._m.get("reserved_cols"), [out_col])
        names = keep + [out_col]
        types = [t.schema.type_of(c) for c in keep] + [self.get_result_type()]
        cols = {c: t.col(c) for c in keep}
        cols[out_col] = out
        self._output = MTable(cols, TableSchema(names, types))
        return self


class UDTFBatchOp(BatchOperator, HasSelectedCols, HasOutputCols, HasReservedCols):
    """Table user function: one row in, zero-or-more out (reference
    batch/utils/UDTFBatchOp.java:47-67).

    ``func(*selected_values) -> iterable of output tuples`` (scalars are
    treated as 1-tuples); reserved columns are replicated per emitted row.
    """

    RESULT_TYPES = ParamInfo("result_types", list, "types of output_cols")

    def __init__(self, params: Optional[Params] = None, func: Optional[Callable] = None,
                 **kwargs):
        super().__init__(params, **kwargs)
        self.func = func

    def set_func(self, func: Callable) -> "UDTFBatchOp":
        self.func = func
        return self

    def link_from(self, in_op: BatchOperator) -> "UDTFBatchOp":
        if self.func is None:
            raise ValueError("a function must be set with set_func")
        t = in_op.get_output_table()
        sel = self.get_selected_cols()
        out_cols = self.get_output_cols()
        keep = _reserved(t, self.params._m.get("reserved_cols"), out_cols)
        keep_data = [t.col(c) for c in keep]
        sel_data = [t.col(c) for c in sel]
        rows: List[tuple] = []
        for i in range(t.num_rows):
            for emitted in self.func(*(d[i] for d in sel_data)):
                if not isinstance(emitted, (tuple, list)):
                    emitted = (emitted,)
                rows.append(tuple(d[i] for d in keep_data) + tuple(emitted))
        types = ([t.schema.type_of(c) for c in keep]
                 + list(self.params._m.get("result_types")
                        or [AlinkTypes.DOUBLE] * len(out_cols)))
        self._output = MTable(rows, TableSchema(keep + list(out_cols), types))
        return self


class FlatMapBatchOp(BatchOperator):
    """Row to zero-or-more rows with a new schema (reference
    batch/utils/FlatMapBatchOp.java).

    ``func(row_tuple) -> iterable of row tuples`` in ``schema_str`` layout.
    """

    SCHEMA_STR = ParamInfo("schema_str", str, "output schema", optional=False)

    def __init__(self, params: Optional[Params] = None, func: Optional[Callable] = None,
                 **kwargs):
        super().__init__(params, **kwargs)
        self.func = func

    def set_func(self, func: Callable) -> "FlatMapBatchOp":
        self.func = func
        return self

    def link_from(self, in_op: BatchOperator) -> "FlatMapBatchOp":
        if self.func is None:
            raise ValueError("a function must be set with set_func")
        t = in_op.get_output_table()
        schema = TableSchema.parse(self.get_schema_str())
        rows: List[tuple] = []
        for row in t.to_rows():
            rows.extend(tuple(r) for r in self.func(row))
        self._output = MTable(rows, schema)
        return self


class PrintBatchOp(BatchOperator):
    """Print the input table and pass it through (reference
    batch/utils/PrintBatchOp.java)."""

    def link_from(self, in_op: BatchOperator) -> "PrintBatchOp":
        t = in_op.get_output_table()
        print(t.to_display_string())
        self._output = t
        return self


class DataSetWrapperBatchOp(TableSourceBatchOp):
    """Wrap an existing table as an operator (reference
    batch/utils/DataSetWrapperBatchOp.java wraps a DataSet<Row> + schema)."""
