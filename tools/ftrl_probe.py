"""One-off probe: strict vs bounded-staleness FTRL kernel rates on the
real chip, mirroring bench.py's ftrl_criteo configuration exactly.
Run EXCLUSIVELY (no concurrent CPU work — see docs/performance.md)."""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
import bench  # noqa: E402  (reuses Harness + its timing discipline)


def main():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from alink_tpu.operator.stream.onlinelearning.ftrl import (
        _ftrl_sparse_staleness_step_factory, _ftrl_sparse_step_factory)

    h = bench.Harness()
    dim, nnz, B = 65_536, 39, 4096
    n_dev = h.chips
    dim_pad = -(-dim // n_dev) * n_dev
    width = -(-(nnz + 1) // 8) * 8
    rng = np.random.RandomState(0)
    w_true = (rng.randn(dim) * (rng.rand(dim) < 0.02)).astype(np.float64)

    def make_batch(seed):
        r = np.random.RandomState(seed)
        idx = np.zeros((B, width), np.int32)
        val = np.zeros((B, width), np.float64)
        raw = r.randint(1, dim, size=(B, nnz)).astype(np.int32)
        idx[:, 0] = 0
        val[:, 0] = 1.0
        idx[:, 1:nnz + 1] = raw
        val[:, 1:nnz + 1] = 1.0
        margin = w_true[raw].sum(1)
        y = (r.rand(B) < 1.0 / (1.0 + np.exp(-margin))).astype(np.float64)
        return idx, val, y

    pool = [make_batch(s) for s in range(24)]
    mesh = h.env.mesh
    shard = NamedSharding(mesh, P("d"))
    zrng = np.random.RandomState(3)
    sp_idx = h.put(np.stack([p[0] for p in pool]))
    sp_val = h.put(np.stack([p[1] for p in pool]))
    sp_y = h.put(np.stack([p[2] for p in pool]))

    def rate_for(step, n_pools):
        @jax.jit
        def chain(si, sv, sy, z, nacc):
            def body(carry, xs):
                z, nacc = carry
                z, nacc, m = step(xs[0], xs[1], xs[2], z, nacc)
                return (z, nacc), m[0]
            (z, nacc), _ = jax.lax.scan(body, (z, nacc), (si, sv, sy))
            return z, nacc

        def run(k):
            z = jax.device_put(zrng.randn(dim_pad) * 1e-8, shard)
            nacc = jax.device_put(np.zeros(dim_pad), shard)
            for _ in range(k):
                z, nacc = chain(sp_idx, sp_val, sp_y, z, nacc)
            np.asarray(z)

        dt = h.delta(run, n_pools)
        return B * len(pool) * n_pools / dt / h.chips

    results = {}
    strict = _ftrl_sparse_step_factory(mesh, alpha=0.05, beta=1.0,
                                       l1=1e-5, l2=1e-5)
    results["strict_K4"] = rate_for(strict, 8)
    print("strict_K4", round(results["strict_K4"], 1), flush=True)

    for K in (8, 16, 32, 64, 128):
        st = _ftrl_sparse_staleness_step_factory(
            mesh, alpha=0.05, beta=1.0, l1=1e-5, l2=1e-5, K=K)
        n_pools = 8 if K <= 16 else 16
        results[f"stale_K{K}"] = rate_for(st, n_pools)
        print(f"stale_K{K}", round(results[f'stale_K{K}'], 1), flush=True)

    print({k: round(v, 1) for k, v in results.items()})


if __name__ == "__main__":
    main()
