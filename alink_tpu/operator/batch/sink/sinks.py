"""Batch sink operators.

Re-design of operator/batch/sink/ (CsvSinkBatchOp, TextSinkBatchOp,
MemSinkBatchOp — the collect backbone, BatchOperator.java:455-494).
"""

from __future__ import annotations

from typing import List, Optional

from ....common.mtable import MTable
from ....common.params import ParamInfo, Params
from ....io.csv import write_csv, write_libsvm
from ...base import BatchOperator


class BaseSinkBatchOp(BatchOperator):
    """Common sink shape (reference batch/sink/BaseSinkBatchOp.java):
    write the input out via ``_sink``, pass the table through."""

    def _sink(self, t: MTable) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def link_from(self, in_op: BatchOperator) -> "BaseSinkBatchOp":
        t = in_op.get_output_table()
        self._sink(t)
        self._output = t
        return self


class CsvSinkBatchOp(BaseSinkBatchOp):
    FILE_PATH = ParamInfo("file_path", str, optional=False)
    FIELD_DELIMITER = ParamInfo("field_delimiter", str, default=",")
    WITH_HEADER = ParamInfo("with_header", bool, default=False)

    def _sink(self, t: MTable) -> None:
        write_csv(t, self.get_file_path(),
                  field_delimiter=self.get_field_delimiter(),
                  with_header=self.get_with_header())


class LibSvmSinkBatchOp(BaseSinkBatchOp):
    FILE_PATH = ParamInfo("file_path", str, optional=False)
    LABEL_COL = ParamInfo("label_col", str, optional=False)
    VECTOR_COL = ParamInfo("vector_col", str, optional=False)

    def _sink(self, t: MTable) -> None:
        write_libsvm(t, self.get_file_path(), self.get_label_col(),
                     self.get_vector_col())


class TextSinkBatchOp(BaseSinkBatchOp):
    """Write a single-column table as plain lines (reference
    batch/sink/TextSinkBatchOp.java — requires exactly one input column)."""

    FILE_PATH = ParamInfo("file_path", str, optional=False)

    def _sink(self, t: MTable) -> None:
        if len(t.col_names) != 1:
            raise ValueError(
                f"TextSink requires exactly one column, got {t.col_names}")
        with open(self.get_file_path(), "w") as f:
            for v in t.col(t.col_names[0]):
                f.write(("" if v is None else str(v)) + "\n")


class MemSinkBatchOp(BatchOperator):
    """Collect rows into host memory (reference MemSinkBatchOp / CollectHelper)."""

    def __init__(self, params: Optional[Params] = None, **kwargs):
        super().__init__(params, **kwargs)
        self.rows: List[tuple] = []

    def link_from(self, in_op: BatchOperator) -> "MemSinkBatchOp":
        self._output = in_op.get_output_table()
        self.rows = self._output.to_rows()
        return self


from ....io.db import HasDB as _HasDB
from ....io.db import HasMySqlDB as _HasMySqlDB


class DBSinkBatchOp(_HasDB, BatchOperator):
    """Write the input table into a registered BaseDB
    (reference: batch/sink/DBSinkBatchOp.java)."""
    OUTPUT_TABLE_NAME = ParamInfo("output_table_name", str, optional=False)
    OVERWRITE_SINK = ParamInfo("overwrite_sink", bool, default=False)

    def link_from(self, in_op: BatchOperator) -> "DBSinkBatchOp":
        t = in_op.get_output_table()
        self._db().write_table(self.params._m["output_table_name"], t,
                               append=not self.params._m.get("overwrite_sink",
                                                             False))
        self.set_output_table(t)
        return self


class MySqlSinkBatchOp(_HasMySqlDB, DBSinkBatchOp):
    """reference: batch/sink/MySqlSinkBatchOp.java"""
