"""Deterministic fault injection — kill, error, delay or corrupt a run
at a named site, on purpose.

The reference inherits chaos testing for free from Flink's checkpointing
integration tests (TaskManager kills mid-job, the job restarts from the
last completed checkpoint). The TPU build has no cluster to kill, so
faults are injected *in process*: durability and serving hot paths call
``maybe_crash(site, index)`` at the exact points where a real failure
would bite — a ComQueue superstep boundary, an FTRL micro-batch
boundary, a serving dispatch — and the hook acts once the configured
index window is reached.

Configuration rides in one env var so tests (and operators reproducing a
field failure) need no code changes::

    ALINK_TPU_FAULT_INJECT="comqueue.superstep:9"          # kill (default)
    ALINK_TPU_FAULT_INJECT="ftrl.batch:5;ckpt.save:2"      # several sites
    ALINK_TPU_FAULT_INJECT="serve.dispatch:1-40:error"     # transient storm
    ALINK_TPU_FAULT_INJECT="serve.dispatch:5:delay:250"    # +250 ms latency
    ALINK_TPU_FAULT_INJECT="feeder.snapshot:2-2:corrupt"   # one bad snapshot

Each entry is ``site:index[-end][:mode[:param]]``:

  * ``index`` — the 1-based visit the fault arms at. A bare ``index``
    fires at the FIRST call whose ``index >= configured`` and every call
    after (the historical kill semantics — a dead process stays dead);
    ``index-end`` fires only while ``index <= visit <= end``, which is
    what makes transient storms *clear* deterministically (a breaker
    recovery or a retry success is then a reproducible event, not a
    race against a test's disarm timing).
  * ``mode`` — what happens inside the window:
      - ``kill``   (default) raise :class:`FaultInjected` — the injected
        process kill; generic handlers must NOT catch it (PR 2 contract);
      - ``error``  raise :class:`TransientFault` — a *catchable*
        ``RuntimeError`` standing in for a transient backend failure
        (the thing retry/breaker policies exist for);
      - ``delay:MS`` sleep ``MS`` milliseconds — latency injection for
        deadline/shed testing;
      - ``corrupt`` make :func:`maybe_crash` return ``True`` — the call
        site owns the corruption (it knows its payload format); sites
        that cannot corrupt ignore the return value.

Sites are plain dotted strings; current producers:

  * ``comqueue.superstep``  — superstep boundary (engine/recovery.py),
    index = 1-based superstep number;
  * ``ftrl.batch``          — after an FTRL micro-batch commits
    (operator/stream/onlinelearning/ftrl.py), index = 1-based batch count;
  * ``ckpt.save``           — just before a checkpoint directory is
    published (common/checkpoint.py), auto-indexed per process;
  * ``serve.dispatch``      — before each compiled serving-program
    execution (serving/predictor.py), auto-indexed;
  * ``serve.swap``          — at each hot model/weights swap
    (serving/predictor.py), auto-indexed;
  * ``feeder.snapshot``     — at each FTRL model-snapshot emission
    (the serving feeder's input; ``corrupt`` mangles the emitted model
    table so the consumer's load fails loudly), auto-indexed;
  * ``prefetch.get``        — inside the bounded channel's ``get``
    (operator/stream/prefetch.py — the serving loop and every stream
    drain pull through it), auto-indexed;
  * ``ingest.batch``        — before the online DAG's resumable ingest
    delivers a micro-batch to the scoring/eval leg (online/dag.py —
    the resume-at-offset restart policy's test point), auto-indexed:
    a redelivery after a crashed delivery advances the visit counter,
    so bounded kill windows clear across ingest restarts.

The env var is re-read on every call (monkeypatch-friendly); parsing is
cached per raw string so the hot-path cost is one dict lookup. Tests
that arm auto-indexed sites should call :func:`reset_faults` first (and
in teardown): the per-process visit counters otherwise leak across
tests that arm the same site twice.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Dict, Iterator, NamedTuple, Optional

__all__ = ["FAULT_ENV", "FAULT_MODES", "FaultInjected", "FaultRule",
           "TransientFault", "fault_spec", "faults_armed", "maybe_crash",
           "reset_faults", "scoped_fault_env"]

FAULT_ENV = "ALINK_TPU_FAULT_INJECT"

FAULT_MODES = ("kill", "error", "delay", "corrupt")


class FaultInjected(RuntimeError):
    """Raised by :func:`maybe_crash` in ``kill`` mode — the injected
    'process kill'.

    Deliberately NOT a subclass of any alink error type: durability code
    must not be able to catch it by accident in a generic handler.
    """

    def __init__(self, site: str, index: int, threshold: int):
        super().__init__(
            f"fault injected at {site}:{index} "
            f"({FAULT_ENV} threshold {threshold})")
        self.site = site
        self.index = index
        self.threshold = threshold


class TransientFault(RuntimeError):
    """Raised by :func:`maybe_crash` in ``error`` mode — a *catchable*
    stand-in for a transient backend failure (device OOM blip, link
    hiccup, preempted core). Retry/backoff and circuit-breaker policies
    are ALLOWED (expected) to catch this; :class:`FaultInjected` they
    are not."""

    def __init__(self, site: str, index: int, threshold: int):
        super().__init__(
            f"transient fault injected at {site}:{index} "
            f"({FAULT_ENV} threshold {threshold})")
        self.site = site
        self.index = index
        self.threshold = threshold


class FaultRule(NamedTuple):
    """One armed site: fire while ``lo <= visit`` (and ``<= hi`` when
    ``hi`` is bounded) with ``mode`` (``param`` = delay milliseconds)."""
    lo: int
    hi: Optional[int]
    mode: str
    param: float

    def active(self, index: int) -> bool:
        return index >= self.lo and (self.hi is None or index <= self.hi)


# parse cache: raw env string -> {site: FaultRule}; the env var is read
# fresh each call but identical strings parse once
_PARSED: Dict[str, Dict[str, FaultRule]] = {}

# per-process visit counters for sites whose callers do not track an
# index themselves (``maybe_crash(site)`` with index=None). Locked: the
# serving sites (serve.dispatch under replicas, prefetch.get from every
# channel consumer) increment concurrently, and a lost/duplicated
# increment would fire a bounded window twice or never — the exactly-
# once determinism the chaos specs are built on
_AUTO_INDEX: Dict[str, int] = {}
_AUTO_LOCK = threading.Lock()


def _next_index(site: str) -> int:
    with _AUTO_LOCK:
        index = _AUTO_INDEX.get(site, 0) + 1
        _AUTO_INDEX[site] = index
    return index


def _malformed(entry: str, why: str) -> ValueError:
    return ValueError(
        f"{FAULT_ENV}: malformed entry {entry!r} ({why}; want "
        f"site:index[-end][:mode[:param]] with integer index/end, "
        f"mode one of {'/'.join(FAULT_MODES)})")


def _parse_entry(entry: str) -> tuple:
    parts = [p.strip() for p in entry.split(":")]
    if len(parts) < 2 or not parts[0]:
        raise _malformed(entry, "want at least site:index")
    site, idx = parts[0], parts[1]
    lo_s, sep, hi_s = idx.partition("-")
    try:
        lo = int(lo_s)
        hi = int(hi_s) if sep else None
    except ValueError:
        # a bare int(idx) traceback names neither the env var nor the
        # site — wrap it in the malformed-entry diagnostic
        raise _malformed(entry, f"non-integer index {idx!r} for site "
                                f"{site!r}") from None
    if hi is not None and hi < lo:
        raise _malformed(entry, f"empty index window {idx!r}")
    mode = parts[2] if len(parts) > 2 and parts[2] else "kill"
    if mode not in FAULT_MODES:
        raise _malformed(entry, f"unknown mode {mode!r}")
    param = 0.0
    if mode == "delay":
        if len(parts) < 4:
            raise _malformed(entry, "delay needs a milliseconds param "
                                    "(site:index:delay:MS)")
        try:
            param = float(parts[3])
        except ValueError:
            raise _malformed(entry, f"non-numeric delay {parts[3]!r}") \
                from None
    elif len(parts) > 3:
        raise _malformed(entry, f"mode {mode!r} takes no param")
    return site, FaultRule(lo, hi, mode, param)


def _parse(raw: str) -> Dict[str, FaultRule]:
    spec = _PARSED.get(raw)
    if spec is None:
        spec = {}
        for entry in raw.replace(",", ";").split(";"):
            entry = entry.strip()
            if not entry:
                continue
            site, rule = _parse_entry(entry)
            if site in spec:
                # last-wins would silently drop the earlier rule — a
                # storm spec that tests nothing; refuse like every
                # other malformed spec
                raise _malformed(
                    entry, f"site {site!r} already has a rule (one "
                           f"entry per site; stage multi-leg storms by "
                           f"re-setting {FAULT_ENV} between legs)")
            spec[site] = rule
        if len(_PARSED) > 64:   # bound the cache; specs are few in practice
            _PARSED.clear()
        _PARSED[raw] = spec
    return spec


def fault_spec() -> Dict[str, FaultRule]:
    """The active {site: rule} map (empty when unset). The raw
    spec string is read through the flag registry (common/flags.py);
    its ``site:index:mode`` grammar stays here with its consumer."""
    from .flags import flag_raw
    raw = flag_raw(FAULT_ENV)
    return _parse(raw) if raw else {}


def faults_armed() -> bool:
    return bool(fault_spec())


def reset_faults() -> None:
    """Clear the per-process auto-index visit counters (and the parse
    cache). Tests that arm an auto-indexed site (``serve.dispatch``,
    ``ckpt.save``, ...) MUST call this in setup/teardown — the counters
    otherwise leak across tests that arm the same site twice, shifting
    every later threshold."""
    _AUTO_INDEX.clear()
    _PARSED.clear()


@contextlib.contextmanager
def scoped_fault_env(spec: Optional[str]) -> Iterator[None]:
    """Arm ``spec`` in :data:`FAULT_ENV` for the duration of a scenario,
    with the counter hygiene the chaos harnesses need (ISSUE 15
    satellite): the per-process auto-index visit counters are reset on
    ENTRY (so the scenario's windows count from zero regardless of what
    ran before) and the previous env value is restored — and the
    counters reset again — on EXIT, **including failure paths** (the
    body raising must not bleed armed faults or shifted visit counters
    into the next scenario). ``spec=None`` runs the body with the fault
    env guaranteed UNSET (a clean scenario between storms).

    One storm leg per ``with`` block; legs that must share one
    uninterrupted visit-counter timeline (the chaos smoke's
    exactly-once corrupt window across an error leg and a delay leg)
    belong inside a SINGLE scope, flipping ``os.environ[FAULT_ENV]``
    directly between them.
    """
    saved = os.environ.get(FAULT_ENV)
    reset_faults()
    if spec:
        os.environ[FAULT_ENV] = spec
    else:
        os.environ.pop(FAULT_ENV, None)
    try:
        yield
    finally:
        if saved is None:
            os.environ.pop(FAULT_ENV, None)
        else:
            os.environ[FAULT_ENV] = saved
        reset_faults()


def maybe_crash(site: str, index: Optional[int] = None) -> bool:
    """Act on ``site``'s armed fault when ``index`` is inside its window.
    With ``index=None`` a per-process visit counter for the site is used
    (1-based; it only advances while some fault spec is armed).

    ``kill`` raises :class:`FaultInjected`; ``error`` raises
    :class:`TransientFault`; ``delay`` sleeps its parameter (ms) and
    returns ``False``; ``corrupt`` returns ``True`` — the CALLER owns
    the corruption (it knows its payload format). Returns ``False``
    otherwise, so legacy call sites can keep ignoring the result.

    Unarmed fast path: ONE os.environ probe (the flag is registered in
    common/flags.py and this read is semantically ``flag_raw``; sites
    like ``prefetch.get`` sit on per-message hot paths, so the unarmed
    cost must stay a dict lookup, not a registry round trip)."""
    if not os.environ.get(FAULT_ENV):
        return False
    spec = fault_spec()
    if not spec:
        return False
    if index is None:
        index = _next_index(site)
    rule = spec.get(site)
    if rule is None or not rule.active(index):
        return False
    # mark the fault in the trace timeline BEFORE acting, so a flight
    # recorder dumped by a crash handler shows exactly where the
    # injected failure hit relative to checkpoint saves / dispatches
    from .tracing import trace_instant
    trace_instant("fault.injected", cat="fault",
                  args={"site": site, "index": int(index),
                        "threshold": rule.lo, "mode": rule.mode})
    if rule.mode == "kill":
        # the injected 'process kill' is exactly the crash class the
        # post-mortem bundle exists for: freeze the evidence BEFORE the
        # raise unwinds the rings' producers (lazy import — this module
        # sits under common/flags.py in the import order; debounced, off
        # without ALINK_TPU_POSTMORTEM_DIR)
        from .postmortem import maybe_bundle
        maybe_bundle("injected_kill", f"fault injected at {site}:{index}",
                     extra={"site": site, "index": int(index),
                            "threshold": rule.lo})
        raise FaultInjected(site, int(index), rule.lo)
    if rule.mode == "error":
        raise TransientFault(site, int(index), rule.lo)
    if rule.mode == "delay":
        time.sleep(rule.param / 1e3)
        return False
    return True       # corrupt: signal the caller
