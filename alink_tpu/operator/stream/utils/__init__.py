"""Generic stream-side mapper adapters.

Re-design of stream/utils/ (ModelMapStreamOp — model loaded once, applied
per record; here per micro-batch with the batched mapper kernel) and the
stateless MapStreamOp family. The model arrives from a *batch* operator via
the DirectReader side channel in the reference (common/io/directreader/
DirectReader.java:43-77); here a batch table handle crosses directly.
"""

from __future__ import annotations

from typing import Optional, Type

from ....common.mtable import MTable
from ....common.params import Params
from ....mapper.base import Mapper, ModelMapper
from ...base import BatchOperator, StreamOperator
from ..core import BaseStreamTransformOp


class MapperStreamOp(BaseStreamTransformOp):
    """Stateless mapper applied to each micro-batch."""

    MAPPER_CLS: Optional[Type[Mapper]] = None

    def __init__(self, params: Optional[Params] = None, mapper_cls=None, **kwargs):
        super().__init__(params, **kwargs)
        if mapper_cls is not None:
            self.MAPPER_CLS = mapper_cls
        self._mapper: Optional[Mapper] = None

    def _open(self, in_schema):
        self._mapper = self.MAPPER_CLS(in_schema, self.params)
        return self._mapper.get_output_schema()

    def _transform(self, mt: MTable):
        return self._mapper.map_table(mt)


class ModelMapStreamOp(BaseStreamTransformOp):
    """Apply a trained (batch) model to a stream (reference
    stream/utils/ModelMapStreamOp; model via DataBridge broadcast)."""

    MAPPER_CLS: Optional[Type[ModelMapper]] = None

    def __init__(self, model_op: Optional[BatchOperator] = None,
                 params: Optional[Params] = None, mapper_cls=None, **kwargs):
        super().__init__(params, **kwargs)
        if mapper_cls is not None:
            self.MAPPER_CLS = mapper_cls
        self._model_op = model_op
        self._mapper: Optional[ModelMapper] = None

    def _open(self, in_schema):
        model_table = self._model_op.get_output_table()
        self._mapper = self.MAPPER_CLS(model_table.schema, in_schema, self.params)
        self._mapper.load_model(model_table)
        return self._mapper.get_output_schema()

    def _transform(self, mt: MTable):
        return self._mapper.map_table(mt)

    def link_from(self, *inputs) -> "ModelMapStreamOp":
        if len(inputs) == 2 and isinstance(inputs[0], BatchOperator):
            self._model_op = inputs[0]
            inputs = inputs[1:]
        return super().link_from(*inputs)
