"""String indexing operators.

Re-design of common/dataproc/ StringIndexerTrain/Predict,
MultiStringIndexer, IndexToString (ordered token -> LONG index models).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np

from ....common.mtable import MTable
from ....common.params import InValidator, ParamInfo, Params
from ....common.types import AlinkTypes, TableSchema
from ....mapper.base import ModelMapper, OutputColsHelper
from ....model.converters import SimpleModelDataConverter
from ....params.shared import (HasOutputCol, HasOutputCols, HasReservedCols,
                               HasSelectedCol, HasSelectedCols)
from ...base import BatchOperator
from ..utils.model_map import ModelMapBatchOp


def _order_tokens(values, order: str) -> List[str]:
    toks = [str(v) for v in values if v is not None]
    if order == "random":
        uniq = list(dict.fromkeys(toks))
        return uniq
    from collections import Counter
    cnt = Counter(toks)
    if order == "frequency_asc":
        return [t for t, _ in sorted(cnt.items(), key=lambda kv: (kv[1], kv[0]))]
    if order == "frequency_desc":
        return [t for t, _ in sorted(cnt.items(), key=lambda kv: (-kv[1], kv[0]))]
    if order == "alphabet_asc":
        return sorted(cnt)
    if order == "alphabet_desc":
        return sorted(cnt, reverse=True)
    raise ValueError(order)


class StringIndexerModelConverter(SimpleModelDataConverter):
    def serialize_model(self, model: Dict[str, List[str]]):
        return Params({"cols": list(model)}), [json.dumps(model)]

    def deserialize_model(self, meta, data):
        return json.loads(data[0])


class StringIndexerTrainBatchOp(BatchOperator, HasSelectedCol, HasSelectedCols):
    """reference: dataproc/StringIndexerTrainBatchOp (MultiStringIndexer when
    several columns are selected)."""
    STRING_ORDER_TYPE = ParamInfo(
        "string_order_type", str, default="random",
        validator=InValidator(["random", "frequency_asc", "frequency_desc",
                               "alphabet_asc", "alphabet_desc"]))

    def link_from(self, in_op: BatchOperator) -> "StringIndexerTrainBatchOp":
        t = in_op.get_output_table()
        cols = self.params._m.get("selected_cols") or [self.get_selected_col()]
        order = self.get_string_order_type()
        model = {c: _order_tokens(t.col(c), order) for c in cols}
        self._output = StringIndexerModelConverter().save_model(model)
        return self


class MultiStringIndexerTrainBatchOp(StringIndexerTrainBatchOp):
    pass


class StringIndexerModelMapper(ModelMapper):
    def __init__(self, model_schema, data_schema, params=None, **kwargs):
        super().__init__(model_schema, data_schema, params, **kwargs)
        self.model: Optional[Dict[str, List[str]]] = None

    def load_model(self, model_table: MTable):
        self.model = StringIndexerModelConverter().load_model(model_table)

    def map_table(self, data: MTable) -> MTable:
        sel = self.params._m.get("selected_cols") or [self.params._m["selected_col"]]
        out_cols = (self.params._m.get("output_cols")
                    or ([self.params._m["output_col"]]
                        if self.params._m.get("output_col") else sel))
        handle = (self.params._m.get("handle_invalid") or "keep").lower()
        outs = []
        for c, _oc in zip(sel, out_cols):
            if c in self.model:
                vocab = self.model[c]
            elif len(self.model) == 1:
                # single-col model may be applied to a differently-named column
                vocab = next(iter(self.model.values()))
            else:
                raise KeyError(f"column {c!r} not in indexer model "
                               f"(trained on {sorted(self.model)})")
            lookup = {t: i for i, t in enumerate(vocab)}
            vals = []
            for v in data.col(c):
                key = None if v is None else str(v)
                if key in lookup:
                    vals.append(lookup[key])
                elif handle == "keep":
                    vals.append(len(lookup))
                elif handle == "skip":
                    vals.append(-1)
                else:
                    raise ValueError(f"unseen token {v!r} in column {c}")
            outs.append(np.asarray(vals, np.int64))
        helper = OutputColsHelper(data.schema, out_cols,
                                  [AlinkTypes.LONG] * len(out_cols))
        return helper.build_output(data, outs)


class StringIndexerPredictBatchOp(ModelMapBatchOp, HasSelectedCol, HasSelectedCols,
                                  HasOutputCol, HasOutputCols, HasReservedCols):
    MAPPER_CLS = StringIndexerModelMapper
    HANDLE_INVALID = ParamInfo("handle_invalid", str, default="keep",
                               validator=InValidator(["keep", "skip", "error"]))


class MultiStringIndexerPredictBatchOp(StringIndexerPredictBatchOp):
    pass


class IndexToStringModelMapper(ModelMapper):
    def __init__(self, model_schema, data_schema, params=None, **kwargs):
        super().__init__(model_schema, data_schema, params, **kwargs)
        self.model = None

    def load_model(self, model_table: MTable):
        self.model = StringIndexerModelConverter().load_model(model_table)

    def map_table(self, data: MTable) -> MTable:
        sel = self.params._m["selected_col"]
        out_col = self.params._m.get("output_col") or sel
        model_col = self.params._m.get("model_name_col")
        vocab = (self.model.get(model_col) if model_col
                 else next(iter(self.model.values())))
        vals = np.empty(data.num_rows, object)
        col = data.col(sel)
        for i, v in enumerate(col):
            iv = int(v)
            vals[i] = vocab[iv] if 0 <= iv < len(vocab) else None
        helper = OutputColsHelper(data.schema, [out_col], [AlinkTypes.STRING])
        return helper.build_output(data, [vals])


class IndexToStringPredictBatchOp(ModelMapBatchOp, HasSelectedCol, HasOutputCol):
    """reference: dataproc/IndexToStringPredictBatchOp."""
    MAPPER_CLS = IndexToStringModelMapper
    MODEL_NAME_COL = ParamInfo("model_name_col", str, "which indexed column's vocab")
