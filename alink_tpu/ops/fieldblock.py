"""Field-blocked sparse format + factored one-hot kernels.

The motivating workload is the reference's Criteo-style CTR pipeline
(FTRLExample.java:46-57): FeatureHasher murmurs every raw feature into one
flat space and the linear trainers then do random gather (w[idx]) and
random scatter-add (grad[idx] += c) per sample — fine on a CPU heap,
catastrophic on TPU where XLA serializes both (measured ~67 ms for 6.4M
random accesses on v5e vs ~0.1 ms of equivalent streaming traffic).

TPU-first redesign: hash each input column (field) into its OWN contiguous
sub-range of the model vector — ``dim = num_fields * field_size`` — so every
sample holds exactly one local index per field: ``fb_idx`` of shape
``(n, F)`` with values in ``[0, field_size)``. Field-aware hashing preserves
the model class (same capacity, per-field collision behaviour is what
production CTR systems use anyway). With that structure both directions of
the sparse design-matrix product become MXU matmuls via a *factored one-hot*:

    idx = hi * LO + lo,  LO = 16
    A[n, f, h] = [hi == h]      (one-hot over field_size/16)
    B[n, f, l] = [lo == l]      (one-hot over 16)

    matvec:   eta = einsum(A, W, B)           # W: (F, H, LO)
    rmatvec:  grad = einsum(A, B * c)

The one-hots are never materialized to HBM — XLA fuses the iota-compares
into the matmul operands. The factoring cuts the one-hot work from
O(n*dim) to O(n*(H + LO)) per field. Measured on v5e-1: fused logistic
gradient 19 ms vs 67+66 ms for XLA gather+scatter at n=200k, F=32,
dim=65536.

A fused Pallas kernel (`fb_fused_grad_pallas`) implements the same math
with explicit VMEM residency; the XLA path is the default (measured faster
— XLA's fusion beats the hand-rolled kernel's loop overheads) but the
kernel is kept as a selectable backend and for the multi-sample-per-field
variants XLA fuses badly.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

LO = 16  # lo-part width; field_size must be a multiple of this


@dataclass(frozen=True)
class FieldBlockMeta:
    """Shape metadata for a field-blocked design matrix.

    dim = num_fields * field_size; global index of (field k, local j) is
    ``k * field_size + j`` (field-major), matching the coefficient layout.
    """
    num_fields: int
    field_size: int

    @property
    def dim(self) -> int:
        return self.num_fields * self.field_size

    @property
    def hi_size(self) -> int:
        return self.field_size // LO

    def __post_init__(self):
        if self.field_size % LO:
            raise ValueError(f"field_size must be a multiple of {LO}")


def hash_to_fields(columns, field_size: int, seed: int = 0) -> np.ndarray:
    """Field-aware feature hashing: one column -> one field (host-side).

    The reference hashes all columns into one flat space
    (FeatureHasherMapper over murmur32); here each column owns a
    ``field_size`` sub-range so the result is field-blocked by
    construction. Returns ``fb_idx`` of shape (n, num_columns) int32.
    """
    from ..operator.batch.feature.feature_ops import murmur32_cells
    cols = list(columns)
    n = len(cols[0])
    out = np.empty((n, len(cols)), np.int32)
    for k, col in enumerate(cols):
        tokens = [f"{k}={v}".encode() for v in col]
        out[:, k] = murmur32_cells(tokens, seed=seed, mod=field_size)
    return out


def fb_to_flat_indices(fb_idx: np.ndarray, meta: FieldBlockMeta) -> np.ndarray:
    """(n, F) field-local -> (n, F) global indices into the dim-vector."""
    offs = (np.arange(meta.num_fields, dtype=np.int64) * meta.field_size)
    return (np.asarray(fb_idx, np.int64) + offs[None, :]).astype(np.int32)


def detect_fieldblock(idx: np.ndarray, val: Optional[np.ndarray], dim: int):
    """Recognize the field-blocked layout in a padded-COO design.

    Field-aware hashing (FeatureHasherBatchOp(field_aware=True)) emits
    exactly one entry per field per row, field k's indices inside
    ``[k*S, (k+1)*S)``; this detects that shape so linear trainers can take
    the MXU fast path automatically. Returns (fb_idx, fb_val|None, meta)
    with fb_val None when all values are 1.0, else None when the pattern
    does not hold (general sparse falls back to COO).
    """
    idx = np.asarray(idx)
    # F >= 2: with a single column every width-1 design would "detect"
    # vacuously and reroute generic sparse data onto the one-hot path
    if idx.ndim != 2 or idx.shape[1] < 2:
        return None
    F = idx.shape[1]
    if dim % F or (dim // F) % LO or dim // F < LO:
        return None
    meta = FieldBlockMeta(F, dim // F)
    local = flat_to_fb_indices(idx, meta)
    if local is None:
        return None
    if val is None or np.all(val == 1.0):
        return local, None, meta
    return local, np.asarray(val), meta


def flat_to_fb_indices(idx: np.ndarray, meta: FieldBlockMeta) -> Optional[np.ndarray]:
    """Recognize a field-blocked pattern in padded-COO indices.

    Returns (n, F) local indices if every row's k-th entry falls in field
    k's range (the shape produced by field-aware hashing), else None.
    """
    idx = np.asarray(idx)
    if idx.ndim != 2 or idx.shape[1] != meta.num_fields:
        return None
    offs = np.arange(meta.num_fields, dtype=idx.dtype) * meta.field_size
    local = idx - offs[None, :]
    if (local < 0).any() or (local >= meta.field_size).any():
        return None
    return local.astype(np.int32)


# ---------------------------------------------------------------------------
# factored one-hot ops (XLA path — default)
# ---------------------------------------------------------------------------

def _default_dtype():
    """bf16 on TPU (MXU-native), f32 elsewhere (CPU dot lacks bf16)."""
    import jax
    import jax.numpy as jnp
    return jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32


def _parts(fb_idx, meta: FieldBlockMeta):
    import jax.numpy as jnp
    hi = fb_idx // LO
    lo = fb_idx - hi * LO
    A = (hi[..., None] == jnp.arange(meta.hi_size)[None, None, :])
    B = (lo[..., None] == jnp.arange(LO)[None, None, :])
    return A, B


def fb_onehot_parts(fb_idx, meta: FieldBlockMeta, dtype=None):
    """Materialized (A, B) one-hot factors of the design matrix.

    The factors depend only on the (fixed) data, not on the iterate, yet
    building them inline makes every einsum pass write+read ~8x the index
    bytes to HBM. An iterative trainer that precomputes them ONCE (in its
    init superstep, device-side) and reuses them across all passes and
    iterations cuts the Criteo-shape L-BFGS superstep ~15 ms -> ~9 ms on
    v5e. Costs n*F*(hi_size + LO) operand bytes of HBM — gate on a budget
    (optimizers.ALINK_TPU_FB_ONEHOT_BYTES) before enabling."""
    import jax.numpy as jnp
    dtype = dtype or _default_dtype()
    A, B = _parts(fb_idx, meta)
    return A.astype(dtype), B.astype(dtype)


def _w3(coef, meta: FieldBlockMeta):
    return coef.reshape(meta.num_fields, meta.hi_size, LO)


def fb_matvec(fb_idx, coef, meta: FieldBlockMeta, val=None, dtype=None,
              parts=None):
    """eta[i] = sum_k val[i,k] * coef[k*S + fb_idx[i,k]]  — all MXU.

    Replaces the per-sample SparseVector dot of the reference's
    LinearModelMapper / OptimObjFunc.calcGradient inner loop.
    ``parts``: precomputed (A, B) from :func:`fb_onehot_parts`.
    """
    import jax.numpy as jnp
    dtype = dtype or _default_dtype()
    if parts is not None:
        A, B = parts
        A = A.astype(dtype)
    else:
        A, B = _parts(fb_idx, meta)
        A = A.astype(dtype)
    W = _w3(coef, meta).astype(dtype)
    rows = jnp.einsum("nfh,fhl->nfl", A, W,
                      preferred_element_type=jnp.float32)
    if val is not None:
        Bv = B.astype(jnp.float32) * val[..., None].astype(jnp.float32)
        return jnp.einsum("nfl,nfl->n", rows, Bv)
    Bc = B.astype(jnp.float32) if B.dtype == bool else B
    return jnp.einsum("nfl,nfl->n", rows, Bc,
                      preferred_element_type=jnp.float32)


def fb_gather(fb_idx, vec, meta: FieldBlockMeta, dtype=None):
    """out[i, k] = vec[k*S + fb_idx[i,k]] — per-field value selection as
    one-hot MXU matmuls (the gather XLA would otherwise serialize).

    Same factored kernel as :func:`fb_matvec` but keeping the field axis
    instead of dotting it away; batched FTRL uses it to read the per-slot
    (n, w) state without a random gather. Defaults to f32 operands: a
    selection must return the value exactly, unlike the matvec whose bf16
    operand rounding is amortized by f32 accumulation over the contraction."""
    import jax.numpy as jnp
    dtype = dtype or jnp.float32
    A, B = _parts(fb_idx, meta)
    W = _w3(vec, meta).astype(dtype)
    rows = jnp.einsum("nfh,fhl->nfl", A.astype(dtype), W,
                      preferred_element_type=jnp.float32)
    return jnp.einsum("nfl,nfl->nf", rows, B.astype(jnp.float32))


def fb_rmatvec(fb_idx, c, meta: FieldBlockMeta, val=None, dtype=None,
               parts=None):
    """grad = X^T c for the field-blocked design matrix — scatter-free.

    Replaces the reference's per-sample scatter-add
    (OptimObjFunc.updateGradient / SparseVector axpy).
    ``parts``: precomputed (A, B) from :func:`fb_onehot_parts`.
    """
    import jax.numpy as jnp
    dtype = dtype or _default_dtype()
    if parts is not None:
        A, B = parts
    else:
        A, B = _parts(fb_idx, meta)
    z = c
    if val is not None:
        z = z[:, None] * val
        Z = B.astype(dtype) * z[..., None].astype(dtype)
    else:
        Z = B.astype(dtype) * z[:, None, None].astype(dtype)
    g = jnp.einsum("nfh,nfl->fhl", A.astype(dtype), Z,
                   preferred_element_type=jnp.float32)
    return g.reshape(meta.dim)


# ---------------------------------------------------------------------------
# fused Pallas superstep kernels (selectable backend, not yet the default)
#
# XLA compiles the factored einsums above into convolution-style fusions
# (EmitOutputBatchInSublanes, ~13.5M est. cycles each) when they appear in a
# training step: ~4.5 ms per pass at n=200k/F=32/dim=64k — far off the MXU
# roofline. These kernels take explicit control: the coefficient table and
# gradient accumulator stay VMEM-resident across the whole pass, rows stream
# chunk-by-chunk, and each field is one (CH,KHI)@(KHI,128) MXU dot with the
# lo-part selected by a 128-lane one-hot on the VPU (requires
# field_size % 128 == 0; smaller fields use the XLA einsum path).
#
# Measured v5e-1 (n=200k, F=32, dim=64k, in-loop): ~10 ms per fused pass vs
# ~4.5 ms per XLA einsum pass — the per-field K=16 dots pay full MXU
# pipeline latency per tile-row, so the XLA path stays the default. Kept as
# the explicit-VMEM reference implementation and the base for a future
# block-diagonal (bigger-K) variant.
# ---------------------------------------------------------------------------

LANE = 128  # lo-part width of the Pallas layout (full VPU lane width)
_VMEM_TABLE_BUDGET = 4 << 20  # coef + grad tables must fit well inside VMEM


def fb_pallas_ok(meta: FieldBlockMeta) -> bool:
    """True when the Pallas kernels support this layout on this backend.

    Besides the lane-alignment constraint, the kernel pins the coefficient
    table and the gradient accumulator (4 bytes * dim each) in VMEM for the
    whole pass — layouts whose tables don't comfortably fit are rejected so
    this predicate can gate backend selection without compile-time VMEM
    failures.
    """
    import jax
    return (jax.default_backend() == "tpu" and
            meta.field_size % LANE == 0 and
            2 * 4 * meta.dim <= _VMEM_TABLE_BUDGET)


def _pad_rows(n: int, chunk: int) -> int:
    return -(-n // chunk) * chunk


def _fused_pallas_call(fb_idx, y, w, coef, meta: FieldBlockMeta,
                       deriv_and_loss, val=None, chunk: int = 4096,
                       interpret: bool = False, matvec_only: bool = False):
    """Shared body for the fused-gradient and matvec-only kernels."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    F, S = meta.num_fields, meta.field_size
    KHI = S // LANE
    n = fb_idx.shape[0]
    if n == 0:  # empty worker shard: zero contribution, nothing to launch
        zg = jnp.zeros(meta.dim, jnp.float32)
        ze = jnp.zeros(0, jnp.float32)
        return ze if matvec_only else (zg, ze, jnp.float32(0.0))
    CH = min(int(chunk), _pad_rows(n, 512))
    n_pad = _pad_rows(n, CH)
    mxu = jnp.float32 if interpret else jnp.bfloat16

    pad = n_pad - n
    idx_t = jnp.pad(fb_idx, ((0, pad), (0, 0))).T  # (F, n_pad)
    has_val = val is not None
    val_t = jnp.pad(val, ((0, pad), (0, 0))).T if has_val else None
    coef2 = coef.reshape(F * KHI, LANE)
    if not matvec_only:
        yp = jnp.pad(y, (0, pad), constant_values=1.0)
        wp = jnp.pad(w, (0, pad))  # w==0 marks padding; they contribute 0

    def kernel(*refs):
        it = iter(refs)
        idx_ref = next(it)
        if not matvec_only:
            y_ref, w_ref = next(it), next(it)
        val_ref = next(it) if has_val else None
        coef_ref = next(it)
        if matvec_only:
            (eta_ref,) = it
        else:
            grad_ref, eta_ref, acc_ref = it
        step = pl.program_id(0)

        if not matvec_only:
            @pl.when(step == 0)
            def _():
                grad_ref[...] = jnp.zeros_like(grad_ref)
                acc_ref[...] = jnp.zeros_like(acc_ref)

        hi_iota = jax.lax.broadcasted_iota(jnp.int32, (CH, KHI), 1)
        lo_iota = jax.lax.broadcasted_iota(jnp.int32, (CH, LANE), 1)

        def fwd(k, eta):
            q = idx_ref[k, :]
            A = ((q // LANE)[:, None] == hi_iota).astype(mxu)
            r0 = pl.multiple_of(k * KHI, KHI)
            ck = coef_ref[pl.ds(r0, KHI), :].astype(mxu)
            rows = jnp.dot(A, ck, preferred_element_type=jnp.float32)
            B = ((q % LANE)[:, None] == lo_iota).astype(jnp.float32)
            r = (rows * B).sum(axis=1)
            if has_val:
                r = r * val_ref[k, :]
            return eta + r

        eta = jax.lax.fori_loop(0, F, fwd, jnp.zeros((CH,), jnp.float32))
        eta_ref[...] = eta
        if matvec_only:
            return
        cvec, loss = deriv_and_loss(eta, y_ref[...], w_ref[...])
        acc_ref[...] += jnp.sum(loss)[None, None]

        def bwd(k, _):
            q = idx_ref[k, :]
            A = ((q // LANE)[:, None] == hi_iota).astype(mxu)
            B = ((q % LANE)[:, None] == lo_iota).astype(mxu)
            ck = cvec * val_ref[k, :] if has_val else cvec
            Z = B * ck[:, None].astype(mxu)
            g = jax.lax.dot_general(A, Z, (((0,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            r0 = pl.multiple_of(k * KHI, KHI)
            grad_ref[pl.ds(r0, KHI), :] += g
            return 0

        jax.lax.fori_loop(0, F, bwd, 0)

    in_specs = [pl.BlockSpec((F, CH), lambda i: (0, i), memory_space=pltpu.VMEM)]
    args = [idx_t]
    if not matvec_only:
        in_specs += [pl.BlockSpec((CH,), lambda i: (i,), memory_space=pltpu.VMEM),
                     pl.BlockSpec((CH,), lambda i: (i,), memory_space=pltpu.VMEM)]
        args += [yp, wp]
    if has_val:
        in_specs.append(pl.BlockSpec((F, CH), lambda i: (0, i),
                                     memory_space=pltpu.VMEM))
        args.append(val_t)
    in_specs.append(pl.BlockSpec((F * KHI, LANE), lambda i: (0, 0),
                                 memory_space=pltpu.VMEM))
    args.append(coef2)

    eta_spec = pl.BlockSpec((CH,), lambda i: (i,), memory_space=pltpu.VMEM)
    eta_shape = jax.ShapeDtypeStruct((n_pad,), jnp.float32)
    if matvec_only:
        out_specs, out_shape = [eta_spec], [eta_shape]
    else:
        out_specs = [pl.BlockSpec((F * KHI, LANE), lambda i: (0, 0),
                                  memory_space=pltpu.VMEM),
                     eta_spec,
                     pl.BlockSpec((1, 1), lambda i: (0, 0),
                                  memory_space=pltpu.VMEM)]
        out_shape = [jax.ShapeDtypeStruct((F * KHI, LANE), jnp.float32),
                     eta_shape,
                     jax.ShapeDtypeStruct((1, 1), jnp.float32)]

    res = pl.pallas_call(kernel, grid=(n_pad // CH,), in_specs=in_specs,
                         out_specs=out_specs, out_shape=out_shape,
                         interpret=interpret)(*args)
    if matvec_only:
        return res[0][:n]
    grad, eta, loss = res
    return grad.reshape(meta.dim), eta[:n], loss[0, 0]


def fb_fused_grad(fb_idx, y, w, coef, meta: FieldBlockMeta, deriv_and_loss,
                  val=None, chunk: int = 4096, interpret: bool = False):
    """One fused pass over the shard: (grad, eta, loss_sum).

    ``deriv_and_loss(eta, y, w) -> (c, loss_vec)`` inlines the unary loss
    into the kernel (the reference's per-loss classes under
    common/linear/unarylossfunc/ become VPU code). Rows stream through VMEM
    in ``chunk``-row tiles; the coefficient table and gradient accumulator
    never leave VMEM.
    """
    return _fused_pallas_call(fb_idx, y, w, coef, meta, deriv_and_loss,
                              val=val, chunk=chunk, interpret=interpret)


def fb_matvec_pallas(fb_idx, coef, meta: FieldBlockMeta, val=None,
                     chunk: int = 4096, interpret: bool = False):
    """eta = X @ coef via the Pallas layout (forward half of fb_fused_grad)."""
    return _fused_pallas_call(fb_idx, None, None, coef, meta, None,
                              val=val, chunk=chunk, interpret=interpret,
                              matvec_only=True)


# ---------------------------------------------------------------------------
# legacy fused Pallas kernel (LO=16 layout; kept as a reference
# implementation of the explicit VMEM/MXU mapping)
# ---------------------------------------------------------------------------

def fb_fused_grad_pallas(fb_idx_t, y, w, coef, meta: FieldBlockMeta,
                         deriv_and_loss, chunk: int = 4096,
                         interpret: bool = False):
    """One pass over the shard: eta, per-sample derivative, gradient, loss.

    ``fb_idx_t``: (F, n_pad) transposed field-local indices (n_pad a
    multiple of ``chunk``); ``deriv_and_loss(eta, y, w) -> (c, loss_vec)``
    is inlined into the kernel (the reference's per-loss classes under
    common/linear/unarylossfunc/ become VPU code here).

    Grid streams row chunks from HBM; the coefficient table and the
    gradient accumulator stay VMEM-resident across all grid steps.
    Returns (grad_flat, eta, loss_sum).
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # interpret mode runs on the host backend, whose dot lacks bf16 support
    mxu = jnp.float32 if interpret else jnp.bfloat16

    F, S, H = meta.num_fields, meta.field_size, meta.hi_size
    CH = int(chunk)
    n_pad = fb_idx_t.shape[1]
    if n_pad % CH:
        raise ValueError(f"padded rows {n_pad} not a multiple of chunk {CH}")
    coef_hl = coef.reshape(F * H, LO)

    def kernel(idx_ref, y_ref, w_ref, coef_ref, grad_ref, eta_ref, acc_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _():
            grad_ref[...] = jnp.zeros_like(grad_ref)
            acc_ref[...] = jnp.zeros_like(acc_ref)

        hi_iota = jax.lax.broadcasted_iota(jnp.int32, (CH, H), 1)
        lo_iota = jax.lax.broadcasted_iota(jnp.int32, (CH, LO), 1)

        def fwd(k, eta):
            q = idx_ref[k, :]
            hi = (q // LO)[:, None]
            lo = (q % LO)[:, None]
            A = (hi == hi_iota).astype(mxu)
            r0 = pl.multiple_of(k * H, H)
            ck = coef_ref[pl.ds(r0, H), :].astype(mxu)
            rows = jnp.dot(A, ck, preferred_element_type=jnp.float32)
            B = (lo == lo_iota).astype(jnp.float32)
            return eta + (rows * B).sum(axis=1)

        eta = jax.lax.fori_loop(0, F, fwd, jnp.zeros((CH,), jnp.float32))
        yv, wv = y_ref[...], w_ref[...]
        cvec, loss = deriv_and_loss(eta, yv, wv)
        acc_ref[...] += jnp.sum(loss)[None, None]
        eta_ref[...] = eta
        cb = cvec[:, None].astype(mxu)

        def bwd(k, _):
            q = idx_ref[k, :]
            hi = (q // LO)[:, None]
            lo = (q % LO)[:, None]
            A = (hi == hi_iota).astype(mxu)
            B = (lo == lo_iota).astype(mxu)
            g = jax.lax.dot_general(A, B * cb, (((0,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            r0 = pl.multiple_of(k * H, H)
            grad_ref[pl.ds(r0, H), :] += g
            return 0

        jax.lax.fori_loop(0, F, bwd, 0)

    grad, eta, loss = pl.pallas_call(
        kernel,
        grid=(n_pad // CH,),
        in_specs=[
            pl.BlockSpec((F, CH), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((CH,), lambda i: (i,), memory_space=pltpu.VMEM),
            pl.BlockSpec((CH,), lambda i: (i,), memory_space=pltpu.VMEM),
            pl.BlockSpec((F * H, LO), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((F * H, LO), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((CH,), lambda i: (i,), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((F * H, LO), jnp.float32),
            jax.ShapeDtypeStruct((n_pad,), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(fb_idx_t, y, w, coef_hl)
    return grad.reshape(meta.dim), eta, loss[0, 0]
