"""Multi-tenant model-fleet serving (ISSUE 17 tentpole).

The reference's platform role is many scenario models behind one
cluster (per-country FTRL arms, per-surface trees, A/B variants); its
``LocalPredictor``/``ModelMapperAdapter`` layer instantiates per model
because the JVM cannot share a compiled program across them. Here it
can: weights are program ARGUMENTS (PR 10), so N same-geometry models
share ONE compiled bucket program. This module is the registry + server
that turns that into a fleet:

* :class:`ModelRegistry` keys tenants by serving-kernel GEOMETRY — the
  :class:`~alink_tpu.serving.plan.ServingPlan` ``geometry_key()``
  (model signature x encoding x dtype x bucket set) — so every tenant
  in a geometry group serves through the group's shared programs;
* :class:`FleetServer` routes per-request tenant ids and COALESCES
  batches across tenants of one group: the group's weight arrays stack
  along a leading tenant-lane axis (the tuning ``(points,)`` carry-lane
  idiom) and each request row gathers its own tenant's weights via an
  int32 lane vector. The stack is the group's cached LANE TABLE —
  every resident member at a stable slot, rebuilt only when a member
  mutates — so steady-state dispatches never pay per-batch stacking.
  Per-row arithmetic and reduction order are IDENTICAL to the
  single-model programs (``ServingKernel.make_fleet_fns`` contract),
  so coalescing is a bitwise no-op vs per-tenant dispatch —
  tests/test_fleet.py pins it;
* cold tenants' device weights are LRU-EVICTED under the
  ``ALINK_TPU_FLEET_HBM_BUDGET`` device-bytes budget and re-admitted
  transparently from the PR-2 snapshot store (``common/checkpoint.py``)
  on their next request — bitwise-identically (the ``.npy`` round trip
  is exact), and an eviction can never race an in-flight swap (the
  evictor only takes tenant locks it can get without blocking);
* per-tenant isolation rides the PR-14 resilience machinery: admission
  quotas (:class:`~alink_tpu.serving.resilience.TenantQuotaExceeded` —
  one tenant's storm fills its own slots, everyone else's admission is
  untouched), per-request deadlines with typed shedding, and a
  per-(tenant, model-version) :class:`~alink_tpu.serving.resilience.
  CircuitBreaker` that degrades ONLY the broken tenant to its host
  mapper while its lane is simply left out of the coalesced batch;
* per-tenant swap streams multiplex through ONE
  :class:`~alink_tpu.serving.server.ModelStreamFeeder`:
  :meth:`FleetServer.feeder_target` adapts the fleet to the feeder's
  ``swap_model`` contract with a tenant router, so a merged snapshot
  stream hot-swaps each tenant independently with zero torn responses.

Observability (ISSUE 16 ops plane): ``alink_fleet_{tenants,
evictions_total,readmissions_total,coalesced_batches_total}`` metrics,
per-tenant rows on adminz ``/statusz``, a fleet section in
``tools/fleetz.py`` aggregates and a ``tools/doctor.py`` fleet verdict.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..common import aotcache, compileledger, reqtrace
from ..common.adminz import acquire_admin, release_admin
from ..common.plan import serving_event_plan
from ..common.checkpoint import load_latest_validated, save_checkpoint
from ..common.faults import FaultInjected, maybe_crash
from ..common.metrics import get_registry, metrics_enabled
from ..common.mtable import MTable
from ..common.tracing import trace_complete, trace_instant
from ..operator.stream.prefetch import _Channel, _EMPTY, _SENTINEL
from .loadgen import percentile as _percentile
from .plan import ServingPlan
from .predictor import (ServingKernel, record_serve_fallback, serve_buckets,
                        serve_min_fill, serve_queue_depth, serve_window_s)
from .resilience import (OPEN, CircuitBreaker, DeadlineExceeded,
                         ReplicaCrashed, RequestCancelled,
                         TenantQuotaExceeded, record_shed,
                         serve_breaker_enabled)
from .server import RequestFuture

_P99_RING = 4096
_TENANT_RING = 256      # per-tenant rolling latency window (SLO clauses)

__all__ = [
    "FleetServer", "ModelRegistry", "fleet_coalesce_enabled",
    "fleet_hbm_budget", "fleet_lanes", "fleet_snapshot_dir",
    "fleet_tenant_quota",
]


# -- flag accessors (common/flags.py registry) ------------------------------

def fleet_hbm_budget() -> int:
    """``ALINK_TPU_FLEET_HBM_BUDGET``: device-bytes budget for resident
    tenant weights; 0 = unlimited (no eviction)."""
    from ..common.flags import flag_value
    return int(flag_value("ALINK_TPU_FLEET_HBM_BUDGET", 0))


def fleet_lanes(default: Sequence[int] = (4, 16, 64)) -> Tuple[int, ...]:
    """``ALINK_TPU_FLEET_LANES``: the tenant-lane bucket set of the
    coalesced programs (comma-separated, like the row buckets): a
    dispatch spanning k tenants pads its weight stack to the smallest
    covering lane bucket, so a handful of compiled lane widths cover
    any tenant mix."""
    from ..common.flags import flag_value
    raw = flag_value("ALINK_TPU_FLEET_LANES", "")
    if not raw:
        return tuple(default)
    out = sorted({int(p) for p in str(raw).split(",") if p.strip()
                  if int(p) > 0})
    return tuple(out) or tuple(default)


def fleet_tenant_quota() -> int:
    """``ALINK_TPU_FLEET_TENANT_QUOTA``: max in-flight requests per
    tenant; 0 = unlimited. Exceeding it is a typed admission rejection
    (:class:`TenantQuotaExceeded`, shed reason ``"quota"``)."""
    from ..common.flags import flag_value
    return int(flag_value("ALINK_TPU_FLEET_TENANT_QUOTA", 0))


def fleet_coalesce_enabled() -> bool:
    """``ALINK_TPU_FLEET_COALESCE``: cross-tenant batch coalescing
    through the lane-stacked programs. Off = per-tenant dispatch
    through the group's single-model programs (bitwise-identical
    answers either way — that is the ``make_fleet_fns`` contract)."""
    from ..common.flags import flag_value
    return bool(flag_value("ALINK_TPU_FLEET_COALESCE", True))


def fleet_snapshot_dir() -> str:
    """``ALINK_TPU_FLEET_SNAPSHOT_DIR``: root of the per-tenant model
    snapshot store (the eviction/re-admission backing). Empty = a
    process-lifetime temp directory."""
    from ..common.flags import flag_value
    return str(flag_value("ALINK_TPU_FLEET_SNAPSHOT_DIR", ""))


def _tenant_dirname(tid: str) -> str:
    """Filesystem-safe per-tenant snapshot subdirectory name."""
    return "".join(c if (c.isalnum() or c in "._-") else "_"
                   for c in str(tid)) or "_"


# -- registry ---------------------------------------------------------------

class _Tenant:
    """One registered model: host mapper (always resident — it is the
    breaker fallback and the decode authority), latest kernel, device
    weights (``None`` while evicted), LRU stamp and counters. ``lock``
    serializes swap vs eviction vs re-admission for THIS tenant."""

    __slots__ = ("tid", "mapper", "kernel", "version", "lock",
                 "device_arrays", "nbytes", "last_used", "snap_dir",
                 "requests", "failed", "shed", "evictions",
                 "readmissions", "swaps", "latencies")

    def __init__(self, tid: str, mapper, kernel: ServingKernel,
                 snap_dir: str):
        self.tid = tid
        self.mapper = mapper
        self.kernel = kernel
        self.version = 1
        self.lock = threading.Lock()
        self.device_arrays: Optional[Tuple] = None
        self.nbytes = 0
        self.last_used = 0
        self.snap_dir = snap_dir
        self.requests = 0
        self.failed = 0
        self.shed = 0
        self.evictions = 0
        self.readmissions = 0
        self.swaps = 0
        self.latencies: deque = deque(maxlen=_TENANT_RING)


class _GeometryGroup:
    """One serving geometry: the shared compiled-program cache of every
    tenant whose :class:`ServingPlan` is equal. ``archetype`` is the
    first registered kernel — its ``device_fns``/``make_fleet_fns`` are
    version-independent pure functions of ``(model_arrays, *encoded)``,
    which is exactly why tenants can share them (the PR-10 contract)."""

    def __init__(self, plan: ServingPlan, archetype: ServingKernel):
        self.plan = plan
        self.archetype = archetype
        self.fleet_fns = (archetype.make_fleet_fns()
                          if archetype.make_fleet_fns is not None else None)
        self.tenants = 0
        self._lock = threading.Lock()
        self._programs: Dict[Tuple, Callable] = {}
        self.hits = 0
        self.misses = 0
        # the coalesced lane table: every resident member stacked once
        # along the lane axis with a stable slot per tenant, reused by
        # every dispatch until a member mutates (``bump_lanes``).
        # ``(L, {tid: slot}, stacked_arrays)`` or None.
        self.lane_stamp = 0
        self._lane_cache: Optional[Tuple] = None

    def bump_lanes(self) -> None:
        """Invalidate the lane table — called by the registry on ANY
        member mutation (register, swap, evict, re-admit), so a cached
        stack can never serve stale or foreign weights."""
        with self._lock:
            self.lane_stamp += 1
            self._lane_cache = None

    def program(self, kind: str, bucket: int, trailing: Tuple,
                lanes: Optional[int] = None) -> Callable:
        """The compiled program for (kind, bucket, trailing shapes,
        lane width): ``lanes=None`` is the single-model program (the
        archetype's ``device_fns``), an int is the lane-stacked
        coalesced twin. Every dimension rides ``plan.program_key`` —
        a cache hit can never serve a stale program."""
        key = self.plan.program_key(kind, bucket, trailing, lanes=lanes)
        prog = self._programs.get(key)
        if prog is not None:
            self.hits += 1
            compileledger.record_hit("fleet.group")
            return prog
        import jax
        _led_t0 = time.perf_counter()
        with self._lock:
            prog = self._programs.get(key)
            if prog is None:
                self.misses += 1
                evplan = serving_event_plan(self.plan, kind=kind,
                                            bucket=bucket,
                                            trailing=trailing,
                                            lanes=lanes)
                # load-before-compile (ISSUE 20): a geometry another
                # process already compiled installs from disk; a fresh
                # compile exports itself at first dispatch (the group
                # never sees example arguments before then)
                if aotcache.active():
                    loaded = aotcache.load(
                        evplan, cache="fleet.group",
                        site="_GeometryGroup.program", subsystem="fleet")
                    if loaded is not None:
                        prog = self._programs[key] = loaded.fn
                        return prog
                fn = (self.archetype.device_fns[kind] if lanes is None
                      else self.fleet_fns[kind])
                prog = jax.jit(fn)
                if aotcache.active():
                    prog = aotcache.deferred_store(
                        evplan, prog, cache="fleet.group",
                        site="_GeometryGroup.program", key=key)
                self._programs[key] = prog
                compileledger.record_event(
                    "fleet.group", evplan,
                    wall_s=time.perf_counter() - _led_t0,
                    site="_GeometryGroup.program", subsystem="fleet")
            else:
                self.hits += 1
                compileledger.record_hit("fleet.group")
        return prog

    def warm_from_disk(self) -> int:
        """Install every AOT artifact whose program key, re-derived
        against THIS group's plan, still digests to the artifact's plan
        digest — the tenant-geometry grid of a previous process loads
        before the fleet admits traffic.  Returns programs installed."""
        if not aotcache.active():
            return 0
        import ast
        n = 0
        for _path, header in aotcache.scan("fleet.group"):
            try:
                key = ast.literal_eval(header.get("key_repr") or "")
            except Exception:
                continue
            if not isinstance(key, tuple) or len(key) != 7:
                continue
            sig, kind, bucket, trailing, buckets, lanes, _mesh = key
            if tuple(buckets) != tuple(self.plan.buckets) \
                    or tuple(sig) != tuple(self.plan.signature):
                continue
            evplan = serving_event_plan(self.plan, kind=kind,
                                        bucket=bucket,
                                        trailing=tuple(trailing),
                                        lanes=lanes)
            if evplan.digest() != header.get("plan_digest"):
                continue
            key = self.plan.program_key(kind, bucket, tuple(trailing),
                                        lanes=lanes)
            with self._lock:
                if key in self._programs:
                    continue
            loaded = aotcache.load(evplan, cache="fleet.group",
                                   site="_GeometryGroup.warm_from_disk",
                                   subsystem="fleet")
            if loaded is None:
                continue
            with self._lock:
                if key not in self._programs:
                    self._programs[key] = loaded.fn
                    n += 1
        return n

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"tenants": self.tenants, "programs": len(self._programs),
                    "hits": self.hits, "misses": self.misses}


class ModelRegistry:
    """Tenant registry: geometry grouping, device-weight residency under
    the HBM budget, and the snapshot store behind eviction/re-admission.

    ``register(tenant_id, mapper)`` takes a LOADED mapper implementing
    ``serving_kernel()``; the tenant's weights go on device and a
    snapshot lands in the store (``<snapshot_dir>/<tenant>/``) with the
    plan's ``swap_signature()`` as the validation signature — a
    re-admission can never resurrect weights of a different geometry.

    Locking: ``tenant.lock`` (outer) serializes swap/evict/re-admit per
    tenant; the registry lock (inner) covers only the tenant map, the
    LRU clock and the byte ledger. The evictor acquires tenant locks
    ``blocking=False`` ONLY — a tenant mid-swap (or mid-re-admission)
    is simply skipped this round, so eviction can never race an
    in-flight swap.
    """

    def __init__(self, snapshot_dir: Optional[str] = None,
                 buckets: Optional[Sequence[int]] = None,
                 hbm_budget: Optional[int] = None, name: str = "fleet"):
        self.name = name
        d = snapshot_dir or fleet_snapshot_dir()
        if not d:
            d = tempfile.mkdtemp(prefix="alink-fleet-")
        self.snapshot_dir = d
        self._buckets = tuple(sorted({int(b) for b in buckets
                                      if int(b) > 0})) \
            if buckets else serve_buckets()
        self._budget = fleet_hbm_budget() if hbm_budget is None \
            else int(hbm_budget)
        self._lock = threading.Lock()
        self._tenants: Dict[str, _Tenant] = {}
        self._groups: Dict[Tuple, _GeometryGroup] = {}
        self._group_of: Dict[str, _GeometryGroup] = {}
        self._clock = 0
        self._resident_bytes = 0
        self._evictions = 0
        self._readmissions = 0

    # -- registration / swap -------------------------------------------
    def _plan_for(self, kernel: ServingKernel) -> ServingPlan:
        # fleet v1 is single-device (replica/sharded fleets ride ROADMAP
        # item 5); the plan still carries sharded/mesh_fp so the
        # geometry key stays honest when that lands
        return ServingPlan(signature=kernel.signature,
                           buckets=self._buckets)

    def register(self, tenant_id: str, mapper) -> ServingPlan:
        """Admit one tenant: geometry-group it, place its weights,
        snapshot it, and evict over budget. Idempotent registration is
        an error — re-loading a tenant's model is :meth:`swap_tenant`."""
        tid = str(tenant_id)
        compileledger.subsystem_start("fleet")
        kernel = mapper.serving_kernel()
        if kernel is None:
            raise TypeError(
                f"tenant {tid!r}: {type(mapper).__name__} does not "
                f"provide a serving kernel")
        plan = self._plan_for(kernel)
        tenant = _Tenant(tid, mapper, kernel,
                         os.path.join(self.snapshot_dir,
                                      _tenant_dirname(tid)))
        with self._lock:
            if tid in self._tenants:
                raise ValueError(f"tenant {tid!r} is already registered "
                                 f"(swap_tenant replaces its model)")
            group = self._groups.get(plan.geometry_key())
            if group is None:
                group = self._groups[plan.geometry_key()] = \
                    _GeometryGroup(plan, kernel)
                if group.fleet_fns is None:
                    record_serve_fallback(type(mapper).__name__,
                                          "no-fleet-kernel",
                                          "tenants of this geometry serve "
                                          "per-tenant (uncoalesced)")
            group.tenants += 1
            self._tenants[tid] = tenant
            self._group_of[tid] = group
        self._snapshot(tenant, plan)
        self._admit_arrays(tenant, kernel.model_arrays)
        self._evict_to_budget(keep=tid)
        if metrics_enabled():
            get_registry().set_gauge("alink_fleet_tenants",
                                     len(self._tenants),
                                     {"fleet": self.name})
        return plan

    def swap_tenant(self, tenant_id: str, model_table: MTable) -> int:
        """Hot-swap one tenant's model (the predictor's double-buffer
        contract, per tenant): mapper build, kernel extraction, device
        placement and the snapshot all happen under the TENANT's lock
        on the caller's thread, then the references flip together; a
        coalesced dispatch in flight keeps the arrays it already
        gathered. A snapshot whose geometry differs from the tenant's
        group is REFUSED (poisoned — a different geometry would need
        new programs and a new group)."""
        t = self._tenant(tenant_id)
        group = self._group_of[t.tid]
        with t.lock:
            maybe_crash("serve.swap")   # the feeders' chaos site
            base = t.mapper
            mapper = type(base)(model_table.schema, base.data_schema,
                                base.params)
            mapper.load_model(model_table)
            kernel = mapper.serving_kernel()
            plan = self._plan_for(kernel)
            if plan.geometry_key() != group.plan.geometry_key():
                raise ValueError(
                    f"tenant {t.tid!r} swap geometry mismatch: "
                    f"{plan.swap_signature()} vs the tenant's group "
                    f"{group.plan.swap_signature()} — a different "
                    f"geometry must register as a new tenant")
            t.version += 1
            t.swaps += 1
            save_checkpoint(t.snap_dir, t.version,
                            [np.asarray(a) for a in kernel.model_arrays],
                            meta={"signature": plan.swap_signature(),
                                  "tenant": t.tid},
                            scope="fleet", keep_last=2)
            was = t.nbytes if t.device_arrays is not None else 0
            import jax
            arrays = tuple(jax.device_put(a) for a in kernel.model_arrays)
            nbytes = sum(int(a.nbytes) for a in arrays)
            # the flip: mapper/kernel/arrays move together under the lock
            t.mapper, t.kernel = mapper, kernel
            t.device_arrays, t.nbytes = arrays, nbytes
            with self._lock:
                self._resident_bytes += nbytes - was
            version = t.version
        group.bump_lanes()
        reqtrace.annotate_inflight("swap", {"fleet": self.name,
                                            "tenant": t.tid,
                                            "version": version})
        self._evict_to_budget(keep=t.tid)
        if metrics_enabled():
            reg = get_registry()
            reg.inc("alink_serve_model_swaps_total", 1,
                    {"predictor": f"{self.name}:{t.tid}"})
        return version

    def _snapshot(self, t: _Tenant, plan: ServingPlan) -> None:
        save_checkpoint(t.snap_dir, t.version,
                        [np.asarray(a) for a in t.kernel.model_arrays],
                        meta={"signature": plan.swap_signature(),
                              "tenant": t.tid},
                        scope="fleet", keep_last=2)

    def _admit_arrays(self, t: _Tenant, host_arrays: Sequence) -> None:
        import jax
        with t.lock:
            if t.device_arrays is not None:
                return
            arrays = tuple(jax.device_put(a) for a in host_arrays)
            t.device_arrays = arrays
            t.nbytes = sum(int(a.nbytes) for a in arrays)
            with self._lock:
                self._resident_bytes += t.nbytes
        self._group_of[t.tid].bump_lanes()

    # -- residency / LRU ------------------------------------------------
    def _tenant(self, tenant_id: str) -> _Tenant:
        t = self._tenants.get(str(tenant_id))
        if t is None:
            raise KeyError(f"unknown tenant {tenant_id!r} (register it "
                           f"before serving it)")
        return t

    def arrays_for(self, tenant_id: str) -> Tuple:
        """The tenant's device weights, touching its LRU stamp; an
        EVICTED tenant re-admits here from the snapshot store — bitwise
        (``.npy`` round trip), geometry-validated against the group
        plan's ``swap_signature()``, transparently to the caller."""
        t = self._tenant(tenant_id)
        with self._lock:
            self._clock += 1
            t.last_used = self._clock
        arrays = t.device_arrays
        if arrays is not None:
            return arrays
        group = self._group_of[t.tid]
        with t.lock:
            if t.device_arrays is not None:    # raced another re-admit
                return t.device_arrays
            loaded = load_latest_validated(
                t.snap_dir, group.plan.swap_signature(),
                scope="fleet", what="fleet tenant model")
            if loaded is None:
                raise RuntimeError(
                    f"tenant {t.tid!r} was evicted and its snapshot "
                    f"store {t.snap_dir!r} holds no valid snapshot")
            payload, _meta = loaded
            import jax
            arrays = tuple(jax.device_put(np.asarray(a)) for a in payload)
            t.device_arrays = arrays
            t.nbytes = sum(int(a.nbytes) for a in arrays)
            t.readmissions += 1
            with self._lock:
                self._resident_bytes += t.nbytes
                self._readmissions += 1
        group.bump_lanes()
        trace_instant("fleet.readmit", cat="serve",
                      args={"tenant": t.tid, "bytes": t.nbytes})
        reqtrace.annotate_inflight("readmit", {"fleet": self.name,
                                               "tenant": t.tid,
                                               "bytes": t.nbytes})
        if metrics_enabled():
            get_registry().inc("alink_fleet_readmissions_total", 1,
                               {"fleet": self.name})
        self._evict_to_budget(keep=t.tid)
        return arrays

    def _evict_to_budget(self, keep: Optional[str] = None) -> int:
        """Drop cold tenants' device weights until the ledger fits the
        budget (0 = unlimited). Candidates go oldest-``last_used``
        first; ``keep`` (the tenant being admitted) and any tenant
        whose lock is HELD (a swap or re-admission in flight) are
        skipped — the no-race rule. References are dropped, never
        ``delete()``d: a coalesced dispatch that already gathered the
        arrays keeps them alive until it lands."""
        if self._budget <= 0:
            return 0
        evicted = 0
        while True:
            with self._lock:
                if self._resident_bytes <= self._budget:
                    break
                candidates = sorted(
                    (t for t in self._tenants.values()
                     if t.device_arrays is not None and t.tid != keep),
                    key=lambda t: t.last_used)
            if not candidates:
                break
            progressed = False
            for t in candidates:
                if not t.lock.acquire(blocking=False):
                    continue            # mid-swap / mid-re-admit: skip
                try:
                    if t.device_arrays is None:
                        continue
                    t.device_arrays = None
                    t.evictions += 1
                    evicted += 1
                    progressed = True
                    with self._lock:
                        self._resident_bytes -= t.nbytes
                        self._evictions += 1
                        done = self._resident_bytes <= self._budget
                finally:
                    t.lock.release()
                self._group_of[t.tid].bump_lanes()
                trace_instant("fleet.evict", cat="serve",
                              args={"tenant": t.tid, "bytes": t.nbytes})
                reqtrace.annotate_inflight("evict",
                                           {"fleet": self.name,
                                            "tenant": t.tid,
                                            "bytes": t.nbytes})
                if metrics_enabled():
                    get_registry().inc("alink_fleet_evictions_total", 1,
                                       {"fleet": self.name})
                if done:
                    break
            if not progressed:
                break                   # everything else is locked
        if evicted and metrics_enabled():
            get_registry().set_gauge("alink_fleet_resident_bytes",
                                     self._resident_bytes,
                                     {"fleet": self.name})
        return evicted

    def touch(self, tenant_ids: Sequence[str]) -> None:
        """LRU-touch without residency work: the coalesced fast path
        serves from the group's cached lane table and must still mark
        its tenants hot, or the evictor would read them as cold."""
        with self._lock:
            for tid in tenant_ids:
                t = self._tenants.get(str(tid))
                if t is not None:
                    self._clock += 1
                    t.last_used = self._clock

    # -- lookups / stats ------------------------------------------------
    def tenant_ids(self) -> List[str]:
        with self._lock:
            return list(self._tenants)

    def group_tenants(self, group: _GeometryGroup) -> List[_Tenant]:
        """Every tenant of ``group`` (the lane-table rebuild scan)."""
        with self._lock:
            return [t for tid, t in self._tenants.items()
                    if self._group_of[tid] is group]

    def tenant(self, tenant_id: str) -> _Tenant:
        return self._tenant(tenant_id)

    def group_of(self, tenant_id: str) -> _GeometryGroup:
        self._tenant(tenant_id)
        return self._group_of[str(tenant_id)]

    @property
    def buckets(self) -> Tuple[int, ...]:
        return self._buckets

    @property
    def hbm_budget(self) -> int:
        return self._budget

    def resident_bytes(self) -> int:
        with self._lock:
            return self._resident_bytes

    def warm_from_disk(self) -> int:
        """Admission warming (ISSUE 20): every registered geometry
        group pre-installs its exported bucket x lane programs from the
        AOT cache.  Called by ``FleetServer`` before its readiness
        source arms; returns programs installed across all groups."""
        if not aotcache.active():
            return 0
        with self._lock:
            groups = list(self._groups.values())
        return sum(g.warm_from_disk() for g in groups)

    def stats(self) -> dict:
        with self._lock:
            tenants = list(self._tenants.values())
            groups = list(self._groups.values())
            resident = self._resident_bytes
            ev, re = self._evictions, self._readmissions
        return {
            "tenants": len(tenants),
            "geometry_groups": len(groups),
            "resident": sum(1 for t in tenants
                            if t.device_arrays is not None),
            "resident_bytes": resident,
            "hbm_budget": self._budget,
            "evictions": ev, "readmissions": re,
            "programs": sum(g.stats()["programs"] for g in groups),
        }


# -- fleet server -----------------------------------------------------------

class _FleetRequest(RequestFuture):
    __slots__ = ("tenant",)

    def __init__(self, tenant: str, row: Tuple,
                 deadline_s: Optional[float] = None):
        super().__init__(row, deadline_s=deadline_s)
        self.tenant = tenant


class _FleetSwapTarget:
    """Adapter exposing the :class:`~alink_tpu.serving.server.
    ModelStreamFeeder` ``swap_model`` contract over the fleet: ONE
    feeder drains a MERGED multi-tenant snapshot stream and
    ``tenant_of(model_table)`` routes each snapshot to its tenant —
    per-tenant swap streams multiplexed through one feeder. ``swaps``
    records ``(tenant, version, model_table)`` so a bench/test can
    re-validate per-tenant responses against the exact model set."""

    def __init__(self, registry: ModelRegistry,
                 tenant_of: Callable[[MTable], str]):
        self._registry = registry
        self._tenant_of = tenant_of
        self.swaps: List[Tuple[str, int, MTable]] = []
        self._lock = threading.Lock()

    def swap_model(self, model_table: MTable) -> int:
        tenant = str(self._tenant_of(model_table))
        version = self._registry.swap_tenant(tenant, model_table)
        with self._lock:
            self.swaps.append((tenant, version, model_table))
        return version


class FleetServer:
    """Micro-batching fleet front end over a :class:`ModelRegistry`.

    One admission channel, one supervised serving loop: each drained
    batch sheds deadline/cancelled requests, splits by tenant, and
    dispatches per GEOMETRY GROUP — tenants of one group coalesce into
    one lane-stacked program execution (when the kernel provides
    ``make_fleet_fns`` and ``ALINK_TPU_FLEET_COALESCE`` is on),
    everything else serves per tenant through the group's single-model
    programs. Both paths answer bitwise-identically.
    """

    def __init__(self, registry: ModelRegistry,
                 max_batch: Optional[int] = None,
                 window_s: Optional[float] = None,
                 queue_depth: Optional[int] = None,
                 min_fill: Optional[int] = None,
                 name: str = "fleet"):
        self.registry = registry
        self.name = name
        self.max_batch = int(max_batch) if max_batch \
            else registry.buckets[-1]
        self.window_s = serve_window_s() if window_s is None \
            else float(window_s)
        self.min_fill = serve_min_fill() if min_fill is None \
            else max(1, int(min_fill))
        depth = serve_queue_depth() if queue_depth is None \
            else int(queue_depth)
        self._quota = fleet_tenant_quota()
        self._ch = _Channel(max(1, depth), gauge_label=name)
        self._closed = threading.Event()
        self._stats_lock = threading.Lock()
        self._requests = 0
        self._failed = 0
        self._batches = 0
        self._coalesced = 0
        self._uncoalesced = 0
        self._shed = 0
        self._fallback_batches = 0
        self._respawns = 0
        self._quarantined = 0
        self._lane_rebuilds = 0
        self._latencies: deque = deque(maxlen=_P99_RING)
        self._inflight: Dict[str, int] = {}
        self._inflight_lock = threading.Lock()
        # per-tenant breakers: {tenant: (version, CircuitBreaker)} — a
        # swap retires the old version's breaker (totals carry over)
        self._breaker_lock = threading.Lock()
        self._breakers: Dict[str, Tuple[int, CircuitBreaker]] = {}
        self._breaker_totals = {"opens": 0, "reopens": 0, "probes": 0}
        # admission warming (ISSUE 20): the registered tenant
        # geometries pre-install their exported programs BEFORE the
        # readiness source arms below — /readyz never flips while the
        # first cross-tenant batches would pay compiles the disk holds
        self.warmed_programs = 0
        try:
            self.warmed_programs = registry.warm_from_disk()
        except Exception:
            pass
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"alink-fleet-{name}")
        self._thread.start()
        self._admin = acquire_admin(name)
        if self._admin is not None:
            self._admin.add_source(f"fleet:{name}", self._readiness)
            self._admin.add_status(f"fleet:{name}", self.status)

    # -- submission (any thread) ---------------------------------------
    def submit(self, tenant_id: str, row: Tuple,
               deadline_s: Optional[float] = None) -> RequestFuture:
        """Enqueue one request for ``tenant_id``. Admission-time
        isolation: an unknown tenant and a tenant over its in-flight
        quota are SYNCHRONOUS typed rejections (``KeyError`` /
        :class:`TenantQuotaExceeded`) — they never consume channel
        slots another tenant could use. A full channel blocks
        (backpressure), deadlines shed in the loop (typed, pre-
        dispatch), exactly like :class:`PredictServer`."""
        if self._closed.is_set():
            raise RuntimeError(f"FleetServer {self.name!r} is closed")
        tid = str(tenant_id)
        self.registry.tenant(tid)            # typed KeyError if unknown
        if self._quota > 0:
            with self._inflight_lock:
                n = self._inflight.get(tid, 0)
                if n >= self._quota:
                    with self._stats_lock:
                        self._shed += 1
                    t = self.registry.tenant(tid)
                    t.shed += 1
                    record_shed(self.name, "quota")
                    raise TenantQuotaExceeded(tid, n, self._quota)
                self._inflight[tid] = n + 1
        fut = _FleetRequest(tid, tuple(row), deadline_s=deadline_s)
        fut.ctx = reqtrace.admit(tenant=tid)
        if not self._ch.put(fut):
            self._release_slot(tid)
            reqtrace.finish(fut.ctx, outcome="rejected_closed")
            raise RuntimeError(f"FleetServer {self.name!r} is closed")
        return fut

    def predict(self, tenant_id: str, row: Tuple,
                timeout: Optional[float] = None,
                deadline_s: Optional[float] = None) -> Tuple:
        return self.submit(tenant_id, row,
                           deadline_s=deadline_s).result(timeout)

    def swap_tenant(self, tenant_id: str, model_table: MTable) -> int:
        return self.registry.swap_tenant(tenant_id, model_table)

    def feeder_target(self, tenant_of: Callable[[MTable], str]
                      ) -> _FleetSwapTarget:
        """The multiplexing adapter: hand this to ONE
        :class:`~alink_tpu.serving.server.ModelStreamFeeder` as its
        ``server`` and every snapshot of the merged stream hot-swaps
        the tenant ``tenant_of(model_table)`` names."""
        return _FleetSwapTarget(self.registry, tenant_of)

    def _release_slot(self, tid: str) -> None:
        if self._quota > 0:
            with self._inflight_lock:
                n = self._inflight.get(tid, 1) - 1
                if n <= 0:
                    self._inflight.pop(tid, None)
                else:
                    self._inflight[tid] = n

    # -- the supervised serving loop ------------------------------------
    def _run(self) -> None:
        backoff = 0.01
        while True:
            inflight: List[_FleetRequest] = []
            try:
                self._loop(inflight)
                return
            except BaseException as e:
                quarantined = [f for f in inflight if not f.done()]
                for f in quarantined:
                    f.set_exception(ReplicaCrashed(0, e))
                    self._release_slot(f.tenant)
                    reqtrace.finish(f.ctx, outcome="replica_crashed")
                with self._stats_lock:
                    self._failed += len(quarantined)
                    self._quarantined += len(quarantined)
                    self._respawns += 1
                trace_instant("fleet.respawn", cat="serve",
                              args={"server": self.name,
                                    "quarantined": len(quarantined),
                                    "error": type(e).__name__})
                if metrics_enabled():
                    get_registry().inc("alink_serve_loop_respawns_total",
                                       1, {"server": self.name})
                if not isinstance(e, Exception):
                    raise
                time.sleep(backoff)
                backoff = min(0.5, backoff * 2)

    def _loop(self, inflight: List[_FleetRequest]) -> None:
        while True:
            del inflight[:]
            first = self._ch.get()
            if first is _SENTINEL:
                return
            if first.ctx is not None:
                first.ctx.mark("dequeue")
            inflight.append(first)
            deadline = None
            closing = False
            while len(inflight) < self.max_batch:
                got = self._ch.drain(self.max_batch - len(inflight))
                if got:
                    for f in got:
                        if f.ctx is not None:
                            f.ctx.mark("dequeue")
                    inflight.extend(got)
                    continue
                if len(inflight) >= self.min_fill:
                    break
                if deadline is None:
                    deadline = time.monotonic() + self.window_s
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                nxt = self._ch.get(timeout=remaining)
                if nxt is _EMPTY:
                    break
                if nxt is _SENTINEL:
                    closing = True
                    break
                if nxt.ctx is not None:
                    nxt.ctx.mark("dequeue")
                inflight.append(nxt)
            self._serve(inflight)
            if closing:
                return

    # -- shedding / breakers --------------------------------------------
    def _admit(self, batch: List[_FleetRequest],
               now: float) -> List[_FleetRequest]:
        kept: List[_FleetRequest] = []
        for fut in batch:
            if fut.cancelled():
                fut.set_exception(RequestCancelled(
                    "request cancelled before dispatch"))
                self._shed_one(fut, "cancelled")
                continue
            dl = fut.deadline_s
            if dl is not None:
                waited = now - fut.submitted_at
                if waited > dl:
                    fut.set_exception(DeadlineExceeded(waited, dl))
                    self._shed_one(fut, "deadline")
                    continue
            kept.append(fut)
        return kept

    def _shed_one(self, fut: _FleetRequest, reason: str) -> None:
        with self._stats_lock:
            self._shed += 1
        try:
            self.registry.tenant(fut.tenant).shed += 1
        except KeyError:
            pass
        record_shed(self.name, reason)
        self._release_slot(fut.tenant)
        reqtrace.finish(fut.ctx, outcome=f"shed_{reason}")

    def _breaker_for(self, tid: str, version: int) -> CircuitBreaker:
        """The tenant's ACTIVE-version breaker. Per-tenant state is the
        isolation: tenant A's failing model opens A's breaker and
        degrades A to ITS host mapper, while A's lane simply drops out
        of the coalesced batch — B's compiled path and error budget
        never notice."""
        with self._breaker_lock:
            got = self._breakers.get(tid)
            if got is not None and got[0] == version:
                return got[1]
            if got is not None:
                got[1].retire()
                s = got[1].snapshot()
                for k in self._breaker_totals:
                    self._breaker_totals[k] += s[k]
            br = CircuitBreaker(f"{self.name}:{tid}", version)
            self._breakers[tid] = (version, br)
            return br

    def breaker_stats(self) -> dict:
        with self._breaker_lock:
            snaps = {tid: br.snapshot()
                     for tid, (_v, br) in self._breakers.items()}
            totals = dict(self._breaker_totals)
        open_tenants = [tid for tid, s in snaps.items()
                        if s["state"] == OPEN]
        for s in snaps.values():
            for k in totals:
                totals[k] += s[k]
        return {"tenants_engaged": len(snaps),
                "open_tenants": open_tenants, **totals}

    # -- dispatch --------------------------------------------------------
    def _serve(self, batch: List[_FleetRequest]) -> None:
        batch = self._admit(batch, time.perf_counter())
        if not batch:
            return
        for f in batch:             # batch assembly / window hold ended
            if f.ctx is not None:
                f.ctx.mark("coalesce")
        # split by tenant, then stage per geometry group
        by_tenant: Dict[str, List[_FleetRequest]] = {}
        for f in batch:
            by_tenant.setdefault(f.tenant, []).append(f)
        staged: Dict[int, Tuple] = {}    # id(group) -> (group, entries)
        for tid, futs in by_tenant.items():
            try:
                group = self.registry.group_of(tid)
            except KeyError as e:        # unregistered mid-flight
                for f in futs:
                    f.set_exception(e)
                    self._release_slot(f.tenant)
                    reqtrace.finish(f.ctx, outcome="KeyError")
                with self._stats_lock:
                    self._failed += len(futs)
                continue
            staged.setdefault(id(group), (group, []))[1].append((tid, futs))
        for group, entries in staged.values():
            try:
                self._serve_group(group, entries)
            except FaultInjected:
                raise                    # supervisor quarantines+respawns
            except BaseException as e:
                for _tid, futs in entries:
                    for f in futs:
                        if not f.done():
                            f.set_exception(e)
                            self._release_slot(f.tenant)
                            reqtrace.finish(f.ctx,
                                            outcome=type(e).__name__)
                with self._stats_lock:
                    self._failed += sum(len(fs) for _t, fs in entries)

    def _serve_group(self, group: _GeometryGroup, entries: List) -> None:
        """One geometry group's slice of the batch: route each tenant
        through its breaker, host-serve the broken ones, coalesce the
        rest (or per-tenant dispatch when the kernel cannot coalesce),
        fan results back out per tenant."""
        maybe_crash("serve.dispatch")
        t0 = time.perf_counter()
        compiled: List[Tuple] = []       # (tenant, futs, route, breaker)
        for tid, futs in entries:
            br, route = None, "compiled"
            if serve_breaker_enabled():
                ten = self.registry.tenant(tid)
                br = self._breaker_for(tid, ten.version)
                route = br.acquire()
            if route == "fallback":
                self._serve_host(tid, futs)
            else:
                compiled.append((tid, futs, route, br))
        if not compiled:
            return
        use_lanes = group.fleet_fns is not None and fleet_coalesce_enabled()
        if use_lanes:
            self._dispatch_coalesced(group, compiled, t0)
        else:
            if group.fleet_fns is None:
                # recorded once per mapper+reason by predictor helper
                record_serve_fallback(
                    type(self.registry.tenant(compiled[0][0]).mapper
                         ).__name__,
                    "no-fleet-kernel")
            for tid, futs, route, br in compiled:
                self._dispatch_single(group, tid, futs, route, br, t0)
            with self._stats_lock:
                self._uncoalesced += 1

    def _serve_host(self, tid: str, futs: List[_FleetRequest]) -> None:
        """Breaker-open degradation, per tenant: the tenant's OWN host
        mapper answers (correct results, degraded throughput) while the
        other tenants keep the compiled path."""
        ten = self.registry.tenant(tid)
        data = MTable([f.row for f in futs], ten.mapper.data_schema)
        out = ten.mapper.map_table(data)
        self._fan_out(tid, futs, out, time.perf_counter())
        with self._stats_lock:
            self._fallback_batches += 1
        if metrics_enabled():
            get_registry().inc("alink_serve_breaker_fallback_total", 1,
                               {"server": self.name})

    def _lane_bucket(self, k: int) -> int:
        lanes = fleet_lanes()
        for b in lanes:
            if k <= b:
                return b
        # wider than the top lane bucket: round up to a multiple of the
        # top bucket, so fleets of 65..128 tenants share ONE compiled
        # width instead of one per exact resident count
        top = lanes[-1] if lanes else 1
        return -(-k // top) * top

    def _lane_table(self, group: _GeometryGroup,
                    tids: List[str]) -> Tuple[Tuple, Dict[str, int], int]:
        """The group's cached coalesced weight stack: every RESIDENT
        member holds a stable lane slot, and the stack is rebuilt only
        when a member mutates (register/swap/evict/re-admit bumps the
        group's lane stamp). Steady-state dispatches therefore reuse
        one device-side stack instead of re-stacking per batch — which
        is where the coalesced path's host cost lived. Returns
        ``(stacked_model, slots, L)``."""
        import jax.numpy as jnp
        with group._lock:
            cache = group._lane_cache
        if cache is not None and all(t in cache[1] for t in tids):
            self.registry.touch(tids)      # the table skips arrays_for
            return cache[2], cache[1], cache[0]
        # touch/re-admit every requested tenant and HOLD the returned
        # references — a concurrent eviction drops its reference only,
        # so this dispatch can never be torn
        held = {tid: self.registry.arrays_for(tid) for tid in tids}
        with group._lock:
            stamp = group.lane_stamp       # read BEFORE capturing arrays
        resident = {}
        for t in self.registry.group_tenants(group):
            ta = t.device_arrays           # atomic reference read
            if ta is not None:
                resident[t.tid] = ta
        resident.update(held)
        order = sorted(resident)
        slots = {tid: i for i, tid in enumerate(order)}
        L = self._lane_bucket(len(order))
        n_arr = len(next(iter(held.values())))
        stacked = tuple(
            jnp.stack([resident[tid][i] for tid in order] +
                      [jnp.zeros_like(resident[order[0]][i])] *
                      (L - len(order)))
            for i in range(n_arr))
        with group._lock:
            if group.lane_stamp == stamp:  # no mutation since capture
                group._lane_cache = (L, slots, stacked)
        with self._stats_lock:
            self._lane_rebuilds += 1
        reqtrace.annotate_inflight("lane_rebuild",
                                   {"fleet": self.name, "lanes": L,
                                    "tenants": len(order)})
        return stacked, slots, L

    def _dispatch_coalesced(self, group: _GeometryGroup, compiled: List,
                            t0: float) -> None:
        """ONE program execution for every compiled-route tenant of the
        group: per-tenant encode (each tenant's OWN kernel — feature
        names differ even when geometry matches) at exact row counts,
        row-concatenated and zero-padded to the covering row bucket; the
        weight stack is the group's cached LANE TABLE (every resident
        member at a stable slot, rebuilt only on member mutation); each
        row carries its tenant's int32 lane index. Per-row arithmetic
        is identical to the single-model program (``make_fleet_fns``
        contract), so the answers are bitwise the same — and a dispatch
        holds the stack it gathered, so a concurrent swap/eviction can
        never tear it."""
        import jax
        import jax.numpy as jnp
        # encode per tenant at exact rows; split by encoding kind
        by_kind: Dict[str, List] = {}
        for tid, futs, route, br in compiled:
            ten = self.registry.tenant(tid)
            data = MTable([f.row for f in futs], ten.mapper.data_schema)
            kind, arrays = ten.kernel.encode(data, len(futs))
            by_kind.setdefault(kind, []).append(
                (tid, ten, futs, data, arrays, route, br))
        for kind, members in by_kind.items():
            rows = sum(len(m[2]) for m in members)
            bucket = self._bucket_for(rows)
            # widths may differ per tenant (sparse nnz drift): pad every
            # encoded array to the max trailing shape — zero-padding the
            # tail of the strict left-to-right sum is bitwise-neutral
            # (the encoders' own padding contract)
            n_arr = len(members[0][4])
            trailing = tuple(
                tuple(max(m[4][i].shape[1:][d] for m in members)
                      for d in range(members[0][4][i].ndim - 1))
                for i in range(n_arr))
            stacked_inputs = []
            for i in range(n_arr):
                proto = members[0][4][i]
                buf = np.zeros((bucket,) + trailing[i], proto.dtype)
                off = 0
                for m in members:
                    a = m[4][i]
                    sl = (slice(off, off + a.shape[0]),) + tuple(
                        slice(0, s) for s in a.shape[1:])
                    buf[sl] = a
                    off += a.shape[0]
                stacked_inputs.append(buf)
            # the group's cached lane table (LRU touch; re-admits
            # evicted tenants and rebuilds only on member mutation)
            stacked_model, slots, L = self._lane_table(
                group, [m[0] for m in members])
            lane = np.zeros(bucket, np.int32)
            off = 0
            for m in members:
                lane[off:off + len(m[2])] = slots[m[0]]
                off += len(m[2])
            prog = group.program(kind, bucket, trailing, lanes=L)
            ctxs = [f.ctx for m in members for f in m[2]
                    if f.ctx is not None]
            settled = False
            try:
                out = prog(stacked_model, jnp.asarray(lane),
                           *stacked_inputs)
                for c in ctxs:
                    c.mark("dispatch")
                if not isinstance(out, (tuple, list)):
                    out = (out,)
                host = jax.device_get(list(out))
                for c in ctxs:
                    c.mark("device")
                done_t = time.perf_counter()
                off = 0
                delivered = []
                for m in members:
                    tid, ten, futs, data, _arr, route, br = m
                    n = len(futs)
                    sliced = tuple(np.asarray(a)[off:off + n]
                                   for a in host)
                    off += n
                    delivered.append((tid, futs,
                                      ten.kernel.decode(sliced, data)))
                for c in ctxs:
                    c.mark("decode")
                # decode succeeded for every member: settle the breakers
                # BEFORE fan-out so a (never-expected) fan-out error
                # cannot double-settle an acquire as both success and
                # failure
                settled = True
                for m in members:
                    if m[6] is not None:
                        m[6].on_success(probe=(m[5] == "probe"))
                for tid, futs, result in delivered:
                    self._fan_out(tid, futs, result, done_t)
            finally:
                if not settled:
                    for m in members:
                        tid, _ten, futs, _d, _a, route, br = m
                        if br is not None:
                            br.on_failure(probe=(route == "probe"))
            with self._stats_lock:
                self._batches += 1
                if len(members) > 1:
                    self._coalesced += 1
            if metrics_enabled() and len(members) > 1:
                get_registry().inc("alink_fleet_coalesced_batches_total",
                                   1, {"fleet": self.name})
            trace_complete("fleet.batch", time.perf_counter() - t0,
                           cat="serve",
                           args={"rows": rows, "bucket": bucket,
                                 "tenants": len(members), "lanes": L,
                                 "kind": kind})

    def _dispatch_single(self, group: _GeometryGroup, tid: str,
                         futs: List[_FleetRequest], route: str,
                         br, t0: float) -> None:
        """Per-tenant dispatch through the group's SHARED single-model
        programs (the fleet-fns-less / coalescing-off path, and the
        bitwise reference the coalesced path is pinned against)."""
        import jax
        ten = self.registry.tenant(tid)
        data = MTable([f.row for f in futs], ten.mapper.data_schema)
        ctxs = [f.ctx for f in futs if f.ctx is not None]
        settled = False
        try:
            n = len(futs)
            bucket = self._bucket_for(n)
            kind, arrays = ten.kernel.encode(data, bucket)
            model = self.registry.arrays_for(tid)
            prog = group.program(
                kind, bucket, tuple(a.shape[1:] for a in arrays))
            out = prog(model, *arrays)
            for c in ctxs:
                c.mark("dispatch")
            if not isinstance(out, (tuple, list)):
                out = (out,)
            host = jax.device_get(list(out))
            for c in ctxs:
                c.mark("device")
            sliced = tuple(np.asarray(a)[:n] for a in host)
            result = ten.kernel.decode(sliced, data)
            for c in ctxs:
                c.mark("decode")
            done_t = time.perf_counter()
            self._fan_out(tid, futs, result, done_t)
            if br is not None:
                br.on_success(probe=(route == "probe"))
            settled = True
        except FaultInjected:
            if br is not None and not settled:
                settled = True
                br.on_failure(probe=(route == "probe"))
            raise
        except Exception:
            if br is not None:
                settled = True
                br.on_failure(probe=(route == "probe"))
                if route == "probe":
                    self._serve_host(tid, futs)
                    with self._stats_lock:
                        self._batches += 1
                    return
            raise
        with self._stats_lock:
            self._batches += 1
        trace_complete("fleet.batch", time.perf_counter() - t0,
                       cat="serve", args={"rows": len(futs), "tenants": 1,
                                          "tenant": tid, "kind": kind})

    def _bucket_for(self, n: int) -> int:
        for b in self.registry.buckets:
            if n <= b:
                return b
        return self.registry.buckets[-1]

    def _fan_out(self, tid: str, futs: List[_FleetRequest], out: MTable,
                 done_t: float) -> None:
        cols = [out.col(nm) for nm in out.col_names]
        ten = self.registry.tenant(tid)
        rec = metrics_enabled()
        reg = get_registry() if rec else None
        lbl = {"server": self.name}
        lats = []
        for i, fut in enumerate(futs):
            fut.set_result(tuple(c[i] for c in cols))
            dt = done_t - fut.submitted_at
            lats.append(dt)
            self._release_slot(tid)
            ctx = fut.ctx
            if ctx is None:
                continue
            qwait = ctx.phase_end("coalesce")
            reqtrace.finish(ctx, outcome="ok")
            if rec:
                ex = {"trace_id": ctx.trace_id, "tenant": tid}
                reg.observe("alink_serve_request_seconds", dt, lbl,
                            exemplar=ex)
                if qwait is not None:
                    reg.observe("alink_serve_queue_wait_seconds", qwait,
                                lbl, exemplar=ex)
        ten.requests += len(futs)
        ten.latencies.extend(lats)
        with self._stats_lock:
            self._requests += len(futs)
            self._latencies.extend(lats)
        if rec:
            reg.inc("alink_serve_requests_total", len(futs), lbl)

    # -- stats / admin / shutdown ---------------------------------------
    def _readiness(self) -> dict:
        admitting = not self._closed.is_set()
        brs = self.breaker_stats()
        ok = admitting and not brs["open_tenants"]
        return {"ready": ok, "healthy": ok,
                "admission_open": admitting,
                "tenants": self.registry.stats()["tenants"],
                "open_breaker_tenants": brs["open_tenants"],
                "queue_depth": self._ch.depth()}

    def tenant_stats(self, tenant_id: str) -> dict:
        t = self.registry.tenant(tenant_id)
        lats = list(t.latencies)
        return {"tenant": t.tid, "version": t.version,
                "resident": t.device_arrays is not None,
                "bytes": t.nbytes, "requests": t.requests,
                "shed": t.shed, "evictions": t.evictions,
                "readmissions": t.readmissions, "swaps": t.swaps,
                "p99_s": _percentile(lats, 99.0)}

    def stats(self) -> dict:
        with self._stats_lock:
            lats = list(self._latencies)
            out = {
                "requests": self._requests, "failed": self._failed,
                "batches": self._batches,
                "coalesced_batches": self._coalesced,
                "uncoalesced_batches": self._uncoalesced,
                "shed": self._shed,
                "fallback_batches": self._fallback_batches,
                "lane_rebuilds": self._lane_rebuilds,
                "loop_respawns": self._respawns,
                "quarantined": self._quarantined,
            }
        out["coalesce_rate"] = (
            out["coalesced_batches"] / out["batches"]
            if out["batches"] else 0.0)
        out["p50_s"] = _percentile(lats, 50.0)
        out["p99_s"] = _percentile(lats, 99.0)
        out["queue_depth"] = self._ch.depth()
        out["registry"] = self.registry.stats()
        out["breaker"] = self.breaker_stats()
        return out

    def status(self) -> dict:
        """adminz ``/statusz`` payload: the server totals plus one row
        per tenant (version, residency, bytes, counters, rolling
        p99)."""
        s = self.stats()
        s["per_tenant"] = [self.tenant_stats(tid)
                           for tid in sorted(self.registry.tenant_ids())]
        return s

    def close(self, timeout: float = 10.0) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        if self._admin is not None:
            self._admin.remove_source(f"fleet:{self.name}")
            self._admin.remove_status(f"fleet:{self.name}")
            self._admin = None
            release_admin()
        self._ch.close()
        self._thread.join(timeout)

    def __enter__(self) -> "FleetServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
