"""String similarity metrics.

Re-design of common/similarity/ (Levenshtein family, LCS, SSK, Jaccard,
Cosine over char n-grams, SimHash hamming — the metric set behind the
reference's StringSimilarityPairwise / TextSimilarityPairwise ops).
Pure host functions; the LSH join ops (lsh.py) carry the device math.
"""

from __future__ import annotations

from typing import Callable, List, Sequence

import numpy as np

from ...batch.feature.feature_ops import murmur32


def levenshtein(a: str, b: str) -> int:
    m, n = len(a), len(b)
    if m == 0 or n == 0:
        return max(m, n)
    prev = list(range(n + 1))
    for i in range(1, m + 1):
        cur = [i] + [0] * n
        ai = a[i - 1]
        for j in range(1, n + 1):
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1,
                         prev[j - 1] + (ai != b[j - 1]))
        prev = cur
    return prev[n]


def levenshtein_sim(a: str, b: str) -> float:
    denom = max(len(a), len(b))
    return 1.0 - levenshtein(a, b) / denom if denom else 1.0


def lcs(a: str, b: str) -> int:
    """Longest common subsequence length."""
    m, n = len(a), len(b)
    if m == 0 or n == 0:
        return 0
    prev = [0] * (n + 1)
    for i in range(1, m + 1):
        cur = [0] * (n + 1)
        ai = a[i - 1]
        for j in range(1, n + 1):
            cur[j] = prev[j - 1] + 1 if ai == b[j - 1] else max(prev[j], cur[j - 1])
        prev = cur
    return prev[n]


def lcs_sim(a: str, b: str) -> float:
    denom = max(len(a), len(b))
    return lcs(a, b) / denom if denom else 1.0


def _ngrams(s: str, n: int) -> List[str]:
    if len(s) < n:
        return [s] if s else []
    return [s[i:i + n] for i in range(len(s) - n + 1)]


def jaccard_sim(a: str, b: str, n: int = 2) -> float:
    A, B = set(_ngrams(a, n)), set(_ngrams(b, n))
    if not A and not B:
        return 1.0
    u = len(A | B)
    return len(A & B) / u if u else 0.0


def cosine_sim(a: str, b: str, n: int = 2) -> float:
    from collections import Counter
    A, B = Counter(_ngrams(a, n)), Counter(_ngrams(b, n))
    if not A or not B:
        return 1.0 if (not A and not B) else 0.0
    common = set(A) & set(B)
    dot = sum(A[g] * B[g] for g in common)
    na = np.sqrt(sum(v * v for v in A.values()))
    nb = np.sqrt(sum(v * v for v in B.values()))
    return float(dot / (na * nb)) if na and nb else 0.0


def simhash(s: str, n: int = 2, bits: int = 64) -> int:
    acc = [0] * bits
    for g in _ngrams(s, n):
        h = murmur32(g.encode("utf-8")) | (murmur32(g.encode("utf-8"), 7) << 32)
        for i in range(bits):
            acc[i] += 1 if (h >> i) & 1 else -1
    out = 0
    for i in range(bits):
        if acc[i] > 0:
            out |= (1 << i)
    return out


def simhash_hamming_sim(a: str, b: str, n: int = 2) -> float:
    d = bin(simhash(a, n) ^ simhash(b, n)).count("1")
    return 1.0 - d / 64.0


SIMILARITY_FUNCS: dict = {
    "LEVENSHTEIN": lambda a, b: float(levenshtein(a, b)),
    "LEVENSHTEIN_SIM": levenshtein_sim,
    "LCS": lambda a, b: float(lcs(a, b)),
    "LCS_SIM": lcs_sim,
    "JACCARD_SIM": jaccard_sim,
    "COSINE": cosine_sim,
    "SIMHASH_HAMMING": lambda a, b: float(
        bin(simhash(a) ^ simhash(b)).count("1")),
    "SIMHASH_HAMMING_SIM": simhash_hamming_sim,
}
