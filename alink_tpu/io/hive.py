"""Hive connector.

Re-design of connectors/connector-hive (HiveDB.java, HiveBatchSource.java,
Hive{Source,Sink}BatchOp, HiveSourceStreamOp, HiveSourceParams/
HiveSinkParams). Two paths, mirroring how the reference actually works:

- **Warehouse files** (the path HiveBatchSource takes after consulting the
  metastore): partitioned ``k=v`` directory trees of Hive-text files, read
  directly with partition pruning and written with static-partition specs —
  io/hive_warehouse.py, fully live with no server. Select it with
  ``warehouse_dir``.
- **Live HiveServer2** over DB-API: ``HiveDB`` binds lazily to ``pyhive``
  and raises a clear ImportError otherwise — gated, not stubbed; with
  pyhive installed the JdbcDB query/write machinery is reused unchanged.
  Select it with ``host``.
"""

from __future__ import annotations

from ..common.params import ParamInfo
from ..common.types import TableSchema
from ..operator.base import BatchOperator
from ..operator.batch.sink.sinks import DBSinkBatchOp
from ..operator.batch.source.sources import DBSourceBatchOp
from ..operator.stream.source.sources import BoundedTableStreamSource
from .db import JdbcDB
from .hive_warehouse import HiveWarehouse


class HiveDB(JdbcDB):
    """reference: connectors/connector-hive HiveDB.java (live-server half)"""

    PARAM_STYLE = "%s"

    def __init__(self, name: str, host: str, port: int = 10000,
                 database: str = "default", username: str = None):
        def factory():
            try:
                from pyhive import hive
            except ImportError as e:
                raise ImportError(
                    "HiveDB needs pyhive (pip install 'pyhive[hive]'); "
                    "not installed in this image") from e
            return hive.Connection(host=host, port=port, database=database,
                                   username=username)

        super().__init__(name, factory)
        self.database = database

    def list_table_names(self):
        return [str(r[0]) for r in self.query("SHOW TABLES").to_rows()]


class _HasHiveDB:
    """Hive connection/location params shared by source and sink.

    ``warehouse_dir`` selects the serverless warehouse-layout path;
    ``host`` selects live HiveServer2 (reference HiveDBParams)."""
    WAREHOUSE_DIR = ParamInfo("warehouse_dir", str,
                              "hive warehouse root (serverless file path)")
    HOST = ParamInfo("host", str, "HiveServer2 host (live-server path)")
    PORT = ParamInfo("port", int, default=10000)
    DB_NAME = ParamInfo("db_name", str, default="default")
    USERNAME = ParamInfo("username", str)

    def _warehouse(self):
        wd = self.params._m.get("warehouse_dir")
        return HiveWarehouse(wd) if wd else None

    def _make_db(self):
        p = self.params._m
        if not p.get("host"):
            raise ValueError("Hive op needs warehouse_dir= (file path) or "
                             "host= (HiveServer2)")
        return HiveDB(f"hive:{p.get('db_name', 'default')}", p["host"],
                      int(p.get("port", 10000)),
                      p.get("db_name", "default"), p.get("username"))

    def _warehouse_read(self):
        wh = self._warehouse()
        p = self.params._m
        if p.get("query"):
            raise ValueError("query needs the live-server path (host=); the "
                             "warehouse_dir path reads whole tables — use "
                             "partitions= to prune, or a downstream Select")
        schema = (TableSchema.parse(p["schema_str"])
                  if p.get("schema_str") else None)
        return wh.read_table(p["input_table_name"],
                             db=p.get("db_name", "default"), schema=schema,
                             partitions=p.get("partitions"))

    def _server_read(self):
        """Live-server read honoring ``query`` (free-form SELECT, like
        DBSourceBatchOp) or ``partitions`` as a pushed-down WHERE (comma =
        OR of alternatives, slash = AND of levels). ``schema_str`` is
        rejected here — the server's schema is authoritative."""
        from .hive_warehouse import parse_partitions_param
        p = self.params._m
        if p.get("schema_str"):
            raise ValueError("schema_str only applies to the warehouse_dir "
                             "path; the live server defines the schema")
        db = self._make_db()
        if p.get("query"):
            if p.get("partitions"):
                raise ValueError("query and partitions are mutually "
                                 "exclusive on the live-server path")
            return db.query(p["query"])
        alts = parse_partitions_param(p.get("partitions"))
        if not alts:
            return db.read_table(p["input_table_name"])
        for alt in alts:
            for k in alt:
                if not k.replace("_", "").isalnum():
                    raise ValueError(f"bad partition column name: {k!r}")
        ors = " OR ".join(
            "(" + " AND ".join(f"{k}=?" for k in alt) + ")" for alt in alts)
        vals = [v for alt in alts for v in alt.values()]
        return db.query(
            f"SELECT * FROM {p['input_table_name']} WHERE {ors}", vals)


class HiveSourceBatchOp(_HasHiveDB, DBSourceBatchOp):
    """reference: connector-hive HiveSourceBatchOp + HiveBatchSource.

    ``partitions`` prunes: "/" separates levels, "," separates alternative
    specs (HiveSourceParams.PARTITIONS: ``ds=20190729/dt=12,ds=20190730``).
    Partition columns come back as appended STRING columns."""

    PARTITIONS = ParamInfo("partitions", str, "partition pruning spec")
    SCHEMA_STR = ParamInfo("schema_str", str,
                           "'col TYPE, ...' (else the table's schema sidecar)")

    def link_from(self, *inputs) -> "HiveSourceBatchOp":
        if self._warehouse() is None:
            self.set_output_table(self._server_read())
            return self
        self.set_output_table(self._warehouse_read())
        return self


class HiveSourceStreamOp(_HasHiveDB, BoundedTableStreamSource):
    """reference: connector-hive HiveSourceStreamOp — the same
    partition-pruned read replayed as timed micro-batches."""

    PARTITIONS = ParamInfo("partitions", str, "partition pruning spec")
    SCHEMA_STR = ParamInfo("schema_str", str,
                           "'col TYPE, ...' (else the table's schema sidecar)")
    INPUT_TABLE_NAME = ParamInfo("input_table_name", str, optional=False)

    def _resolve(self):
        if self._table is None:
            table = (self._server_read() if self._warehouse() is None
                     else self._warehouse_read())
            self._set_table(table)
        return self._table

    def timed_batches(self):
        self._resolve()
        return super().timed_batches()

    def get_schema(self):
        self._resolve()
        return super().get_schema()


class HiveSinkBatchOp(_HasHiveDB, DBSinkBatchOp):
    """reference: connector-hive HiveSinkBatchOp.

    ``partition`` is a static-partition spec ``k=v/k2=v2``
    (HiveSinkParams.PARTITION; HiveDB.java:135-178)."""

    PARTITION = ParamInfo("partition", str, "static partition spec k=v/k2=v2")

    def link_from(self, in_op: BatchOperator) -> "HiveSinkBatchOp":
        wh = self._warehouse()
        if wh is None:
            return DBSinkBatchOp.link_from(self, in_op)
        t = in_op.get_output_table()
        p = self.params._m
        wh.write_table(p["output_table_name"], t,
                       db=p.get("db_name", "default"),
                       partition=p.get("partition"),
                       overwrite=bool(p.get("overwrite_sink", False)))
        self.set_output_table(t)
        return self
