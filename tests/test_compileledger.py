"""Compile-ledger behavior (ISSUE 19): event recording + named diffs,
the bounded ring, storm detection with dominant-dimension attribution,
lru-factory classification, the /compilez admin endpoint, fleetz
mixed-fleet tolerance, and the doctor's offline compile verdict.
"""

import functools
import json
import os
import sys
import urllib.request

import pytest

from alink_tpu.common import compileledger as cl
from alink_tpu.common.plan import ExecutionPlan

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_ledger():
    cl.reset()
    yield
    cl.reset()


def _plan(**dims):
    return ExecutionPlan("test", tuple(dims.items()))


# ---------------------------------------------------------------------------
# events + diffs + ring
# ---------------------------------------------------------------------------

class TestEvents:
    def test_first_event_is_cold_start(self):
        ev = cl.record_event("t.cache", _plan(x=1), site="here",
                             subsystem="test")
        assert ev["diff"] == [{"dim": "cold-start", "old": "-",
                               "new": "-"}]
        assert ev["site"] == "here" and ev["cache"] == "t.cache"

    def test_diff_names_the_changed_dimension(self):
        cl.record_event("t.cache", _plan(dtype="f32", bucket=128))
        ev = cl.record_event("t.cache", _plan(dtype="int8", bucket=128))
        assert ev["diff"] == [{"dim": "dtype", "old": "'f32'",
                               "new": "'int8'"}]

    def test_diffs_are_per_cache(self):
        cl.record_event("a", _plan(x=1))
        cl.record_event("b", _plan(x=99))
        ev = cl.record_event("a", _plan(x=2))
        assert ev["diff"] == [{"dim": "x", "old": "1", "new": "2"}]

    def test_ring_is_bounded_by_flag(self, monkeypatch):
        monkeypatch.setenv("ALINK_TPU_COMPILE_RING", "16")
        for i in range(40):
            cl.record_event("t.cache", _plan(x=i))
        doc = cl.compilez_doc()
        assert doc["ring_capacity"] == 16
        assert len(doc["events"]) == 16
        assert doc["events"][-1]["seq"] == 40
        # the cache row keeps the full miss count even past the ring
        assert doc["caches"]["t.cache"]["misses"] == 40

    def test_disabled_ledger_records_nothing(self, monkeypatch):
        monkeypatch.setenv("ALINK_TPU_COMPILE_LEDGER", "0")
        assert cl.record_event("t.cache", _plan(x=1)) == {}
        cl.record_hit("t.cache")
        cl.subsystem_start("test")
        doc = cl.compilez_doc()
        assert doc["enabled"] is False
        assert doc["caches"] == {} and doc["events"] == []

    def test_note_wall_attaches_once(self):
        cl.record_event("t.cache", _plan(x=1))
        cl.note_wall("t.cache", 1.25)
        cl.note_wall("t.cache", 9.0)   # second report must not clobber
        ev = cl.compilez_doc()["events"][-1]
        assert ev["wall_s"] == 1.25

    def test_cold_start_attribution(self):
        cl.subsystem_start("serving")
        cl.record_event("serve.x", _plan(x=1), subsystem="serving")
        ttfp = cl.compilez_doc()["cold_start"]["time_to_first_program_s"]
        assert "serving" in ttfp and ttfp["serving"] >= 0.0

    def test_doc_is_json_serializable(self):
        cl.register_cache("t.cache", "test", capacity=8)
        cl.record_event("t.cache", _plan(x=(1, 2), y="s"))
        cl.register_stage("dag", "serving", _plan(stage="serving"))
        json.dumps(cl.compilez_doc())


# ---------------------------------------------------------------------------
# storms
# ---------------------------------------------------------------------------

class TestStorms:
    def test_storm_fires_once_and_names_dominant_dim(self):
        for i in range(cl.STORM_MISSES + 2):
            cl.record_event("t.cache",
                            _plan(dtype="f32" if i % 2 else "int8",
                                  bucket=128))
        doc = cl.compilez_doc()
        row = doc["caches"]["t.cache"]
        assert row["storm_active"] is True
        assert row["storms"] == 1           # transition edge, not per-miss
        dom = row["dominant_dim"]
        assert dom["dim"] == "dtype" and dom["count"] >= cl.STORM_MISSES
        assert cl.storms() == ["t.cache"]

    def test_below_threshold_is_not_a_storm(self):
        for i in range(cl.STORM_MISSES - 1):
            cl.record_event("t.cache", _plan(x=i))
        row = cl.compilez_doc()["caches"]["t.cache"]
        assert row["storms"] == 0 and row["storm_active"] is False


# ---------------------------------------------------------------------------
# lru-factory classification
# ---------------------------------------------------------------------------

class TestLruCall:
    def test_miss_then_hit_classification(self):
        calls = []

        @functools.lru_cache(maxsize=None)
        def factory(a, b, donate=True):
            calls.append((a, b, donate))
            return (a, b, donate)

        p = _plan(a=1)
        out1 = cl.lru_call("f.step", factory, (1, 2), plan=p,
                           site="t", subsystem="f",
                           kwargs={"donate": False})
        out2 = cl.lru_call("f.step", factory, (1, 2), plan=p,
                           site="t", subsystem="f",
                           kwargs={"donate": False})
        assert out1 == out2 == (1, 2, False)
        assert calls == [(1, 2, False)]     # lru key untouched
        row = cl.compilez_doc()["caches"]["f.step"]
        assert row["misses"] == 1 and row["hits"] == 1
        assert row["size"] == 1

    def test_plain_function_bypasses(self):
        """A monkeypatched (non-lru) factory is called straight through
        — the tests that stub factories must keep working."""
        def plain(a):
            return a * 2
        assert cl.lru_call("f.step", plain, (21,), plan=_plan(),
                           site="t") == 42
        assert "f.step" not in cl.compilez_doc()["caches"]


# ---------------------------------------------------------------------------
# /compilez over the admin endpoint
# ---------------------------------------------------------------------------

class TestCompilezEndpoint:
    def test_endpoint_serves_the_doc(self):
        from alink_tpu.common.adminz import AdminServer
        cl.register_cache("t.cache", "test")
        cl.record_event("t.cache", _plan(dtype="f32"))
        cl.record_event("t.cache", _plan(dtype="int8"))
        srv = AdminServer(port=0)
        srv.start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            doc = json.loads(urllib.request.urlopen(
                f"{base}/compilez?n=1", timeout=10).read())
            assert doc["enabled"] is True
            assert "t.cache" in doc["caches"]
            assert len(doc["events"]) == 1
            assert doc["events"][0]["diff"][0]["dim"] == "dtype"
            assert "/compilez" in AdminServer.ENDPOINTS
            idx = urllib.request.urlopen(base + "/",
                                         timeout=10).read().decode()
            assert "/compilez" in idx
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# fleetz: mixed-fleet tolerance + snapshot archiving
# ---------------------------------------------------------------------------

def _load_fleetz():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "fleetz_under_test", os.path.join(ROOT, "tools", "fleetz.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestFleetz:
    def test_scrapes_compilez_and_tolerates_old_workers(self, tmp_path):
        """A current worker contributes compilez.json to the snapshot;
        a worker predating /compilez (404) scrapes clean without it —
        the ISSUE 18 tracez/requestz mixed-fleet contract extended."""
        import http.server
        import threading

        from alink_tpu.common.adminz import AdminServer
        fleetz = _load_fleetz()
        cl.record_event("t.cache", _plan(x=1), subsystem="test")
        new = AdminServer(port=0)
        new.start()

        class OldWorker(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                path = self.path.split("?")[0].rstrip("/") or "/"
                bodies = {"/varz": b"[]", "/statusz": b"{}",
                          "/healthz": b"{}", "/readyz": b"{}",
                          "/metrics": b""}
                body = bodies.get(path)
                self.send_response(200 if body is not None else 404)
                if body is None:
                    body = b"404"
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        old = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                              OldWorker)
        t = threading.Thread(target=old.serve_forever, daemon=True)
        t.start()
        try:
            workers = [f"127.0.0.1:{new.port}",
                       f"127.0.0.1:{old.server_address[1]}"]
            scrapes = [fleetz.scrape_worker(w, timeout=10)
                       for w in workers]
            assert "error" not in scrapes[0]
            assert "error" not in scrapes[1]
            assert scrapes[0]["compilez"]["caches"]["t.cache"]
            assert "compilez" not in scrapes[1]
            report = fleetz.fleet_report(scrapes)
            assert report["aggregate"]["reachable"] == 2
            assert report["aggregate"]["alink_compile_total"] >= 1
            out = tmp_path / "snap"
            fleetz.write_snapshot(str(out), scrapes, report)
            archived = list(out.glob("*/compilez.json"))
            assert len(archived) == 1
        finally:
            new.close()
            old.shutdown()

    def test_series_value_reads_histogram_sum(self):
        fleetz = _load_fleetz()
        varz = [{"kind": "histogram", "name": "alink_compile_seconds",
                 "labels": {}, "sum": 2.5, "count": 3,
                 "buckets": [], "counts": []}]
        assert fleetz._series_value(varz, "alink_compile_seconds") == 2.5


# ---------------------------------------------------------------------------
# doctor: offline compile verdict
# ---------------------------------------------------------------------------

def _load_doctor():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "doctor_under_test", os.path.join(ROOT, "tools", "doctor.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestDoctorCompileVerdict:
    def _storm_doc(self):
        cl.subsystem_start("serving")
        for i in range(cl.STORM_MISSES + 2):
            cl.record_event("serve.x",
                            _plan(**{"ALINK_TPU_SERVE_DTYPE":
                                     "f32" if i % 2 else "int8"}),
                            subsystem="serving")
        return cl.compilez_doc()

    def test_storm_verdict_names_the_flag(self, tmp_path, capsys):
        doctor = _load_doctor()
        (tmp_path / "compilez.json").write_text(
            json.dumps(self._storm_doc()))
        assert doctor.main(["--run-dir", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "compile plane" in out
        assert "RECOMPILE STORM" in out
        assert "ALINK_TPU_SERVE_DTYPE" in out
        assert "env flag is flapping" in out

    def test_cold_start_dominated_verdict(self, tmp_path, capsys):
        doctor = _load_doctor()
        doc = cl.compilez_doc()
        doc["caches"] = {"engine.program": {
            "subsystem": "engine", "size": 1, "capacity": 32,
            "hits": 5, "misses": 1, "evictions": 0, "hit_rate": 0.83,
            "last_digest": "d", "storm_active": False, "storms": 0,
            "dominant_dim": None}}
        doc["cold_start"] = {"started": ["engine"],
                             "time_to_first_program_s": {"engine": 42.0}}
        (tmp_path / "compilez.json").write_text(json.dumps(doc))
        assert doctor.main(["--run-dir", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "cold-start-dominated restart" in out
        assert "engine paid 42.0s" in out

    def test_healthy_verdict(self, tmp_path, capsys):
        doctor = _load_doctor()
        cl.record_event("t.cache", _plan(x=1))
        for _ in range(8):
            cl.record_hit("t.cache")
        (tmp_path / "compilez.json").write_text(
            json.dumps(cl.compilez_doc()))
        assert doctor.main(["--run-dir", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "verdict: healthy — every compile is attributed" in out
