"""Persistent AOT executable store (common/aotcache.py, ISSUE 20).

The contract under test: a disk artifact loads ONLY when its plan
digest and rig fingerprint both match exactly — anything stale,
foreign or corrupt is refused loudly (never deserialized wrong, never
a crash) and the caller falls through to a fresh compile; loaded
programs are bitwise-identical to freshly compiled ones; retention is
bounded; the ledger records deserializes as ``disk-hit`` events
distinct from compiles.
"""

import json
import os
import struct

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from alink_tpu.common import aotcache, compileledger
from alink_tpu.common.plan import ExecutionPlan


@pytest.fixture
def store_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("ALINK_TPU_AOT_CACHE_DIR", str(tmp_path))
    monkeypatch.delenv("ALINK_TPU_AOT_CACHE", raising=False)
    monkeypatch.delenv("ALINK_TPU_AOT_CACHE_KEEP", raising=False)
    aotcache.reset()
    compileledger.reset()
    yield str(tmp_path)
    aotcache.reset()
    compileledger.reset()


def _plan(**dims):
    base = {"kind": "unit", "n": 3}
    base.update(dims)
    return ExecutionPlan("test", tuple(sorted(base.items())))


def _fn():
    return jax.jit(lambda x: jnp.sin(x) * 2.0 + jnp.cumsum(x))


X = np.linspace(-2.0, 3.0, 17, dtype=np.float32)


def _mutate(path, fix):
    """Parse blob -> (header dict, payload), apply ``fix(header,
    payload) -> (header, payload)``, rewrite the artifact in place."""
    blob = open(path, "rb").read()
    assert blob[:8] == aotcache.MAGIC
    (hlen,) = struct.unpack(">I", blob[8:12])
    header = json.loads(blob[12:12 + hlen].decode())
    payload = blob[12 + hlen:]
    header, payload = fix(header, payload)
    hdr = json.dumps(header).encode()
    with open(path, "wb") as fh:
        fh.write(aotcache.MAGIC + struct.pack(">I", len(hdr)) + hdr
                 + payload)


# ---------------------------------------------------------------------------
# round trip + ledger
# ---------------------------------------------------------------------------

def test_inactive_without_dir(monkeypatch):
    monkeypatch.delenv("ALINK_TPU_AOT_CACHE_DIR", raising=False)
    assert not aotcache.active()
    assert aotcache.load(_plan(), cache="t") is None
    assert not aotcache.store(_plan(), _fn(), (X,), cache="t")


def test_flag_kills_store(store_dir, monkeypatch):
    monkeypatch.setenv("ALINK_TPU_AOT_CACHE", "0")
    assert not aotcache.active()


def test_roundtrip_bitwise_and_disk_hit_event(store_dir):
    plan = _plan()
    fresh = _fn()
    want = np.asarray(fresh(X))
    assert aotcache.store(plan, fresh, (X,), cache="t", site="unit")
    loaded = aotcache.load(plan, cache="t", site="unit",
                           subsystem="unit")
    assert loaded is not None
    got = np.asarray(loaded.fn(X))
    assert got.tobytes() == want.tobytes()
    assert loaded.wall_s >= 0.0
    assert loaded.header["plan_digest"] == plan.digest()
    doc = compileledger.compilez_doc()
    evs = [e for e in doc["events"] if e["cache"] == "t"]
    assert [e.get("kind") for e in evs] == ["disk-hit"]
    assert doc["caches"]["t"]["disk_hits"] == 1
    assert doc["caches"]["t"]["misses"] == 0


def test_different_plan_is_a_silent_miss(store_dir):
    aotcache.store(_plan(), _fn(), (X,), cache="t")
    assert aotcache.load(_plan(n=4), cache="t") is None
    assert aotcache.stats()["refusals"] == 0


# ---------------------------------------------------------------------------
# the refusal matrix: stale/foreign/corrupt artifacts never deserialize
# ---------------------------------------------------------------------------

def _stored_path(plan):
    p = aotcache.artifact_path("t", plan.digest())
    assert os.path.exists(p)
    return p


def test_refuses_plan_digest_mismatch(store_dir):
    plan_a, plan_b = _plan(), _plan(n=99)
    aotcache.store(plan_a, _fn(), (X,), cache="t")
    # a stale artifact squatting on plan_b's path (e.g. a buggy sync)
    os.replace(_stored_path(plan_a),
               aotcache.artifact_path("t", plan_b.digest()))
    with pytest.warns(RuntimeWarning, match="plan-digest-mismatch"):
        assert aotcache.load(plan_b, cache="t") is None
    assert aotcache.stats()["refusals"] == 1
    # refusal falls through to a fresh compile that still works
    assert np.allclose(np.asarray(_fn()(X)), np.asarray(_fn()(X)))


def test_refuses_jaxlib_version_mismatch(store_dir):
    plan = _plan()
    aotcache.store(plan, _fn(), (X,), cache="t")

    def bump(header, payload):
        header["fingerprint"]["jaxlib"] = "0.0.1-other-rig"
        return header, payload

    _mutate(_stored_path(plan), bump)
    with pytest.warns(RuntimeWarning, match="fingerprint-mismatch.*jaxlib"):
        assert aotcache.load(plan, cache="t") is None
    assert aotcache.stats()["refusals"] == 1


def test_refuses_device_count_mismatch(store_dir):
    plan = _plan()
    aotcache.store(plan, _fn(), (X,), cache="t")

    def bump(header, payload):
        header["fingerprint"]["device_count"] = 8192
        return header, payload

    _mutate(_stored_path(plan), bump)
    with pytest.warns(RuntimeWarning,
                      match="fingerprint-mismatch.*device_count"):
        assert aotcache.load(plan, cache="t") is None


def test_refuses_truncated_payload(store_dir):
    plan = _plan()
    aotcache.store(plan, _fn(), (X,), cache="t")
    path = _stored_path(plan)
    blob = open(path, "rb").read()
    with open(path, "wb") as fh:
        fh.write(blob[:-16])
    with pytest.warns(RuntimeWarning, match="payload-corrupt"):
        assert aotcache.load(plan, cache="t") is None


def test_refuses_flipped_payload_byte(store_dir):
    plan = _plan()
    aotcache.store(plan, _fn(), (X,), cache="t")

    def flip(header, payload):
        mid = len(payload) // 2
        return header, (payload[:mid]
                        + bytes([payload[mid] ^ 0xFF])
                        + payload[mid + 1:])

    _mutate(_stored_path(plan), flip)
    with pytest.warns(RuntimeWarning, match="payload-corrupt"):
        assert aotcache.load(plan, cache="t") is None


def test_refuses_bad_magic(store_dir):
    plan = _plan()
    aotcache.store(plan, _fn(), (X,), cache="t")
    path = _stored_path(plan)
    blob = open(path, "rb").read()
    with open(path, "wb") as fh:
        fh.write(b"NOTANAOT" + blob[8:])
    with pytest.warns(RuntimeWarning):
        assert aotcache.load(plan, cache="t") is None
    assert aotcache.stats()["refusals"] == 1


def test_refusal_never_feeds_the_ledger_a_disk_hit(store_dir):
    plan = _plan()
    aotcache.store(plan, _fn(), (X,), cache="t")
    _mutate(_stored_path(plan),
            lambda h, p: ({**h, "plan_digest": "f" * 32}, p))
    with pytest.warns(RuntimeWarning):
        assert aotcache.load(plan, cache="t", subsystem="unit") is None
    doc = compileledger.compilez_doc()
    assert all(e.get("kind") != "disk-hit" for e in doc["events"])


# ---------------------------------------------------------------------------
# retention + scan + header
# ---------------------------------------------------------------------------

def test_retention_prunes_to_keep(store_dir, monkeypatch):
    monkeypatch.setenv("ALINK_TPU_AOT_CACHE_KEEP", "8")
    for i in range(11):
        assert aotcache.store(_plan(n=i), _fn(), (X,), cache="t")
    files = [p for p, _ in aotcache.scan("t")]
    assert len(files) == 8


def test_keep_floor_is_eight(monkeypatch):
    monkeypatch.setenv("ALINK_TPU_AOT_CACHE_KEEP", "1")
    assert aotcache.aot_keep() == 8


def test_scan_headers(store_dir):
    plan = _plan()
    aotcache.store(plan, _fn(), (X,), cache="t", site="unit",
                   key=("k", 1))
    ((path, header),) = aotcache.scan("t")
    assert header["plan_digest"] == plan.digest()
    assert header["cache"] == "t"
    assert header["key_repr"] == repr(("k", 1))
    assert header["fingerprint"] == aotcache.fingerprint()
    assert aotcache.scan("missing-cache") == []


def test_tmp_files_never_published(store_dir):
    aotcache.store(_plan(), _fn(), (X,), cache="t")
    leftovers = [f for f in os.listdir(os.path.join(store_dir, "t"))
                 if not f.endswith(".aot")]
    assert leftovers == []


# ---------------------------------------------------------------------------
# lazy factory wrapper (the FTRL path)
# ---------------------------------------------------------------------------

def test_aot_jit_roundtrip(store_dir):
    dims = (("factory", "unit"), ("alpha", 0.5))
    w1 = aotcache.aot_jit(_fn(), subsystem="unit", cache="t",
                          site="unit", dims=dims)
    want = np.asarray(w1(X))
    assert aotcache.stats()["stores"] == 1
    w2 = aotcache.aot_jit(_fn(), subsystem="unit", cache="t",
                          site="unit", dims=dims)
    got = np.asarray(w2(X))
    assert aotcache.stats()["loads"] == 1
    assert got.tobytes() == want.tobytes()
    # second dispatch uses the installed impl, no second load
    np.asarray(w2(X))
    assert aotcache.stats()["loads"] == 1


def test_aot_jit_inactive_returns_fn(monkeypatch):
    monkeypatch.delenv("ALINK_TPU_AOT_CACHE_DIR", raising=False)
    fn = _fn()
    assert aotcache.aot_jit(fn, subsystem="u", cache="t", site="s",
                            dims=()) is fn


def test_aot_jit_avals_split_the_key(store_dir):
    dims = (("factory", "unit"),)
    w1 = aotcache.aot_jit(_fn(), subsystem="unit", cache="t",
                          site="unit", dims=dims)
    w1(X)
    w2 = aotcache.aot_jit(_fn(), subsystem="unit", cache="t",
                          site="unit", dims=dims)
    w2(X.astype(np.float64).astype(np.float32)[:5])  # different shape
    # two artifacts: the input avals joined the plan
    assert len(aotcache.scan("t")) == 2


# ---------------------------------------------------------------------------
# engine wiring: cache-on vs cache-off bitwise identity
# ---------------------------------------------------------------------------

def _run_engine(key):
    from alink_tpu.engine.comqueue import IterativeComQueue

    def stage(ctx):
        if ctx.is_init_step:
            ctx.put_obj("acc", jnp.zeros(()))
        x = ctx.get_obj("x")
        ctx.put_obj("acc",
                    ctx.get_obj("acc") + ctx.all_reduce_sum(x.sum()))

    x = np.arange(16, dtype=np.float32) / 7.0
    q = (IterativeComQueue(max_iter=3)
         .init_with_partitioned_data("x", x)
         .add(stage)
         .set_program_key(key))
    return np.asarray(q.exec().get("acc"))


def test_engine_cache_on_off_bitwise(store_dir, monkeypatch):
    from alink_tpu.engine.comqueue import clear_program_cache

    clear_program_cache()
    monkeypatch.delenv("ALINK_TPU_AOT_CACHE_DIR", raising=False)
    off = _run_engine(("aot_unit", 1))

    monkeypatch.setenv("ALINK_TPU_AOT_CACHE_DIR", store_dir)
    clear_program_cache()
    compileledger.reset()
    stored = _run_engine(("aot_unit", 1))  # compiles + exports
    assert aotcache.stats()["stores"] >= 1
    assert stored.tobytes() == off.tobytes()

    clear_program_cache()  # simulate the restart: only the disk remains
    compileledger.reset()
    warm = _run_engine(("aot_unit", 1))
    assert warm.tobytes() == off.tobytes()
    doc = compileledger.compilez_doc()
    evs = [e for e in doc["events"] if e["cache"] == "engine.program"]
    assert [e.get("kind") for e in evs] == ["disk-hit"]
    assert doc["caches"]["engine.program"]["misses"] == 0


def test_engine_stale_artifact_recompiles(store_dir, monkeypatch):
    from alink_tpu.engine.comqueue import clear_program_cache

    monkeypatch.setenv("ALINK_TPU_AOT_CACHE_DIR", store_dir)
    clear_program_cache()
    off = _run_engine(("aot_unit_stale", 1))
    ((path, _),) = [ph for ph in aotcache.scan("engine.program")]
    _mutate(path, lambda h, p: ({**h, "plan_digest": "0" * 32}, p))
    clear_program_cache()
    compileledger.reset()
    aotcache.reset()
    with pytest.warns(RuntimeWarning, match="plan-digest-mismatch"):
        warm = _run_engine(("aot_unit_stale", 1))
    assert warm.tobytes() == off.tobytes()
    doc = compileledger.compilez_doc()
    evs = [e for e in doc["events"] if e["cache"] == "engine.program"]
    assert [e.get("kind", "miss") for e in evs] == ["miss"]
