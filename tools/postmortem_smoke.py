#!/usr/bin/env python
"""Post-mortem capture smoke (perf_gate leg, ISSUE 18) — exit 12.

Drives the two highest-signal incident triggers back-to-back against a
live ``PredictServer`` with ``ALINK_TPU_POSTMORTEM_DIR`` armed:

  1. a scripted ``serve.dispatch`` error storm trips the circuit
     breaker OPEN — the transition captures a bundle while the request
     ring and exemplar slots still hold the storm's evidence;
  2. an immediate SLO fast-window burn (``SloBurnRate.record`` with a
     blown latency clause) fires its paging alert, whose bundle hook
     must be DEBOUNCED away — incidents cascade, captures must not.

The contract it gates:

  * exactly ONE bundle lands, atomically — one ``postmortem_*.json``
    in the directory, zero ``*.tmp`` leftovers, reason named after the
    FIRST trigger (``breaker_open``);
  * the bundle is self-contained: finished request timelines with the
    full mark chain (admit -> ... -> decode), a metrics dump whose
    ``alink_serve_request_seconds`` p99 exemplar resolves to one of
    those timelines, and the resolved flag values;
  * a FRESH interpreter renders the verdict from the bundle ALONE —
    ``tools/doctor.py --bundle`` (verdict + per-request timeline
    table) and ``tools/trace.py --trace-id`` (one request's lifetime)
    both exit 0 with nothing else on disk.

Runs in a fresh child interpreter (bootenv CPU mesh) so flags, fault
counters, the request ring and the debounce clock start from zero.
"""

import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

EXIT = 12
_MARK = "ALINK_POSTMORTEM_SMOKE_CHILD"

# visits 1-10 after arming fail: > breaker threshold (3 consecutive),
# bounded so the post-storm sweep serves compiled again
STORM_SPEC = "serve.dispatch:1-10:error"
_MARKS = ("admit", "dequeue", "coalesce", "dispatch", "device", "decode")


def main() -> int:
    if os.environ.get(_MARK) != "1":
        import tempfile

        import bootenv
        env = bootenv.cpu_mesh_env(4)
        env[_MARK] = "1"
        env.pop("ALINK_TPU_FAULT_INJECT", None)
        env["ALINK_TPU_POSTMORTEM_DIR"] = tempfile.mkdtemp(
            prefix="alink-postmortem-smoke-")
        env["ALINK_TPU_SERVE_BREAKER_MAX_MS"] = "200"
        out = subprocess.run([sys.executable, os.path.abspath(__file__)],
                             cwd=ROOT, env=env, timeout=900)
        return out.returncode

    import glob
    import json

    import numpy as np

    from alink_tpu.common.faults import scoped_fault_env
    from alink_tpu.common.metrics import MetricsRegistry, set_registry
    from alink_tpu.common.mtable import MTable
    from alink_tpu.common.params import Params
    from alink_tpu.common import reqtrace
    from alink_tpu.common.vector import DenseVector
    from alink_tpu.online.slo import SloBurnRate
    from alink_tpu.operator.batch.classification.linear import (
        LogisticRegressionTrainBatchOp)
    from alink_tpu.operator.batch.source.sources import MemSourceBatchOp
    from alink_tpu.operator.common.linear.mapper import LinearModelMapper
    from alink_tpu.serving import CompiledPredictor, PredictServer

    set_registry(MetricsRegistry())
    pmdir = os.environ["ALINK_TPU_POSTMORTEM_DIR"]
    bad = []

    # -- fixture: a trained dense-LR model + request rows -----------------
    n_rows, dim = 256, 16
    rng = np.random.RandomState(7)
    X = rng.randn(n_rows, dim)
    y = (X @ rng.randn(dim) > 0).astype(np.int64)
    vecs = np.empty(n_rows, object)
    vecs[:] = [DenseVector(X[i]) for i in range(n_rows)]
    tbl = MTable({"vec": vecs, "label": y}, "vec VECTOR, label LONG")
    warm = LogisticRegressionTrainBatchOp(
        vector_col="vec", label_col="label", max_iter=2).link_from(
        MemSourceBatchOp(tbl.first_n(128)))
    data_schema = tbl.select(["vec"]).schema
    mapper = LinearModelMapper(warm.get_output_table().schema, data_schema,
                               Params({"prediction_col": "pred",
                                       "vector_col": "vec"}))
    mapper.load_model(warm.get_output_table())
    req = tbl.select(["vec"])

    srv = PredictServer(CompiledPredictor(mapper, buckets=(1, 4, 16)),
                        name="pm_smoke")
    try:
        # -- clean traffic: fill the request ring + exemplar slots --------
        for f in [srv.submit(req.row(i % n_rows)) for i in range(32)]:
            f.result(60)

        # -- trigger 1: dispatch error storm trips the breaker OPEN ------
        # closed-loop (one request in flight at a time) so the batcher
        # cannot coalesce the storm below the breaker's consecutive-
        # failure threshold
        with scoped_fault_env(STORM_SPEC):
            for i in range(12):
                try:
                    srv.submit(req.row(i % n_rows)).result(60)
                except Exception:      # noqa: BLE001 — typed rejections ok
                    pass

        # -- trigger 2 (cascade): SLO fast-window burn fires, and its
        # bundle hook must be debounced away ------------------------------
        burn = SloBurnRate(fast_s=0.5, slow_s=10.0, name="pm_smoke")
        burn.record("serve_p99", observed=10.0, bound=1e-6)
        if not any(a["state"] == "firing" and a["window"] == "fast"
                   for a in burn.alerts):
            bad.append("the SLO fast-window burn alert never fired — "
                       "the cascade trigger was not exercised")
    finally:
        srv.close()

    # -- exactly ONE bundle, atomically published -------------------------
    bundles = sorted(glob.glob(os.path.join(pmdir, "postmortem_*.json")))
    leftovers = glob.glob(os.path.join(pmdir, "*.tmp"))
    if len(bundles) != 1:
        bad.append(f"{len(bundles)} bundles in {pmdir}, expected exactly "
                   f"1 (breaker_open first, slo_burn debounced): "
                   f"{[os.path.basename(b) for b in bundles]}")
    if leftovers:
        bad.append(f"atomic publish leaked tmp files: {leftovers}")

    trace_id = None
    if bundles:
        with open(bundles[0]) as fh:
            doc = json.load(fh)
        if doc.get("format") != "alink_tpu_postmortem_v1":
            bad.append(f"bundle format {doc.get('format')!r}")
        if doc.get("reason") != "breaker_open":
            bad.append(f"bundle reason {doc.get('reason')!r}, expected "
                       f"'breaker_open' (the FIRST trigger wins the "
                       f"debounce window)")
        reqs = doc.get("requests") or []
        full = [r for r in reqs
                if {m["phase"] for m in r.get("marks", ())}
                >= set(_MARKS) and r.get("outcome") == "ok"]
        if not full:
            bad.append(f"no finished request in the bundle carries the "
                       f"full {'->'.join(_MARKS)} timeline "
                       f"({len(reqs)} requests captured)")
        if not doc.get("flags"):
            bad.append("bundle carries no resolved flag values")
        # the p99 exemplar of the request histogram must resolve to a
        # timeline the bundle itself holds (offline debuggability)
        ids = {r.get("trace_id") for r in reqs}
        for rec in doc.get("metrics") or []:
            if rec.get("name") != "alink_serve_request_seconds":
                continue
            ex = reqtrace.p99_exemplar(rec)
            if ex is None or ex.get("trace_id") not in ids:
                bad.append(f"request-histogram p99 exemplar {ex!r} does "
                           f"not resolve to a captured timeline")
            elif trace_id is None:
                trace_id = ex["trace_id"]
        if trace_id is None and full:
            trace_id = full[0]["trace_id"]

    # -- fresh-interpreter renders: the bundle alone is enough ------------
    if bundles and trace_id is not None:
        doctor = subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools", "doctor.py"),
             "--bundle", bundles[0]],
            cwd=ROOT, capture_output=True, text=True, timeout=120)
        if doctor.returncode != 0:
            bad.append(f"doctor --bundle exited {doctor.returncode}: "
                       f"{doctor.stderr[-400:]}")
        elif ("post-mortem: breaker_open" not in doctor.stdout
              or "verdict:" not in doctor.stdout):
            bad.append("doctor --bundle rendered no post-mortem verdict "
                       "from the bundle alone")
        tr = subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools", "trace.py"),
             bundles[0], "--trace-id", trace_id],
            cwd=ROOT, capture_output=True, text=True, timeout=120)
        if tr.returncode != 0:
            bad.append(f"trace --trace-id {trace_id} exited "
                       f"{tr.returncode}: {tr.stderr[-400:]}")
        elif f"request {trace_id}" not in tr.stdout:
            bad.append(f"trace --trace-id did not render {trace_id}'s "
                       f"lifetime from the bundle")

    if bad:
        print("postmortem_smoke: FAILED:", file=sys.stderr)
        for m in bad:
            print(f"  {m}", file=sys.stderr)
        return EXIT
    print(f"postmortem_smoke: clean — breaker storm + SLO burn cascade "
          f"produced exactly one atomic bundle "
          f"({os.path.basename(bundles[0])}); doctor and trace rendered "
          f"request {trace_id} offline")
    return 0


if __name__ == "__main__":
    sys.exit(main())
