from .sources import (BaseSourceBatchOp, MemSourceBatchOp, CsvSourceBatchOp,
                      DBSourceBatchOp, LibSvmSourceBatchOp, MySqlSourceBatchOp,
                      TextSourceBatchOp, NumSeqSourceBatchOp, RandomTableSourceBatchOp)
from ...base import TableSourceBatchOp

__all__ = ["BaseSourceBatchOp", "MemSourceBatchOp", "CsvSourceBatchOp",
           "DBSourceBatchOp", "LibSvmSourceBatchOp", "MySqlSourceBatchOp",
           "TextSourceBatchOp", "NumSeqSourceBatchOp", "RandomTableSourceBatchOp",
           "TableSourceBatchOp"]
