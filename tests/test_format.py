"""Format-conversion matrix + JsonValue tests (reference
batch/dataproc/format/* and JsonValueBatchOp tests)."""

import json

import numpy as np
import pytest

import alink_tpu.operator.batch.dataproc.format as F
from alink_tpu.operator.batch.dataproc import JsonValueBatchOp
from alink_tpu.operator.batch.source import MemSourceBatchOp
from alink_tpu.operator.stream.dataproc.format import (JsonValueStreamOp,
                                                       KvToJsonStreamOp)
from alink_tpu.operator.stream.source.sources import MemSourceStreamOp
from alink_tpu.operator.stream.sink.sinks import CollectSinkStreamOp
from alink_tpu.operator.base import StreamOperator


def _src():
    return MemSourceBatchOp([(1, "a", 0.5), (2, "b", 1.5)],
                            "id LONG, name STRING, score DOUBLE")


def test_matrix_completeness():
    # 5 formats pairwise (20) + 5 *ToTriple + 5 TripleTo* + AnyToTriple;
    # TripleToAnyBatchOp is the abstract grouping base, exported but not
    # in the concrete-op matrix
    assert len(F.FORMAT_OPS) == 31
    assert "TripleToAnyBatchOp" not in F.FORMAT_OPS
    assert hasattr(F, "TripleToAnyBatchOp")
    for a in ("Columns", "Csv", "Json", "Kv", "Vector"):
        for b in ("Columns", "Csv", "Json", "Kv", "Vector", "Triple"):
            if a != b:
                assert f"{a}To{b}BatchOp" in F.FORMAT_OPS, (a, b)
        assert f"TripleTo{a}BatchOp" in F.FORMAT_OPS


def test_columns_json_roundtrip():
    j = F.ColumnsToJsonBatchOp(selected_cols=["name", "score"], json_col="js",
                               reserved_cols=["id"]).link_from(_src())
    assert json.loads(j.collect_mtable().col("js")[0]) == {"name": "a",
                                                           "score": 0.5}
    back = F.JsonToColumnsBatchOp(
        json_col="js", schema_str="name STRING, score DOUBLE").link_from(j)
    out = back.collect_mtable()
    assert list(out.col("name")) == ["a", "b"]
    np.testing.assert_allclose(np.asarray(out.col("score")), [0.5, 1.5])


def test_kv_vector_csv():
    kv = MemSourceBatchOp([("0:1.5,3:2.0",), ("1:7.0",)], "kv STRING")
    v = F.KvToVectorBatchOp(kv_col="kv", vector_col="vec",
                            vector_size=4).link_from(kv)
    assert v.collect_mtable().col("vec")[0] == "$4$0:1.5 3:2.0"
    back = F.VectorToKvBatchOp(vector_col="vec", kv_col="kv2").link_from(v)
    assert back.collect_mtable().col("kv2")[1] == "1:7.0"
    csv = F.KvToCsvBatchOp(kv_col="kv", csv_col="c",
                           schema_str="f0 DOUBLE, f1 DOUBLE").link_from(kv)
    # kv keys 0/1 -> schema names f0/f1 not present => empty fields
    assert csv.get_schema().names[-1] == "c"


def test_triple_roundtrip():
    tri = F.ColumnsToTripleBatchOp(selected_cols=["name", "score"]).link_from(_src())
    rows = tri.collect_mtable().to_rows()
    assert ("column" in tri.get_schema().names and len(rows) == 4)
    back = F.TripleToJsonBatchOp(triple_row_col="row", triple_column_col="column",
                                 triple_value_col="value",
                                 json_col="js").link_from(tri)
    out = back.collect_mtable()
    assert json.loads(out.col("js")[0])["name"] == "a"


def test_json_value_batch_and_stream():
    rows = [('{"a": {"b": [1, 2, 3]}, "c": "x"}',),
            ('{"a": {"b": [9]}, "c": "y"}',)]
    src = MemSourceBatchOp(rows, "js STRING")
    op = JsonValueBatchOp(selected_col="js", json_path=["$.a.b[0]", "$.c"],
                          output_cols=["b0", "c"]).link_from(src)
    out = op.collect_mtable()
    assert list(out.col("b0")) == ["1", "9"]
    assert list(out.col("c")) == ["x", "y"]
    # missing path errors unless skip_failed
    with pytest.raises(ValueError):
        JsonValueBatchOp(selected_col="js", json_path=["$.zz"],
                         output_cols=["z"]).link_from(src)
    ok = JsonValueBatchOp(selected_col="js", json_path=["$.zz"],
                          output_cols=["z"], skip_failed=True).link_from(src)
    assert list(ok.collect_mtable().col("z")) == [None, None]

    s = MemSourceStreamOp(rows, "js STRING", batch_size=1)
    sop = JsonValueStreamOp(selected_col="js", json_path=["$.c"],
                            output_cols=["c"]).link_from(s)
    sink = CollectSinkStreamOp().link_from(sop)
    StreamOperator.execute()
    got = sink.get_and_remove_values().to_rows()
    assert [r[-1] for r in got] == ["x", "y"]


def test_kv_to_json_stream():
    s = MemSourceStreamOp([("k:1",), ("k:2",)], "kv STRING", batch_size=1)
    sop = KvToJsonStreamOp(kv_col="kv", json_col="js").link_from(s)
    sink = CollectSinkStreamOp().link_from(sop)
    StreamOperator.execute()
    got = sink.get_and_remove_values().to_rows()
    assert json.loads(got[0][-1]) == {"k": "1"}


def test_kv_digit_keys_not_positionally_remapped():
    # regression: KV dicts whose keys happen to be digits must be matched by
    # NAME, never remapped to positions like vector-sourced dicts
    kv = MemSourceBatchOp([("1:2.0,3:4.0",)], "kv STRING")
    csv = F.KvToCsvBatchOp(kv_col="kv", csv_col="c",
                           schema_str="1 DOUBLE, 3 DOUBLE").link_from(kv)
    assert csv.collect_mtable().col("c")[0] == "2.0,4.0"
    cols = F.KvToColumnsBatchOp(kv_col="kv",
                                schema_str="5 DOUBLE, 3 DOUBLE").link_from(kv)
    assert cols.collect_mtable().to_rows()[0] == (None, 4.0)


def test_vector_to_csv_positional():
    v = MemSourceBatchOp([("1.5 2.5",)], "v STRING")
    csv = F.VectorToCsvBatchOp(vector_col="v", csv_col="c",
                               schema_str="a DOUBLE, b DOUBLE").link_from(v)
    assert csv.collect_mtable().col("c")[0] == "1.5,2.5"
