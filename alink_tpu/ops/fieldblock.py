"""Field-blocked sparse format + factored one-hot kernels.

The motivating workload is the reference's Criteo-style CTR pipeline
(FTRLExample.java:46-57): FeatureHasher murmurs every raw feature into one
flat space and the linear trainers then do random gather (w[idx]) and
random scatter-add (grad[idx] += c) per sample — fine on a CPU heap,
catastrophic on TPU where XLA serializes both (measured ~67 ms for 6.4M
random accesses on v5e vs ~0.1 ms of equivalent streaming traffic).

TPU-first redesign: hash each input column (field) into its OWN contiguous
sub-range of the model vector — ``dim = num_fields * field_size`` — so every
sample holds exactly one local index per field: ``fb_idx`` of shape
``(n, F)`` with values in ``[0, field_size)``. Field-aware hashing preserves
the model class (same capacity, per-field collision behaviour is what
production CTR systems use anyway). With that structure both directions of
the sparse design-matrix product become MXU matmuls via a *factored one-hot*:

    idx = hi * LO + lo,  LO = 16
    A[n, f, h] = [hi == h]      (one-hot over field_size/16)
    B[n, f, l] = [lo == l]      (one-hot over 16)

    matvec:   eta = einsum(A, W, B)           # W: (F, H, LO)
    rmatvec:  grad = einsum(A, B * c)

The one-hots are never materialized to HBM — XLA fuses the iota-compares
into the matmul operands. The factoring cuts the one-hot work from
O(n*dim) to O(n*(H + LO)) per field. Measured on v5e-1: fused logistic
gradient 19 ms vs 67+66 ms for XLA gather+scatter at n=200k, F=32,
dim=65536.

For iterative trainers the factors can instead be materialized ONCE
(`fb_onehot_parts`) and reused across every pass and iteration — see the
design note at the bottom of this file for why that beats both the inline
one-hot and a hand-written Pallas kernel on v5e.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

LO = 16  # lo-part width; field_size must be a multiple of this


@dataclass(frozen=True)
class FieldBlockMeta:
    """Shape metadata for a field-blocked design matrix.

    dim = num_fields * field_size; global index of (field k, local j) is
    ``k * field_size + j`` (field-major), matching the coefficient layout.
    """
    num_fields: int
    field_size: int

    @property
    def dim(self) -> int:
        return self.num_fields * self.field_size

    @property
    def hi_size(self) -> int:
        return self.field_size // LO

    def __post_init__(self):
        if self.field_size % LO:
            raise ValueError(f"field_size must be a multiple of {LO}")


def hash_to_fields(columns, field_size: int, seed: int = 0) -> np.ndarray:
    """Field-aware feature hashing: one column -> one field (host-side).

    The reference hashes all columns into one flat space
    (FeatureHasherMapper over murmur32); here each column owns a
    ``field_size`` sub-range so the result is field-blocked by
    construction. Returns ``fb_idx`` of shape (n, num_columns) int32.
    """
    from ..operator.batch.feature.feature_ops import murmur32_cells
    cols = list(columns)
    n = len(cols[0])
    out = np.empty((n, len(cols)), np.int32)
    for k, col in enumerate(cols):
        tokens = [f"{k}={v}".encode() for v in col]
        out[:, k] = murmur32_cells(tokens, seed=seed, mod=field_size)
    return out


def fb_to_flat_indices(fb_idx: np.ndarray, meta: FieldBlockMeta) -> np.ndarray:
    """(n, F) field-local -> (n, F) global indices into the dim-vector."""
    offs = (np.arange(meta.num_fields, dtype=np.int64) * meta.field_size)
    return (np.asarray(fb_idx, np.int64) + offs[None, :]).astype(np.int32)


def detect_fieldblock(idx: np.ndarray, val: Optional[np.ndarray], dim: int):
    """Recognize the field-blocked layout in a padded-COO design.

    Field-aware hashing (FeatureHasherBatchOp(field_aware=True)) emits
    exactly one entry per field per row, field k's indices inside
    ``[k*S, (k+1)*S)``; this detects that shape so linear trainers can take
    the MXU fast path automatically. Returns (fb_idx, fb_val|None, meta)
    with fb_val None when all values are 1.0, else None when the pattern
    does not hold (general sparse falls back to COO).
    """
    idx = np.asarray(idx)
    # F >= 2: with a single column every width-1 design would "detect"
    # vacuously and reroute generic sparse data onto the one-hot path
    if idx.ndim != 2 or idx.shape[1] < 2:
        return None
    F = idx.shape[1]
    if dim % F or (dim // F) % LO or dim // F < LO:
        return None
    meta = FieldBlockMeta(F, dim // F)
    local = flat_to_fb_indices(idx, meta)
    if local is None:
        return None
    if val is None or np.all(val == 1.0):
        return local, None, meta
    return local, np.asarray(val), meta


def flat_to_fb_indices(idx: np.ndarray, meta: FieldBlockMeta) -> Optional[np.ndarray]:
    """Recognize a field-blocked pattern in padded-COO indices.

    Returns (n, F) local indices if every row's k-th entry falls in field
    k's range (the shape produced by field-aware hashing), else None.
    """
    idx = np.asarray(idx)
    if idx.ndim != 2 or idx.shape[1] != meta.num_fields:
        return None
    offs = np.arange(meta.num_fields, dtype=idx.dtype) * meta.field_size
    local = idx - offs[None, :]
    if (local < 0).any() or (local >= meta.field_size).any():
        return None
    return local.astype(np.int32)


# ---------------------------------------------------------------------------
# factored one-hot ops (XLA path — default)
# ---------------------------------------------------------------------------

def _default_dtype():
    """bf16 on TPU (MXU-native), f32 elsewhere (CPU dot lacks bf16)."""
    import jax
    import jax.numpy as jnp
    return jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32


def _parts(fb_idx, meta: FieldBlockMeta):
    import jax.numpy as jnp
    hi = fb_idx // LO
    lo = fb_idx - hi * LO
    A = (hi[..., None] == jnp.arange(meta.hi_size)[None, None, :])
    B = (lo[..., None] == jnp.arange(LO)[None, None, :])
    return A, B


def fb_onehot_parts(fb_idx, meta: FieldBlockMeta, dtype=None):
    """Materialized (A, B) one-hot factors of the design matrix.

    The factors depend only on the (fixed) data, not on the iterate, yet
    building them inline makes every einsum pass write+read ~8x the index
    bytes to HBM. An iterative trainer that precomputes them ONCE (in its
    init superstep, device-side) and reuses them across all passes and
    iterations cuts the Criteo-shape L-BFGS superstep ~15 ms -> ~9 ms on
    v5e. Costs n*F*(hi_size + LO) operand bytes of HBM — gate on a budget
    (optimizers.ALINK_TPU_FB_ONEHOT_BYTES) before enabling."""
    import jax.numpy as jnp
    dtype = dtype or _default_dtype()
    A, B = _parts(fb_idx, meta)
    return A.astype(dtype), B.astype(dtype)


def _w3(coef, meta: FieldBlockMeta):
    return coef.reshape(meta.num_fields, meta.hi_size, LO)


def fb_matvec(fb_idx, coef, meta: FieldBlockMeta, val=None, dtype=None,
              parts=None):
    """eta[i] = sum_k val[i,k] * coef[k*S + fb_idx[i,k]]  — all MXU.

    Replaces the per-sample SparseVector dot of the reference's
    LinearModelMapper / OptimObjFunc.calcGradient inner loop.
    ``parts``: precomputed (A, B) from :func:`fb_onehot_parts`.
    """
    import jax.numpy as jnp
    dtype = dtype or _default_dtype()
    if parts is not None:
        A, B = parts
        A = A.astype(dtype)
    else:
        A, B = _parts(fb_idx, meta)
        A = A.astype(dtype)
    W = _w3(coef, meta).astype(dtype)
    rows = jnp.einsum("nfh,fhl->nfl", A, W,
                      preferred_element_type=jnp.float32)
    if val is not None:
        Bv = B.astype(jnp.float32) * val[..., None].astype(jnp.float32)
        return jnp.einsum("nfl,nfl->n", rows, Bv)
    Bc = B.astype(jnp.float32) if B.dtype == bool else B
    return jnp.einsum("nfl,nfl->n", rows, Bc,
                      preferred_element_type=jnp.float32)


def fb_gather(fb_idx, vec, meta: FieldBlockMeta, dtype=None):
    """out[i, k] = vec[k*S + fb_idx[i,k]] — per-field value selection as
    one-hot MXU matmuls (the gather XLA would otherwise serialize).

    Same factored kernel as :func:`fb_matvec` but keeping the field axis
    instead of dotting it away; batched FTRL uses it to read the per-slot
    (n, w) state without a random gather. Defaults to f32 operands: a
    selection must return the value exactly, unlike the matvec whose bf16
    operand rounding is amortized by f32 accumulation over the contraction."""
    import jax.numpy as jnp
    dtype = dtype or jnp.float32
    A, B = _parts(fb_idx, meta)
    W = _w3(vec, meta).astype(dtype)
    rows = jnp.einsum("nfh,fhl->nfl", A.astype(dtype), W,
                      preferred_element_type=jnp.float32)
    return jnp.einsum("nfl,nfl->nf", rows, B.astype(jnp.float32))


def fb_rmatvec(fb_idx, c, meta: FieldBlockMeta, val=None, dtype=None,
               parts=None):
    """grad = X^T c for the field-blocked design matrix — scatter-free.

    Replaces the reference's per-sample scatter-add
    (OptimObjFunc.updateGradient / SparseVector axpy).
    ``parts``: precomputed (A, B) from :func:`fb_onehot_parts`.
    """
    import jax.numpy as jnp
    dtype = dtype or _default_dtype()
    if parts is not None:
        A, B = parts
    else:
        A, B = _parts(fb_idx, meta)
    z = c
    if val is not None:
        z = z[:, None] * val
        Z = B.astype(dtype) * z[..., None].astype(dtype)
    else:
        Z = B.astype(dtype) * z[:, None, None].astype(dtype)
    g = jnp.einsum("nfh,nfl->fhl", A.astype(dtype), Z,
                   preferred_element_type=jnp.float32)
    return g.reshape(meta.dim)


# ---------------------------------------------------------------------------
# Why there is no Pallas kernel here (round-1/2 measurements, v5e-1,
# n=200k, F=32, dim=64k):
#
# A hand-written fused Pallas pass (coefficient table + gradient
# accumulator pinned in VMEM, rows streamed in chunks, per-field
# (CH,K)@(K,LANE) MXU dots) measured ~10 ms/pass — the per-field K=16
# dots pay full MXU pipeline latency per tile-row.  The XLA einsum path
# above measured ~4.5 ms/pass, and with the data-constant one-hot factors
# precomputed once (fb_onehot_parts, reused across every pass and
# iteration) the whole three-pass L-BFGS superstep runs ~7.8 ms — faster
# than a single Pallas pass.  Per the round-1 review, the losing kernels
# were removed rather than carried as a maintenance surface; this note
# and git history (commit e18c612) preserve the design and the numbers
# for whoever revisits with a bigger-K block-diagonal layout.
# ---------------------------------------------------------------------------
