"""Tree-family tests: GBDT / RandomForest / DecisionTree, cls + reg."""

import json

import numpy as np
import pytest

from alink_tpu.operator.base import TableSourceBatchOp
from alink_tpu.operator.batch.source import MemSourceBatchOp
from alink_tpu.operator.batch.classification.tree_ops import (
    GbdtTrainBatchOp, GbdtPredictBatchOp, GbdtRegTrainBatchOp,
    GbdtRegPredictBatchOp, RandomForestTrainBatchOp, RandomForestPredictBatchOp,
    DecisionTreeTrainBatchOp, DecisionTreePredictBatchOp,
    RandomForestRegTrainBatchOp, RandomForestRegPredictBatchOp,
    TreeModelDataConverter)
from alink_tpu.operator.batch.evaluation import EvalBinaryClassBatchOp


def _nonlinear_cls(n=800, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, 4)
    # axis-aligned nonlinear rule — tree-friendly, linear-hostile
    y = np.where((X[:, 0] > 0.5) ^ (X[:, 1] > 0.5), "pos", "neg")
    cols = "a DOUBLE, b DOUBLE, c DOUBLE, d DOUBLE, label STRING"
    return MemSourceBatchOp([tuple(r) + (t,) for r, t in zip(X, y)], cols), X, y


def test_gbdt_classifier():
    src, X, y = _nonlinear_cls()
    train = GbdtTrainBatchOp(feature_cols=["a", "b", "c", "d"],
                             label_col="label", num_trees=30, max_depth=4,
                             learning_rate=0.3).link_from(src)
    out = (GbdtPredictBatchOp(prediction_col="pred", prediction_detail_col="dt")
           .link_from(train, src)).collect_mtable()
    acc = np.mean([p == l for p, l in zip(out.col("pred"), out.col("label"))])
    assert acc > 0.95
    m = (EvalBinaryClassBatchOp(label_col="label", prediction_detail_col="dt")
         .link_from(TableSourceBatchOp(out))).collect_metrics()
    assert m.get("AUC") > 0.98
    losses = np.asarray(train.get_side_output(0).get_output_table().col("loss"))
    assert losses[-1] < losses[0] * 0.5


def test_gbdt_regression():
    rng = np.random.RandomState(1)
    n = 600
    X = rng.rand(n, 3)
    y = np.sin(4 * X[:, 0]) + (X[:, 1] > 0.6) * 2.0 + 0.05 * rng.randn(n)
    src = MemSourceBatchOp([tuple(r) + (t,) for r, t in zip(X, y)],
                           "a DOUBLE, b DOUBLE, c DOUBLE, y DOUBLE")
    train = GbdtRegTrainBatchOp(feature_cols=["a", "b", "c"], label_col="y",
                                num_trees=60, max_depth=4,
                                learning_rate=0.2).link_from(src)
    out = (GbdtRegPredictBatchOp(prediction_col="p").link_from(train, src)
           ).collect_mtable()
    rmse = np.sqrt(np.mean((np.asarray(out.col("p")) - y) ** 2))
    assert rmse < 0.25


def test_random_forest_multiclass():
    rng = np.random.RandomState(2)
    n = 600
    X = rng.rand(n, 3)
    y = np.select([X[:, 0] > 0.66, X[:, 0] > 0.33], ["hi", "mid"], "lo")
    src = MemSourceBatchOp([tuple(r) + (t,) for r, t in zip(X, y)],
                           "a DOUBLE, b DOUBLE, c DOUBLE, label STRING")
    train = RandomForestTrainBatchOp(feature_cols=["a", "b", "c"],
                                     label_col="label", num_trees=20,
                                     max_depth=5, seed=5).link_from(src)
    out = (RandomForestPredictBatchOp(prediction_col="pred",
                                      prediction_detail_col="d")
           .link_from(train, src)).collect_mtable()
    acc = np.mean([p == l for p, l in zip(out.col("pred"), out.col("label"))])
    assert acc > 0.93
    probs = json.loads(out.col("d")[0])
    assert set(probs) == {"hi", "mid", "lo"}


def test_decision_tree_and_converter_roundtrip():
    rng = np.random.RandomState(3)
    X = rng.rand(400, 4)
    y = np.where((X[:, 0] > 0.5) & (X[:, 1] > 0.3), "pos", "neg")
    src = MemSourceBatchOp(
        [tuple(r) + (t,) for r, t in zip(X, y)],
        "a DOUBLE, b DOUBLE, c DOUBLE, d DOUBLE, label STRING")
    train = DecisionTreeTrainBatchOp(feature_cols=["a", "b", "c", "d"],
                                     label_col="label", max_depth=4).link_from(src)
    model = TreeModelDataConverter().load_model(train.get_output_table())
    assert model.features.shape == (1, 15)
    out = (DecisionTreePredictBatchOp(prediction_col="pred")
           .link_from(train, src)).collect_mtable()
    acc = np.mean([p == l for p, l in zip(out.col("pred"), out.col("label"))])
    assert acc > 0.95


def test_random_forest_regression():
    rng = np.random.RandomState(4)
    n = 500
    X = rng.rand(n, 2)
    y = X[:, 0] * 3 + (X[:, 1] > 0.5)
    src = MemSourceBatchOp([tuple(r) + (t,) for r, t in zip(X, y)],
                           "a DOUBLE, b DOUBLE, y DOUBLE")
    train = RandomForestRegTrainBatchOp(feature_cols=["a", "b"], label_col="y",
                                        num_trees=30, max_depth=7,
                                        feature_subsampling_ratio=1.0,
                                        subsampling_ratio=0.9).link_from(src)
    out = (RandomForestRegPredictBatchOp(prediction_col="p")
           .link_from(train, src)).collect_mtable()
    rmse = np.sqrt(np.mean((np.asarray(out.col("p")) - y) ** 2))
    assert rmse < 0.35


def test_gbdt_integer_labels():
    src, X, y = _nonlinear_cls(n=300, seed=5)
    rows = [(float(a), float(b), 1 if t == "pos" else 0)
            for (a, b, _, _), t in zip(X, y)]
    src2 = MemSourceBatchOp(rows, "a DOUBLE, b DOUBLE, label LONG")
    train = GbdtTrainBatchOp(feature_cols=["a", "b"], label_col="label",
                             num_trees=20, max_depth=4).link_from(src2)
    out = (GbdtPredictBatchOp(prediction_col="pred").link_from(train, src2)
           ).collect_mtable()
    assert set(out.col("pred")) <= {0, 1}
    acc = np.mean([p == l for p, l in zip(out.col("pred"), out.col("label"))])
    assert acc > 0.9


class TestLevelHist:
    def test_onehot_matches_scatter(self):
        """The TPU one-hot einsum histogram must agree with the scatter-add
        path (exercised here with f32 one-hots since CPU lacks bf16 dots)."""
        import jax.numpy as jnp
        from alink_tpu.operator.common.tree.hist import level_hist
        rng = np.random.RandomState(11)
        n, F, B, m, n_nodes = 200, 5, 8, 3, 4
        binned = jnp.asarray(rng.randint(0, B, (n, F)).astype(np.int32))
        stats = jnp.asarray(rng.randn(n, m).astype(np.float32))
        node_id = jnp.asarray(rng.randint(0, n_nodes, n).astype(np.int32))
        a = level_hist(binned, stats, node_id, n_nodes, B, use_onehot=False)
        b = level_hist(binned, stats, node_id, n_nodes, B, use_onehot=True,
                       onehot_dtype=jnp.float32)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                                   atol=1e-5)


def test_gbdt_categorical_subset_split():
    """A label driven by membership in a scattered category subset needs
    ~1 categorical subset split but many ordinal threshold splits: shallow
    trees with categorical_cols must beat the same trees without
    (VERDICT round-2 item 6, ref seriestree/CategoricalSplitter.java)."""
    import numpy as np
    from alink_tpu.operator.batch.source import MemSourceBatchOp
    from alink_tpu.operator.batch.classification.tree_ops import (
        GbdtTrainBatchOp, GbdtPredictBatchOp)

    rng = np.random.RandomState(0)
    n = 3000
    cats = np.asarray(list("ABCDEFGHIJKL"))
    cvals = cats[rng.randint(0, 12, n)]
    subset = {"B", "F", "K"}          # scattered in ordinal order
    x0 = rng.randn(n)
    y = ((np.isin(cvals, list(subset))) ^ (x0 > 1.5)).astype(int)
    rows = [(str(c), float(v), int(t)) for c, v, t in zip(cvals, x0, y)]
    src = MemSourceBatchOp(rows, "cat STRING, x0 DOUBLE, label LONG")

    def acc(train_op):
        pred = GbdtPredictBatchOp(prediction_col="p").link_from(train_op, src)
        out = pred.collect_mtable()
        return np.mean(np.asarray(out.col("p")) == y)

    with_cat = GbdtTrainBatchOp(
        feature_cols=["x0"], categorical_cols=["cat"], label_col="label",
        num_trees=5, max_depth=2).link_from(src)
    acc_cat = acc(with_cat)
    assert acc_cat > 0.97, acc_cat

    # importances present and dominated by the categorical column
    info = with_cat.get_model_info()
    items = dict(zip(info.col("item"), info.col("value")))
    assert float(items["importance[cat]"]) > 0.5
    ti = with_cat.get_side_output(1).get_output_table()
    imp = dict(zip(ti.col("feature"), ti.col("importance")))
    assert abs(sum(imp.values()) - 1.0) < 1e-9
    assert imp["cat"] > imp["x0"]


def test_gbdt_categorical_roundtrip_and_oov():
    """Split masks and vocabularies survive the model-table round trip;
    unseen categories at predict time route right (no crash)."""
    import numpy as np
    from alink_tpu.common import MTable
    from alink_tpu.operator.batch.source import MemSourceBatchOp
    from alink_tpu.operator.batch.classification.tree_ops import (
        GbdtTrainBatchOp, GbdtPredictBatchOp, TreeModelDataConverter)

    rng = np.random.RandomState(1)
    n = 800
    cvals = np.asarray(list("PQRS"))[rng.randint(0, 4, n)]
    y = (np.isin(cvals, ["Q", "S"])).astype(int)
    rows = [(str(c), int(t)) for c, t in zip(cvals, y)]
    src = MemSourceBatchOp(rows, "cat STRING, label LONG")
    train = GbdtTrainBatchOp(feature_cols=[], categorical_cols=["cat"],
                             label_col="label", num_trees=3,
                             max_depth=2).link_from(src)
    m = TreeModelDataConverter().load_model(train.get_output_table())
    assert m.split_masks is not None and m.cat_vocabs["cat"] == list("PQRS")
    # round trip through rows (string serialization)
    t = train.get_output_table()
    m2 = TreeModelDataConverter().load_model(MTable(t.to_rows(), t.schema))
    np.testing.assert_array_equal(m.split_masks, m2.split_masks)

    test_rows = [("P", 0), ("Q", 1), ("ZZZ", 0)]   # ZZZ unseen
    out = GbdtPredictBatchOp(prediction_col="p").link_from(
        train, MemSourceBatchOp(test_rows, "cat STRING, label LONG")
    ).collect_mtable()
    p = np.asarray(out.col("p"))
    assert p[0] == 0 and p[1] == 1

    # forests get importances too
    from alink_tpu.operator.batch.classification.tree_ops import (
        RandomForestTrainBatchOp)
    rf = RandomForestTrainBatchOp(feature_cols=[], categorical_cols=["cat"],
                                  label_col="label", num_trees=4,
                                  max_depth=3).link_from(src)
    info = rf.get_model_info()
    assert any("importance[cat]" in i for i in info.col("item"))
    # RF *classification* predict must route categorical nodes by subset
    # membership too (regression + gbdt paths are covered above)
    from alink_tpu.operator.batch.classification.tree_ops import (
        RandomForestPredictBatchOp)
    rf_out = RandomForestPredictBatchOp(prediction_col="p").link_from(
        rf, src).collect_mtable()
    rf_acc = np.mean(np.asarray(rf_out.col("p")) == y)
    assert rf_acc > 0.97, rf_acc


def test_rf_ensemble_parallelism():
    """Ensemble mode (default): W independent trees per superstep —
    ceil(T/W) supersteps for T trees — with quality parity vs the
    histogram-parallel mode (VERDICT round-2 item 10)."""
    from alink_tpu.common.mlenv import MLEnvironmentFactory
    from alink_tpu.operator.common.tree.trainers import (TreeTrainParams,
                                                         forest_train)
    rng = np.random.RandomState(0)
    n = 4000
    X = rng.rand(n, 4)
    y = ((X[:, 0] > 0.5) ^ (X[:, 1] > 0.5)).astype(int)
    stats = np.concatenate([np.eye(2)[y], np.ones((n, 1))], 1)
    W = MLEnvironmentFactory.get_default().num_workers
    T = 11                                     # NOT a multiple of W
    p = TreeTrainParams(num_trees=T, max_depth=5, n_bins=32,
                        subsample_ratio=0.8, feature_subsample_ratio=0.9)

    def acc(ensemble):
        tf, tb, tm, tv, edges, imp = forest_train(X, stats, p, "gini",
                                                  ensemble=ensemble)
        assert tf.shape == (T, 31)
        from alink_tpu.operator.common.tree.hist import (bin_data,
                                                         tree_apply_binned)
        binned = bin_data(X, edges)
        probs = np.zeros((n, 2))
        for t in range(T):
            leaf = np.asarray(tree_apply_binned(binned, tf[t], tb[t], 5, tm[t]))
            probs += tv[t][leaf]
        return (probs.argmax(1) == y).mean(), tf

    a_ens, tf_ens = acc(True)
    a_hist, _ = acc(False)
    assert a_ens > 0.95, a_ens
    assert a_ens > a_hist - 0.03, (a_ens, a_hist)   # parity within 3 points
    # trees grown on different workers in the same superstep must differ
    # (independent bagging/rng per worker): first W trees not all identical
    first_round = [tf_ens[t].tobytes() for t in range(min(W, T))]
    assert len(set(first_round)) > 1


def test_random_forest_label_sorted_input():
    """Ensemble trees see only their worker's partition; a label-sorted
    dataset must not hand workers single-class slices (rows are shuffled
    before partitioning, mirroring the reference's AvgPartition)."""
    src, X, y = _nonlinear_cls(n=800, seed=4)
    order = np.argsort(y, kind="stable")   # all "neg" rows, then all "pos"
    rows = [tuple(r) + (t,) for r, t in zip(X[order], y[order])]
    cols = "a DOUBLE, b DOUBLE, c DOUBLE, d DOUBLE, label STRING"
    sorted_src = MemSourceBatchOp(rows, cols)
    train = RandomForestTrainBatchOp(feature_cols=["a", "b", "c", "d"],
                                     label_col="label", num_trees=16,
                                     max_depth=5).link_from(sorted_src)
    out = (RandomForestPredictBatchOp(prediction_col="pred")
           .link_from(train, sorted_src)).collect_mtable()
    acc = np.mean([p == l for p, l in zip(out.col("pred"), out.col("label"))])
    assert acc > 0.9


def test_bin_edges_nan_host_device_agree():
    """Host and device binning must agree on NaN handling: a column with
    missing values still gets real cut points on both paths."""
    from alink_tpu.operator.common.tree.hist import make_bin_edges
    rng = np.random.RandomState(0)
    X = rng.randn(400, 3)
    X[rng.rand(400) < 0.1, 1] = np.nan
    e_host = make_bin_edges(X, 8, device=False)
    e_dev = make_bin_edges(X, 8, device=True)
    assert np.isfinite(e_host[1]).any(), "NaN column dead on host path"
    assert np.isfinite(e_dev[1]).any()
    np.testing.assert_allclose(e_host[0], e_dev[0], atol=0.15)
