"""Generate docs/operators.md and the env-flag reference tables.

The reference ships per-operator docs for every op (docs/cn + docs/en, 359
files each); here one generated markdown reference covers the whole flat
namespace: class name, defining module, first docstring paragraph, and the
parameter table (name, type, default, description) from the Params system.

The env-flag tables in ``docs/performance.md`` and
``docs/observability.md`` render from the declarative registry in
``alink_tpu/common/flags.py`` (name, default, what it gates, which cache
keys it folds into), between ``BEGIN/END GENERATED FLAG TABLE`` markers
— the docs cannot drift from the registry, and a new flag shows up in
the docs by being declared, the same declaration ``tools/lint``'s
ENV-KEY-FOLD rule cross-checks.

Usage:  python tools/gen_docs.py            # rewrite operators.md + flag tables
        python tools/gen_docs.py --flags    # flag tables only (no jax import)
        python tools/gen_docs.py --check    # exit 1 if any flag table is stale
"""

from __future__ import annotations

import collections
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "docs", "operators.md")

_SECTION_ORDER = [
    ("operator.batch.classification", "Batch — Classification"),
    ("operator.batch.regression", "Batch — Regression"),
    ("operator.batch.clustering", "Batch — Clustering"),
    ("operator.batch.recommendation", "Batch — Recommendation"),
    ("operator.batch.nlp", "Batch — NLP"),
    ("operator.batch.similarity", "Batch — Similarity / LSH"),
    ("operator.batch.feature", "Batch — Feature engineering"),
    ("operator.batch.dataproc", "Batch — Data processing"),
    ("operator.batch.statistics", "Batch — Statistics"),
    ("operator.batch.evaluation", "Batch — Evaluation"),
    ("operator.batch.outlier", "Batch — Outlier"),
    ("operator.batch.associationrule", "Batch — Association rules"),
    ("operator.batch.sql", "Batch — SQL"),
    ("operator.batch.source", "Batch — Sources"),
    ("operator.batch.sink", "Batch — Sinks"),
    ("operator.batch.utils", "Batch — Utilities"),
    ("operator.stream", "Stream operators"),
    ("io", "Connectors / IO"),
    ("pipeline", "Pipeline API"),
]


def _first_paragraph(doc: str) -> str:
    if not doc:
        return ""
    para = doc.strip().split("\n\n")[0]
    return " ".join(line.strip() for line in para.splitlines())


def _param_rows(cls) -> list:
    from alink_tpu.common.params import ParamInfo
    infos = getattr(cls, "_PARAM_INFOS", None) or {}
    rows = []
    for name, pi in sorted(infos.items()):
        if not isinstance(pi, ParamInfo) or name == "ml_environment_id":
            continue
        typ = getattr(pi.type, "__name__", str(pi.type))
        default = repr(pi.default) if pi.has_default else ("—" if pi.optional
                                                           else "required")
        desc = (pi.description or "").replace("|", "\\|")
        rows.append(f"| `{name}` | {typ} | {default} | {desc} |")
    return rows


def _section_for(module: str) -> str:
    rel = module[len("alink_tpu."):]
    best = None
    for prefix, title in _SECTION_ORDER:
        if rel.startswith(prefix) and (best is None or len(prefix) > len(best[0])):
            best = (prefix, title)
    return best[1] if best else "Other"


# ---------------------------------------------------------------------------
# env-flag reference tables (from the FlagRegistry, no jax import)
# ---------------------------------------------------------------------------

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FLAGS_BEGIN = ("<!-- BEGIN GENERATED FLAG TABLE (tools/gen_docs.py — "
               "edit alink_tpu/common/flags.py instead) -->")
FLAGS_END = "<!-- END GENERATED FLAG TABLE -->"

# doc file -> registry sections rendered into its marked block
FLAG_TABLE_TARGETS = {
    os.path.join("docs", "performance.md"):
        ("performance", "durability", "debug", "io", "bench"),
    os.path.join("docs", "observability.md"):
        ("observability",),
    os.path.join("docs", "serving.md"):
        ("serving", "e2e"),
    os.path.join("docs", "tuning.md"):
        ("tuning",),
}


def _load_registry():
    """The FLAGS registry, standalone (stdlib-only module — no jax).

    Resolved through the ``tools.lint`` package (the repo root is already
    on ``sys.path``) so the analyzer module is never bound a second time
    under a bare top-level ``lint`` name."""
    from tools.lint.analyzer import load_flag_registry
    return load_flag_registry()


def flag_table_md(registry, sections) -> str:
    """One markdown table: name, default, what it gates, key folds."""
    lines = [
        "| flag | type | default | folds into cache keys | effect |",
        "|---|---|---|---|---|",
    ]
    for r in registry.doc_rows(sections):
        desc = r["description"].replace("|", "\\|")
        folds = r["folds"]
        if folds == "—":
            folds = "— (key-neutral)"
        lines.append(f"| `{r['name']}` | {r['kind']} | `{r['default']}` "
                     f"| {folds} | {desc} |")
    lines.append("")
    lines.append("Key-neutral flags carry a written justification in "
                 "`alink_tpu/common/flags.py` for WHY no cache-key fold "
                 "is needed; `python -m tools.lint` (ENV-KEY-FOLD) "
                 "cross-checks both claims against the code.")
    return "\n".join(lines)


def _spliced(text: str, table: str, path: str) -> str:
    try:
        head, rest = text.split(FLAGS_BEGIN, 1)
        _, tail = rest.split(FLAGS_END, 1)
    except ValueError:
        raise SystemExit(
            f"{path}: missing {FLAGS_BEGIN!r}/{FLAGS_END!r} markers")
    return head + FLAGS_BEGIN + "\n" + table + "\n" + FLAGS_END + tail


def gen_flag_tables(check: bool = False) -> bool:
    """Rewrite (or with ``check=True`` just diff) every marked flag
    table. Returns True when all tables were already current."""
    registry = _load_registry()
    current = True
    for rel, sections in FLAG_TABLE_TARGETS.items():
        path = os.path.join(_ROOT, rel)
        with open(path) as f:
            text = f.read()
        want = _spliced(text, flag_table_md(registry, sections), rel)
        if want != text:
            current = False
            if check:
                print(f"{rel}: flag table is STALE — run "
                      f"python tools/gen_docs.py --flags")
            else:
                with open(path, "w") as f:
                    f.write(want)
                print(f"wrote {rel}: flag table ({len(sections)} sections)")
        elif not check:
            print(f"{rel}: flag table already current")
    return current


def check_readme_bench() -> bool:
    """Docs freshness gate (ISSUE 15 satellite, VERDICT #2): the
    README's machine-generated measured-performance table must equal a
    fresh regeneration from the NEWEST driver-captured ``BENCH_r*.json``
    — a new capture landing without the table being regenerated fails
    the gate instead of silently drifting from the recorded evidence.
    Returns True when current (or when no capture exists to check
    against)."""
    import re

    from tools import gen_readme_table as grt
    path = grt.newest_capture()
    if path is None:
        print("README bench table: no BENCH_r*.json capture to check "
              "against — skipped")
        return True
    try:
        workloads = grt.load_workloads(path)
    except SystemExit as e:
        print(f"README bench table: {e} — cannot verify freshness")
        return False
    want = (grt.START + "\n"
            + grt.render(workloads, os.path.basename(path)) + "\n"
            + grt.END)
    rp = os.path.join(_ROOT, "README.md")
    with open(rp) as f:
        readme = f.read()
    m = re.search(re.escape(grt.START) + r".*?" + re.escape(grt.END),
                  readme, flags=re.S)
    if m is None:
        print("README.md: BENCH_TABLE markers missing — run "
              "python tools/gen_readme_table.py")
        return False
    if m.group(0) != want:
        print(f"README.md: measured-performance table is STALE vs "
              f"{os.path.basename(path)} — run "
              f"python tools/gen_readme_table.py")
        return False
    return True


def gen_operators() -> None:
    import alink_tpu
    exports = alink_tpu._collect_exports()
    sections = collections.defaultdict(list)
    for name, cls in sorted(exports.items()):
        sections[_section_for(cls.__module__)].append((name, cls))

    lines = [
        "# Operator reference",
        "",
        "*Generated by `tools/gen_docs.py` — do not edit by hand.*",
        "",
        f"{len(exports)} public classes, importable flat from `alink_tpu` "
        "(`from alink_tpu import *`).",
        "",
    ]
    titles = [t for _, t in _SECTION_ORDER] + ["Other"]
    for title in titles:
        entries = sections.get(title)
        if not entries:
            continue
        lines += [f"## {title}", ""]
        for name, cls in entries:
            lines.append(f"### `{name}`")
            lines.append("")
            lines.append(f"*module:* `{cls.__module__}`")
            doc = _first_paragraph(cls.__doc__ or "")
            if doc:
                lines += ["", doc]
            rows = _param_rows(cls)
            if rows:
                lines += ["", "| param | type | default | description |",
                          "|---|---|---|---|", *rows]
            lines.append("")
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "w") as f:
        f.write("\n".join(lines))
    print(f"wrote {OUT}: {len(exports)} classes, "
          f"{sum(len(v) for v in sections.values())} entries")


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--flags", action="store_true",
                    help="regenerate only the env-flag tables")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 if any flag table — or the README's "
                         "measured-performance table vs the newest "
                         "BENCH_r*.json capture — is stale (CI mode)")
    args = ap.parse_args(argv)
    if args.check:
        flags_ok = gen_flag_tables(check=True)
        readme_ok = check_readme_bench()
        return 0 if (flags_ok and readme_ok) else 1
    gen_flag_tables()
    if not args.flags:
        gen_operators()
    return 0


if __name__ == "__main__":
    sys.exit(main())
