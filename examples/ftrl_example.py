"""FTRL online-learning example — mirror of the reference FTRLExample
(examples/src/main/java/com/alibaba/alink/FTRLExample.java:18-113):
batch feature pipeline (StandardScaler + FeatureHasher) -> batch LR
warm start -> FTRL online train (model-snapshot stream) -> FTRL predict
with hot model reload -> windowed + cumulative streaming eval.
Synthetic Criteo/avazu-style CTR data (no egress).

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
     python examples/ftrl_example.py
"""

try:
    import _bootstrap  # noqa: F401  (repo root onto sys.path)
except ImportError:  # running as a module: python -m examples.foo
    from . import _bootstrap  # noqa: F401

import json

import numpy as np

from alink_tpu.common.mlenv import use_local_env
from alink_tpu.operator.base import StreamOperator
from alink_tpu.operator.batch.source import MemSourceBatchOp
from alink_tpu.operator.batch.classification.linear import (
    LogisticRegressionTrainBatchOp)
from alink_tpu.operator.stream.evaluation import EvalBinaryClassStreamOp
from alink_tpu.operator.stream.onlinelearning.ftrl import (
    FtrlPredictStreamOp, FtrlTrainStreamOp)
from alink_tpu.operator.stream.sink.sinks import CollectSinkStreamOp
from alink_tpu.operator.stream.source.sources import MemSourceStreamOp
from alink_tpu.pipeline import Pipeline
from alink_tpu.pipeline.feature import FeatureHasher, StandardScaler


def ctr_rows(n, seed):
    """(site, device, c1 DOUBLE, c2 DOUBLE, click)"""
    rng = np.random.RandomState(seed)
    sites = [f"site_{i}" for i in range(20)]
    devs = [f"dev_{i}" for i in range(8)]
    site_w = rng.randn(20)
    dev_w = rng.randn(8)
    rows = []
    for _ in range(n):
        s = rng.randint(20)
        d = rng.randint(8)
        c1, c2 = rng.randn(), rng.randn()
        logit = site_w[s] + dev_w[d] + 0.8 * c1 - 0.5 * c2
        y = int(rng.rand() < 1.0 / (1.0 + np.exp(-logit)))
        rows.append((sites[s], devs[d], c1, c2, y))
    return rows


SCHEMA = "site STRING, device STRING, c1 DOUBLE, c2 DOUBLE, click LONG"


def main():
    use_local_env()   # all available devices (8 on the CPU test mesh)
    batch_data = MemSourceBatchOp(ctr_rows(1500, 1), SCHEMA)

    # 1. feature engineering pipeline (fit on the batch data)
    feature_pipeline = Pipeline(
        StandardScaler(selected_cols=["c1", "c2"]),
        FeatureHasher(selected_cols=["site", "device", "c1", "c2"],
                      categorical_cols=["site", "device"],
                      output_col="vec", num_features=512))
    feature_model = feature_pipeline.fit(batch_data)

    # 2. batch LR warm start
    init_model = LogisticRegressionTrainBatchOp(
        vector_col="vec", label_col="click",
        max_iter=15).link_from(feature_model.transform(batch_data))

    # 3. FTRL online train on the feature-transformed stream
    stream_data = MemSourceStreamOp(ctr_rows(4000, 2), SCHEMA, batch_size=250)
    feat_stream = feature_model.transform_stream(stream_data)
    model_stream = FtrlTrainStreamOp(init_model, vector_col="vec",
                                     label_col="click", alpha=0.1, beta=1.0,
                                     l1=1e-4, l2=1e-4,
                                     time_interval=1.0).link_from(feat_stream)

    # 4. hot-reload predict on a second stream
    eval_data = MemSourceStreamOp(ctr_rows(2000, 3), SCHEMA, batch_size=250)
    pred_stream = FtrlPredictStreamOp(init_model, vector_col="vec",
                                      prediction_col="pred",
                                      prediction_detail_col="details",
                                      reserved_cols=["click"]).link_from(
        model_stream, feature_model.transform_stream(eval_data))

    # 5. windowed + cumulative streaming eval
    ev = EvalBinaryClassStreamOp(label_col="click",
                                 prediction_detail_col="details",
                                 time_interval=2.0).link_from(pred_stream)
    sink = CollectSinkStreamOp().link_from(ev)
    StreamOperator.execute()
    out = sink.get_and_remove_values()
    for row in out.to_rows():
        stat, metrics = row[0], json.loads(row[1])
        if "AUC" in metrics:
            print(f"{stat:>6}: AUC={metrics['AUC']:.4f} "
                  f"Accuracy={metrics.get('Accuracy', 0):.4f} "
                  f"n={metrics.get('TotalSamples')}")


if __name__ == "__main__":
    main()
