"""Stream operator layer (reference operator/stream/ — 14 categories).

The DataStream substrate is the timed micro-batch runtime in ``core.py``;
see ``alink_tpu.operator.base.StreamOperator``.
"""

from .core import BaseStreamTransformOp, FnStreamOp
from .dataproc import (AppendIdStreamOp, FirstNStreamOp,
                       NumericalTypeCastStreamOp, SampleStreamOp,
                       ShuffleStreamOp, SplitStreamOp)
from .evaluation import (EvalBinaryClassStreamOp, EvalMultiClassStreamOp,
                         EvalRegressionStreamOp)
from .nlp import (NGramStreamOp, RegexTokenizerStreamOp, SegmentStreamOp,
                  StopWordsRemoverStreamOp, TokenizerStreamOp)
from .onlinelearning import FtrlPredictStreamOp, FtrlTrainStreamOp
from .predict_ops import *  # noqa: F401,F403 — the *PredictStreamOp family
from .predict_ops import __all__ as _predict_all
from .sink.sinks import (CollectSinkStreamOp, CsvSinkStreamOp,
                         LibSvmSinkStreamOp, TextSinkStreamOp)
from .source.sources import (CsvSourceStreamOp, LibSvmSourceStreamOp,
                             MemSourceStreamOp, NumSeqSourceStreamOp,
                             RandomTableSourceStreamOp, TableSourceStreamOp,
                             TextSourceStreamOp)
from .sql import (AsStreamOp, FilterStreamOp, SelectStreamOp, UnionAllStreamOp,
                  WhereStreamOp, WindowGroupByStreamOp)
from .utils import MapperStreamOp, ModelMapStreamOp

__all__ = [
    "BaseStreamTransformOp", "FnStreamOp",
    "AppendIdStreamOp", "FirstNStreamOp", "NumericalTypeCastStreamOp",
    "SampleStreamOp", "ShuffleStreamOp", "SplitStreamOp",
    "EvalBinaryClassStreamOp", "EvalMultiClassStreamOp", "EvalRegressionStreamOp",
    "FtrlTrainStreamOp", "FtrlPredictStreamOp",
    "NGramStreamOp", "RegexTokenizerStreamOp", "SegmentStreamOp",
    "StopWordsRemoverStreamOp", "TokenizerStreamOp",
    "CollectSinkStreamOp", "CsvSinkStreamOp", "LibSvmSinkStreamOp",
    "TextSinkStreamOp",
    "CsvSourceStreamOp", "LibSvmSourceStreamOp", "MemSourceStreamOp",
    "NumSeqSourceStreamOp", "RandomTableSourceStreamOp", "TableSourceStreamOp",
    "TextSourceStreamOp",
    "AsStreamOp", "FilterStreamOp", "SelectStreamOp", "UnionAllStreamOp",
    "WhereStreamOp", "WindowGroupByStreamOp",
    "MapperStreamOp", "ModelMapStreamOp",
] + list(_predict_all)
