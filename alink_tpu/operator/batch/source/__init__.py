from .sources import (MemSourceBatchOp, CsvSourceBatchOp, LibSvmSourceBatchOp,
                      TextSourceBatchOp, NumSeqSourceBatchOp, RandomTableSourceBatchOp)
from ...base import TableSourceBatchOp

__all__ = ["MemSourceBatchOp", "CsvSourceBatchOp", "LibSvmSourceBatchOp",
           "TextSourceBatchOp", "NumSeqSourceBatchOp", "RandomTableSourceBatchOp",
           "TableSourceBatchOp"]
