"""Stream sink operators.

Re-design of operator/stream/sink/ (CsvSinkStreamOp, LibSvmSinkStreamOp,
TextSinkStreamOp) plus CollectSinkStreamOp — the in-memory sink the tests
drain into (reference tests use CollectSinkStreamOp / StreamOperator
print + execute).
"""

from __future__ import annotations

from typing import List, Optional

from ....common.mtable import MTable
from ....common.params import ParamInfo, Params
from ....io.csv import format_csv_rows, format_libsvm_rows
from ...base import StreamOperator


class BaseSinkStreamOp(StreamOperator):
    def _consume(self, mt: MTable):  # pragma: no cover - interface
        raise NotImplementedError

    def link_from(self, in_op: StreamOperator) -> "BaseSinkStreamOp":
        try:
            self._schema = in_op.get_schema()
        except RuntimeError:
            self._schema = None  # upstream schema data-dependent

        self._stream_fn = in_op.timed_batches
        self._sinks.append(self._consume)
        return self._register()


class CollectSinkStreamOp(BaseSinkStreamOp):
    """Collect every micro-batch into one host table."""

    def __init__(self, params: Optional[Params] = None, **kwargs):
        super().__init__(params, **kwargs)
        self._batches: List[MTable] = []

    def _consume(self, mt: MTable):
        self._batches.append(mt)

    def get_and_remove_values(self) -> Optional[MTable]:
        out = None
        for mt in self._batches:
            out = mt if out is None else out.concat_rows(mt)
        self._batches = []
        return out


class CheckpointSinkStreamOp(BaseSinkStreamOp):
    """Durable generic sink: micro-batches land as atomic, checksummed
    checkpoints with bounded retention (common/checkpoint.py).

    Point it at any stream — most usefully a model-snapshot stream (the
    FTRL trainer's output), which makes the newest complete model survive
    a process kill: a restarted job reloads it with
    ``CheckpointSinkStreamOp.load_latest(dir)`` and hands it to the
    predictor as the warm start. All-numeric tables persist as ``.npy``
    column payloads; tables with string/vector columns persist via the
    MTable JSON row codec (exact round trip either way).
    """

    def __init__(self, checkpoint_dir: str, every: int = 1,
                 keep_last: int = 5, params: Optional[Params] = None,
                 **kwargs):
        super().__init__(params, **kwargs)
        if int(every) < 1 or int(keep_last) < 1:
            raise ValueError("every and keep_last must be >= 1")
        self.checkpoint_dir = checkpoint_dir
        self.every = int(every)
        self.keep_last = int(keep_last)
        self._seen = 0

    def link_from(self, in_op):
        from ....common.checkpoint import checkpoint_tag, latest_checkpoint
        # continue the tag sequence across restarts: starting over at 1
        # would make tag-ordered retention delete every NEW snapshot
        # while load_latest kept serving the previous run's data
        latest = latest_checkpoint(self.checkpoint_dir, validate=False)
        self._seen = checkpoint_tag(latest) if latest is not None else 0
        return super().link_from(in_op)

    def _consume(self, mt: MTable):
        from ....common.checkpoint import save_checkpoint
        self._seen += 1
        if (self._seen - 1) % self.every:
            return
        cols = {name: mt.col(name) for name in mt.col_names}
        if all(c.dtype != object and c.dtype.kind in "biuf"
               for c in cols.values()):
            payload = cols
            meta = {"mode": "arrays", "schema": mt.schema.to_spec(),
                    "batch_index": self._seen}
        else:
            payload = {}
            meta = {"mode": "json_rows", "table": mt.to_json_rows(),
                    "batch_index": self._seen}
        save_checkpoint(self.checkpoint_dir, self._seen, payload, meta=meta,
                        scope="stream_sink", keep_last=self.keep_last)

    @staticmethod
    def load_latest(checkpoint_dir: str) -> Optional[MTable]:
        """Newest valid persisted batch, or None (corrupted snapshots are
        skipped — the crash-during-write recovery path)."""
        from ....common.checkpoint import latest_checkpoint, load_checkpoint
        from ....common.types import TableSchema
        path = latest_checkpoint(checkpoint_dir)
        if path is None:
            return None
        # already checksummed by latest_checkpoint
        payload, meta = load_checkpoint(path, scope="stream_sink",
                                        validate=False)
        if meta.get("mode") == "arrays":
            schema = TableSchema.parse(meta["schema"])
            return MTable({n: payload[n] for n in schema.names}, schema)
        return MTable.from_json_rows(meta["table"])


class CsvSinkStreamOp(BaseSinkStreamOp):
    """reference: stream/sink/CsvSinkStreamOp (append per micro-batch)."""

    def __init__(self, file_path: str, field_delimiter: str = ",",
                 params=None, **kwargs):
        super().__init__(params, **kwargs)
        self.file_path = file_path
        self.field_delimiter = field_delimiter
        self._started = False

    def link_from(self, in_op):
        self._started = False
        return super().link_from(in_op)

    def _consume(self, mt: MTable):
        mode = "a" if self._started else "w"
        with open(self.file_path, mode) as f:
            f.write(format_csv_rows(mt, self.field_delimiter))
        self._started = True


class LibSvmSinkStreamOp(BaseSinkStreamOp):
    """reference: stream/sink/LibSvmSinkStreamOp."""

    def __init__(self, file_path: str, label_col: str, vector_col: str,
                 params=None, **kwargs):
        super().__init__(params, **kwargs)
        self.file_path = file_path
        self.label_col = label_col
        self.vector_col = vector_col
        self._started = False

    def link_from(self, in_op):
        self._started = False
        return super().link_from(in_op)

    def _consume(self, mt: MTable):
        mode = "a" if self._started else "w"
        with open(self.file_path, mode) as f:
            f.write(format_libsvm_rows(mt, self.label_col, self.vector_col))
        self._started = True


class TextSinkStreamOp(BaseSinkStreamOp):
    """reference: stream/sink/TextSinkStreamOp (single string column)."""

    def __init__(self, file_path: str, params=None, **kwargs):
        super().__init__(params, **kwargs)
        self.file_path = file_path
        self._started = False

    def link_from(self, in_op):
        self._started = False
        return super().link_from(in_op)

    def _consume(self, mt: MTable):
        mode = "a" if self._started else "w"
        col = mt.col_names[0]
        with open(self.file_path, mode) as f:
            for v in mt.col(col):
                f.write(f"{v}\n")
        self._started = True


from ....io.db import HasDB as _HasDB


class DBSinkStreamOp(_HasDB, BaseSinkStreamOp):
    """Append every micro-batch into a DB table
    (reference: stream/sink/DBSinkStreamOp.java)."""
    OUTPUT_TABLE_NAME = ParamInfo("output_table_name", str, optional=False)

    def _consume(self, mt: MTable):
        self._db().write_table(self.params._m["output_table_name"], mt,
                               append=True)


class JdbcRetractSinkStreamOp(DBSinkStreamOp):
    """Upsert sink: rows replace earlier rows with the same key
    (reference: stream/sink/JdbcRetractSinkStreamOp.java — there Flink
    retract-stream semantics; here delete-then-insert per micro-batch)."""
    KEY_COLS = ParamInfo("key_cols", list, "primary-key columns",
                         optional=False)

    def _consume(self, mt: MTable):
        db = self._db()
        table = self.params._m["output_table_name"]
        keys = self.params._m["key_cols"]
        if not db.has_table(table):
            db.create_table(table, mt.schema)
        kidx = [mt.col_names.index(k) for k in keys]
        # last write wins within a micro-batch too (upsert contract)
        last = {}
        for r in mt.to_rows():
            last[tuple(_pyv(r[i]) for i in kidx)] = r
        where = " AND ".join(f"{k} = ?" for k in keys)
        non_null = [kv for kv in last if all(v is not None for v in kv)]
        if non_null:
            db.executemany(f"DELETE FROM {table} WHERE {where}", non_null)
        for kv in last:
            if any(v is None for v in kv):  # NULL never matches '= ?'
                clause = " AND ".join(
                    f"{k} IS NULL" if v is None else f"{k} = ?"
                    for k, v in zip(keys, kv))
                db.execute(f"DELETE FROM {table} WHERE {clause}",
                           [v for v in kv if v is not None])
        db.write_table(table, MTable(list(last.values()), mt.schema),
                       append=True)


def _pyv(v):
    return v.item() if hasattr(v, "item") else v


from ....io.db import HasMySqlDB as _HasMySqlDB


class MySqlSinkStreamOp(_HasMySqlDB, DBSinkStreamOp):
    """reference: stream/sink/MySqlSinkStreamOp.java"""
