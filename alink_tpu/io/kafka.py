"""Kafka connector (gated).

Re-design of connectors/connector-kafka* (Kafka*SourceStreamOp /
Kafka*SinkStreamOp + builders). No Kafka client library ships in this
image, so the ops bind to a client through an injectable interface:
pass ``consumer=``/``producer=`` objects (anything iterable / with a
``send``-like callable — the in-memory ``FakeKafka`` below implements
both), or install ``kafka-python``/``confluent-kafka`` and the ops pick
it up. Mirrors the reference's connector tests, which are builder/config
tests without a live broker (connectors/connector-kafka/src/test).
"""

from __future__ import annotations

import json
from collections import defaultdict
from typing import Dict, Iterable, List, Optional

from ..common.mtable import MTable
from ..common.params import ParamInfo
from ..operator.batch.dataproc.format import _cast
from ..common.types import AlinkTypes, TableSchema
from ..operator.base import StreamOperator
from ..operator.stream.sink.sinks import BaseSinkStreamOp


class FakeKafka:
    """In-memory topic log usable as both consumer and producer side —
    the test double the connector tests run against."""

    def __init__(self):
        self.topics: Dict[str, List[bytes]] = defaultdict(list)

    def send(self, topic: str, value: bytes):
        self.topics[topic].append(
            value if isinstance(value, bytes) else str(value).encode())

    def poll(self, topic: str) -> List[bytes]:
        msgs = self.topics[topic]
        self.topics[topic] = []
        return msgs


class _KafkaPythonClient:
    """Adapter giving kafka-python the poll/send surface the ops use."""

    def __init__(self, bootstrap_servers: str):
        import kafka
        self._kafka = kafka
        self.bootstrap = bootstrap_servers
        self._consumers: Dict[str, object] = {}
        self._producer = None

    def poll(self, topic: str) -> List[bytes]:
        c = self._consumers.get(topic)
        if c is None:
            c = self._kafka.KafkaConsumer(
                topic, bootstrap_servers=self.bootstrap,
                consumer_timeout_ms=1000, auto_offset_reset="earliest")
            self._consumers[topic] = c
        batch = c.poll(timeout_ms=1000)
        return [m.value for msgs in batch.values() for m in msgs]

    def send(self, topic: str, value: bytes):
        if self._producer is None:
            self._producer = self._kafka.KafkaProducer(
                bootstrap_servers=self.bootstrap)
        self._producer.send(topic, value)


def _default_client(bootstrap_servers: Optional[str]):
    try:
        import kafka  # noqa: F401  (kafka-python)
    except ImportError:
        raise ImportError(
            "no Kafka client installed and no consumer/producer injected; "
            "install kafka-python or pass a client object (e.g. FakeKafka)")
    if not bootstrap_servers:
        raise ValueError("bootstrap_servers is required when using the "
                         "installed kafka-python client")
    return _KafkaPythonClient(bootstrap_servers)


class KafkaSourceStreamOp(StreamOperator):
    """reference: Kafka011SourceStreamOp / KafkaSourceStreamOp — reads a
    topic as micro-batches; messages are json or csv per ``format``."""
    TOPIC = ParamInfo("topic", str, "topic to read", optional=False)
    FORMAT = ParamInfo("format", str, "json | csv", default="json")
    SCHEMA_STR = ParamInfo("schema_str", str, "output schema", optional=False)
    FIELD_DELIMITER = ParamInfo("field_delimiter", str, default=",")
    BOOTSTRAP_SERVERS = ParamInfo("bootstrap_servers", str,
                                  "broker list for the installed client")
    MAX_BATCHES = ParamInfo("max_batches", int,
                            "poll rounds before the bounded drain ends",
                            default=1)

    def __init__(self, params=None, consumer=None, **kwargs):
        super().__init__(params, **kwargs)
        self.consumer = (consumer if consumer is not None else
                         _default_client(self.params._m.get("bootstrap_servers")))
        self._schema = TableSchema.parse(self.get_schema_str())
        self._stream_fn = self._gen

    def _gen(self):
        schema = self.get_schema()
        topic = self.get_topic()
        fmt = self.get_format().lower()
        delim = self.get_field_delimiter()
        for b in range(int(self.get_max_batches())):
            msgs = self.consumer.poll(topic)
            rows = []
            for m in msgs:
                s = m.decode() if isinstance(m, bytes) else str(m)
                if fmt == "json":
                    d = json.loads(s)
                    rows.append(tuple(d.get(n) for n in schema.names))
                else:
                    parts = s.split(delim)
                    rows.append(tuple(
                        _cast(parts[i], ty) if i < len(parts) else None
                        for i, ty in enumerate(schema.types)))
            yield float(b), MTable(rows, schema)


class KafkaSinkStreamOp(BaseSinkStreamOp):
    """reference: Kafka011SinkStreamOp / KafkaSinkStreamOp."""
    TOPIC = ParamInfo("topic", str, "topic to write", optional=False)
    FORMAT = ParamInfo("format", str, "json | csv", default="json")
    FIELD_DELIMITER = ParamInfo("field_delimiter", str, default=",")
    BOOTSTRAP_SERVERS = KafkaSourceStreamOp.BOOTSTRAP_SERVERS

    def __init__(self, params=None, producer=None, **kwargs):
        super().__init__(params, **kwargs)
        self.producer = (producer if producer is not None else
                         _default_client(self.params._m.get("bootstrap_servers")))

    def _consume(self, mt: MTable):
        topic = self.get_topic()
        fmt = self.get_format().lower()
        delim = self.get_field_delimiter()
        for r in mt.to_rows():
            if fmt == "json":
                msg = json.dumps(dict(zip(mt.col_names, [_j(v) for v in r])))
            else:
                msg = delim.join("" if v is None else str(v) for v in r)
            self.producer.send(topic, msg.encode())


def _j(v):
    import numpy as np
    return v.item() if isinstance(v, np.generic) else v


# naming parity with the reference's per-kafka-version modules
Kafka011SourceStreamOp = KafkaSourceStreamOp
Kafka011SinkStreamOp = KafkaSinkStreamOp
Kafka010SourceStreamOp = KafkaSourceStreamOp
Kafka010SinkStreamOp = KafkaSinkStreamOp
