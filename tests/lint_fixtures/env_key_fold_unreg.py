"""ENV-KEY-FOLD structural backstop: an lru_cache'd program factory
nobody registered as a factory root. Reading a key-affecting flag from
it must be flagged until the factory is registered with its key
dimensions; a key-neutral read stays silent."""
import functools
import os


@functools.lru_cache(maxsize=8)
def _rogue_step_factory(mesh):
    flip = os.environ.get("ALINK_TPU_GOOD")   # folds into program_cache
    return (mesh, flip)


@functools.lru_cache(maxsize=1)
def _benign_cached_loader():
    return os.environ.get("ALINK_TPU_NEUTRAL")   # key-neutral: fine
