#!/usr/bin/env python
"""Diff two BENCH_*.json dumps — the bench regression gate.

Usage:
    python tools/bench_compare.py OLD.json NEW.json [--threshold PCT]
    python tools/bench_compare.py                   # newest pair in repo root
    python tools/bench_compare.py --dir DIR [--threshold PCT] [--json]

Accepted file shapes (both appear in this repo):
  * the driver dump ``{"n", "cmd", "rc", "tail", "parsed": {...}}``
    (``BENCH_r*.json``) — the bench's final combined line lives under
    ``parsed``;
  * a bare final-line object carrying ``workloads_sps_vs`` directly.

``workloads_sps_vs`` maps workload name -> ``[samples/sec/chip,
vs_baseline, pct_chip_peak_flops]``; the diff is on samples/sec/chip.

``--threshold PCT`` turns the report into a gate: exit 2 when any
workload present in both dumps regressed by more than PCT percent
(workloads appearing or disappearing are reported but never gated).
Without it the tool only reports (exit 0). ``--json`` emits the machine
shape instead of the table.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_workloads(path: str) -> Tuple[Dict[str, float], str, Optional[str]]:
    """``({workload: samples_per_sec_per_chip}, mode, baseline_fp)`` from
    either file shape; ``mode`` is ``"quick"`` for ``bench.py --quick``
    dumps, else ``"full"`` (pre-quick dumps carry no marker and are
    full). ``baseline_fp`` is the capture's rig/baseline fingerprint
    (None on pre-r06 dumps)."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "parsed" in doc \
            and isinstance(doc["parsed"], dict):
        doc = doc["parsed"]
    wl = doc.get("workloads_sps_vs") if isinstance(doc, dict) else None
    if not isinstance(wl, dict) or not wl:
        raise ValueError(f"{path}: no workloads_sps_vs map found "
                         f"(not a bench dump?)")
    out = {}
    for name, row in wl.items():
        sps = row[0] if isinstance(row, (list, tuple)) else row
        out[str(name)] = float(sps)
    fp = doc.get("baseline_fp")
    if fp is None and isinstance(doc.get("rig"), dict):
        fp = doc["rig"].get("baseline_fp")
    return out, str(doc.get("mode", "full")), \
        (str(fp) if fp is not None else None)


def newest_pair(directory: str) -> Tuple[str, str]:
    """The two most recent ``BENCH_*.json`` dumps (by mtime, name as the
    tie-break) — returned (older, newer). Excludes ``BENCH_full.json``
    (per-run detail, not a comparable dump) and ``BENCH_quick*.json``
    (smoke fixtures — auto-pairing one against a full capture would gate
    on fixture-size deltas; quick dumps compare via explicit paths)."""
    cands = [p for p in glob.glob(os.path.join(directory, "BENCH_*.json"))
             if os.path.basename(p) != "BENCH_full.json"
             and not os.path.basename(p).startswith("BENCH_quick")]
    if len(cands) < 2:
        raise ValueError(f"{directory}: need at least two BENCH_*.json "
                         f"dumps, found {len(cands)}")
    cands.sort(key=lambda p: (os.path.getmtime(p), p))
    return cands[-2], cands[-1]


def compare(old: Dict[str, float], new: Dict[str, float]) -> List[dict]:
    """One record per workload: old/new samples-per-sec and delta_pct
    (None when the workload exists on only one side, or when the old
    rate is 0 — a failed/zeroed run has no percentage baseline)."""
    rows = []
    for name in sorted(set(old) | set(new)):
        o, n = old.get(name), new.get(name)
        delta = (100.0 * (n - o) / o) \
            if o is not None and n is not None and o != 0 else None
        rows.append({"workload": name, "old": o, "new": n,
                     "delta_pct": delta})
    return rows


def regressions(rows: List[dict], threshold_pct: float) -> List[dict]:
    return [r for r in rows
            if r["delta_pct"] is not None
            and r["delta_pct"] < -abs(threshold_pct)]


def _fmt_sps(v: Optional[float]) -> str:
    return f"{v:,.1f}" if v is not None else "-"


def _display_name(name: str) -> str:
    """Rows whose rate is not samples/sec get their unit called out.
    ONE implementation serves both gate tools: this delegates to
    tools/bench_history.py, so the compare table and the history table
    can never label the same row differently."""
    if ROOT not in sys.path:
        sys.path.insert(0, ROOT)   # script invocation from elsewhere
    from tools.bench_history import _display_name as _impl
    return _impl(name)


def render(rows: List[dict], old_path: str, new_path: str) -> str:
    rows = [{**r, "workload": _display_name(r["workload"])} for r in rows]
    out = [f"bench compare: {os.path.basename(old_path)} -> "
           f"{os.path.basename(new_path)}  (samples/sec/chip)"]
    headers = ["workload", "old", "new", "delta"]
    widths = [max(len(headers[0]), *(len(r["workload"]) for r in rows)),
              max(len(headers[1]), *(len(_fmt_sps(r["old"])) for r in rows)),
              max(len(headers[2]), *(len(_fmt_sps(r["new"])) for r in rows)),
              8]
    def line(cells, pads="lrrr"):
        return "  " + "  ".join(
            str(c).rjust(w) if p == "r" else str(c).ljust(w)
            for c, w, p in zip(cells, widths, pads)).rstrip()
    out.append(line(headers))
    out.append("  " + "  ".join("-" * w for w in widths))
    for r in rows:
        if r["delta_pct"] is not None:
            d = f"{r['delta_pct']:+.1f}%"
        elif r["old"] is None:
            d = "new"
        elif r["new"] is None:
            d = "gone"
        else:
            d = "n/a"          # present in both, old rate 0: no baseline
        out.append(line([r["workload"], _fmt_sps(r["old"]),
                         _fmt_sps(r["new"]), d]))
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_compare.py", description=__doc__.splitlines()[0])
    ap.add_argument("old", nargs="?", help="older BENCH_*.json")
    ap.add_argument("new", nargs="?", help="newer BENCH_*.json")
    ap.add_argument("--dir", default=ROOT,
                    help="directory to find the newest pair in when no "
                         "files are given (default: repo root)")
    ap.add_argument("--threshold", type=float, metavar="PCT",
                    help="exit 2 when any shared workload regressed by "
                         "more than PCT percent")
    ap.add_argument("--json", action="store_true",
                    help="emit the comparison as JSON")
    ap.add_argument("--baseline-provenance", action="store_true",
                    help="refuse (exit 3) to compare dumps whose "
                         "baseline/rig fingerprints differ — a "
                         "re-measured or cross-rig baseline can then "
                         "never silently inflate vs_baseline; dumps "
                         "without a fingerprint (pre-r06) warn instead")
    args = ap.parse_args(argv)
    if (args.old is None) != (args.new is None):
        ap.error("give both OLD and NEW, or neither (newest pair)")
    if args.old is None:
        try:
            old_path, new_path = newest_pair(args.dir)
        except ValueError as e:
            print(f"bench_compare.py: {e}", file=sys.stderr)
            return 1
    else:
        old_path, new_path = args.old, args.new
    try:
        old_wl, old_mode, old_fp = load_workloads(old_path)
        new_wl, new_mode, new_fp = load_workloads(new_path)
        rows = compare(old_wl, new_wl)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"bench_compare.py: {e}", file=sys.stderr)
        return 1
    if args.baseline_provenance:
        if old_fp is not None and new_fp is not None:
            if old_fp != new_fp:
                print(f"bench_compare.py: REFUSING to compare — baseline "
                      f"fingerprints differ ({old_fp} vs {new_fp}): the "
                      f"dumps were captured against different rigs or a "
                      f"re-pinned baseline, so vs_baseline deltas would "
                      f"be provenance artifacts, not code changes "
                      f"(re-run both captures on one rig, or drop "
                      f"--baseline-provenance to diff anyway)",
                      file=sys.stderr)
                return 3
        else:
            missing = [p for p, fp in ((old_path, old_fp),
                                       (new_path, new_fp)) if fp is None]
            print(f"WARNING: --baseline-provenance: no baseline "
                  f"fingerprint recorded in "
                  f"{', '.join(os.path.basename(m) for m in missing)} "
                  f"(pre-r06 capture?) — provenance not verifiable",
                  file=sys.stderr)
    if old_mode != new_mode:
        # quick fixtures are a fraction of the full suite's — a cross-
        # mode delta is a fixture-size artifact, not a regression. Warn
        # loudly but keep reporting (the workload sets barely overlap
        # anyway when one side errored out).
        print(f"WARNING: comparing a {old_mode!r} dump against a "
              f"{new_mode!r} dump — deltas reflect fixture sizes, not "
              f"code changes (use two --quick runs for the gate)",
              file=sys.stderr)
    bad = regressions(rows, args.threshold) \
        if args.threshold is not None else []
    if args.json:
        json.dump({"old": old_path, "new": new_path,
                   "threshold_pct": args.threshold,
                   "workloads": rows,
                   "regressions": [r["workload"] for r in bad]},
                  sys.stdout, indent=1)
        sys.stdout.write("\n")
    else:
        print(render(rows, old_path, new_path))
        if args.threshold is not None:
            if bad:
                print(f"REGRESSION: {len(bad)} workload(s) slower than "
                      f"-{abs(args.threshold):g}%: "
                      + ", ".join(f"{r['workload']} "
                                  f"({r['delta_pct']:+.1f}%)"
                                  for r in bad))
            else:
                print(f"ok: no workload regressed more than "
                      f"{abs(args.threshold):g}%")
    return 2 if bad else 0


if __name__ == "__main__":
    raise SystemExit(main())
