"""Pipeline API — Estimator / Transformer / Model / Pipeline.

Re-design of pipeline/ (Pipeline.java:113 ``fit``, Trainer.java:45-104
reflective trainer->model creation, PipelineModel.java:128-149
transform/save/load, LocalPredictor.java, MapModel.java:38-60) and the
vendored Flink-ML core interfaces (java/org/apache/flink/ml/api/core/).
"""

from __future__ import annotations

import importlib
import json
import os
from typing import Any, List, Optional, Sequence, Tuple, Type

from ..common.mtable import MTable
from ..common.params import Params, WithParams
from ..mapper.base import ModelMapper
from ..operator.base import BatchOperator, TableSourceBatchOp


def caller_module(depth: int = 2) -> str:
    """__name__ of the module ``depth`` frames up.

    Class factories (_trainer/_wrap) mint classes on behalf of their caller;
    the minted class's ``__module__`` must name the caller's module or
    repr/pickle/docs attribution points at the factory instead.
    """
    import sys
    return sys._getframe(depth).f_globals.get("__name__", __name__)


class PipelineStage(WithParams):
    def clone(self):
        return type(self)(self.params.clone())


class Transformer(PipelineStage):
    def transform(self, in_op) -> BatchOperator:
        raise NotImplementedError


class Estimator(PipelineStage):
    def fit(self, in_op) -> "Model":
        raise NotImplementedError


class Model(Transformer):
    """A transformer backed by a model table."""

    def __init__(self, params: Optional[Params] = None, **kwargs):
        super().__init__(params, **kwargs)
        self.model_data: Optional[MTable] = None

    def set_model_data(self, table_or_op) -> "Model":
        self.model_data = (table_or_op.get_output_table()
                           if isinstance(table_or_op, BatchOperator) else table_or_op)
        return self

    def get_model_data(self) -> MTable:
        if self.model_data is None:
            raise RuntimeError(f"{type(self).__name__} has no model data")
        return self.model_data


class MapModel(Model):
    """Model applied through a ModelMapper (reference pipeline/MapModel.java)."""

    MAPPER_CLS: Optional[Type[ModelMapper]] = None

    def transform(self, in_op) -> BatchOperator:
        in_op = _as_op(in_op)
        from ..operator.batch.utils.model_map import ModelMapBatchOp
        op = ModelMapBatchOp(self.params.clone(), mapper_cls=self.MAPPER_CLS)
        return op.link_from(TableSourceBatchOp(self.get_model_data()), in_op)

    def get_local_predictor(self) -> "LocalPredictor":
        return LocalPredictor(self.MAPPER_CLS, self.get_model_data(), self.params)


class Trainer(Estimator):
    """Estimator whose fit() runs a train batch op and wraps the model
    (reference pipeline/Trainer.java:45-48,89-104 ``createModel``)."""

    TRAIN_OP_CLS: Optional[Type[BatchOperator]] = None
    MODEL_CLS: Optional[Type[Model]] = None

    def fit(self, in_op) -> Model:
        in_op = _as_op(in_op)
        train_op = self.TRAIN_OP_CLS(self.params.clone())
        train_op.link_from(in_op)
        self._last_train_op = train_op
        m = self.params._m
        if "__lazy_train_info" in m:
            if train_op.get_side_output_count() > 0:
                train_op.lazy_print_train_info(m["__lazy_train_info"])
            else:
                print(f"[alink_tpu] {type(train_op).__name__} emits no "
                      "train info; lazy_print_train_info skipped")
        if "__lazy_model_info" in m:
            train_op.lazy_print_model_info(m["__lazy_model_info"])
        model = self.MODEL_CLS(self.params.clone())
        model.set_model_data(train_op.get_output_table())
        return model

    # train-info hooks (reference WithTrainInfo.enableLazyPrintTrainInfo /
    # WithModelInfoBatchOp.enableLazyPrintModelInfo, fired from Trainer.fit,
    # pipeline/Trainer.java:50-66)
    def enable_lazy_print_train_info(self, title=None) -> "Trainer":
        # stored in params so the enablement survives PipelineStage.clone()
        # (meta-estimators like OneVsRest clone their sub-stages)
        self.params._m["__lazy_train_info"] = title
        return self

    def enable_lazy_print_model_info(self, title=None) -> "Trainer":
        self.params._m["__lazy_model_info"] = title
        return self

    def get_train_info(self) -> MTable:
        if not getattr(self, "_last_train_op", None):
            raise RuntimeError("fit() first")
        return self._last_train_op.get_side_output(0).get_output_table()


class Pipeline(Estimator):
    """Ordered stages; fit() trains estimators and chains transforms
    (reference pipeline/Pipeline.java:113)."""

    def __init__(self, *stages: PipelineStage, params: Optional[Params] = None):
        super().__init__(params)
        self.stages: List[PipelineStage] = list(stages)

    def add(self, stage: PipelineStage) -> "Pipeline":
        self.stages.append(stage)
        return self

    def size(self) -> int:
        return len(self.stages)

    def get(self, i: int) -> PipelineStage:
        return self.stages[i]

    def fit(self, in_op) -> "PipelineModel":
        in_op = _as_op(in_op)
        fitted: List[Transformer] = []
        cur = in_op
        for stage in self.stages:
            if isinstance(stage, Estimator):
                model = stage.fit(cur)
                fitted.append(model)
                cur = model.transform(cur)
            elif isinstance(stage, Transformer):
                fitted.append(stage)
                cur = stage.transform(cur)
            else:
                raise TypeError(f"stage {stage!r} is neither Estimator nor Transformer")
        return PipelineModel(*fitted)

    def fit_and_transform(self, in_op) -> Tuple["PipelineModel", BatchOperator]:
        model = self.fit(in_op)
        return model, model.transform(in_op)


class PipelineModel(Model):
    """Chain of fitted transformers (reference pipeline/PipelineModel.java)."""

    def __init__(self, *transformers: Transformer, params: Optional[Params] = None):
        super().__init__(params)
        self.transformers: List[Transformer] = list(transformers)

    def transform(self, in_op) -> BatchOperator:
        from ..operator.base import StreamOperator
        if isinstance(in_op, StreamOperator):
            return self.transform_stream(in_op)
        cur = _as_op(in_op)
        for t in self.transformers:
            cur = t.transform(cur)
        return cur

    def transform_stream(self, in_op):
        """Apply the fitted chain to a stream (reference
        PipelineModel.transform(StreamOperator), pipeline/PipelineModel.java):
        MapModels become ModelMapStreamOps; stateless batch-op transformers
        run per micro-batch."""
        from ..operator.stream.core import BatchApplyStreamOp
        from ..operator.stream.utils import ModelMapStreamOp
        cur = in_op
        for t in self.transformers:
            if isinstance(t, PipelineModel):
                cur = t.transform_stream(cur)
            elif isinstance(t, MapModel):
                op = ModelMapStreamOp(
                    TableSourceBatchOp(t.get_model_data()),
                    params=t.params.clone(), mapper_cls=t.MAPPER_CLS)
                cur = op.link_from(cur)
            elif getattr(t, "OP_CLS", None) is not None:
                cur = BatchApplyStreamOp(params=t.params.clone(),
                                         batch_cls=t.OP_CLS).link_from(cur)
            else:
                raise TypeError(f"{type(t).__name__} has no stream transform")
        return cur

    # -- persistence (reference ModelExporterUtils.java:40-120) -----------
    def save(self, path: str):
        stages = []
        for t in self.transformers:
            entry = {
                "className": f"{type(t).__module__}.{type(t).__qualname__}",
                "params": t.params.to_json(),
            }
            if isinstance(t, Model) and t.model_data is not None:
                entry["modelData"] = t.get_model_data().to_json_rows()
            stages.append(entry)
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"format": "alink_tpu.pipeline.v1", "stages": stages}, f)

    @staticmethod
    def load(path: str) -> "PipelineModel":
        with open(path, "r", encoding="utf-8") as f:
            obj = json.load(f)
        transformers = []
        for entry in obj["stages"]:
            mod_name, _, cls_name = entry["className"].rpartition(".")
            cls = getattr(importlib.import_module(mod_name), cls_name)
            t = cls(Params.from_json(entry["params"]))
            if "modelData" in entry:
                t.set_model_data(MTable.from_json_rows(entry["modelData"]))
            transformers.append(t)
        return PipelineModel(*transformers)

    def get_local_predictor(self) -> "LocalPredictor":
        preds = []
        for t in self.transformers:
            if isinstance(t, MapModel):
                preds.append(t.get_local_predictor())
            elif hasattr(t, "get_local_predictor"):
                preds.append(t.get_local_predictor())
            else:
                preds.append(_TransformerPredictor(t))
        return _ChainPredictor(preds)


class LocalPredictor:
    """Embedded single-row/small-batch serving (reference pipeline/LocalPredictor.java:18-49).

    No session/engine involvement — pure host mapper application.
    """

    def __init__(self, mapper_cls: Type[ModelMapper], model_data: MTable,
                 params: Params, data_schema=None):
        self.mapper_cls = mapper_cls
        self.model_data = model_data
        self.params = params
        self._mapper: Optional[ModelMapper] = None
        self._schema = data_schema

    def _ensure(self, schema):
        if self._mapper is None:
            self._mapper = self.mapper_cls(self.model_data.schema, schema, self.params)
            self._mapper.load_model(self.model_data)
        return self._mapper

    def map(self, row: Tuple, schema=None) -> Tuple:
        from ..common.types import TableSchema
        if schema is None and self._schema is None:
            raise ValueError("LocalPredictor.map needs a data schema on first use")
        schema = schema or self._schema
        self._schema = schema
        return self._ensure(schema).map_row(row)

    def predict(self, table: MTable) -> MTable:
        return self._ensure(table.schema).map_table(table)


class _TransformerPredictor:
    def __init__(self, transformer: Transformer):
        self.t = transformer

    def predict(self, table: MTable) -> MTable:
        return self.t.transform(TableSourceBatchOp(table)).get_output_table()


class _ChainPredictor:
    def __init__(self, predictors):
        self.predictors = predictors

    def predict(self, table: MTable) -> MTable:
        for p in self.predictors:
            table = p.predict(table)
        return table

    def map(self, row: Tuple, schema) -> Tuple:
        t = MTable([row], schema)
        return self.predict(t).row(0)


def _as_op(in_op) -> BatchOperator:
    if isinstance(in_op, BatchOperator):
        return in_op
    if isinstance(in_op, MTable):
        return TableSourceBatchOp(in_op)
    raise TypeError(f"expected BatchOperator or MTable, got {type(in_op)}")
