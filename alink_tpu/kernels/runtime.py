"""The Pallas kernel tier's availability/demotion contract (ISSUE 13).

Every hand-written kernel in ``alink_tpu/kernels/`` rides the SAME
contract PR 6's fused-histogram accumulator proved out (and whose
check/warn machinery used to live inlined in
``operator/common/tree/hist.py`` — deduped here):

* **availability** — a Pallas kernel runs when the backend can execute
  it: a real TPU, or any backend with ``ALINK_TPU_PALLAS_INTERPRET=1``
  (the CPU tier-1 rig's mode: ``pl.pallas_call(interpret=True)``
  executes the kernel with jnp semantics, so parity tests run without
  hardware);
* **demotion, never silence** — when a requested kernel cannot run
  (backend unavailable, Mosaic compile rejection, trace failure), the
  call site demotes to its XLA formulation with ONE RuntimeWarning per
  (kernel, reason) per process. A demoted run is always numerically
  valid — the XLA path is the reference the kernel is parity-pinned
  against — but it must never be *silently* slower;
* **flag-off byte-identity** — with the gating flag off, the call site
  executes its pre-existing statements verbatim: the lowered HLO is
  byte-identical to pre-kernel-tier programs (pinned per flag by the
  tests), so the tier contributes ZERO risk to anyone who does not opt
  in;
* **eager probing** — ``pl.pallas_call`` only *stages* the primitive at
  trace time; a Mosaic failure would otherwise surface at the engine's
  compile, outside any try/except around the traced call.
  :func:`eager_probe` compiles+runs a tiny instance of the kernel in a
  genuinely eager context (a fresh thread — jax trace contexts are
  thread-local) once per shape class, so compile-time failures demote
  exactly like trace-time ones.
"""

from __future__ import annotations

import warnings as _warnings
from typing import Callable, Dict, Tuple

__all__ = ["pallas_interpret", "pallas_available", "interpret_mode",
           "demote_once", "eager_probe", "reset_demotions"]


def pallas_interpret() -> bool:
    """``ALINK_TPU_PALLAS_INTERPRET``: run Pallas kernels in interpret
    mode off-TPU (tests/CI). Key-neutral by registry declaration: only
    the RESOLVED kernel mode reaches any cache key."""
    from ..common.flags import flag_value
    return bool(flag_value("ALINK_TPU_PALLAS_INTERPRET", False))


def pallas_available() -> bool:
    """Can this process execute a Pallas kernel right now? True on a
    TPU backend, or anywhere under ``ALINK_TPU_PALLAS_INTERPRET=1``."""
    import jax
    return jax.default_backend() == "tpu" or pallas_interpret()


def interpret_mode() -> bool:
    """The ``interpret=`` argument every kernel passes to
    ``pl.pallas_call``: interpret everywhere except a real TPU."""
    import jax
    return jax.default_backend() != "tpu"


# one warning per (kernel, reason-class) per process — a drain that
# dispatches 10k micro-batches must not emit 10k demotion warnings,
# but the FIRST demotion of each kernel must always be visible
_DEMOTION_WARNED: Dict[Tuple[str, str], bool] = {}


def demote_once(kernel: str, reason: str, detail: str = "",
                message: str = None, gate=None) -> None:
    """Record one kernel demotion: ONE RuntimeWarning per
    ``(kernel, reason)`` pair per process, plus an
    ``alink_kernel_demotions_total{kernel=,reason=}`` counter on every
    call. ``reason`` must be a small stable enum (it is a metric
    label); request-specific text goes in ``detail``.

    ``message`` overrides the default warning text (the fused-hist
    kernel keeps its historical, test-pinned wording); ``gate`` — a
    mutable ``[bool]`` cell — overrides the module-global once-per-
    (kernel, reason) memo for call sites that own their warn state
    (hist.py's ``_PALLAS_WARNED``, which tests monkeypatch to re-arm).
    """
    from ..common.metrics import get_registry, metrics_enabled
    if metrics_enabled():
        get_registry().inc("alink_kernel_demotions_total", 1,
                           {"kernel": kernel, "reason": reason})
    if gate is not None:
        if gate[0]:
            return
        gate[0] = True
    else:
        key = (kernel, reason)
        if _DEMOTION_WARNED.get(key):
            return
        _DEMOTION_WARNED[key] = True
    _warnings.warn(
        message or (
            f"Pallas kernel {kernel!r} demoted to its XLA path: {reason}"
            f"{' (' + detail + ')' if detail else ''} — results are "
            f"unchanged (the XLA path is the parity reference) but the "
            f"kernel-tier speedup is lost; this warning fires once per "
            f"kernel+reason (recorded as alink_kernel_demotions_total"
            f"{{kernel={kernel!r},reason={reason!r}}})"),
        RuntimeWarning, stacklevel=3)


def reset_demotions() -> None:
    """Test hook: re-arm the once-per-(kernel, reason) warnings."""
    _DEMOTION_WARNED.clear()


def run_eagerly(probe: Callable[[], None]) -> None:
    """Execute ``probe`` in a genuinely eager context.

    jax trace contexts are THREAD-LOCAL: kernel call sites usually sit
    inside a jit/shard_map trace, where even concrete-input
    pallas_calls bind as tracers. A fresh thread is outside every
    trace, so the probe really compiles+runs the kernel here and now
    (the hist.py probe trick, deduped)."""
    import concurrent.futures
    with concurrent.futures.ThreadPoolExecutor(1) as ex:
        ex.submit(probe).result()


# (kernel name, shape-class key) -> bool (compiled+ran ok)
_PROBED: Dict[Tuple, bool] = {}


def eager_probe(kernel: str, key: Tuple, probe: Callable[[], None]) -> bool:
    """EAGERLY compile+run ``probe`` (a tiny instance of the kernel at
    this call's shape class) before the kernel is traced into a
    compiled program. One probe per (kernel, shape class) per process;
    a probe failure demotes via :func:`demote_once` and is memoized so
    the XLA path is chosen at trace time from then on.

    ``pl.pallas_call`` only stages the primitive at trace time — a
    Mosaic failure would otherwise surface at the engine's compile,
    outside any try/except around the traced call. The eager probe is
    what makes the demotion contract real for compile-time failures
    (VMEM overflow, lane-alignment rejections), not just trace-time
    ones."""
    memo_key = (kernel,) + tuple(key)
    ok = _PROBED.get(memo_key)
    if ok is None:
        try:
            run_eagerly(probe)
            ok = True
        except Exception as e:  # pragma: no cover - backend-specific
            ok = False
            demote_once(kernel, "probe-failed",
                        f"shape class {key}: {type(e).__name__}: {e}")
        _PROBED[memo_key] = ok
    return ok
