# -*- coding: utf-8 -*-
"""Open-domain segmentation quality metrics (VERDICT r4 #3).

Scores the bundled segmenter against the hand-authored gold set
(tools/zh_gold_segmentation.txt) and reports:

- ``oov_rate``: share of gold token INSTANCES absent from the dictionary
  (multi-char tokens only; single chars always "exist");
- ``viterbi_share``: share of emitted tokens produced by the HMM
  fallback rather than the dictionary DAG (SegmentDict stats hook);
- ``precision/recall/f1``: standard bakeoff scoring — tokens are
  compared as character SPANS, so a wrong boundary penalizes both sides.

Also reports dictionary size by category via tools/gen_zh_dict.py's
generators, so vocabulary growth is measurable instead of anecdotal.

Run: python tools/segment_eval.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

GOLD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "zh_gold_segmentation.txt")


def load_gold():
    out = []
    with open(GOLD, encoding="utf-8") as f:
        for ln in f:
            ln = ln.strip()
            if not ln or ln.startswith("#"):
                continue
            out.append(ln.split())
    return out


def spans(tokens):
    """Token list -> set of (start, end) character spans."""
    out = set()
    pos = 0
    for t in tokens:
        out.add((pos, pos + len(t)))
        pos += len(t)
    return out


def evaluate(seg=None):
    from alink_tpu.operator.common.nlp.segment import SegmentDict
    seg = seg or SegmentDict()
    gold = load_gold()
    tp = fp = fn = 0
    oov = oov_total = 0
    stats = {}
    for toks in gold:
        sent = "".join(toks)
        for t in toks:
            if len(t) > 1:
                oov_total += 1
                if t not in seg.freq:
                    oov += 1
        pred = seg.cut(sent, stats=stats)
        g, p = spans(toks), spans(pred)
        tp += len(g & p)
        fp += len(p - g)
        fn += len(g - p)
    prec = tp / max(tp + fp, 1)
    rec = tp / max(tp + fn, 1)
    f1 = 2 * prec * rec / max(prec + rec, 1e-12)
    return {
        "sentences": len(gold),
        "oov_rate": round(oov / max(oov_total, 1), 4),
        "viterbi_share": round(stats.get("hmm_tokens", 0)
                               / max(stats.get("tokens", 1), 1), 4),
        "precision": round(prec, 4),
        "recall": round(rec, 4),
        "f1": round(f1, 4),
        "dict_entries": len(seg.freq),
        "general_words": general_inventory(),
    }


#: enumerable closed classes — everything else in the dictionary counts
#: as open-class GENERAL vocabulary (the ISSUE 15 / VERDICT #4
#: inventory). The excluded classes are the unboundedly-enumerable ones
#: (names, numerals, dates, measures, places, reduplications) that
#: could inflate the anchor without lexical content; the counted
#: classes include curated words AND productive single-char-affix
#: derivation over real stems (gen_zh_dict.derived_words — X性/X化/X者,
#: resultative verb compounds), the word-formation stratum a
#: corpus-derived segmenter dictionary carries at scale. The per-class
#: composition is always printed in ``category_stats`` so the anchor's
#: make-up is auditable, and the gold-set F1 certifies the grown
#: inventory does not degrade segmentation.
_CLOSED_CATEGORIES = {"name", "number", "date", "measure", "place",
                      "redup"}


def general_inventory():
    """Open-class general-word count from the generated dictionary's
    ``# category-stats:`` header (``None`` when the header is absent —
    e.g. a user-supplied dictionary)."""
    from alink_tpu.operator.common.nlp import segment as segmod
    try:
        with open(segmod._DICT_PATH, encoding="utf-8") as f:
            for ln in f:
                if not ln.startswith("#"):
                    return None
                if ln.startswith("# category-stats:"):
                    stats = dict(
                        kv.split("=") for kv in ln.split(":", 1)[1].split())
                    return sum(int(v) for k, v in stats.items()
                               if k not in _CLOSED_CATEGORIES)
    except OSError:
        return None
    return None


def main():
    import json
    row = evaluate()
    try:
        import subprocess
        out = subprocess.run(
            [sys.executable, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "gen_zh_dict.py"), "--stats"],
            capture_output=True, text=True, timeout=120)
        for ln in out.stdout.splitlines():
            if ln.startswith("category stats:"):
                row["category_stats"] = ln.split(":", 1)[1].strip()
    except Exception:
        pass
    print(json.dumps(row, ensure_ascii=False))


if __name__ == "__main__":
    main()
