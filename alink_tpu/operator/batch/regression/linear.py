"""Linear regression family batch operators.

Re-design of operator/batch/regression/ LinearRegTrainBatchOp,
RidgeRegTrainBatchOp, LassoRegTrainBatchOp, LinearSvrTrainBatchOp
(+ predict ops) over the shared linear core.
"""

from __future__ import annotations

from ....common.params import ParamInfo, RangeValidator
from ...base import BatchOperator
from ...common.linear.base import LinearModelType
from ..classification.linear import (BaseLinearTrainBatchOp,
                                     LinearModelPredictBatchOp)


class LinearRegTrainBatchOp(BaseLinearTrainBatchOp):
    """reference: batch/regression/LinearRegTrainBatchOp.java (square loss)"""
    MODEL_TYPE = LinearModelType.LinearReg


class LinearRegPredictBatchOp(LinearModelPredictBatchOp):
    pass


class RidgeRegTrainBatchOp(BaseLinearTrainBatchOp):
    """reference: batch/regression/RidgeRegTrainBatchOp.java (L2 required)"""
    MODEL_TYPE = LinearModelType.LinearReg
    LAMBDA = ParamInfo("lambda_", float, "ridge L2 strength", default=0.1,
                       aliases=("lambda",), validator=RangeValidator(0.0, None))

    def link_from(self, in_op: BatchOperator):
        self.params.set("l2", float(self.get_lambda_()))
        return super().link_from(in_op)


class RidgeRegPredictBatchOp(LinearModelPredictBatchOp):
    pass


class LassoRegTrainBatchOp(BaseLinearTrainBatchOp):
    """reference: batch/regression/LassoRegTrainBatchOp.java (L1 required)"""
    MODEL_TYPE = LinearModelType.LinearReg
    LAMBDA = ParamInfo("lambda_", float, "lasso L1 strength", default=0.1,
                       aliases=("lambda",), validator=RangeValidator(0.0, None))

    def link_from(self, in_op: BatchOperator):
        self.params.set("l1", float(self.get_lambda_()))
        return super().link_from(in_op)


class LassoRegPredictBatchOp(LinearModelPredictBatchOp):
    pass


class LinearSvrTrainBatchOp(BaseLinearTrainBatchOp):
    """reference: batch/regression/LinearSvrTrainBatchOp.java (eps-insensitive)"""
    MODEL_TYPE = LinearModelType.SVR
    TAU = ParamInfo("tau", float, "epsilon-insensitive band", default=0.1)


class LinearSvrPredictBatchOp(LinearModelPredictBatchOp):
    pass
