"""Test harness: force an 8-device virtual CPU mesh.

Mirrors the reference's "distributed without a cluster" strategy (SURVEY §4):
Alink tests run on a Flink local mini-cluster whose parallel subtasks are
threads in one JVM; we run on 8 virtual CPU devices in one process
(``--xla_force_host_platform_device_count=8``), so collectives, supersteps
and sharding get real multi-worker semantics.

The container's sitecustomize registers the TPU backend before any test code
runs, and XLA flags are latched at backend init — so the process is re-exec'd
with a scrubbed CPU environment by the early plugin ``bootenv.py`` (repo
root, loaded via pytest.ini ``addopts = -p bootenv`` before fd capture
starts).
"""

import numpy as np
import pytest


@pytest.fixture(scope="session", autouse=True)
def _default_env():
    import jax
    assert len(jax.devices()) == 8, f"expected 8 CPU devices, got {jax.devices()}"
    from alink_tpu.common.mlenv import MLEnvironmentFactory, use_local_env
    use_local_env(parallelism=8)
    yield
    MLEnvironmentFactory.reset()


@pytest.fixture
def rng():
    return np.random.RandomState(2026)
