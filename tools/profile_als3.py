"""Profiler round 3 (fixed): per-kernel cost via in-program iteration
deltas. Each iteration's result enters a FULL reduction (`.sum()`), so
XLA cannot dead-code-eliminate any of the kernel, and the perturbed
input defeats the device service's execution memoization."""
import time

import numpy as np
import jax
import jax.numpy as jnp

nnz, U, rank = 1_000_000, 6040, 10
K = rank * rank + rank + 1
k0 = jax.random.PRNGKey(0)
contrib = jax.random.uniform(k0, (nnz, K), jnp.float32)
x = jax.random.uniform(k0, (nnz, rank), jnp.float32)
ids = jnp.clip(jnp.arange(nnz, dtype=jnp.int32) // (nnz // U), 0, U - 1)
starts = jnp.arange(U, dtype=jnp.int32) * (nnz // U)
ends = starts + nnz // U
A0 = jax.random.uniform(k0, (U, rank, rank), jnp.float32)
Amat = jnp.einsum("nij,nkj->nik", A0, A0) + 10 * jnp.eye(rank)
bvec = jax.random.uniform(k0, (U, rank), jnp.float32)
C = 512
Lb = -(-nnz // C)
pad = Lb * C - nnz


def kernel_delta(name, body, arg, iters=8, reps=3):
    def many(n):
        def f(a, i):
            return jnp.asarray(body(a + i * 1e-7)).sum()
        return jax.jit(lambda a: jax.lax.fori_loop(
            0, n, lambda i, s: s + f(a, i), jnp.asarray(0.0)))

    g1, gn = many(1), many(1 + iters)
    np.asarray(g1(arg)); np.asarray(gn(arg))          # compile both
    t1, tn = [], []
    for _ in range(reps):
        t0 = time.perf_counter(); np.asarray(g1(arg))
        t1.append(time.perf_counter() - t0)
        t0 = time.perf_counter(); np.asarray(gn(arg))
        tn.append(time.perf_counter() - t0)
    dt = (min(tn) - min(t1)) / iters
    print(f"{name:44s} {dt*1e3:8.2f} ms", flush=True)


def blocks(c):
    cpad = jnp.concatenate([c, jnp.zeros((pad, K), c.dtype)])
    return cpad.reshape(Lb, C, K)


def bsum(c):
    return blocks(c).sum(axis=1)


def intra(c):
    return jnp.cumsum(blocks(c), axis=1)


def twolevel_f64(c):
    it = jnp.cumsum(blocks(c), axis=1)
    with jax.enable_x64(True):
        bs = it[:, -1, :].astype(jnp.float64)
        inter = jnp.concatenate(
            [jnp.zeros((1, K), jnp.float64), jnp.cumsum(bs, axis=0)])

        def prefix(t):
            bi, ri = t // C, t % C
            part = jnp.where((ri > 0)[:, None], it[bi, ri - 1], 0.0)
            return inter[bi] + part.astype(jnp.float64)

        return (prefix(ends) - prefix(starts)).astype(c.dtype)


def centered_f32(c):
    blk = blocks(c)
    mean = blk.sum(axis=1).sum(axis=0) / (Lb * C)
    it = jnp.cumsum(blk - mean, axis=1)
    inter = jnp.concatenate(
        [jnp.zeros((1, K), jnp.float32), jnp.cumsum(it[:, -1, :], axis=0)])

    def prefix(t):
        bi, ri = t // C, t % C
        return inter[bi] + jnp.where((ri > 0)[:, None], it[bi, ri - 1], 0.0)

    span = (ends - starts).astype(jnp.float32)[:, None]
    return (prefix(ends) - prefix(starts)) + mean * span


def build_contrib(xa):
    return jnp.concatenate(
        [(xa[:, :, None] * xa[:, None, :]).reshape(-1, rank * rank),
         xa, jnp.ones((nnz, 1), xa.dtype)], axis=1)


def solve(c):
    A2 = Amat + c.ravel()[0] * 1e-9
    return jnp.linalg.solve(A2, bvec[..., None])[..., 0]


def gj(c):
    A2 = Amat + c.ravel()[0] * 1e-9
    M = jnp.concatenate(
        [A2, jnp.broadcast_to(jnp.eye(rank, dtype=A2.dtype), A2.shape)], -1)
    for i in range(rank):
        piv = M[:, i, :] / M[:, i, i:i + 1]
        M = M - M[:, :, i:i + 1] * piv[:, None, :]
        M = M.at[:, i, :].set(piv)
    return jnp.einsum("nij,nj->ni", M[:, :, rank:], bvec)


def scatter(c):
    return jnp.zeros((U, K), jnp.float32).at[ids[:U]].add(c[:U])


kernel_delta("build contrib (outer+concat)", build_contrib, x)
kernel_delta("block sums", bsum, contrib)
kernel_delta("intra cumsum", intra, contrib)
kernel_delta("full twolevel f64", twolevel_f64, contrib)
kernel_delta("centered all-f32", centered_f32, contrib)
kernel_delta("scatter-add (U rows)", scatter, contrib)
kernel_delta("linalg.solve (U,10,10)", solve, contrib)
kernel_delta("gauss-jordan (U,10,10)", gj, contrib)
print("done", flush=True)
