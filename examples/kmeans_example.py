"""KMeans pipeline example — mirror of the reference KMeansExample
(examples/src/main/java/com/alibaba/alink/KMeansExample.java:14-32),
with a synthetic iris-like fixture instead of the hosted CSV (no egress).

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
     python examples/kmeans_example.py
"""

try:
    import _bootstrap  # noqa: F401  (repo root onto sys.path)
except ImportError:  # running as a module: python -m examples.foo
    from . import _bootstrap  # noqa: F401

import numpy as np

from alink_tpu.common.mlenv import use_local_env
from alink_tpu.operator.batch.source import MemSourceBatchOp
from alink_tpu.operator.batch.evaluation import EvalClusterBatchOp
from alink_tpu.pipeline import Pipeline
from alink_tpu.pipeline.clustering import KMeans
from alink_tpu.pipeline.feature import VectorAssembler


def iris_like(n_per: int = 50, seed: int = 7):
    rng = np.random.RandomState(seed)
    centers = np.asarray([[5.0, 3.4, 1.5, 0.25],
                          [5.9, 2.8, 4.3, 1.3],
                          [6.6, 3.0, 5.6, 2.0]])
    rows = []
    for ci, c in enumerate(centers):
        pts = c + 0.25 * rng.randn(n_per, 4)
        rows += [tuple(p) + (ci,) for p in pts]
    rng.shuffle(rows)
    return rows


def main():
    use_local_env()   # all available devices (8 on the CPU test mesh)
    data = MemSourceBatchOp(
        iris_like(),
        "sepal_length DOUBLE, sepal_width DOUBLE, petal_length DOUBLE, "
        "petal_width DOUBLE, category LONG")

    pipeline = Pipeline(
        VectorAssembler(
            selected_cols=["sepal_length", "sepal_width",
                           "petal_length", "petal_width"],
            output_col="features"),
        KMeans(vector_col="features", k=3, prediction_col="cluster_id"))
    model = pipeline.fit(data)
    pred = model.transform(data)

    ev = EvalClusterBatchOp(vector_col="features",
                            prediction_col="cluster_id").link_from(pred)
    m = ev.collect_metrics()
    print(pred.collect_mtable().to_display_string(10))
    print(f"k={m.get('K')}  silhouette={m.get('SilhouetteCoefficient'):.3f}  "
          f"CH={m.get('CalinskiHarabasz'):.1f}")


if __name__ == "__main__":
    main()
