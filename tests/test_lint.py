"""alink-lint (tools/lint) + flag-registry (common/flags.py) tests.

Three layers:

1. **fixture self-tests** — one minimal positive and negative case per
   rule under ``tests/lint_fixtures/`` (parsed, never imported), so each
   rule's semantics are pinned independently of the real tree;
2. **the tier-1 gate** — the analyzer runs over the whole ``alink_tpu``
   package and must report ZERO non-baselined violations and no stale
   baseline entries (exactly what ``python -m tools.lint --strict`` and
   ``tools/perf_gate.sh`` enforce);
3. **migration regression** — the registry migration must leave env-flag
   semantics, program-cache keys and lowered HLO byte-identical to the
   pre-migration ad-hoc parsers for a representative flag combination.
"""

import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:                      # direct pytest invocation
    sys.path.insert(0, REPO)

from tools.lint.analyzer import (ModuleIndex, env_reads_in,      # noqa: E402
                                 load_flag_registry, repo_root)
from tools.lint.baseline import (Baseline, BaselineEntry,        # noqa: E402
                                 BaselineError, load_baseline)
from tools.lint.rules import (FactoryRoot, LintConfig,           # noqa: E402
                              default_config, run_lint)
from alink_tpu.common import flags as flagmod                    # noqa: E402
from alink_tpu.common.flags import (FLAGS, Flag, FlagRegistry,   # noqa: E402
                                    env_flag, flag_raw, flag_value,
                                    parse_bool)

FIXDIR = "tests/lint_fixtures"


# ---------------------------------------------------------------------------
# fixture harness
# ---------------------------------------------------------------------------

def _fixture_registry() -> FlagRegistry:
    reg = FlagRegistry()
    reg.register("ALINK_TPU_GOOD", "bool", False, "fixture flag", "debug",
                 folds_into=frozenset({flagmod.PROGRAM_CACHE}))
    reg.register("ALINK_TPU_NEUTRAL", "bool", False, "fixture flag", "debug",
                 key_neutral="fixture: host-side only, never traced")
    reg.register("ALINK_TPU_BAD", "bool", False, "fixture flag", "debug",
                 folds_into=frozenset({flagmod.STEP_LRU}))
    return reg


def _fixture_config(*files: str, roots=(), allowed=(),
                    compiled=()) -> LintConfig:
    return LintConfig(
        package_dirs=tuple(f"{FIXDIR}/{f}" for f in files),
        factory_roots=tuple(roots),
        collective_allowed=tuple(allowed),
        compiled_path_globs=tuple(compiled),
    )


def _lint_fixture(files, **kw):
    cfg = _fixture_config(*files, **kw)
    index = ModuleIndex.build(REPO, cfg.package_dirs)
    return run_lint(root=REPO, config=cfg, registry=_fixture_registry(),
                    index=index)


def _rules_of(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# per-rule fixture self-tests
# ---------------------------------------------------------------------------

class TestEnvKeyFoldFixtures:
    ROOT = (FactoryRoot(f"{FIXDIR}/env_key_fold_pos.py", "make_program",
                        frozenset({flagmod.PROGRAM_CACHE})),)
    ROOT_NEG = (FactoryRoot(f"{FIXDIR}/env_key_fold_neg.py", "make_program",
                            frozenset({flagmod.PROGRAM_CACHE})),)

    def test_positive(self):
        got = _lint_fixture(["env_key_fold_pos.py"], roots=self.ROOT)
        assert _rules_of(got) == ["ENV-KEY-FOLD"]
        by_flag = {f.ident for f in got}
        # wrong-dimension declared flag, (constant-resolved) undeclared,
        # and the os.getenv spelling of an undeclared read
        assert by_flag == {"ALINK_TPU_BAD", "ALINK_TPU_UNDECLARED",
                           "ALINK_TPU_UNDECLARED_GETENV"}
        assert any("step_lru" in f.message for f in got)

    def test_negative(self):
        got = _lint_fixture(["env_key_fold_neg.py"], roots=self.ROOT_NEG)
        assert got == []

    def test_unregistered_factory_backstop(self):
        """A NEW lru_cache'd factory nobody added to default_config()
        must not silently escape the rule: a key-affecting env read
        reachable from it is flagged until the factory is registered;
        key-neutral reads stay silent."""
        got = _lint_fixture(["env_key_fold_unreg.py"])
        assert _rules_of(got) == ["ENV-KEY-FOLD"]
        assert {f.ident for f in got} == {
            "unregistered-factory:_rogue_step_factory"}
        assert "register" in got[0].message
        # once registered with the right key dimension, it is clean
        root = (FactoryRoot(f"{FIXDIR}/env_key_fold_unreg.py",
                            "_rogue_step_factory",
                            frozenset({flagmod.PROGRAM_CACHE})),)
        assert _lint_fixture(["env_key_fold_unreg.py"], roots=root) == []

    def test_missing_root_is_reported_not_crashed(self):
        bad = (FactoryRoot(f"{FIXDIR}/env_key_fold_neg.py", "nope",
                           frozenset({flagmod.PROGRAM_CACHE})),)
        got = _lint_fixture(["env_key_fold_neg.py"], roots=bad)
        assert [f.rule for f in got] == ["ENV-KEY-FOLD"]
        assert "missing-root" in got[0].ident


class TestTracedCaptureFixtures:
    def test_positive(self):
        got = _lint_fixture(["traced_capture_pos.py"])
        assert _rules_of(got) == ["TRACED-CAPTURE"]
        idents = {f.ident for f in got}
        assert "stage:dev" in idents       # device-array capture
        assert "stage:state" in idents     # mutated mutable container

    def test_negative(self):
        got = _lint_fixture(["traced_capture_neg.py"])
        assert got == []


class TestDonateUseAfterFixtures:
    def test_positive(self):
        got = _lint_fixture(["donate_use_after_pos.py"])
        assert set(_rules_of(got)) == {"DONATE-USE-AFTER"}
        # direct call AND the pass-through-wrapper call (run_step shape)
        assert sorted(f.ident for f in got) == ["train:z",
                                                "train_wrapped:z"]
        assert "donate_argnums" in got[0].message

    def test_negative(self):
        got = _lint_fixture(["donate_use_after_neg.py"])
        assert got == []


class TestCollectiveSiteFixtures:
    def test_positive(self):
        got = _lint_fixture(["collective_site_pos.py"])
        assert _rules_of(got) == ["COLLECTIVE-SITE"]
        assert {f.ident for f in got} == {"shard_fn:psum",
                                          "shard_fn:all_gather",
                                          "aliased:pmax",
                                          "aliased:ppermute"}

    def test_negative(self):
        got = _lint_fixture(["collective_site_neg.py"])
        assert got == []

    def test_allowed_file_is_exempt(self):
        got = _lint_fixture(["collective_site_pos.py"],
                            allowed=(f"{FIXDIR}/collective_site_pos.py",))
        assert got == []


class TestHostCallbackFixtures:
    GLOBS = (f"{FIXDIR}/host_callback_*",)

    def test_positive(self):
        got = _lint_fixture(["host_callback_pos.py"], compiled=self.GLOBS)
        assert _rules_of(got) == ["HOST-CALLBACK-FREE"]
        assert {f.ident for f in got} == {"stage:debug.print",
                                          "stage:io_callback",
                                          "stage_aliased:debug.print"}

    def test_negative(self):
        got = _lint_fixture(["host_callback_neg.py"], compiled=self.GLOBS)
        assert got == []

    def test_outside_compiled_path_is_fine(self):
        got = _lint_fixture(["host_callback_pos.py"], compiled=())
        assert got == []


class TestParseError:
    def test_broken_file_is_a_finding_not_a_traceback(self, tmp_path):
        """The analyzer's "total" contract: a file that fails to parse
        must surface as a PARSE-ERROR finding (the CLI's documented
        exit-code contract), never an uncaught SyntaxError — the gate
        would otherwise die with a traceback instead of a diagnostic."""
        pkg = tmp_path / "alink_tpu"
        pkg.mkdir()
        (pkg / "bad.py").write_text("def broken(:\n")
        (pkg / "good.py").write_text("X = 1\n")
        cfg = LintConfig(package_dirs=("alink_tpu",), factory_roots=(),
                         collective_allowed=(), compiled_path_globs=())
        got = run_lint(root=str(tmp_path), config=cfg,
                       registry=_fixture_registry())
        assert _rules_of(got) == ["PARSE-ERROR"]
        (f,) = got
        assert (f.file, f.line, f.ident) == ("alink_tpu/bad.py", 1, "syntax")
        # the parseable sibling was still indexed
        index = ModuleIndex.build(str(tmp_path), cfg.package_dirs)
        assert "alink_tpu/good.py" in index.by_path
        assert "alink_tpu/bad.py" not in index.by_path


# ---------------------------------------------------------------------------
# baseline machinery
# ---------------------------------------------------------------------------

class TestBaseline:
    def test_entry_consumes_matching_finding(self):
        got = _lint_fixture(["collective_site_pos.py"])
        bl = Baseline(path="<mem>", entries=[BaselineEntry(
            "COLLECTIVE-SITE", f"{FIXDIR}/collective_site_pos.py",
            "shard_fn:*", "fixture: glob idents keep baselines stable "
                          "across reformatting")])
        violations, baselined, stale = bl.split(got)
        # the glob consumes only shard_fn's findings; the aliased-import
        # ones stay live violations
        assert len(baselined) == 2 and stale == []
        assert {f.ident for f in violations} == {"aliased:pmax",
                                                 "aliased:ppermute"}

    def test_stale_entry_detected(self):
        bl = Baseline(path="<mem>", entries=[BaselineEntry(
            "COLLECTIVE-SITE", "gone.py", "x:psum",
            "matched nothing on purpose for this test")])
        violations, baselined, stale = bl.split([])
        assert stale == bl.entries

    def test_malformed_baseline_refused(self, tmp_path):
        import json
        p = tmp_path / "bl.json"
        p.write_text(json.dumps({"entries": [
            {"rule": "X", "file": "f.py", "ident": "i",
             "justification": "too short"}]}))
        with pytest.raises(BaselineError, match="explain WHY"):
            load_baseline(str(p))
        p.write_text(json.dumps({"entries": [
            {"rule": "X", "file": "f.py"}]}))
        with pytest.raises(BaselineError, match="missing"):
            load_baseline(str(p))

    def test_broken_json_baseline_is_exit_2_not_traceback(self, tmp_path):
        """A mis-edited baseline (trailing comma, truncated file) must
        surface as the documented exit-2 diagnostic, not a raw
        json.JSONDecodeError traceback out of the tier-1/perf gate."""
        from tools.lint.cli import main as lint_main
        p = tmp_path / "bl.json"
        p.write_text('{"entries": [,]}')
        with pytest.raises(BaselineError, match="not valid JSON"):
            load_baseline(str(p))
        assert lint_main(["--strict", "--baseline", str(p)]) == 2
        p.write_text('["not", "an", "object"]')
        with pytest.raises(BaselineError, match="entries"):
            load_baseline(str(p))

    def test_broken_flags_py_is_exit_2_not_traceback(self, tmp_path,
                                                     capsys):
        """A syntax error (or a refused declaration) in the linted
        tree's flags.py is a configuration error: documented exit 2
        with a diagnostic, never an unhandled traceback out of the
        perf gate."""
        from tools.lint.cli import main as lint_main
        root = tmp_path / "tree"
        (root / "alink_tpu" / "common").mkdir(parents=True)
        (root / "tools").mkdir()
        (root / "alink_tpu" / "common" / "flags.py").write_text(
            "def broken(:\n")
        assert lint_main(["--strict", "--root", str(root)]) == 2
        assert "flag registry" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# the tier-1 gate: the whole package must be clean
# ---------------------------------------------------------------------------

class TestWholePackage:
    def test_zero_nonbaselined_violations(self):
        """Exactly what ``python -m tools.lint --strict`` enforces in
        tools/perf_gate.sh: every finding on the current tree is either
        fixed or carries a written justification in
        tools/lint_baseline.json — and no baseline entry outlives the
        code it excuses."""
        findings = run_lint(root=repo_root(), config=default_config(),
                            registry=load_flag_registry())
        baseline = load_baseline()
        violations, baselined, stale = baseline.split(findings)
        assert violations == [], "\n".join(f.render() for f in violations)
        assert stale == [], [e.ident for e in stale]

    def test_every_alink_env_read_in_package_is_declared(self):
        """Repo-wide (not just factory-reachable): every ALINK_* env
        read inside alink_tpu/ resolves to a literal/constant name that
        is declared in the registry — no flag can exist outside it."""
        cfg = default_config()
        index = ModuleIndex.build(repo_root(), cfg.package_dirs)
        registry = load_flag_registry()
        undeclared = []
        for mod in index.by_path.values():
            if mod.path in cfg.env_read_exempt:
                continue
            for read in env_reads_in(mod.tree, mod, index):
                if read.name.startswith("ALINK_") \
                        and registry.get(read.name) is None:
                    undeclared.append((mod.path, read.line, read.name))
        assert undeclared == []

    def test_cli_strict_exits_zero(self):
        from tools.lint.cli import main
        assert main(["--strict"]) == 0

    def test_cli_json_shape(self, capsys):
        import json
        from tools.lint.cli import main
        assert main(["--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert set(doc) == {"violations", "baselined", "stale_baseline"}
        assert doc["violations"] == []


# ---------------------------------------------------------------------------
# registry semantics + migration byte-identity regression
# ---------------------------------------------------------------------------

class TestRegistryValidation:
    def test_every_flag_declares_fold_or_neutral(self):
        for f in FLAGS:
            assert bool(f.folds_into) != bool(f.key_neutral), f.name

    def test_duplicate_refused(self):
        reg = _fixture_registry()
        with pytest.raises(ValueError, match="twice"):
            reg.register("ALINK_TPU_GOOD", "bool", False, "dup", "debug",
                         key_neutral="fixture justification text")

    def test_silent_on_staleness_refused(self):
        reg = FlagRegistry()
        with pytest.raises(ValueError, match="exactly one"):
            reg.register("ALINK_TPU_X", "bool", False, "d", "debug")
        with pytest.raises(ValueError, match="exactly one"):
            reg.register("ALINK_TPU_X", "bool", False, "d", "debug",
                         folds_into=frozenset({flagmod.PROGRAM_CACHE}),
                         key_neutral="both is as bad as neither")

    def test_bad_dimension_and_prefix_refused(self):
        reg = FlagRegistry()
        with pytest.raises(ValueError, match="not a subset"):
            reg.register("ALINK_TPU_X", "bool", False, "d", "debug",
                         folds_into=frozenset({"nope"}))
        with pytest.raises(ValueError, match="ALINK_ prefix"):
            reg.register("OTHER_FLAG", "bool", False, "d", "debug",
                         key_neutral="prefix check fires first here")

    def test_undeclared_read_refused(self):
        with pytest.raises(KeyError, match="not declared"):
            flag_value("ALINK_TPU_NOT_A_FLAG")
        with pytest.raises(KeyError, match="not declared"):
            flag_raw("ALINK_TPU_NOT_A_FLAG")

    def test_tolerant_fallback_respects_callsite_default(self, monkeypatch):
        """An unparseable value on a tolerant flag falls back to the
        CALL-SITE default when one is given, not the registered one."""
        monkeypatch.setenv("ALINK_TPU_TRACE_BUFFER", "junk")
        assert flag_value("ALINK_TPU_TRACE_BUFFER") == 65536
        assert flag_value("ALINK_TPU_TRACE_BUFFER", 1024) == 1024


class TestMigrationByteIdentity:
    """The registry migration must be a pure refactor: same parsed
    values as every pre-migration ad-hoc parser, same program-cache
    keys, same lowered HLO."""

    # the pre-migration parsers, copied verbatim from the r06 tree
    @staticmethod
    def _legacy_env_flag(env, name, default=False):
        v = env.get(name)
        if v is None:
            return default
        return v.strip().lower() not in {"", "0", "false", "off", "no"}

    @staticmethod
    def _legacy_trace_buffer(env):
        raw = env.get("ALINK_TPU_TRACE_BUFFER")
        if not raw:
            return 65536
        try:
            n = int(raw)
        except ValueError:
            return 65536
        return max(1, n)

    @staticmethod
    def _legacy_prefetch_depth(env, default=2):
        v = env.get("ALINK_TPU_STREAM_PREFETCH", "")
        if v == "":
            return default
        return max(0, int(v))

    @staticmethod
    def _legacy_stream_workers(env, default=1):
        v = env.get("ALINK_TPU_STREAM_WORKERS", "")
        if v == "":
            return default
        return max(1, int(v))

    BOOL_RAWS = [None, "", "0", "1", "false", "False", " OFF ", "no",
                 "yes", "on", "2", "junk"]

    def test_bool_semantics_identical(self, monkeypatch):
        for flag in ("ALINK_TPU_METRICS", "ALINK_TPU_DONATE",
                     "ALINK_TPU_HEALTH", "ALINK_TPU_STEP_LOG",
                     "ALINK_TPU_TRACE", "ALINK_TPU_ASYNC_SNAPSHOT"):
            default = FLAGS.get(flag).default
            for raw in self.BOOL_RAWS:
                if raw is None:
                    monkeypatch.delenv(flag, raising=False)
                else:
                    monkeypatch.setenv(flag, raw)
                env = {} if raw is None else {flag: raw}
                assert flag_value(flag) == \
                    self._legacy_env_flag(env, flag, default), (flag, raw)
                assert env_flag(flag, default) == \
                    self._legacy_env_flag(env, flag, default), (flag, raw)
            monkeypatch.delenv(flag, raising=False)

    INT_NAMES = ("ALINK_TPU_TRACE_BUFFER", "ALINK_TPU_STREAM_PREFETCH",
                 "ALINK_TPU_STREAM_WORKERS")

    def test_int_semantics_identical(self, monkeypatch):
        for raw in (None, "", "0", "7", "-3", "junk"):
            for name in self.INT_NAMES:
                if raw is None:
                    monkeypatch.delenv(name, raising=False)
                else:
                    monkeypatch.setenv(name, raw)
            env = {} if raw is None else {n: raw for n in self.INT_NAMES}
            # tolerant buffer flag: junk -> default (legacy semantics)
            assert flag_value("ALINK_TPU_TRACE_BUFFER") == \
                self._legacy_trace_buffer(env)
            if raw == "junk":     # strict int flags raised pre-migration too
                with pytest.raises(ValueError):
                    flag_value("ALINK_TPU_STREAM_PREFETCH")
            else:
                assert flag_value("ALINK_TPU_STREAM_PREFETCH") == \
                    self._legacy_prefetch_depth(env)
                assert flag_value("ALINK_TPU_STREAM_WORKERS") == \
                    self._legacy_stream_workers(env)
        for name in self.INT_NAMES:
            monkeypatch.delenv(name, raising=False)

    def test_fused_hist_mode_semantics_identical(self, monkeypatch):
        legacy = {None: "off", "": "off", "0": "off", "off": "off",
                  "false": "off", "pallas": "pallas", "1": "xla",
                  "xla": "xla", "anything": "xla"}
        for raw, want in legacy.items():
            if raw is None:
                monkeypatch.delenv("ALINK_TPU_FUSED_HIST", raising=False)
            else:
                monkeypatch.setenv("ALINK_TPU_FUSED_HIST", raw)
            assert flag_value("ALINK_TPU_FUSED_HIST") == want, raw
        monkeypatch.delenv("ALINK_TPU_FUSED_HIST", raising=False)

    def test_accessor_functions_route_through_registry(self, monkeypatch):
        """The canonical accessors (the ones compiled-path code calls)
        agree with the registry on the unified falsy convention."""
        from alink_tpu.common.health import health_enabled
        from alink_tpu.common.metrics import metrics_enabled
        from alink_tpu.common.tracing import _buffer_capacity
        from alink_tpu.engine.comqueue import donation_enabled
        from alink_tpu.operator.stream.prefetch import (prefetch_depth,
                                                        stream_workers)
        monkeypatch.setenv("ALINK_TPU_HEALTH", "OFF")
        monkeypatch.setenv("ALINK_TPU_METRICS", "No")
        monkeypatch.setenv("ALINK_TPU_DONATE", "0")
        monkeypatch.setenv("ALINK_TPU_TRACE_BUFFER", "-5")
        monkeypatch.setenv("ALINK_TPU_STREAM_PREFETCH", "")
        monkeypatch.setenv("ALINK_TPU_STREAM_WORKERS", "0")
        assert health_enabled() is False
        assert metrics_enabled() is False
        assert donation_enabled() is False
        assert _buffer_capacity() == 1          # legacy max(1, n) clamp
        assert prefetch_depth() == 2            # set-but-empty == unset
        assert stream_workers() == 1            # legacy max(1, n) clamp

    def test_fault_spec_reads_through_registry(self, monkeypatch):
        from alink_tpu.common.faults import FaultRule, fault_spec
        monkeypatch.setenv("ALINK_TPU_FAULT_INJECT", "ftrl.batch:3")
        # the r14 grammar parses the legacy site:index form to an
        # open-ended kill rule — same semantics, richer spec type
        assert fault_spec() == {"ftrl.batch": FaultRule(3, None, "kill",
                                                        0.0)}
        monkeypatch.delenv("ALINK_TPU_FAULT_INJECT", raising=False)
        assert fault_spec() == {}

    def test_program_cache_key_and_hlo_identical(self, monkeypatch):
        """For the default flag combination, explicitly setting every
        key-folded flag to its registered default must produce the SAME
        program-cache key and byte-identical lowered HLO as leaving the
        environment unset — the registry parse path adds nothing to the
        key contents."""
        import jax.numpy as jnp
        import alink_tpu.engine.comqueue as cq
        from alink_tpu.engine.communication import AllReduce
        from alink_tpu.engine.comqueue import IterativeComQueue

        X = np.arange(16.0).reshape(8, 2)

        def stage(ctx):
            if ctx.is_init_step:
                ctx.put_obj("s", jnp.zeros(()))
            ctx.put_obj("s", ctx.get_obj("X").sum())

        def build(key):
            return (IterativeComQueue(max_iter=3)
                    .init_with_partitioned_data("X", X)
                    .add(stage).add(AllReduce("s"))
                    .set_program_key(key))

        for name in ("ALINK_TPU_STEP_LOG", "ALINK_TPU_HEALTH",
                     "ALINK_TPU_DONATE"):
            monkeypatch.delenv(name, raising=False)
        key = "lint_migration_identity"
        hlo_unset = build(key).lowered().as_text()
        build(key).exec()
        ck_unset = [k for k in cq._PROGRAM_CACHE if k and k[0] == key]

        monkeypatch.setenv("ALINK_TPU_STEP_LOG", "0")   # registered defaults
        monkeypatch.setenv("ALINK_TPU_HEALTH", "1")
        monkeypatch.setenv("ALINK_TPU_DONATE", "1")
        hlo_set = build(key).lowered().as_text()
        build(key).exec()
        ck_set = [k for k in cq._PROGRAM_CACHE if k and k[0] == key]

        assert hlo_set == hlo_unset                     # byte-identical
        assert ck_set == ck_unset                       # same cache key set
        # and the flag slots carry the documented defaults
        # (ckey layout: ..., step_log, probes_on, donate, parts, bcast)
        (ck,) = set(ck_unset)
        assert (False, True, True) == (ck[7], ck[8], ck[9])


class TestGeneratedDocs:
    def test_flag_tables_current(self):
        """docs/performance.md + docs/observability.md flag tables match
        the registry (regenerate with python tools/gen_docs.py --flags)."""
        from tools.gen_docs import gen_flag_tables
        assert gen_flag_tables(check=True)

    def test_doc_rows_cover_all_sections(self):
        rows = FLAGS.doc_rows()
        assert {r["section"] for r in rows} == {
            "observability", "performance", "durability", "debug", "io",
            "bench", "serving", "tuning", "e2e"}
        by_name = {r["name"]: r for r in rows}
        assert by_name["ALINK_TPU_DONATE"]["folds"] == \
            "program_cache, step_lru"
        assert "key-neutral" not in by_name["ALINK_TPU_DONATE"]["key_note"]
        assert by_name["ALINK_TPU_METRICS"]["folds"] == "—"

    def test_readme_bench_table_current(self):
        """The docs freshness gate (ISSUE 15 satellite, VERDICT #2):
        README's measured-performance table matches a regeneration from
        the newest BENCH_r*.json capture (gen_docs --check gates it in
        perf_gate.sh; regenerate with tools/gen_readme_table.py)."""
        from tools.gen_docs import check_readme_bench
        assert check_readme_bench()

    def test_readme_bench_check_catches_staleness(self, monkeypatch,
                                                  tmp_path, capsys):
        """A doctored README (numbers drifted from the capture) fails
        the check and the message names the regeneration command."""
        import tools.gen_docs as gd
        from tools import gen_readme_table as grt
        with open(os.path.join(gd._ROOT, "README.md")) as f:
            readme = f.read()
        start = readme.index(grt.START)
        stale = readme[:start] + readme[start:].replace(
            "|", "|", 1).replace("M |", "G |", 1)
        assert stale != readme, "fixture needs a number to doctor"
        (tmp_path / "README.md").write_text(stale)
        monkeypatch.setattr(gd, "_ROOT", str(tmp_path))
        # the captures stay the real ones (grt.ROOT untouched)
        assert not gd.check_readme_bench()
        assert "STALE" in capsys.readouterr().out

    def test_readme_bench_check_skips_without_capture(self, monkeypatch,
                                                      capsys):
        import tools.gen_docs as gd
        from tools import gen_readme_table as grt
        monkeypatch.setattr(grt, "newest_capture", lambda: None)
        assert gd.check_readme_bench()
        assert "skipped" in capsys.readouterr().out
